"""On-chip xplane profile of a bench workload, aggregated by op category.

Usage: python tools/profile_step.py [moe|dense2b|dit|ernie] [steps]

Traces `steps` post-warmup train steps with jax.profiler, parses the
xplane via jax.profiler.ProfileData, and prints per-op-category device
time so perf work (VERDICT r3 next-1) is evidence-driven rather than
guessed. Categories are keyed on the fusion/op names XLA emits for this
codebase (pallas kernel names survive into the xplane as custom-calls).
"""
from __future__ import annotations

import collections
import glob
import os
import re
import sys
import tempfile

import numpy as np


def build(which):
    import jax
    import jax.numpy as jnp
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    if which == "moe":
        from paddle_tpu.nlp import moe, train
        cfg = moe.MoeConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            moe_intermediate_size=1024, num_experts=16,
            num_experts_per_tok=2, num_shared_experts=1,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=2048,
            param_dtype=jnp.bfloat16)
        tx = train.make_optimizer(1e-4, state_quant="8bit", grad_clip=1.0)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=None,
                                 model=moe)
        step = train.make_train_step(cfg, tx, mesh=None, model=moe)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (20, 2048)),
                             jnp.int32)
        return step, state, tokens
    if which == "dense2b":
        from paddle_tpu.nlp import llama, train
        cfg = bench.flagship_2b_cfg()
        tx = train.make_optimizer(1e-4, state_quant="8bit", grad_clip=1.0)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=None)
        step = train.make_train_step(cfg, tx, mesh=None)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 2048)),
                             jnp.int32)
        return step, state, tokens
    if which == "dit":
        step, state, batch_xy, _ = bench.build_dit_step()
        return step, state, batch_xy
    if which == "ernie":
        step, state, batch_xy, _ = bench.build_ernie_step()
        return step, state, batch_xy
    raise SystemExit(f"unknown workload {which}")


CATS = [
    ("flash_attn", re.compile(r"flash|attention", re.I)),
    ("moe_gather", re.compile(r"gather_rows|_gather_rows", re.I)),
    ("fusion", re.compile(r"^(loop_)?fusion", re.I)),
    ("convolution", re.compile(r"convolution|conv", re.I)),
    ("matmul", re.compile(r"dot|einsum|matmul", re.I)),
    ("copy/transpose", re.compile(r"copy|transpose|bitcast", re.I)),
    ("dynamic-slice/update", re.compile(r"dynamic", re.I)),
    ("scatter", re.compile(r"scatter", re.I)),
    ("gather(jnp)", re.compile(r"gather", re.I)),
    ("reduce", re.compile(r"reduce", re.I)),
    ("sort/cumsum", re.compile(r"sort|cumulative|scan", re.I)),
]


def categorize(name):
    # classify on the op's own name only (text before " = "), not its
    # operand list — operand names polluted whole-text matching
    own = name.split(" = ")[0]
    for cat, pat in CATS:
        if pat.search(own):
            return cat
    return "other"


def main():
    import jax
    which = sys.argv[1] if len(sys.argv) > 1 else "moe"
    nsteps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    step, state, tokens = build(which)
    # warmup/compile
    state, loss = step(state, tokens)
    jax.block_until_ready(loss)
    import time
    t0 = time.perf_counter()
    state, loss = step(state, tokens)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    print(f"[{which}] step time {dt*1e3:.0f} ms")

    tmpd = tempfile.mkdtemp(prefix="prof_")
    with jax.profiler.trace(tmpd):
        for _ in range(nsteps):
            state, loss = step(state, tokens)
        jax.block_until_ready(loss)

    from jax.profiler import ProfileData
    files = glob.glob(os.path.join(tmpd, "**", "*.xplane.pb"),
                      recursive=True)
    if not files:
        raise SystemExit(f"no xplane under {tmpd}")
    pd = ProfileData.from_file(files[0])
    by_op = collections.Counter()     # EXCLUSIVE ns per op name
    total = 0
    for plane in pd.planes:
        if "TPU" not in plane.name and "/device" not in plane.name.lower():
            continue
        for line in plane.lines:
            if "xla ops" not in line.name.lower():
                continue
            evs = sorted(((ev.start_ns, ev.duration_ns, ev.name)
                          for ev in line.events), key=lambda t: (t[0], -t[1]))
            # exclusive time: walk a stack of open intervals; a nested
            # event's duration is subtracted from its parent
            stack = []  # (end_ns, name, child_ns_accum) — mutable via list
            def close_until(start):
                while stack and stack[-1][0] <= start:
                    end, name, child = stack.pop()
                    dur = end - stack_start.pop()
                    excl = dur - child
                    by_op[name] += excl
                    if stack:
                        stack[-1][2] += dur
            stack_start = []
            for s, d, name in evs:
                close_until(s)
                stack.append([s + d, name, 0])
                stack_start.append(s)
            close_until(float("inf"))
    # async copy lifetimes (slice-start/copy-start/async-start) overlap
    # real compute on the core timeline — report them separately, never in
    # the core total (round-4 lesson: counting them pointed at the
    # optimizer's DMA streams, which measured at only 14 ms in isolation)
    async_ns = sum(ns for n, ns in by_op.items()
                   if "-start" in n.split(" = ")[0])
    by_op = collections.Counter(
        {n: ns for n, ns in by_op.items()
         if "-start" not in n.split(" = ")[0]})
    total = sum(by_op.values())
    by_cat = collections.Counter()
    for name, ns in by_op.items():
        by_cat[categorize(name)] += ns
    print(f"core time {total/1e6/nsteps:.0f} ms/step over {nsteps} steps "
          f"(+{async_ns/1e6/nsteps:.0f} ms async-copy lifetimes, overlapped)")
    print("\n== by category (ms/step) ==")
    for cat, ns in by_cat.most_common():
        print(f"  {cat:22s} {ns/1e6/nsteps:8.1f}")
    print("\n== top 60 ops (ms/step) ==")
    for name, ns in by_op.most_common(60):
        print(f"  {ns/1e6/nsteps:8.1f}  {name[:130]}")
    conv = [(ns, n) for n, ns in by_op.items()
            if "convolution" in n or "dot" in n]
    conv.sort(reverse=True)
    print(f"\n== all dot/conv ops ({sum(ns for ns,_ in conv)/1e6/nsteps:.0f} ms/step) ==")
    for ns, name in conv[:40]:
        print(f"  {ns/1e6/nsteps:8.1f}  {name[:130]}")


if __name__ == "__main__":
    main()
