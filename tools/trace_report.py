#!/usr/bin/env python
"""Summarize a serving trace artifact (bench_serving.py --trace out.json).

Reads the Chrome-trace JSON exported by `serving.trace.TraceSink.
to_chrome_trace()` and answers, per request and in aggregate, the
questions the raw timeline is too granular for:

  * per-phase time breakdown — queue wait (enqueued→admitted), prefill
    (sum of prefill_chunk spans), decode (first_token→terminal), total;
  * pad waste — bucket-padding tokens vs real suffix tokens across
    every prefill chunk (the overhead the bucket ladder trades for
    zero recompiles);
  * cache-hit attribution — prompt tokens the prefix cache skipped,
    per request and total, next to the tokens actually prefilled;
  * scheduling mix — fused vs standalone prefill chunks, engine step
    span count/total;
  * quantization — the resolved weight/KV dtype config each request
    was prepared under, and the KV bytes its block footprint pins
    (per-block bytes off the prepared event, int8 scale overhead
    included);
  * recovery churn — the "requeued" phase: how often each request went
    back to the queue front (quarantine victims, rolled-back pending
    siblings) and how many backoff retries it consumed, so a
    fault-tolerance event cascade is visible instead of reading as
    unexplained repeat prefills;
  * replica attribution — which replica served each request (the
    `replica_id` the batcher stamps on `prepared` events, or the
    Router's `routed`/`failover` events in a merged multi-replica
    artifact), a per-replica request breakdown in the totals, and a
    `failovers` churn column so the cross-replica recovery path reads
    like the in-replica requeue one;
  * self-healing churn — supervisor `restarting`/`restarted` events
    (replica-scoped spans, no trace_id) counted into the recovery
    totals next to failovers, so a replica that died and was respawned
    is visible in the same summary as the requests it stranded.

Standard library only (no jax import): runs anywhere the JSON landed,
including the CI bench-smoke job where it ships as a non-blocking
artifact. `--json` prints the summary as one JSON object instead of
the text table.
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter, defaultdict

TERMINAL = {"finished", "cancelled", "failed", "timed_out"}


def load_events(path: str):
    """The artifact's non-metadata trace events, sorted by timestamp."""
    with open(path) as f:
        data = json.load(f)
    evs = [e for e in data.get("traceEvents", []) if e.get("ph") != "M"]
    evs.sort(key=lambda e: e.get("ts", 0.0))
    return evs


def summarize(events) -> dict:
    """Aggregate the per-request phase/pad/cache numbers (all times in
    milliseconds; `ts`/`dur` in the artifact are microseconds)."""
    per_req = defaultdict(lambda: {
        "enqueued_ts": None, "admitted_ts": None, "first_token_ts": None,
        "terminal_ts": None, "terminal": None, "prompt_len": None,
        "slot": None, "prefill_ms": 0.0, "chunks": 0, "fused_chunks": 0,
        "pad_tokens": 0, "real_tokens": 0, "cached_tokens": 0,
        "generated": 0, "requeues": 0, "retries": 0, "kv_bytes": 0,
        "replica": None, "failovers": 0,
    })
    steps = {"count": 0, "total_ms": 0.0}
    quant = {"weight_dtype": None, "kv_dtype": None}
    # replica-scoped (not request-scoped) churn: supervisor restart
    # events ride the engine sinks' span lane with no trace_id
    restarts = {"restarting": 0, "restarted": 0}
    for e in events:
        name, args = e.get("name"), e.get("args", {})
        if name == "engine.step":
            steps["count"] += 1
            steps["total_ms"] += e.get("dur", 0.0) / 1e3
            continue
        if name in ("restarting", "restarted"):
            restarts[name] += 1
            continue
        tid = args.get("trace_id")
        if tid is None:
            continue
        r = per_req[tid]
        ts = e.get("ts", 0.0)
        if name == "enqueued":
            r["enqueued_ts"] = ts
            r["prompt_len"] = args.get("prompt_len")
        elif name == "admitted":
            r["admitted_ts"] = ts
        elif name == "routed":
            # the Router's placement decision (replica + policy score)
            r["replica"] = args.get("replica", r["replica"])
        elif name == "failover":
            # cross-replica recovery: the request resumed elsewhere
            r["failovers"] += 1
            r["replica"] = args.get("to_replica", r["replica"])
        elif name == "prepared":
            r["slot"] = args.get("slot")
            r["replica"] = args.get("replica_id", r["replica"])
            # quantized-serving bytes: the batcher stamps its resolved
            # dtype config + per-block bytes (scale overhead included)
            # on every prepared event, so the report can price each
            # request's KV residency without re-deriving model geometry
            r["kv_bytes"] = (args.get("blocks", 0)
                             * args.get("kv_block_bytes", 0))
            quant["weight_dtype"] = args.get("weight_dtype",
                                             quant["weight_dtype"])
            quant["kv_dtype"] = args.get("kv_dtype", quant["kv_dtype"])
        elif name == "prefill_chunk":
            r["chunks"] += 1
            r["prefill_ms"] += e.get("dur", 0.0) / 1e3
            r["pad_tokens"] += args.get("pad", 0)
            r["real_tokens"] += args.get("end", 0) - args.get("start", 0)
            r["cached_tokens"] += args.get("cached_tokens", 0)
            if args.get("fused"):
                r["fused_chunks"] += 1
        elif name == "first_token":
            r["first_token_ts"] = ts
        elif name == "retired":
            r["generated"] = args.get("generated", 0)
        elif name == "requeued":
            r["requeues"] += 1
        elif name == "retried":
            r["retries"] += 1
        elif name in TERMINAL:
            r["terminal_ts"] = ts
            r["terminal"] = name

    rows = []
    for tid, r in per_req.items():
        def delta(a, b):
            return None if r[a] is None or r[b] is None \
                else (r[b] - r[a]) / 1e3
        rows.append({
            # an artifact exported mid-run carries requests with no
            # terminal event yet — report them as "live", don't crash
            "trace_id": tid, "terminal": r["terminal"] or "live",
            "replica": r["replica"], "failovers": r["failovers"],
            "slot": r["slot"], "prompt_len": r["prompt_len"],
            "generated": r["generated"],
            "queue_wait_ms": delta("enqueued_ts", "admitted_ts"),
            "ttft_ms": delta("enqueued_ts", "first_token_ts"),
            "decode_ms": delta("first_token_ts", "terminal_ts"),
            "total_ms": delta("enqueued_ts", "terminal_ts"),
            "prefill_ms": round(r["prefill_ms"], 3),
            "chunks": r["chunks"], "fused_chunks": r["fused_chunks"],
            "cached_tokens": r["cached_tokens"],
            "prefilled_tokens": r["real_tokens"],
            "pad_tokens": r["pad_tokens"],
            "requeues": r["requeues"], "retries": r["retries"],
            "kv_bytes": r["kv_bytes"],
        })
    # (len, str) sorts t2 before t10 — ids are a prefix plus a
    # monotonic sequence number, so length order IS numeric order
    rows.sort(key=lambda x: (len(x["trace_id"]), x["trace_id"]))
    pad = sum(x["pad_tokens"] for x in rows)
    real = sum(x["prefilled_tokens"] for x in rows)
    cached = sum(x["cached_tokens"] for x in rows)
    total = {
        "requests": len(rows),
        "terminals": dict(sorted(
            Counter(x["terminal"] for x in rows).items())),
        "prefill_chunks": sum(x["chunks"] for x in rows),
        "fused_chunks": sum(x["fused_chunks"] for x in rows),
        "prefilled_tokens": real,
        "pad_tokens": pad,
        "pad_waste": round(pad / (pad + real), 4) if pad + real else 0.0,
        "cached_tokens": cached,
        "cache_hit_rate": round(cached / (cached + real), 4)
        if cached + real else 0.0,
        "engine_steps": steps["count"],
        "engine_step_ms_total": round(steps["total_ms"], 3),
        "requeued_events": sum(x["requeues"] for x in rows),
        "retried_events": sum(x["retries"] for x in rows),
        "failover_events": sum(x["failovers"] for x in rows),
        "restart_events": restarts["restarted"],
        "restarting_events": restarts["restarting"],
        "replicas": dict(sorted(Counter(
            x["replica"] for x in rows
            if x["replica"] is not None).items())),
        "weight_dtype": quant["weight_dtype"],
        "kv_dtype": quant["kv_dtype"],
        "kv_bytes_total": sum(x["kv_bytes"] for x in rows),
    }
    return {"total": total, "requests": rows}


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.2f}"
    return str(v)


def render(summary: dict) -> str:
    """The human view: one aggregate block + one row per request."""
    t = summary["total"]
    lines = [
        "== serving trace summary ==",
        f"requests: {t['requests']}  terminals: {t['terminals']}",
        f"prefill chunks: {t['prefill_chunks']} "
        f"({t['fused_chunks']} fused)  prefilled tokens: "
        f"{t['prefilled_tokens']}  pad: {t['pad_tokens']} "
        f"(waste {t['pad_waste']:.1%})",
        f"cache-hit tokens: {t['cached_tokens']} "
        f"(hit rate {t['cache_hit_rate']:.1%})",
        f"engine steps: {t['engine_steps']} "
        f"({t['engine_step_ms_total']:.1f} ms total)",
        f"recovery: {t['requeued_events']} requeues, "
        f"{t['retried_events']} retries, "
        f"{t['failover_events']} failovers, "
        f"{t['restart_events']} restarts",
        f"replicas: {t['replicas'] or '-'}",
        f"quantization: weights {t['weight_dtype'] or '-'}, "
        f"kv {t['kv_dtype'] or '-'}  kv bytes admitted: "
        f"{t['kv_bytes_total']}",
        "",
    ]
    cols = ["trace_id", "terminal", "replica", "slot", "prompt_len",
            "generated", "queue_wait_ms", "ttft_ms", "decode_ms",
            "prefill_ms", "chunks", "fused_chunks", "cached_tokens",
            "pad_tokens", "requeues", "retries", "failovers", "kv_bytes"]
    rows = [[_fmt(r[c]) for c in cols] for r in summary["requests"]]
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome-trace JSON written by "
                                  "bench_serving.py --trace")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON object")
    a = ap.parse_args(argv)
    summary = summarize(load_events(a.trace))
    try:
        print(json.dumps(summary) if a.json else render(summary))
    except BrokenPipeError:
        pass                 # downstream (e.g. `| head`) closed early
    return 0


if __name__ == "__main__":
    sys.exit(main())
