#!/usr/bin/env python
"""Summarize a serving trace artifact (bench_serving.py --trace out.json).

Reads the Chrome-trace JSON exported by `serving.trace.TraceSink.
to_chrome_trace()` and answers, per request and in aggregate, the
questions the raw timeline is too granular for:

  * per-phase time breakdown — queue wait (enqueued→admitted), prefill
    (sum of prefill_chunk spans), decode (first_token→terminal), total;
  * pad waste — bucket-padding tokens vs real suffix tokens across
    every prefill chunk (the overhead the bucket ladder trades for
    zero recompiles);
  * cache-hit attribution — prompt tokens the prefix cache skipped,
    per request and total, next to the tokens actually prefilled;
  * scheduling mix — fused vs standalone prefill chunks, engine step
    span count/total;
  * quantization — the resolved weight/KV dtype config each request
    was prepared under, and the KV bytes its block footprint pins
    (per-block bytes off the prepared event, int8 scale overhead
    included);
  * recovery churn — the "requeued" phase: how often each request went
    back to the queue front (quarantine victims, rolled-back pending
    siblings) and how many backoff retries it consumed, so a
    fault-tolerance event cascade is visible instead of reading as
    unexplained repeat prefills;
  * replica attribution — which replica served each request (the
    `replica_id` the batcher stamps on `prepared` events, or the
    Router's `routed`/`failover` events in a merged multi-replica
    artifact), a per-replica request breakdown in the totals, and a
    `failovers` churn column so the cross-replica recovery path reads
    like the in-replica requeue one;
  * KV migration — disaggregated prefill→decode handoffs (`migrated`
    events: a per-request migrations count and handoff latency column,
    plus aggregate count/bytes and warm-vs-reprefill split), and
    slot-in-place quarantine restores (`restored` events) counted into
    the recovery totals next to requeues;
  * self-healing churn — supervisor `restarting`/`restarted` events
    (replica-scoped spans, no trace_id) counted into the recovery
    totals next to failovers, so a replica that died and was respawned
    is visible in the same summary as the requests it stranded;
  * device-time attribution — when a profiler capture window ran
    (`ServingEngine.capture_profile` / `POST /debug/profile`), the
    fenced `device.*` spans and per-chunk ``device_dur`` annotations
    land device-wall columns next to the host-wall ones
    (``device_ms`` per request, device step totals), so a TTFT
    regression is attributable to the kernel vs host scheduling;
    artifacts that predate the capture fields render "-" instead of
    crashing;
  * SLO breach windows (``--slo``) — `slo_breach` / `slo_recovered`
    spans from the engine's SLO tracker become per-objective breach
    windows, each listing the requests whose timelines rode it — the
    request-correlated view of "which users felt the burn".

Standard library only (no jax import): runs anywhere the JSON landed,
including the CI bench-smoke job where it ships as a non-blocking
artifact. `--json` prints the summary as one JSON object instead of
the text table.
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter, defaultdict

TERMINAL = {"finished", "cancelled", "failed", "timed_out"}


def load_events(path: str):
    """The artifact's non-metadata trace events, sorted by timestamp."""
    with open(path) as f:
        data = json.load(f)
    evs = [e for e in data.get("traceEvents", []) if e.get("ph") != "M"]
    evs.sort(key=lambda e: e.get("ts", 0.0))
    return evs


def summarize(events) -> dict:
    """Aggregate the per-request phase/pad/cache numbers (all times in
    milliseconds; `ts`/`dur` in the artifact are microseconds)."""
    per_req = defaultdict(lambda: {
        "enqueued_ts": None, "admitted_ts": None, "first_token_ts": None,
        "terminal_ts": None, "terminal": None, "prompt_len": None,
        "slot": None, "prefill_ms": 0.0, "chunks": 0, "fused_chunks": 0,
        "pad_tokens": 0, "real_tokens": 0, "cached_tokens": 0,
        "generated": 0, "requeues": 0, "retries": 0, "kv_bytes": 0,
        "replica": None, "failovers": 0, "device_ms": None,
        "migrations": 0, "handoff_ms": None, "restored": 0,
        "spec_steps": 0, "spec_accepted": 0, "spec_emitted": 0,
        "first_ts": None, "last_ts": None,
    })
    steps = {"count": 0, "total_ms": 0.0}
    # device-wall spans from a profiler capture window (device.decode /
    # device.fused / device.prefill on the device lane)
    dev_steps = {"count": 0, "total_ms": 0.0}
    quant = {"weight_dtype": None, "kv_dtype": None}
    # fast-path attribution stamped on every prepared event: the
    # resolved attention backend, spec score path and TP mesh degree —
    # so a mixed fleet's artifacts say which replicas ran the kernel
    fastpath = {"attention_impl": None, "spec_backend": None,
                "mesh_tp": None}
    # replica-scoped (not request-scoped) churn: supervisor restart
    # events ride the engine sinks' span lane with no trace_id
    restarts = {"restarting": 0, "restarted": 0}
    # KV migration spans (router handoffs, destination sink, no
    # trace_id — the per-request twin is counted into the rows below):
    # count + payload bytes + the warm/re-prefill split
    migration = {"count": 0, "bytes": 0, "kv_import": 0, "reprefill": 0}
    # speculative decoding: spec_draft spans are engine-scoped (one
    # per tick), spec_verify events are per-request with accepted
    # counts — the accepted-per-step column comes from the latter
    spec_draft_spans = 0
    spec_depth_hist: Counter = Counter()
    # SLO verdict transitions (engine-scoped spans, no trace_id):
    # paired breach→recovered edges become breach windows below
    slo_edges = []
    for e in events:
        name, args = e.get("name"), e.get("args", {})
        if name == "engine.step":
            steps["count"] += 1
            steps["total_ms"] += e.get("dur", 0.0) / 1e3
            continue
        if isinstance(name, str) and name.startswith("device."):
            dev_steps["count"] += 1
            dev_steps["total_ms"] += e.get("dur", 0.0) / 1e3
            continue
        if name in ("slo_breach", "slo_recovered"):
            slo_edges.append({
                "edge": name, "ts": e.get("ts", 0.0),
                "objective": args.get("objective"),
                "replica": args.get("replica_id"),
                "burn_rate_fast": args.get("burn_rate_fast"),
                "window_s": args.get("window_s"),
                "target": args.get("target")})
            continue
        if name in ("restarting", "restarted"):
            restarts[name] += 1
            continue
        if name == "migrated" and args.get("trace_id") is None:
            # the router's destination-sink span (the per-request
            # "migrated" event carries a trace_id and lands in the
            # rows; this aggregate-only twin must not double-count it)
            migration["count"] += 1
            migration["bytes"] += args.get("bytes", 0)
            via = args.get("via")
            if via in migration:
                migration[via] += 1
            continue
        if name == "spec_draft":
            spec_draft_spans += 1
            continue
        tid = args.get("trace_id")
        if tid is None:
            continue
        r = per_req[tid]
        ts = e.get("ts", 0.0)
        if r["first_ts"] is None or ts < r["first_ts"]:
            r["first_ts"] = ts
        if r["last_ts"] is None or ts > r["last_ts"]:
            r["last_ts"] = ts
        if name == "enqueued":
            r["enqueued_ts"] = ts
            r["prompt_len"] = args.get("prompt_len")
        elif name == "admitted":
            r["admitted_ts"] = ts
        elif name == "routed":
            # the Router's placement decision (replica + policy score)
            r["replica"] = args.get("replica", r["replica"])
        elif name == "failover":
            # cross-replica recovery: the request resumed elsewhere
            r["failovers"] += 1
            r["replica"] = args.get("to_replica", r["replica"])
        elif name == "migrated":
            # disaggregated handoff: prefill KV imported (or warm
            # re-prefilled) at the decode replica this event rode
            r["migrations"] += 1
            r["replica"] = args.get("to_replica", r["replica"])
            if args.get("handoff_s") is not None:
                r["handoff_ms"] = (r["handoff_ms"] or 0.0) \
                    + args["handoff_s"] * 1e3
        elif name == "restored":
            r["restored"] += 1
        elif name == "prepared":
            r["slot"] = args.get("slot")
            r["replica"] = args.get("replica_id", r["replica"])
            # quantized-serving bytes: the batcher stamps its resolved
            # dtype config + per-block bytes (scale overhead included)
            # on every prepared event, so the report can price each
            # request's KV residency without re-deriving model geometry
            r["kv_bytes"] = (args.get("blocks", 0)
                             * args.get("kv_block_bytes", 0))
            quant["weight_dtype"] = args.get("weight_dtype",
                                             quant["weight_dtype"])
            quant["kv_dtype"] = args.get("kv_dtype", quant["kv_dtype"])
            for fk in fastpath:
                fastpath[fk] = args.get(fk, fastpath[fk])
        elif name == "prefill_chunk":
            r["chunks"] += 1
            r["prefill_ms"] += e.get("dur", 0.0) / 1e3
            r["pad_tokens"] += args.get("pad", 0)
            r["real_tokens"] += args.get("end", 0) - args.get("start", 0)
            r["cached_tokens"] += args.get("cached_tokens", 0)
            if args.get("fused"):
                r["fused_chunks"] += 1
            # device wall rides only on chunks a capture window fenced
            # (device_dur is seconds; absent on older artifacts)
            if args.get("device_dur") is not None:
                r["device_ms"] = (r["device_ms"] or 0.0) \
                    + args["device_dur"] * 1e3
        elif name == "first_token":
            r["first_token_ts"] = ts
        elif name == "spec_verify":
            r["spec_steps"] += 1
            r["spec_accepted"] += args.get("accepted", 0)
            r["spec_emitted"] += args.get("emitted", 0)
            # per-(sweep, request) accepted-path-length distribution —
            # the tree-shape tuning signal (mirrors the engine's
            # spec_accept_depth Prometheus histogram)
            if args.get("accepted") is not None:
                spec_depth_hist[int(args["accepted"])] += 1
            # a capture window's fenced spec ticks carry device wall
            # exactly like fenced prefill chunks do
            if args.get("device_dur") is not None:
                r["device_ms"] = (r["device_ms"] or 0.0) \
                    + args["device_dur"] * 1e3
        elif name == "retired":
            r["generated"] = args.get("generated", 0)
        elif name == "requeued":
            r["requeues"] += 1
        elif name == "retried":
            r["retries"] += 1
        elif name in TERMINAL:
            r["terminal_ts"] = ts
            r["terminal"] = name

    rows = []
    for tid, r in per_req.items():
        def delta(a, b):
            return None if r[a] is None or r[b] is None \
                else (r[b] - r[a]) / 1e3
        rows.append({
            # an artifact exported mid-run carries requests with no
            # terminal event yet — report them as "live", don't crash
            "trace_id": tid, "terminal": r["terminal"] or "live",
            "replica": r["replica"], "failovers": r["failovers"],
            "slot": r["slot"], "prompt_len": r["prompt_len"],
            "generated": r["generated"],
            "queue_wait_ms": delta("enqueued_ts", "admitted_ts"),
            "ttft_ms": delta("enqueued_ts", "first_token_ts"),
            "decode_ms": delta("first_token_ts", "terminal_ts"),
            "total_ms": delta("enqueued_ts", "terminal_ts"),
            "prefill_ms": round(r["prefill_ms"], 3),
            "device_ms": (None if r["device_ms"] is None
                          else round(r["device_ms"], 3)),
            "first_ts": r["first_ts"], "last_ts": r["last_ts"],
            "chunks": r["chunks"], "fused_chunks": r["fused_chunks"],
            "cached_tokens": r["cached_tokens"],
            "prefilled_tokens": r["real_tokens"],
            "pad_tokens": r["pad_tokens"],
            "requeues": r["requeues"], "retries": r["retries"],
            "restored": r["restored"],
            "migrations": r["migrations"],
            "handoff_ms": (None if r["handoff_ms"] is None
                           else round(r["handoff_ms"], 3)),
            "kv_bytes": r["kv_bytes"],
            "spec_steps": r["spec_steps"],
            "spec_accepted": r["spec_accepted"],
            # accepted DRAFT tokens per verify sweep (the emitted
            # count adds the corrected token on top — the engine's
            # tokens_per_step); None when it never rode a spec tick
            "acc_per_step": (round(r["spec_accepted"] / r["spec_steps"],
                                   2) if r["spec_steps"] else None),
        })
    # (len, str) sorts t2 before t10 — ids are a prefix plus a
    # monotonic sequence number, so length order IS numeric order
    rows.sort(key=lambda x: (len(x["trace_id"]), x["trace_id"]))
    pad = sum(x["pad_tokens"] for x in rows)
    real = sum(x["prefilled_tokens"] for x in rows)
    cached = sum(x["cached_tokens"] for x in rows)
    total = {
        "requests": len(rows),
        "terminals": dict(sorted(
            Counter(x["terminal"] for x in rows).items())),
        "prefill_chunks": sum(x["chunks"] for x in rows),
        "fused_chunks": sum(x["fused_chunks"] for x in rows),
        "prefilled_tokens": real,
        "pad_tokens": pad,
        "pad_waste": round(pad / (pad + real), 4) if pad + real else 0.0,
        "cached_tokens": cached,
        "cache_hit_rate": round(cached / (cached + real), 4)
        if cached + real else 0.0,
        "engine_steps": steps["count"],
        "engine_step_ms_total": round(steps["total_ms"], 3),
        "device_steps": dev_steps["count"],
        "device_step_ms_total": round(dev_steps["total_ms"], 3),
        "device_ms_total": round(sum(x["device_ms"] or 0.0
                                     for x in rows), 3),
        "requeued_events": sum(x["requeues"] for x in rows),
        "retried_events": sum(x["retries"] for x in rows),
        "restored_events": sum(x["restored"] for x in rows),
        "failover_events": sum(x["failovers"] for x in rows),
        "restart_events": restarts["restarted"],
        "restarting_events": restarts["restarting"],
        "migration_events": migration["count"],
        "migration_bytes": migration["bytes"],
        "migrations_kv_import": migration["kv_import"],
        "migrations_reprefill": migration["reprefill"],
        "spec_draft_spans": spec_draft_spans,
        "spec_verify_steps": sum(x["spec_steps"] for x in rows),
        "spec_accepted_tokens": sum(x["spec_accepted"] for x in rows),
        # per (sweep, request): accepted DRAFT tokens, and total
        # tokens landed (accepted + the corrected one — comparable to
        # plain decode's 1.0); both 0.0 for a plain-decode artifact
        "accepted_per_step": round(
            sum(x["spec_accepted"] for x in rows)
            / max(1, sum(x["spec_steps"] for x in rows)), 4),
        "spec_tokens_per_step": round(
            sum(r["spec_emitted"] for r in per_req.values())
            / max(1, sum(x["spec_steps"] for x in rows)), 4),
        "spec_accept_depth_hist": {str(k): v for k, v in
                                   sorted(spec_depth_hist.items())},
        "replicas": dict(sorted(Counter(
            x["replica"] for x in rows
            if x["replica"] is not None).items())),
        "weight_dtype": quant["weight_dtype"],
        "kv_dtype": quant["kv_dtype"],
        "kv_bytes_total": sum(x["kv_bytes"] for x in rows),
        "attention_impl": fastpath["attention_impl"],
        "spec_backend": fastpath["spec_backend"],
        "mesh_tp": fastpath["mesh_tp"],
    }
    return {"total": total, "requests": rows,
            "slo": _breach_windows(slo_edges, rows)}


def _breach_windows(slo_edges, rows) -> dict:
    """Pair slo_breach → slo_recovered edges per (objective, replica)
    into breach windows, each listing the trace ids whose timelines
    overlap it (the requests that rode the breach). An edge set from
    an artifact that predates SLO tracking is simply empty."""
    edges = sorted(slo_edges, key=lambda e: e.get("ts", 0.0))
    open_w, windows = {}, []
    for e in edges:
        key = (e.get("objective"), e.get("replica"))
        if e["edge"] == "slo_breach":
            if key not in open_w:
                w = {"objective": e.get("objective"),
                     "replica": e.get("replica"),
                     "start_ms": round(e.get("ts", 0.0) / 1e3, 3),
                     "end_ms": None,        # None = still open at export
                     "burn_rate_fast": e.get("burn_rate_fast"),
                     # the verdict was computed over this trailing
                     # window — request attribution reaches back by it
                     "window_s": e.get("window_s"),
                     "target": e.get("target"), "requests": []}
                open_w[key] = w
                windows.append(w)
        else:
            w = open_w.pop(key, None)
            if w is not None:
                w["end_ms"] = round(e.get("ts", 0.0) / 1e3, 3)
    for w in windows:
        # reach back over the fast window that triggered the verdict:
        # the offending samples predate the breach event by up to it
        s_us = w["start_ms"] * 1e3 - (w.get("window_s") or 0.0) * 1e6
        e_us = None if w["end_ms"] is None else w["end_ms"] * 1e3
        for r in rows:
            a, b = r.get("first_ts"), r.get("last_ts")
            if a is None or b is None:
                continue
            if (e_us is None or a <= e_us) and b >= s_us:
                w["requests"].append(r["trace_id"])
    return {"breach_events": sum(1 for e in edges
                                 if e["edge"] == "slo_breach"),
            "recovered_events": sum(1 for e in edges
                                    if e["edge"] == "slo_recovered"),
            "breach_windows": windows}


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.2f}"
    return str(v)


def render(summary: dict, show_slo: bool = False) -> str:
    """The human view: one aggregate block + one row per request
    (plus, with `show_slo`, the breach-window section)."""
    t = summary["total"]
    lines = [
        "== serving trace summary ==",
        f"requests: {t['requests']}  terminals: {t['terminals']}",
        f"prefill chunks: {t['prefill_chunks']} "
        f"({t['fused_chunks']} fused)  prefilled tokens: "
        f"{t['prefilled_tokens']}  pad: {t['pad_tokens']} "
        f"(waste {t['pad_waste']:.1%})",
        f"cache-hit tokens: {t['cached_tokens']} "
        f"(hit rate {t['cache_hit_rate']:.1%})",
        f"engine steps: {t['engine_steps']} "
        f"({t['engine_step_ms_total']:.1f} ms total)  device steps: "
        f"{t.get('device_steps', 0)} "
        f"({t.get('device_step_ms_total', 0.0):.1f} ms device wall)",
        f"recovery: {t['requeued_events']} requeues, "
        f"{t['retried_events']} retries, "
        f"{t.get('restored_events', 0)} restored, "
        f"{t['failover_events']} failovers, "
        f"{t['restart_events']} restarts",
        f"migrations: {t.get('migration_events', 0)} "
        f"({t.get('migrations_kv_import', 0)} kv_import, "
        f"{t.get('migrations_reprefill', 0)} reprefill)  "
        f"bytes moved: {t.get('migration_bytes', 0)}",
        f"speculative: {t.get('spec_verify_steps', 0)} verify steps, "
        f"{t.get('spec_accepted_tokens', 0)} accepted "
        f"({t.get('accepted_per_step', 0.0)} accepted/step, "
        f"{t.get('spec_tokens_per_step', 0.0)} tokens/step)  "
        f"accept-depth hist: "
        + (" ".join(f"{k}:{v}" for k, v in sorted(
            t.get("spec_accept_depth_hist", {}).items(),
            key=lambda kv: int(kv[0]))) or "-"),
        f"replicas: {t['replicas'] or '-'}",
        f"quantization: weights {t['weight_dtype'] or '-'}, "
        f"kv {t['kv_dtype'] or '-'}  kv bytes admitted: "
        f"{t['kv_bytes_total']}",
        f"fast path: attention {t.get('attention_impl') or '-'}, "
        f"spec backend {t.get('spec_backend') or '-'}, "
        f"mesh tp {t.get('mesh_tp') or '-'}",
        "",
    ]
    cols = ["trace_id", "terminal", "replica", "slot", "prompt_len",
            "generated", "queue_wait_ms", "ttft_ms", "decode_ms",
            "prefill_ms", "device_ms", "chunks", "fused_chunks",
            "cached_tokens", "pad_tokens", "requeues", "retries",
            "failovers", "migrations", "handoff_ms",
            "acc_per_step", "kv_bytes"]
    # old artifacts may predate a column: .get keeps the report
    # rendering instead of KeyError-crashing on missing fields
    rows = [[_fmt(r.get(c)) for c in cols] for r in summary["requests"]]
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    if show_slo:
        slo = summary.get("slo") or {}
        wins = slo.get("breach_windows", [])
        lines += ["", "== SLO breach windows ==",
                  f"breaches: {slo.get('breach_events', 0)}  "
                  f"recoveries: {slo.get('recovered_events', 0)}"]
        if not wins:
            lines.append("no breach windows in this artifact")
        for w in wins:
            end = "open" if w["end_ms"] is None else f"{w['end_ms']:.1f}"
            lines.append(
                f"[{w['start_ms']:.1f} ms → {end}] "
                f"{w['objective']} on {w['replica'] or '-'} "
                f"(burn {w['burn_rate_fast']}, target {w['target']}) — "
                f"{len(w['requests'])} requests rode it: "
                f"{', '.join(w['requests']) or '-'}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome-trace JSON written by "
                                  "bench_serving.py --trace")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON object")
    ap.add_argument("--slo", action="store_true",
                    help="append the SLO section: breach windows "
                         "(slo_breach → slo_recovered spans) and the "
                         "requests whose timelines rode each one")
    a = ap.parse_args(argv)
    summary = summarize(load_events(a.trace))
    try:
        print(json.dumps(summary) if a.json
              else render(summary, show_slo=a.slo))
    except BrokenPipeError:
        pass                 # downstream (e.g. `| head`) closed early
    return 0


if __name__ == "__main__":
    sys.exit(main())
