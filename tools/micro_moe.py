"""Microbenchmarks for the MoE-step hot spots (gathers, 8-bit Adam).

Usage: python tools/micro_moe.py [gather|opt]
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _force(out):
    # host sync: the axon remote queue does not drain on block_until_ready
    leaves = jax.tree.leaves(out)
    float(jnp.sum(leaves[0].astype(jnp.float32)))


def timeit(f, *args, n=10):
    out = f(*args)
    _force(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = f(*args)
    _force(out)
    return (time.perf_counter() - t0) / n


import jax
import jax.numpy as jnp


def bench_gather():
    from paddle_tpu.kernels.moe_dispatch import (_gather_rows_jnp,
                                                 gather_rows_pallas)
    rng = np.random.default_rng(0)
    # bench shapes: dispatch direction [1, 81920, D] -> [1, 102400, D]
    # (~20% of idx invalid), combine direction the reverse
    for (N, M, frac_valid) in [(81920, 102400, 0.8), (102400, 81920, 1.0)]:
        src = jnp.asarray(rng.normal(size=(1, N, 2048)), jnp.bfloat16)
        idx = rng.integers(0, N, (1, M)).astype(np.int32)
        drop = rng.random((1, M)) > frac_valid
        idx[drop] = -1
        idx_sorted = np.sort(idx, axis=1)  # monotone variant
        idx = jnp.asarray(idx)
        idxs = jnp.asarray(idx_sorted)
        gb = (M * frac_valid + M) * 2048 * 2 / 1e9  # read + write
        jnp_f = jax.jit(_gather_rows_jnp)
        t = timeit(jnp_f, src, idx)
        print(f"N={N} M={M}: jnp gather       {t*1e3:7.2f} ms  {gb/t:6.1f} GB/s")
        for bm in (128, 256):
            pal = jax.jit(lambda s, i, bm=bm: gather_rows_pallas(s, i, bm=bm))
            t = timeit(pal, src, idx)
            print(f"N={N} M={M}: pallas bm={bm:4d}  {t*1e3:7.2f} ms  {gb/t:6.1f} GB/s")
        t = timeit(pal, src, idxs)
        print(f"N={N} M={M}: pallas bm=256 SORTED idx {t*1e3:7.2f} ms  {gb/t:6.1f} GB/s")


def bench_opt():
    from paddle_tpu.nlp import moe, train
    cfg = moe.MoeConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        moe_intermediate_size=1024, num_experts=16, num_experts_per_tok=2,
        num_shared_experts=1, num_hidden_layers=12, num_attention_heads=16,
        num_key_value_heads=8, max_position_embeddings=2048,
        param_dtype=jnp.bfloat16)
    tx = train.make_optimizer(1e-4, state_quant="8bit", grad_clip=1.0)
    params = moe.init_params(jax.random.key(0), cfg)
    opt_state = tx.init(params)
    grads = jax.tree.map(lambda p: (p * 1e-3).astype(p.dtype), params)

    @jax.jit
    def upd(grads, opt_state, params):
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax
        return optax.apply_updates(params, updates), opt_state

    t = timeit(upd, grads, opt_state, params, n=5)
    nparams = sum(x.size for x in jax.tree.leaves(params))
    # traffic: params r+w (2B), grads r (2B), moments r+w (2x1B+scales)
    gb = nparams * (2 * 2 + 2 + 2 * 2 * 1) / 1e9
    print(f"8bit adam update: {t*1e3:.1f} ms for {nparams/1e9:.2f}B params "
          f"(~{gb:.1f} GB traffic -> {gb/t:.0f} GB/s)")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "gather"
    {"gather": bench_gather, "opt": bench_opt}[which]()
