"""One-off experiments for the gather-kernel redesign (not a test).

Variants:
  base     — current shipped kernel (per-row sems, per-row conditional)
  nocond   — always-DMA clipped index + mask multiply, per-row sems
  agg      — nocond + ONE shared DMA sem per buffer, aggregate wait
             (discovers the semaphore unit: count vs bytes)
"""
import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel_nocond(idx_ref, src_ref, out_ref, scratch, sems, *, bm):
    b = pl.program_id(0)
    mb = pl.program_id(1)
    nmb = pl.num_programs(1)

    def start_block(mb_, buf):
        for r in range(bm):
            i = jnp.maximum(idx_ref[b, mb_ * bm + r], 0)
            pltpu.make_async_copy(src_ref.at[b, i], scratch.at[buf, r],
                                  sems.at[buf, r]).start()

    @pl.when(mb == 0)
    def _prologue():
        start_block(0, 0)

    @pl.when(mb + 1 < nmb)
    def _next():
        start_block(mb + 1, (mb + 1) % 2)

    for r in range(bm):
        i = jnp.maximum(idx_ref[b, mb * bm + r], 0)
        pltpu.make_async_copy(src_ref.at[b, i], scratch.at[mb % 2, r],
                              sems.at[mb % 2, r]).wait()
    out_ref[0] = scratch[mb % 2].reshape(out_ref.shape[1:])


def _kernel_agg(idx_ref, src_ref, out_ref, scratch, sems, *, bm, unit):
    b = pl.program_id(0)
    mb = pl.program_id(1)
    nmb = pl.num_programs(1)

    def start_block(mb_, buf):
        for r in range(bm):
            i = jnp.maximum(idx_ref[b, mb_ * bm + r], 0)
            pltpu.make_async_copy(src_ref.at[b, i], scratch.at[buf, r],
                                  sems.at[buf]).start()

    @pl.when(mb == 0)
    def _prologue():
        start_block(0, 0)

    @pl.when(mb + 1 < nmb)
    def _next():
        start_block(mb + 1, (mb + 1) % 2)

    # one aggregate wait: DMA sems count bytes; a wait descriptor sized
    # as the WHOLE buffer consumes all bm row-copy completions at once
    pltpu.make_async_copy(scratch.at[mb % 2], scratch.at[mb % 2],
                          sems.at[mb % 2]).wait()
    out_ref[0] = scratch[mb % 2].reshape(out_ref.shape[1:])


def build(variant, B, N, M, D, bm, unit=1):
    lanes = 128
    if variant == "nocond":
        kern = functools.partial(_kernel_nocond, bm=bm)
        sems = pltpu.SemaphoreType.DMA((2, bm))
    else:
        kern = functools.partial(_kernel_agg, bm=bm, unit=unit)
        sems = pltpu.SemaphoreType.DMA((2,))

    @jax.jit
    def f(src, idx, mask):
        src4 = src.reshape(B, N, D // lanes, lanes)
        with jax.enable_x64(False):
            out = pl.pallas_call(
                kern,
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=1,
                    grid=(B, M // bm),
                    in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                    out_specs=pl.BlockSpec((1, bm, D), lambda b, m, idx: (b, m, 0)),
                    scratch_shapes=[
                        pltpu.VMEM((2, bm, D // lanes, lanes), src.dtype), sems],
                ),
                out_shape=jax.ShapeDtypeStruct((B, M, D), src.dtype),
            )(idx, src4)
        return out * mask[..., None]

    return f


def main():
    from paddle_tpu.kernels.moe_dispatch import (gather_rows_pallas,
                                                 _gather_rows_jnp)
    from devloop import loop_time
    rng = np.random.default_rng(0)
    B, N, M, D = 1, 81920, 102400, 2048
    src = jnp.asarray(rng.normal(size=(B, N, D)), jnp.bfloat16)
    idx_np = rng.integers(0, N, (B, M)).astype(np.int32)
    idx_np[rng.random((B, M)) > 0.8] = -1
    idx = jnp.asarray(idx_np)
    mask = (idx >= 0).astype(jnp.bfloat16)
    gb = (0.8 * M + M) * D * 2 / 1e9

    ref = np.where(idx_np[..., None] >= 0,
                   np.asarray(src)[0][np.clip(idx_np, 0, None)[0]][None], 0)

    t = loop_time(lambda s, i: gather_rows_pallas(s, i, bm=128), (src, idx),
                  roll_arg=1)
    print(f"base bm=128             {t*1e3:7.2f} ms  {gb/t:6.1f} GB/s")
    t = loop_time(_gather_rows_jnp, (src, idx), roll_arg=1)
    print(f"jnp                     {t*1e3:7.2f} ms  {gb/t:6.1f} GB/s")

    for bm in (64, 128):
        f = build("nocond", B, N, M, D, bm)
        out = f(src, idx, mask)
        ok = np.allclose(np.asarray(out), ref)
        t = loop_time(f, (src, idx, mask), roll_arg=1)
        print(f"nocond bm={bm:4d}          {t*1e3:7.2f} ms  {gb/t:6.1f} GB/s  ok={ok}")


if __name__ == "__main__":
    main()
