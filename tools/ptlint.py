#!/usr/bin/env python3
"""ptlint — paddle_tpu static analysis, without importing the framework.

`python -m paddle_tpu.analysis` works but executes paddle_tpu/__init__
(jax import, device init — tens of seconds). This wrapper loads the
analysis package standalone via importlib so CI and pre-push hooks get
sub-second lints. Same flags, same exit codes:

    python tools/ptlint.py                     # check paddle_tpu/
    python tools/ptlint.py --format json       # CI
    python tools/ptlint.py --update-baseline   # burn down the ratchet
    python tools/ptlint.py --changed-only      # pre-commit: only the
                                               # files git sees as
                                               # changed can report
    python tools/ptlint.py --fail-dead-roots   # gate: no HOT_ROOTS
                                               # pattern may match zero
                                               # functions
"""
from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO_ROOT, "paddle_tpu", "analysis")


def _load_analysis_standalone():
    """Import paddle_tpu.analysis WITHOUT running paddle_tpu/__init__.

    A stub parent package with the right __path__ lets the analysis
    package's relative imports resolve while the heavy framework
    __init__ never executes. If paddle_tpu is already fully imported
    (e.g. inside pytest), just use it."""
    if "paddle_tpu" in sys.modules:
        import paddle_tpu.analysis
        return paddle_tpu.analysis
    parent = importlib.util.module_from_spec(
        importlib.machinery.ModuleSpec(
            "paddle_tpu", None, is_package=True))
    parent.__path__ = [os.path.join(REPO_ROOT, "paddle_tpu")]
    sys.modules["paddle_tpu"] = parent
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu.analysis", os.path.join(PKG_DIR, "__init__.py"),
        submodule_search_locations=[PKG_DIR])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    analysis = _load_analysis_standalone()
    argv = sys.argv[1:]
    # default the root to the repo so fingerprints match the committed
    # baseline no matter where the hook runs from
    if "--root" not in argv:
        argv = ["--root", REPO_ROOT] + argv
    return analysis.main(argv)


if __name__ == "__main__":
    sys.exit(main())
