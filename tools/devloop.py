"""Device-side loop timing harness for TPU microbenchmarks.

The axon tunnel adds ~10 ms dispatch overhead per host->device call, which
swamps ms-scale kernels when timed with a host loop. loop_time() runs N
iterations inside ONE jit (fori_loop with a rolled-index data dependency so
XLA cannot hoist the loop-invariant kernel call) and returns seconds/iter.
"""
import time

import jax
import jax.numpy as jnp


def loop_time(f, args, n=20, roll_arg=None, reps=3):
    """f(*args) -> array. roll_arg: index of an int array arg to roll by i
    each iteration (defeats loop-invariant hoisting); None rolls arg 0."""
    ra = 0 if roll_arg is None else roll_arg

    @jax.jit
    def run(*args):
        def body(i, acc):
            a = list(args)
            a[ra] = jnp.roll(a[ra], i, axis=-1)
            out = f(*a)
            first = jax.tree.leaves(out)[0]
            return acc + first.reshape(-1)[:8].astype(jnp.float32).sum()
        return jax.lax.fori_loop(0, n, body, jnp.zeros((), jnp.float32))

    best = float("inf")
    for _ in range(reps):
        acc = run(*args)
        float(acc)           # host sync (block_until_ready lies via axon)
        t0 = time.perf_counter()
        acc = run(*args)
        float(acc)
        best = min(best, (time.perf_counter() - t0) / n)
    return best
