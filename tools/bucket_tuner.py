"""Pad-aware prefill bucket-ladder tuner.

`bench_serving.py` emits the accounting a workload-specific ladder is
fitted from: `prefill_suffix_hist` (real pre-padding chunk length ->
count), `prefill_buckets` (the ladder that served the run),
`prefill_pad_tokens` and `prefill_compile_count`. The default
power-of-two ladder is workload-agnostic — chat-like traffic whose
prompts cluster under 64 tokens pays pad tokens a denser sub-64 ladder
would not — so this tool fits the ladder that MINIMIZES total pad
tokens over the observed length distribution, subject to a bucket-count
budget (every extra bucket is another compiled shape per group size and
phase, i.e. warmup time and executable cache).

Exact fit, not a heuristic: with lengths sorted, an optimal ladder's
buckets sit ON observed lengths (any bucket between two observed
lengths can be lowered to the smaller one without adding pad), so a
classic O(n^2 * k) interval DP over the (length, count) histogram finds
the minimum-pad ladder with at most k buckets.

Usage:
    python bench_serving.py --bucketed > bench.json
    python tools/bucket_tuner.py bench.json [--max-buckets 4]
    python tools/bucket_tuner.py bench.json --json   # machine-readable

Prints the recommended ladder as a `prefill_buckets=(...)` /
`--prefill-buckets` setting plus the projected pad-token saving vs the
ladder the bench actually ran (re-costed over the same histogram).
Standalone stdlib tool — no jax import, safe anywhere ptlint runs.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple


def pad_cost(hist: Dict[int, int], ladder: List[int]) -> int:
    """Total pad tokens when every observed chunk length pads up to the
    smallest ladder bucket that fits it (the batcher's `_bucket_for`
    rule; a length above the top bucket would have been chunked, so the
    histogram never contains one)."""
    total = 0
    ladder = sorted(ladder)
    for length, count in hist.items():
        bucket = next((b for b in ladder if b >= length), length)
        total += (bucket - length) * count
    return total


def fit_ladder(hist: Dict[int, int], k: int) -> Tuple[List[int], int]:
    """Minimum-pad ladder with at most `k` buckets over the observed
    (length -> count) histogram: interval DP where cost(i, j) is the pad
    paid when lengths[i..j] all share bucket lengths[j]."""
    lengths = sorted(hist)
    n = len(lengths)
    if n == 0:
        return [], 0
    k = max(1, min(k, n))
    counts = [hist[L] for L in lengths]
    # prefix sums for O(1) interval cost:
    #   cost(i, j) = L[j] * sum(c[i..j]) - sum(c*L)[i..j]
    pc = [0] * (n + 1)
    pcl = [0] * (n + 1)
    for t, (L, c) in enumerate(zip(lengths, counts)):
        pc[t + 1] = pc[t] + c
        pcl[t + 1] = pcl[t] + c * L

    def cost(i: int, j: int) -> int:
        return lengths[j] * (pc[j + 1] - pc[i]) - (pcl[j + 1] - pcl[i])

    INF = float("inf")
    # f[j][m]: min pad covering lengths[0..j] with exactly m buckets,
    # the m-th bucket at lengths[j]; arg for reconstruction
    f = [[INF] * (k + 1) for _ in range(n)]
    arg = [[-1] * (k + 1) for _ in range(n)]
    for j in range(n):
        f[j][1] = cost(0, j)
        for m in range(2, k + 1):
            for i in range(1, j + 1):
                if f[i - 1][m - 1] is INF:
                    continue
                c = f[i - 1][m - 1] + cost(i, j)
                if c < f[j][m]:
                    f[j][m] = c
                    arg[j][m] = i - 1
    best_m = min(range(1, k + 1), key=lambda m: f[n - 1][m])
    ladder, j, m = [], n - 1, best_m
    while j >= 0 and m >= 1:
        ladder.append(lengths[j])
        j, m = arg[j][m], m - 1
    return sorted(ladder), int(f[n - 1][best_m])


def tune(bench: Dict, max_buckets: int = 0) -> Dict:
    """Fit a ladder from one bench JSON record. max_buckets 0 keeps the
    observed ladder's bucket count (same compile budget, less pad)."""
    raw = bench.get("prefill_suffix_hist") or {}
    hist = {int(k): int(v) for k, v in raw.items()}
    observed = [int(b) for b in bench.get("prefill_buckets", [])]
    if not hist:
        raise SystemExit(
            "bench record has no prefill_suffix_hist — rerun "
            "bench_serving.py from this tree")
    k = max_buckets or (len(observed) or 4)
    ladder, best = fit_ladder(hist, k)
    current = pad_cost(hist, observed) if observed else None
    out = {
        "observed_ladder": observed,
        "recommended_ladder": ladder,
        "max_buckets": k,
        "chunk_lengths_seen": len(hist),
        "chunks_observed": sum(hist.values()),
        "pad_tokens_current_ladder": current,
        "pad_tokens_recommended": best,
    }
    if current:
        out["pad_reduction"] = round(1.0 - best / current, 4)
    # price the padding in KV-gather bytes under the run's kv_dtype:
    # kv_bytes_per_token (emitted by bench_serving from quantization.
    # kv.kv_block_bytes) already includes the int8 scale-pool overhead,
    # so an int8-KV run's pad bytes are ~half an fp run's for the same
    # ladder — the tuner's recommendation stays token-driven (the DP is
    # dtype-invariant), but the byte stakes it reports reflect what the
    # attention gather actually moves.
    bpt = bench.get("kv_bytes_per_token")
    if bpt:
        out["kv_dtype"] = bench.get("kv_dtype", "fp")
        out["kv_bytes_per_token"] = bpt
        if current is not None:
            out["pad_kv_bytes_current_ladder"] = int(current * bpt)
        out["pad_kv_bytes_recommended"] = int(best * bpt)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bench", nargs="?", default="-",
                    help="bench_serving.py JSON line (file or '-')")
    ap.add_argument("--max-buckets", type=int, default=0,
                    help="bucket-count budget (0 = match the observed "
                         "ladder: same compile cost, less pad)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line instead of the report")
    a = ap.parse_args(argv)
    text = (sys.stdin.read() if a.bench == "-"
            else open(a.bench).read())
    # tolerate a log with one JSON object per line: last record wins
    rec = None
    for line in text.strip().splitlines():
        line = line.strip()
        if line.startswith("{"):
            rec = json.loads(line)
    if rec is None:
        raise SystemExit(f"no JSON record found in {a.bench!r}")
    r = tune(rec, a.max_buckets)
    if a.json:
        print(json.dumps(r))
        return 0
    print(f"observed ladder : {tuple(r['observed_ladder'])} "
          f"-> {r['pad_tokens_current_ladder']} pad tokens over "
          f"{r['chunks_observed']} prefill chunks")
    print(f"recommended     : {tuple(r['recommended_ladder'])} "
          f"-> {r['pad_tokens_recommended']} pad tokens "
          f"({r.get('pad_reduction', 0) * 100:.1f}% less padding, "
          f"same <= {r['max_buckets']}-bucket compile budget)")
    if "kv_bytes_per_token" in r:
        cur = r.get("pad_kv_bytes_current_ladder")
        print(f"pad gather cost : {cur if cur is not None else '-'} -> "
              f"{r['pad_kv_bytes_recommended']} KV bytes at "
              f"{r['kv_bytes_per_token']:.0f} B/token "
              f"(kv_dtype={r['kv_dtype']}, scale overhead included)")
    print("apply with      : ContinuousBatcher(..., prefill_buckets="
          f"{tuple(r['recommended_ladder'])}) or the ServingEngine "
          "kwarg of the same name")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
