"""Serving benchmark: offline throughput + latency percentiles through
the ServingEngine on the CPU backend.

Prints ONE JSON line (bench.py convention, landed alongside the
BENCH_*.json records): generated tokens/s end-to-end through the full
admission→batcher→channel path, plus TTFT and queue-wait percentiles —
the serving-layer numbers the device-side decode benches in bench.py
cannot see (queueing, scheduling, host fan-out overhead).

Deliberately a tiny model on CPU: this measures the HOST serving layer's
overhead and scheduling behavior deterministically; device-side decode
throughput is bench.py's `decode_tok_s`.
"""
from __future__ import annotations

import json
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main(n_requests: int = 16, max_new: int = 8, max_batch: int = 4,
         block_size: int = 8, chunk: int = 4) -> dict:
    import jax
    from paddle_tpu.nlp import llama
    from paddle_tpu import serving

    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    prompts = [list(map(int, rng.randint(1, 200, int(L))))
               for L in rng.randint(4, 16, n_requests)]

    eng = serving.ServingEngine(
        params, cfg, max_batch=max_batch, block_size=block_size,
        max_total_len=64, max_new_tokens=max_new, chunk=chunk,
        max_queue_depth=n_requests, start=False)
    # warmup: compile the chunk fn + prefill shapes outside the timing
    eng.start()
    eng.generate(prompts[0], timeout=600)
    completed0 = eng.metrics.counter("requests_completed").value

    t0 = time.perf_counter()
    reqs = [eng.submit(p) for p in prompts]
    if not eng.drain(timeout=600):
        raise RuntimeError("drain timed out — benchmark invalid")
    wall = time.perf_counter() - t0
    eng.shutdown()

    toks = sum(len(r.result()) for r in reqs)
    ttft = np.asarray([r.first_token_time - r.submit_time for r in reqs])
    wait = np.asarray([r.admit_time - r.submit_time for r in reqs])
    snap = eng.snapshot()
    pct = lambda a, q: round(float(np.percentile(a, q)), 4)
    result = {
        "metric": "serving_offline_tok_s",
        "value": round(toks / wall, 1),
        "unit": "tokens/s",
        "n_requests": n_requests,
        "max_batch": max_batch,
        "max_new_tokens": max_new,
        "wall_s": round(wall, 3),
        "ttft_s_p50": pct(ttft, 50),
        "ttft_s_p90": pct(ttft, 90),
        "ttft_s_p99": pct(ttft, 99),
        "queue_wait_s_p50": pct(wait, 50),
        "queue_wait_s_p90": pct(wait, 90),
        "queue_wait_s_p99": pct(wait, 99),
        "step_s_p50": snap["histograms"]["serving.step_s"].get("p50"),
        "per_token_s_p50": snap["histograms"]["per_token_s"].get("p50"),
        "requests_completed": snap["counters"]["requests_completed"]
        - completed0,
        "kv_high_water_blocks": snap["allocator"]["high_water_blocks"],
        "kv_reused_blocks": snap["allocator"]["reused_blocks"],
    }
    return result


if __name__ == "__main__":
    print(json.dumps(main()))
