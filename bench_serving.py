"""Serving benchmark: offline throughput + latency percentiles through
the ServingEngine on the CPU backend.

Prints ONE JSON line (bench.py convention, landed alongside the
BENCH_*.json records): generated tokens/s end-to-end through the full
admission→batcher→channel path, plus TTFT, queue-wait and inter-token
latency percentiles — the serving-layer numbers the device-side decode
benches in bench.py cannot see (queueing, scheduling, host fan-out
overhead).

Workloads:
  * `random` (default) — independent prompts of random lengths, the
    original scheduling/overhead bench;
  * `prefix-share` (`--prefix-share`) — N requests sharing one common
    prompt prefix (the system-prompt / few-shot pattern), exercising the
    `serving.cache` prefix cache: the JSON line gains
    `prefix_cache_hit_rate` and `prefill_tokens_saved`;
  * `mixed` (`--bucketed`) — prompt lengths spread wide enough to span
    every prefill bucket AND chunk past the largest one, exercising the
    bucketed/chunked prefill path. Asserts ZERO prefill recompiles after
    warmup (the TTFT story: admission dispatches to pre-compiled
    shapes), so a recompile regression fails the bench;
  * `fused` (`--fused`) — the mixed admission-during-decode workload run
    TWICE, fusion on then off: admissions land while other slots decode
    (n_requests >> max_batch), so the unfused run pays a standalone
    prefill stall per admission and the fused run piggybacks the same
    chunk on the decode call. Asserts `decode_stall_steps` strictly
    below the unfused baseline AND zero prefill recompiles after warmup
    — both shape/schedule accounting, deterministic on CPU. The JSON
    line carries `decode_stall_steps` / `fused_steps` / `itl_ms_p99`
    for the fused run and the `*_unfused` baselines next to them.

Warmup pre-compiles EVERY prefill shape via `engine.warmup()` (AOT
lowering — no device compute): the standalone ladder AND, with fusion
on, the fused decode+prefill variants; plus one served request for the
decode chunk fn. Before it, the first timed request of each new prompt
length ate a fresh XLA trace+compile and TTFT p99 measured the
compiler, not the server.

Observability: `--trace out.json` writes the run's per-request trace
timelines (serving.trace.TraceSink) as Chrome-trace/Perfetto JSON —
slot lanes show prefill chunks with bucket/pad/cached-token/fused
annotations next to the engine step spans; `tools/trace_report.py`
summarizes the artifact. `--trace-overhead` runs one DISCARDED leg to
burn process-wide warm-up (jax platform init, compilation cache),
then an ABBA sequence — untraced, traced, traced, untraced — so each
side runs once early and once late and first-order warm-state drift
cancels from the pooled tok/s; it HARD-FAILS unless pooled traced
tok/s holds >= 0.97x pooled untraced with zero post-warmup recompiles
across all four legs: the gate that keeps tracing always-on-cheap.

Quantized (`--quantized`): the quantized-serving gate. The mixed
workload runs through FOUR engine configurations — fp, w8 weights,
int8 paged KV, and w8+int8-KV — each a full lifecycle of AOT warmup, a
cold round, and a warm round of the SAME prompts (prefix-cache hits
re-read the quantized pool the cold round committed). HARD-FAILS on
any post-warmup recompile (the (weight_dtype, kv_dtype) memo keys must
stay on the warmed ladder), any warm-vs-cold token mismatch, int8 KV
gather bytes above 0.55x the fp pool's per-token bytes (scale-pool
overhead included), or quantized-vs-fp greedy divergence below the
documented floor. The JSON line carries decode_tok_s_{fp,w8,int8kv,
w8kv8}, kv_pool_bytes, kv_bytes_per_token_{fp,int8}, kv_gather_ratio
and the per-leg token-match rates.

Chaos (`--chaos`): the fault-isolation gate. The staggered-budget
admission-during-decode workload runs TWICE — fault-free (the token
baseline) and with a seeded `serving.faults.FaultInjector` arming a
persistent fail-on-rid fault against one request the moment it streams
its first token (mid-stream poison landing in a fused batch). The leg
HARD-FAILS unless the engine's quarantine isolates the blast radius:
the culprit alone reaches FAILED with its streamed tokens a prefix of
its baseline (nothing re-emitted or lost), every innocent completes
with BIT-identical tokens to the fault-free run, post-warmup
recompiles stay 0 (quarantine probes and victim re-prefills stay on
the warmed ladder), and the allocator drains clean. The JSON line
carries quarantines / requests_requeued / culprit_tokens_streamed and
the engine `health()` snapshot.

Router (`--router`): the multi-replica failover gate, e2e over HTTP.
The mixed workload first runs through ONE engine (the token
reference), then through 2 `ServingEngine` replicas behind
`serving.Router` + `serving.HttpFrontend` as concurrent SSE streams
over a real socket. When the longest-budget request (the victim)
streams its first token, a seeded chaos hang poisons its serving
replica's next device calls: the hung-step watchdog flips that
replica UNHEALTHY and the router must fail its stranded/queued
requests over to the survivor, resuming each from `prompt + tokens`.
HARD-FAILS unless the victim completes on the OTHER replica with its
pre-failover stream a strict prefix of the final one, EVERY request's
streamed tokens are bit-identical to the single-engine reference
(innocents included), post-warmup recompiles stay 0 on both replicas,
and the survivor's pool drains clean. The JSON line carries
router_failovers / router_victim_tokens_kept /
router_recompiles_after_warmup / router_serving_replicas.

Restart (`--restart`): the self-healing gate. Same chaos shape as
`--router` — a seeded hang kills the victim's serving replica
mid-stream and every stranded SSE stream must fail over with the
strict-prefix invariant — but the Router runs `auto_restart=True`:
the leg then HARD-FAILS unless the dead slot is respawned through the
supervisor's readiness gate (teardown → rebuild → AOT warmup →
synthetic probe), rejoins rotation, serves a post-restart request,
and recompiles stay 0 on every engine incarnation with the crash-loop
breaker shut.

TP (`--tp`): the tensor-parallel gate, under 4 forced host devices
(`--xla_force_host_platform_device_count=4`, appended to XLA_FLAGS at
module import when the flag is on argv — before jax binds a backend).
The mixed workload runs through a single-device reference engine,
then through a `mesh=MeshConfig(tp=4)` engine whose weights are
Megatron-sharded and whose paged-KV pool is sharded on the head axis
(serving.tp). HARD-FAILS unless the TP output is bit-identical to
single-device, post-warmup recompiles stay 0 on both engines (the
mesh key rides every compiled-shape memo), and a TP=2-sharded
replica pair survives the `--restart` chaos shape — hang → failover
→ supervisor respawn of the SHARDED slot through its readiness gate
→ rejoin → serve — under the same bit-identity and zero-recompile
bars. The JSON line carries tp_mesh / tp_kv_pool_bytes_per_device /
tp_recompiles_after_warmup plus the restart_* fields.

Load (`--load`): the closed-loop load generator (ROADMAP direction-3
follow-on): Poisson session arrivals, multi-turn sessions (each turn
extends the previous prompt + generated tokens — the prefix-cache
steady state), shared-system-prompt populations. Emits goodput
(tokens of requests completed within `--deadline-s`, per wall second)
and request-latency p50/p99 under load as tracked JSON fields.
`--load --router` runs the same generator through a 2-replica Router
(the "load-leg router mode" follow-on): multi-replica
`goodput_tok_s` / `latency_s_p99_load` plus per-replica routing
counts land in the JSON line.

`--attention-impl {auto,xla,pallas}` selects the paged-attention
backend (nlp/ragged_attention.py); the JSON line records the RESOLVED
impl plus `decode_tok_s` — generated tokens over time spent inside
batcher.step(), the number the attention backend actually moves. On
CPU pallas runs in Pallas interpret mode: a correctness/parity
configuration, not a speed one (the kernel's win is HBM traffic on
TPU). `--fused-units N` lets one fused step carry up to N pending
prefill units (admission bursts drain faster under sustained decode).

Deliberately a tiny model on CPU: this measures the HOST serving layer's
overhead and scheduling behavior deterministically; device-side decode
throughput is bench.py's `decode_tok_s`.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--tp" in sys.argv:
    # the tensor-parallel gate needs a 4-device mesh on a CPU host;
    # forcing host devices only works BEFORE jax binds its backend, so
    # this must happen at module import — every jax import in this
    # file is lazy behind it
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4")

import numpy as np


def _make_prompts(rng, n_requests: int, workload: str,
                  prefix_len: int, suffix_len: int):
    if workload in ("prefix-share", "speculative"):
        # the speculative gate runs the shared-prefix population too:
        # the accept-rate story is the steady-state serving shape
        # (system prompt + short user turns), and the prefix cache
        # must stay warm==cold under spec commits
        common = list(map(int, rng.randint(1, 200, prefix_len)))
        return [common + list(map(int, rng.randint(1, 200, suffix_len)))
                for _ in range(n_requests)]
    if workload in ("mixed", "fused", "chaos", "quantized", "router",
                    "restart", "slo", "disagg", "tp"):
        # lengths spanning the whole ladder, incl. past the largest
        # bucket (chunked prefill) — every request a different length
        return [list(map(int, rng.randint(1, 200, int(L))))
                for L in rng.randint(3, 41, n_requests)]
    return [list(map(int, rng.randint(1, 200, int(L))))
            for L in rng.randint(4, 16, n_requests)]


def _serve(params, cfg, prompts, *, max_new: int, max_batch: int,
           block_size: int, chunk: int, prefix_cache: bool,
           max_prefill_bucket: int, fused_prefill: bool,
           attention_impl: str = "auto", fused_units: int = 1,
           budgets=None, trace: bool = True,
           profile_sample_every: int = 0,
           speculative: bool = False, spec_k: int = 4,
           draft_layers=None, spec_tree=None,
           spec_draft_w8: bool = False, spec_attention_impl=None,
           mesh=None) -> dict:
    """One engine lifecycle over `prompts`: warmup (AOT ladder + one
    served request), timed serve, drain. Returns the raw numbers the
    workload-specific JSON assembly picks from. `profile_sample_every`
    defaults OFF here (unlike the engine's 64) so every non-SLO leg's
    numbers stay fence-free; the --slo leg passes it explicitly."""
    from paddle_tpu import serving

    eng = serving.ServingEngine(
        params, cfg, max_batch=max_batch, block_size=block_size,
        max_total_len=64, max_new_tokens=max_new, chunk=chunk,
        max_queue_depth=len(prompts), prefix_cache=prefix_cache,
        max_prefill_bucket=max_prefill_bucket,
        fused_prefill=fused_prefill, fused_units=fused_units,
        attention_impl=attention_impl, trace=trace,
        profile_sample_every=profile_sample_every,
        speculative=speculative, spec_k=spec_k,
        draft_layers=draft_layers, spec_tree=spec_tree,
        spec_draft_w8=spec_draft_w8,
        spec_attention_impl=spec_attention_impl,
        mesh=mesh, start=False)
    # warmup: AOT-compile EVERY prefill shape (group ladder x bucket
    # ladder x cold/cached, + the fused variants) before the loop
    # starts, then serve one request to compile the decode chunk fn
    # (for prefix-share it also PRIMES the cache — the steady-state
    # view a shared system prompt actually serves under)
    t_w = time.perf_counter()
    warmed = eng.warmup()
    eng.start()
    eng.generate(prompts[0], timeout=600)
    warmup_s = time.perf_counter() - t_w
    completed0 = eng.metrics.counter("requests_completed").value
    pc0 = eng.snapshot()["prefix_cache"]
    # compile_count covers EVERY device-step shape (prefill/fused
    # ladder + the plain decode chunk) — the zero-post-warmup gate
    compiles_warm = eng.batcher.compile_count
    itl = eng.metrics.histogram("itl_s")
    # the warmup request's gaps include the decode chunk fn's XLA
    # compile — rank only samples observed inside the timed window
    itl0 = itl.summary().get("count", 0)
    step_h = eng.metrics.histogram("serving.step_s")
    step_s0 = step_h.summary().get("sum", 0.0)

    t0 = time.perf_counter()
    budgets = budgets or [None] * len(prompts)
    reqs = [eng.submit(p, max_new_tokens=mn)
            for p, mn in zip(prompts, budgets)]
    if not eng.drain(timeout=600):
        raise RuntimeError("drain timed out — benchmark invalid")
    wall = time.perf_counter() - t0
    eng.shutdown()

    toks = sum(len(r.result()) for r in reqs)
    b = eng.batcher
    # device-step throughput of the timed window: generated tokens over
    # time spent INSIDE batcher.step() — queueing/host fan-out excluded,
    # so this is the number the attention backend actually moves
    step_s = step_h.summary().get("sum", 0.0) - step_s0
    return {
        "snap": eng.snapshot(),
        "trace": eng.trace,
        "pc0": pc0,
        "reqs": reqs,
        "wall_s": wall,
        "warmup_s": warmup_s,
        "warmed": warmed,
        "completed0": completed0,
        "tok_s": toks / wall,
        "decode_tok_s": toks / step_s if step_s else None,
        "attention_impl": eng.attention_impl,
        "recompiles": b.compile_count - compiles_warm,
        "profile_samples": b.profiler.report()["samples"],
        "compile_count": b.prefill_compile_count,
        "compile_count_total": b.compile_count,
        "fused_unit_count": b.fused_unit_count,
        "pad_tokens": b.prefill_pad_tokens,
        "buckets": list(b.prefill_buckets),
        "suffix_hist": {str(k): v
                        for k, v in sorted(b.prefill_suffix_hist.items())},
        "fused_steps": b.fused_steps,
        "decode_stall_steps": b.decode_stall_steps,
        "itl_ms_p50": _ms(itl.percentile(0.50, since=itl0)),
        "itl_ms_p99": _ms(itl.percentile(0.99, since=itl0)),
    }


def _ms(v):
    return None if v is None else round(v * 1000.0, 3)


# Documented quantized-vs-fp greedy divergence floor on the smoke model
# (README "Quantized serving" has the bound's rationale): across the
# workload, at least this fraction of the fp run's greedy tokens must
# match the quantized run position-for-position up to each request's
# first divergence. Weight/KV int8 error on the tiny random-init model
# flips the argmax on a small minority of steps; a collapse below the
# floor means the quantized math broke, not that rounding moved a
# borderline logit.
QUANT_MATCH_FLOOR = 0.60

# int8 KV must at least HALVE the per-token gather bytes vs the fp
# pool modulo the per-block scale overhead — 0.55x is the gate with
# that overhead priced in (bs >= 8 keeps the scale share under 5%).
KV_GATHER_RATIO_CEIL = 0.55


def _prefix_match(base, quant) -> float:
    """Fraction of baseline greedy tokens the quantized run reproduces
    up to each request's first divergence (1.0 = bit-identical)."""
    total = sum(len(b) for b in base)
    lcp = 0
    for b, t in zip(base, quant):
        for x, y in zip(b, t):
            if x != y:
                break
            lcp += 1
    return lcp / total if total else 1.0


def _quantized_leg(params, cfg, prompts, budgets, *, weight_dtype,
                   kv_dtype, **kw) -> dict:
    """One quantization configuration through a full engine lifecycle:
    AOT warmup, a COLD round over the workload, then a WARM round of
    the SAME prompts (prefix-cache hits re-read the quantized pool the
    cold round committed). HARD-FAILS on any post-warmup recompile
    (the quantized ladder must be as warmable as fp) and on any
    warm-vs-cold token mismatch (cached-prefix reads must reproduce
    the cold prefill exactly — the pool stores what every consumer
    dequantizes)."""
    import time as _t

    from paddle_tpu import serving

    eng = serving.ServingEngine(
        params, cfg, max_batch=kw["max_batch"],
        block_size=kw["block_size"], max_total_len=64,
        max_new_tokens=kw["max_new"], chunk=kw["chunk"],
        max_queue_depth=len(prompts), prefix_cache=kw["prefix_cache"],
        max_prefill_bucket=kw["max_prefill_bucket"],
        attention_impl=kw["attention_impl"],
        fused_units=kw["fused_units"], weight_dtype=weight_dtype,
        kv_dtype=kv_dtype, start=False)
    eng.warmup()
    eng.start()
    warm_compiles = eng.batcher.compile_count
    step_h = eng.metrics.histogram("serving.step_s")

    def _round():
        t0 = _t.perf_counter()
        s0 = step_h.summary().get("sum", 0.0)
        reqs = [eng.submit(p, max_new_tokens=mn)
                for p, mn in zip(prompts, budgets)]
        if not eng.drain(timeout=600):
            raise RuntimeError(
                "quantized drain timed out — benchmark invalid")
        toks = [r.result() for r in reqs]
        wall = _t.perf_counter() - t0
        step_s = step_h.summary().get("sum", 0.0) - s0
        n = sum(len(t) for t in toks)
        return toks, n / wall, (n / step_s if step_s else None)

    cold, tok_s, decode_tok_s = _round()
    warm, _, _ = _round()
    recompiles = eng.batcher.compile_count - warm_compiles
    snap = eng.snapshot()
    eng.shutdown()
    leg = f"{weight_dtype}/{kv_dtype}"
    if recompiles:
        raise RuntimeError(
            f"quantized leg {leg} recompiled {recompiles} shapes after "
            f"warmup — the (weight_dtype, kv_dtype) memo keys fell off "
            f"the warmed ladder")
    if warm != cold:
        raise RuntimeError(
            f"quantized leg {leg} lost warm==cold token parity — "
            f"cached-prefix reads disagree with the cold prefill under "
            f"quantization")
    return {"tokens": cold, "tok_s": tok_s, "decode_tok_s": decode_tok_s,
            "quant": snap["quantization"]}


def _quantized_gates(params, cfg, prompts, budgets, **kw) -> dict:
    """The --quantized matrix: fp / w8 / int8-KV / w8+int8-KV over the
    same workload, each warm==cold and recompile-free, plus the two
    cross-leg gates — int8 KV gather bytes <= 0.55x fp and quantized
    greedy divergence within the documented floor vs the fp leg."""
    legs = {}
    for name, (wd, kd) in (("fp", ("fp", "fp")), ("w8", ("int8", "fp")),
                           ("int8kv", ("fp", "int8")),
                           ("w8kv8", ("int8", "int8"))):
        legs[name] = _quantized_leg(params, cfg, prompts, budgets,
                                    weight_dtype=wd, kv_dtype=kd, **kw)
    fp_bpt = legs["fp"]["quant"]["kv_bytes_per_token"]
    q_bpt = legs["w8kv8"]["quant"]["kv_bytes_per_token"]
    ratio = q_bpt / fp_bpt
    if ratio > KV_GATHER_RATIO_CEIL:
        raise RuntimeError(
            f"quantized gate: int8 KV gather bytes at {ratio:.3f}x fp "
            f"(ceiling {KV_GATHER_RATIO_CEIL}) — the int8 pool no "
            f"longer halves per-token HBM traffic")
    out = {
        "kv_bytes_per_token_fp": fp_bpt,
        "kv_bytes_per_token_int8": q_bpt,
        "kv_gather_ratio": round(ratio, 4),
        "kv_pool_bytes": legs["w8kv8"]["quant"]["kv_pool_bytes"],
        "kv_pool_bytes_fp": legs["fp"]["quant"]["kv_pool_bytes"],
        "weight_bytes_fp": legs["fp"]["quant"]["weight_bytes"],
        "weight_bytes_w8": legs["w8"]["quant"]["weight_bytes"],
        "quantized_recompiles_after_warmup": 0,   # each leg hard-gated
    }
    base = legs["fp"]["tokens"]
    for name in ("w8", "int8kv", "w8kv8"):
        m = _prefix_match(base, legs[name]["tokens"])
        if m < QUANT_MATCH_FLOOR:
            raise RuntimeError(
                f"quantized gate: {name} greedy output matches only "
                f"{m:.3f} of the fp run (documented floor "
                f"{QUANT_MATCH_FLOOR}) — quantization error exceeds "
                f"the accuracy bound")
        out[f"quantized_token_match_{name}"] = round(m, 4)
    for name, leg in legs.items():
        out[f"tok_s_{name}"] = round(leg["tok_s"], 1)
        out[f"decode_tok_s_{name}"] = (round(leg["decode_tok_s"], 1)
                                       if leg["decode_tok_s"] else None)
    return out


def _spec_leg(params, cfg, prompts, *, spec_tree=(2, 1, 1, 1),
              **kw) -> dict:
    """The speculative-decoding gate: the shared-prefix workload runs
    plain (the greedy token reference), then self-speculatively with
    a chain draft, then with a TREE draft (`--spec-tree`, default
    [2,1,1,1]). HARD-FAILS unless BOTH spec runs' outputs are
    BIT-identical to the plain reference (greedy speculation changes
    the schedule, never the tokens), accepted tokens/step exceeds 1
    (speculation actually multiplies decode), the tree leg's accepted
    tokens per sweep >= the chain leg's at equal accepted-path budget
    (the tree's depth equals the chain's k, and child 0 of every tree
    node IS the chain's draft token, so the tree's candidate set
    contains the chain path — acceptance can only dominate), and
    post-warmup recompiles stay 0 on all runs (the spec config —
    branching spec included — rides every memo/warmup key). Drafts
    run at FULL depth here: on the random-init smoke model a
    truncated draft's proposals essentially never match the target's
    greedy choices, so the accept path would be vacuous — truncation
    (`draft_layers=`) is a quality/cost knob for real checkpoints,
    exercised for token parity by tests/test_speculative.py."""
    spec_tree = tuple(int(b) for b in spec_tree)
    ref = _serve(params, cfg, prompts, fused_prefill=True, **kw)
    base_tokens = [q.result() for q in ref["reqs"]]
    # chain leg: k = the tree's depth, so both legs can accept the
    # same number of draft tokens per verify sweep (the fair
    # acceptance comparison; the tree spends more verify WIDTH —
    # that is the trade speculation v2 buys)
    chain_k = len(spec_tree)
    spec = _serve(params, cfg, prompts, fused_prefill=True,
                  speculative=True, spec_k=chain_k,
                  draft_layers=None, **kw)
    spec_tokens = [q.result() for q in spec["reqs"]]
    st = spec["snap"]["speculative"]
    tree = _serve(params, cfg, prompts, fused_prefill=True,
                  speculative=True, spec_tree=list(spec_tree),
                  draft_layers=None, **kw)
    tree_tokens = [q.result() for q in tree["reqs"]]
    tt = tree["snap"]["speculative"]
    for name, toks, stats in (("chain", spec_tokens, st),
                              ("tree", tree_tokens, tt)):
        if toks != base_tokens:
            bad = sum(1 for a, b in zip(base_tokens, toks) if a != b)
            raise RuntimeError(
                f"speculative gate: {name} leg — {bad}/"
                f"{len(base_tokens)} requests diverged from the plain "
                f"greedy reference — greedy speculative decoding must "
                f"be output-identical (accept_rate "
                f"{stats['accept_rate']})")
    if ref["recompiles"] or spec["recompiles"] or tree["recompiles"]:
        raise RuntimeError(
            f"speculative gate: post-warmup recompiles (plain "
            f"{ref['recompiles']}, chain {spec['recompiles']}, tree "
            f"{tree['recompiles']}) — the spec config (branching "
            f"spec included) must ride every memo/warmup key")
    if not st["tokens_per_step"] > 1.0:
        raise RuntimeError(
            f"speculative gate: {st['tokens_per_step']} accepted "
            f"tokens/step over {st['steps']} verify sweeps — "
            f"speculation is not multiplying decode (accept_rate "
            f"{st['accept_rate']})")
    if tt["accepted_per_sweep"] < st["accepted_per_sweep"]:
        raise RuntimeError(
            f"speculative gate: tree accepted/sweep "
            f"{tt['accepted_per_sweep']} < chain's "
            f"{st['accepted_per_sweep']} at equal accepted-path "
            f"budget — the tree's candidate set contains the chain "
            f"path, so tree acceptance must dominate")
    return {
        "_ref": ref,
        "spec_accept_rate": st["accept_rate"],
        "spec_tokens_per_step": st["tokens_per_step"],
        "spec_k": st["k"],
        "spec_draft_layers": st["draft_layers"],
        "spec_verify_steps": st["steps"],
        "spec_token_match": 1.0,
        "spec_recompiles_after_warmup": spec["recompiles"],
        "spec_tree": list(spec_tree),
        "spec_tree_k": tt["k"],
        "spec_tree_accept_rate": tt["accept_rate"],
        "spec_tree_tokens_per_step": tt["tokens_per_step"],
        "spec_tree_accepted_per_sweep": tt["accepted_per_sweep"],
        "spec_chain_accepted_per_sweep": st["accepted_per_sweep"],
        "spec_tree_accept_depth_hist": tt["accept_depth_hist"],
        "spec_tree_token_match": 1.0,
        "spec_tree_recompiles_after_warmup": tree["recompiles"],
        "tok_s_spec": round(spec["tok_s"], 1),
        "decode_tok_s_spec": (round(spec["decode_tok_s"], 1)
                              if spec["decode_tok_s"] else None),
        "tok_s_spec_tree": round(tree["tok_s"], 1),
        "decode_tok_s_spec_tree": (round(tree["decode_tok_s"], 1)
                                   if tree["decode_tok_s"] else None),
    }


def _chaos_leg(params, cfg, prompts, budgets, culprit_idx: int,
               base_tokens, **kw) -> dict:
    """The fault-isolation gate: re-serve the same workload with a
    persistent fail-on-rid fault armed against request `culprit_idx`
    at its first streamed token, and HARD-FAIL unless quarantine
    contains the blast radius (see module docstring)."""
    import threading

    from paddle_tpu import serving
    from paddle_tpu.serving.faults import FaultInjector

    inj = FaultInjector(seed=0)
    eng = serving.ServingEngine(
        params, cfg, max_batch=kw["max_batch"],
        block_size=kw["block_size"], max_total_len=64,
        max_new_tokens=kw["max_new"], chunk=kw["chunk"],
        max_queue_depth=len(prompts), prefix_cache=kw["prefix_cache"],
        max_prefill_bucket=kw["max_prefill_bucket"],
        attention_impl=kw["attention_impl"],
        fused_units=kw["fused_units"], fault_injector=inj, start=False)
    eng.warmup()
    eng.start()
    eng.generate(prompts[0], timeout=600)
    compiles_warm = eng.batcher.compile_count
    armed = threading.Event()

    def arm(tok):
        # first streamed token of the culprit: poison its rid from
        # here on — the fault lands mid-stream, typically inside a
        # fused decode+prefill batch carrying innocents
        if not armed.is_set():
            armed.set()
            inj.fail_on_rid(culprit_req.request_id)

    # the handle is built BEFORE submission so the engine-thread
    # callback never races the submit loop's list bookkeeping
    culprit_req = serving.GenerationRequest(
        prompts[culprit_idx], max_new_tokens=int(budgets[culprit_idx]),
        on_token=arm)
    reqs = []
    for i, (p, mn) in enumerate(zip(prompts, budgets)):
        reqs.append(eng.submit(culprit_req) if i == culprit_idx
                    else eng.submit(p, max_new_tokens=mn))
    if not eng.drain(timeout=600):
        raise RuntimeError("chaos drain timed out — benchmark invalid")
    recompiles = eng.batcher.compile_count - compiles_warm
    health = eng.health()
    blocks_in_use = eng.batcher.alloc.stats()["blocks_in_use"]
    eng.shutdown()

    culprit = reqs[culprit_idx]
    failed = [i for i, r in enumerate(reqs)
              if r.state is serving.RequestState.FAILED]
    if failed != [culprit_idx]:
        raise RuntimeError(
            f"chaos gate: FAILED set {failed} != [{culprit_idx}] — the "
            f"quarantine did not contain the fault to the culprit")
    if not culprit.tokens or \
            culprit.tokens != base_tokens[culprit_idx][:len(culprit.tokens)]:
        raise RuntimeError(
            "chaos gate: the culprit's streamed tokens are not a prefix "
            "of its fault-free run — tokens were re-emitted or lost")
    for i, r in enumerate(reqs):
        if i == culprit_idx:
            continue
        if r.result() != base_tokens[i]:
            raise RuntimeError(
                f"chaos gate: innocent request {i} finished with "
                f"different tokens than the fault-free run — recovery "
                f"lost or corrupted streamed output")
    if recompiles:
        raise RuntimeError(
            f"chaos gate: {recompiles} post-warmup recompiles — "
            f"quarantine re-execution left the warmed ladder")
    if blocks_in_use:
        raise RuntimeError(
            f"chaos gate: {blocks_in_use} KV blocks still in use after "
            f"drain — the recovery path leaked pool blocks")
    if not health["quarantines"]:
        raise RuntimeError(
            "chaos gate: no quarantine ran — the fault never fired "
            "(workload produced no poisoned step)")
    return {
        "chaos_culprit_index": culprit_idx,
        "chaos_culprit_tokens_streamed": len(culprit.tokens),
        "chaos_innocents": len(reqs) - 1,
        "chaos_quarantines": health["quarantines"],
        "chaos_requests_requeued": health["requests_requeued"],
        "chaos_recompiles_after_warmup": recompiles,
        "chaos_injected": inj.stats()["injected"],
        "chaos_health_status": health["status"],
    }


def _sse_stream(host: str, port: int, payload: dict):
    """One SSE round-trip over a real socket (stdlib http.client):
    POST /v1/stream, parse the event stream incrementally. Yields
    ("routed"|"token"|"done"|"error", data) tuples as they arrive, so
    the caller can react mid-stream (the chaos arm)."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=600)
    try:
        conn.request("POST", "/v1/stream", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(
                f"/v1/stream answered {resp.status}: {resp.read()!r}")
        event = None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.decode().rstrip("\n")
            if line.startswith("event: "):
                event = line[len("event: "):].strip()
            elif line.startswith("data: "):
                data = json.loads(line[len("data: "):])
                yield (event or ("token" if "token" in data else "data"),
                       data)
                event = None
    finally:
        conn.close()


def _sse_chaos_run(host, port, prompts, budgets, injs, hang_s):
    """The shared chaos harness of the --router and --restart legs:
    stream every prompt concurrently over SSE through the frontend;
    when the victim (the largest-budget request — it must still be
    DECODING when the poison arms) streams its first token, hang its
    serving replica's next device calls (a spread of step numbers
    absorbs the arm-vs-step race; only the first match fires, the
    rest stay idle). Returns (results, victim_index, wall_s) where
    results[i] = {"tokens", "routed", "final"}."""
    import threading

    victim = max(range(len(prompts)), key=lambda i: budgets[i])
    armed = threading.Event()
    results = [None] * len(prompts)

    def run_one(i):
        toks, routed, final = [], None, None
        for event, data in _sse_stream(
                host, port, {"prompt": prompts[i],
                             "max_new_tokens": int(budgets[i])}):
            if event == "routed":
                routed = data["replica"]
            elif event in ("done", "error"):
                final = data
            elif "token" in data:
                toks.append(data["token"])
                if i == victim and not armed.is_set():
                    armed.set()
                    inj = injs[int(routed[1:])]
                    c = inj.stats()["calls"]
                    for k in range(1, 6):
                        inj.hang_on_step(c + k, hang_s)
        results[i] = {"tokens": toks, "routed": routed, "final": final}

    t0 = time.perf_counter()
    threads = [threading.Thread(target=run_one, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    return results, victim, time.perf_counter() - t0


def _check_sse_failover(results, victim, base_tokens, snap, gate):
    """The shared failover gates of the --router and --restart legs:
    the victim finished on ANOTHER replica after >=1 failover, its
    pre-failover stream is a strict prefix of the final one, and
    EVERY stream is bit-identical to the single-engine reference.
    Returns (tokens_kept, dead_replica_id) on success; raises the
    gate's hard failure otherwise."""
    v = results[victim]
    if v is None or v["final"] is None:
        raise RuntimeError(
            f"{gate} gate: the victim's SSE stream never finished — "
            f"failover did not recover it")
    if v["final"]["state"] != "FINISHED":
        raise RuntimeError(
            f"{gate} gate: victim ended {v['final']['state']} "
            f"({v['final'].get('error')}) instead of completing on "
            f"the surviving replica")
    if not v["final"]["failovers"] or v["final"]["replica"] == v["routed"]:
        raise RuntimeError(
            f"{gate} gate: victim finished on {v['final']['replica']} "
            f"with {v['final']['failovers']} failovers — the chaos "
            f"hang never forced a cross-replica failover")
    log = {e["router_rid"]: e for e in snap["failover_log"]}
    kept = log.get(v["final"]["request_id"], {}).get("tokens_kept", 0)
    if not (0 < kept < len(base_tokens[victim])):
        raise RuntimeError(
            f"{gate} gate: victim kept {kept} of "
            f"{len(base_tokens[victim])} tokens across failover — the "
            f"pre-failover stream is not a strict prefix (fault fired "
            f"before the first token, or after the last)")
    for i, r in enumerate(results):
        if r is None or r["tokens"] != base_tokens[i]:
            got = None if r is None else r["tokens"]
            raise RuntimeError(
                f"{gate} gate: request {i} streamed {got} != the "
                f"single-engine reference — failover re-emitted, lost "
                f"or corrupted tokens")
    return kept, v["routed"]


def _router_leg(params, cfg, prompts, budgets, base_tokens, **kw) -> dict:
    """The cross-replica failover gate, e2e over HTTP: 2 replicas
    behind a Router + HttpFrontend serve the mixed workload as
    concurrent SSE streams; when the longest-budget request (the
    victim) streams its first token, a seeded chaos hang poisons its
    serving replica's next device calls — the hung-step watchdog flips
    that replica UNHEALTHY and every stranded/queued request must fail
    over to the survivor. HARD-FAILS unless the victim completes on
    the OTHER replica with its pre-failover stream a strict prefix of
    the final one, every request's tokens are bit-identical to the
    single-engine reference, post-warmup recompiles stay 0 on both
    replicas, and the survivor's pool drains clean."""
    from paddle_tpu import serving
    from paddle_tpu.serving.faults import FaultInjector

    injs = [FaultInjector(seed=0), FaultInjector(seed=1)]
    router = serving.Router(
        params, cfg, replicas=2, max_batch=kw["max_batch"],
        block_size=kw["block_size"], max_total_len=64,
        max_new_tokens=kw["max_new"], chunk=kw["chunk"],
        max_queue_depth=2 * len(prompts),
        prefix_cache=kw["prefix_cache"],
        max_prefill_bucket=kw["max_prefill_bucket"],
        attention_impl=kw["attention_impl"],
        fused_units=kw["fused_units"], watchdog_s=0.5,
        per_replica=[{"fault_injector": injs[0]},
                     {"fault_injector": injs[1]}],
        start=False)
    warmed = router.warmup()
    router.start()
    compiles_warm = [e.batcher.compile_count for e in router.engines]
    fe = serving.HttpFrontend(router, port=0, shutdown_router=False)
    host, port = fe.start()
    results, victim, wall = _sse_chaos_run(
        host, port, prompts, budgets, injs, hang_s=3.0)
    recompiles = sum(e.batcher.compile_count - c0
                     for e, c0 in zip(router.engines, compiles_warm))
    snap = router.snapshot()
    health = router.health()
    fe.shutdown(drain=True)
    router.shutdown(drain=False)

    kept, dead_rid = _check_sse_failover(results, victim, base_tokens,
                                         snap, "router")
    if recompiles:
        raise RuntimeError(
            f"router gate: {recompiles} post-warmup recompiles across "
            f"replicas — failover re-prefills left the warmed ladder")
    survivor = next(e for e in router.engines
                    if e.replica_id != dead_rid)
    leaked = survivor.batcher.alloc.stats()["blocks_in_use"]
    if leaked:
        raise RuntimeError(
            f"router gate: {leaked} KV blocks still in use on the "
            f"survivor after drain — cross-replica recovery leaked")
    ntok = sum(len(r["tokens"]) for r in results)
    return {
        "router_replicas": 2,
        "router_tok_s": round(ntok / wall, 1),
        "router_shapes_warmed": warmed,
        "router_failovers": health["failovers"],
        "router_victim_tokens_kept": kept,
        "router_victim_replicas": [
            dead_rid, results[victim]["final"]["replica"]],
        "router_recompiles_after_warmup": recompiles,
        "router_serving_replicas": health["serving_replicas"],
        "router_watchdog_trips": sum(
            h["watchdog_trips"] for h in health["replicas"].values()),
    }


def _restart_leg(params, cfg, prompts, budgets, base_tokens, *,
                 mesh=None, **kw) -> dict:
    """The self-healing gate (`--restart`), e2e over HTTP: like the
    `--router` leg, a seeded chaos hang kills the victim's replica
    mid-stream and every stranded SSE stream must fail over to the
    survivor with the strict-prefix invariant intact — but here the
    Router runs `auto_restart=True`, so the leg then HARD-FAILS unless
    the dead slot is respawned through the supervisor's readiness gate
    (teardown → rebuild → AOT warmup → synthetic probe), rejoins
    rotation, and serves a post-restart request — with zero
    post-warmup recompiles on EVERY engine incarnation (the originals
    against their warmup baseline, the respawn against the compile
    count its readiness gate recorded) and no circuit-breaker trip."""
    from paddle_tpu import serving
    from paddle_tpu.serving.faults import FaultInjector

    injs = [FaultInjector(seed=0), FaultInjector(seed=1)]
    per_replica = [{"fault_injector": injs[0]},
                   {"fault_injector": injs[1]}]
    if mesh is not None:
        # the --tp leg reruns this chaos shape with BOTH slots sharded:
        # the supervisor replays these per-replica kwargs on respawn,
        # so the rebuilt slot re-derives its mesh + shardings too
        for slot_kw in per_replica:
            slot_kw["mesh"] = mesh
    router = serving.Router(
        params, cfg, replicas=2, max_batch=kw["max_batch"],
        block_size=kw["block_size"], max_total_len=64,
        max_new_tokens=kw["max_new"], chunk=kw["chunk"],
        max_queue_depth=2 * len(prompts),
        prefix_cache=kw["prefix_cache"],
        max_prefill_bucket=kw["max_prefill_bucket"],
        attention_impl=kw["attention_impl"],
        # compile-scale watchdog headroom: a supervisor respawn runs
        # jax tracing + XLA compile CONCURRENTLY with the survivor's
        # serving steps, and a sub-second deadline can trip on that CPU
        # contention alone (the injected hang below is 8s — far past
        # any honest step)
        fused_units=kw["fused_units"], watchdog_s=2.0,
        per_replica=per_replica,
        auto_restart=True,
        # leftover hang rules from the arm spread can poison the first
        # respawn probes (the injector follows the slot) — threshold 5
        # keeps the breaker shut through that worst case; the leg
        # heals the injectors as soon as the streams complete
        restart_opts={"backoff_s": 0.1, "breaker_threshold": 5,
                      "probe_timeout_s": 120.0},
        start=False)
    warmed = router.warmup()
    router.start()
    compiles_warm = {e.replica_id: e.batcher.compile_count
                     for e in router.engines}
    originals = {e.replica_id: e for e in router.engines}
    fe = serving.HttpFrontend(router, port=0, shutdown_router=False)
    host, port = fe.start()
    results, victim, wall = _sse_chaos_run(
        host, port, prompts, budgets, injs, hang_s=8.0)
    # streams done (failover complete): disarm the chaos so the
    # supervisor's respawn probes run against a clean replica
    for inj in injs:
        inj.heal()
    kept, dead_rid = _check_sse_failover(results, victim, base_tokens,
                                         router.snapshot(), "restart")

    # --- the self-healing half: the dead slot must rejoin ---------------
    deadline = time.perf_counter() + 300
    while time.perf_counter() < deadline:
        h = router.health()
        if h["serving_replicas"] == 2 and h["replica_restarts"] >= 1:
            break
        time.sleep(0.05)
    else:
        h = router.health()
        raise RuntimeError(
            f"restart gate: the dead slot never rejoined rotation "
            f"(serving_replicas={h['serving_replicas']}, "
            f"restarts={h['replica_restarts']}, "
            f"supervisor={h.get('supervisor')})")
    if h["circuit_open"]:
        raise RuntimeError(
            "restart gate: the crash-loop breaker opened on what "
            "should have been a recoverable replica")
    # the respawned engine must be a NEW incarnation in the same slot
    respawn = next(e for e in router.engines if e.replica_id == dead_rid)
    if respawn is originals[dead_rid]:
        raise RuntimeError(
            "restart gate: the victim slot still holds the dead "
            "engine — no respawn happened")

    # post-restart: a concurrent burst of FRESH prompts (short enough
    # to carry no affinity blocks, so placement is pure occupancy and
    # spreads) must land traffic on the respawned slot and complete
    post_rng = np.random.RandomState(99)
    post = [router.submit(list(map(int, post_rng.randint(1, 200, 5))),
                          max_new_tokens=kw["max_new"])
            for _ in range(4)]
    outs = [q.result(300) for q in post]
    if not all(outs):
        raise RuntimeError(
            "restart gate: a post-restart request generated nothing")
    served = [q.replica_id for q in post]
    if dead_rid not in served:
        raise RuntimeError(
            f"restart gate: the respawned slot {dead_rid} served none "
            f"of the post-restart burst (placements: {served}) — it "
            f"rejoined health but not rotation")

    # recompile accounting per incarnation: survivors vs their warmup
    # baseline, the respawn vs the compile count its readiness gate
    # recorded (supervisor slot info)
    sup = router.health()["supervisor"]
    recompiles = 0
    for e in router.engines:
        if e is respawn:
            recompiles += e.batcher.compile_count \
                - sup[e.replica_id]["warm_compile_count"]
        else:
            recompiles += e.batcher.compile_count \
                - compiles_warm[e.replica_id]
    if recompiles:
        raise RuntimeError(
            f"restart gate: {recompiles} post-warmup recompiles across "
            f"engine incarnations — the respawn's readiness gate or "
            f"the failover re-prefills left the warmed ladder")
    health = router.health()
    fe.shutdown(drain=True)
    router.shutdown(drain=False)
    ntok = sum(len(r["tokens"]) for r in results)
    return {
        "restart_replicas": 2,
        "restart_tok_s": round(ntok / wall, 1),
        "restart_shapes_warmed": warmed,
        "restart_failovers": health["failovers"],
        "restart_victim_tokens_kept": kept,
        "restart_victim_replica": dead_rid,
        "restart_replica_restarts": health["replica_restarts"],
        "restart_respawn_attempts": health["restart_failures"] + 1,
        "restart_circuit_open": health["circuit_open"],
        "restart_recompiles_after_warmup": recompiles,
        "restart_serving_replicas": health["serving_replicas"],
        "restart_post_burst_replicas": sorted(set(served)),
        "restart_injector_attachments": [
            inj.stats()["attachments"] for inj in injs],
    }


def _tp_leg(params, cfg, prompts, budgets, speculative=False,
            spec_tree=None, **kw) -> dict:
    """The tensor-parallel gate (`--tp`), under 4 forced host devices:
    the mixed workload through a single-device reference engine, then
    the SAME workload through a `mesh=MeshConfig(tp=4)` engine whose
    weights are Megatron-sharded and whose paged-KV pool is sharded on
    the head axis (serving.tp). HARD-FAILS unless the TP output is
    bit-identical to single-device, post-warmup recompiles stay 0 on
    BOTH engines (the mesh key rides every compiled-shape memo, so the
    warmup ladder covers the sharded shapes), and a TP=2-sharded
    replica pair survives the `--restart` chaos shape — hang →
    failover → supervisor respawn of the SHARDED slot through its
    readiness gate → rejoin → serve — under the same bit-identity and
    zero-recompile bars.

    `speculative=True` (`--tp --speculative`) is the fast-path
    COMPOSITION gate: the sharded engine additionally turns on tree
    speculation (with `--attention-impl pallas` the ragged kernel and
    its suffix-slab verify run shard_map-wrapped on the mesh) while
    the reference stays mesh-off PLAIN decode — so the bit-identity
    bar covers mesh x impl x speculation all at once, plus the
    resolved fast-path stamps in snapshot()."""
    import jax

    from paddle_tpu.serving.tp import MeshConfig

    if len(jax.devices()) < 4:
        raise RuntimeError(
            f"tp gate: only {len(jax.devices())} devices visible — "
            f"--tp must be on argv at interpreter start so the module "
            f"top can force 4 host devices via XLA_FLAGS before jax "
            f"binds its backend")

    ref = _serve(params, cfg, prompts, fused_prefill=True,
                 budgets=budgets, **kw)
    base_tokens = [q.result() for q in ref["reqs"]]
    spec_kw = dict(speculative=True, spec_tree=spec_tree) \
        if speculative else {}
    tp = _serve(params, cfg, prompts, fused_prefill=True,
                budgets=budgets, mesh=MeshConfig(tp=4), **spec_kw,
                **kw)
    tp_tokens = [q.result() for q in tp["reqs"]]
    what = "TP=4 mesh engine" if not speculative else \
        "TP=4 mesh+speculative engine"
    if tp_tokens != base_tokens:
        bad = sum(a != b for a, b in zip(tp_tokens, base_tokens))
        raise RuntimeError(
            f"tp gate: {bad}/{len(prompts)} requests diverged between "
            f"the {what} and single-device plain decode — greedy "
            f"sharded decode must be bit-identical (a mismatch means a "
            f"wrong sharding spec, a silently resharded intermediate, "
            f"or a verify/commit divergence)")
    if ref["recompiles"] or tp["recompiles"]:
        raise RuntimeError(
            f"tp gate: post-warmup recompiles (single-device "
            f"{ref['recompiles']}, tp=4 {tp['recompiles']}) — the "
            f"warmup ladder no longer covers the sharded shapes (mesh "
            f"key missing from a memo?)")
    # the fast-path stamps must say what actually ran: a silent
    # fallback to the XLA gather under the mesh would pass bit-identity
    # while forfeiting the kernel — exactly the regression this guards
    mesh_stamp = tp["snap"]["tp"]["mesh"]
    if mesh_stamp["attention_impl"] != tp["attention_impl"]:
        raise RuntimeError(
            f"tp gate: snapshot mesh stamp says attention_impl="
            f"{mesh_stamp['attention_impl']!r} but the engine resolved "
            f"{tp['attention_impl']!r}")
    if speculative:
        spec_snap = tp["snap"]["speculative"]
        if not spec_snap["enabled"] or spec_snap["steps"] < 1:
            raise RuntimeError(
                "tp gate: the mesh+speculative engine reports no spec "
                "verify sweeps — speculation silently off under TP")
        if mesh_stamp["spec_backend"] != spec_snap["backend"]:
            raise RuntimeError(
                f"tp gate: mesh stamp spec_backend="
                f"{mesh_stamp['spec_backend']!r} != batcher backend "
                f"{spec_snap['backend']!r}")

    # the self-healing half at TP=2 × 2 replicas (4 devices, host
    # shards overlap freely): chaos hang, SSE failover, supervisor
    # respawn of a sharded slot, rejoin, post-restart serve
    chaos = _restart_leg(params, cfg, prompts, budgets, base_tokens,
                         mesh=MeshConfig(tp=2), **kw)

    snap_tp = tp["snap"]["tp"]
    result = {
        "metric": "serving_offline_tok_s",
        "value": round(tp["tok_s"], 1),
        "unit": "tokens/s",
        "workload": "tp",
        "attention_impl": tp["attention_impl"],
        "n_requests": len(prompts),
        "tp_mesh": snap_tp["mesh"],
        "tp_kv_pool_bytes_per_device":
            snap_tp["kv_pool_bytes_per_device"],
        "tp_weight_bytes_per_device":
            snap_tp.get("weight_bytes_per_device"),
        "tok_s_single_device": round(ref["tok_s"], 1),
        "tp_bit_identical": True,
        "tp_shapes_warmed": tp["warmed"],
        "tp_recompiles_after_warmup": tp["recompiles"],
        "tp_restart_mesh": MeshConfig(tp=2).describe(),
        "tp_spec_backend": snap_tp["mesh"]["spec_backend"],
    }
    if speculative:
        spec_snap = tp["snap"]["speculative"]
        result["tp_speculative"] = True
        result["tp_spec_tree"] = spec_snap.get("tree")
        result["tp_spec_accept_rate"] = spec_snap["accept_rate"]
        result["tp_spec_tokens_per_step"] = \
            spec_snap["tokens_per_step"]
    result.update(chaos)
    return result


def _disagg_leg(params, cfg, prompts, budgets, *, weight_dtype,
                kv_dtype, **kw) -> dict:
    """One quantization configuration through the disaggregated
    prefill/decode topology: a monolithic single-engine reference
    first, then the SAME workload through `Router(disaggregated=True)`
    with one prefill-role and one decode-role replica. Every request
    prefills on replica 0, surrenders at the first step boundary with
    its KV chain exported as a `KVSnapshot`, and resumes on replica 1
    via `import_kv`. HARD-FAILS unless the disaggregated streams are
    bit-identical to the monolithic reference, the decode replica ran
    ZERO prefill chunks (all of its KV arrived by snapshot import),
    every request migrated exactly once, post-warmup recompiles stay 0
    on BOTH replicas, and both pools drain clean."""
    import time as _t

    from paddle_tpu import serving

    ekw = dict(max_batch=kw["max_batch"], block_size=kw["block_size"],
               max_total_len=64, max_new_tokens=kw["max_new"],
               chunk=kw["chunk"], max_queue_depth=2 * len(prompts),
               prefix_cache=kw["prefix_cache"],
               max_prefill_bucket=kw["max_prefill_bucket"],
               attention_impl=kw["attention_impl"],
               fused_units=kw["fused_units"],
               weight_dtype=weight_dtype, kv_dtype=kv_dtype)
    leg = f"{weight_dtype}/{kv_dtype}"

    # monolithic reference: the same engine config, both roles in one
    # process — its tokens are the bit-identity bar for the hop
    eng = serving.ServingEngine(params, cfg, start=False, **ekw)
    eng.warmup()
    eng.start()
    refs = [eng.submit(p, max_new_tokens=mn)
            for p, mn in zip(prompts, budgets)]
    if not eng.drain(timeout=600):
        raise RuntimeError(
            f"disagg leg {leg}: monolithic reference drain timed out")
    base = [r.result() for r in refs]
    eng.shutdown()

    router = serving.Router(
        params, cfg, replicas=2, disaggregated=True,
        per_replica=[{"role": "prefill"}, {"role": "decode"}],
        start=False, **ekw)
    warmed = router.warmup()
    router.start()
    compiles_warm = [e.batcher.compile_count for e in router.engines]
    t0 = _t.perf_counter()
    reqs = [router.submit(p, max_new_tokens=mn, timeout_s=120.0)
            for p, mn in zip(prompts, budgets)]
    toks = [r.result(timeout=600) for r in reqs]
    wall = _t.perf_counter() - t0
    recompiles = sum(e.batcher.compile_count - c0
                     for e, c0 in zip(router.engines, compiles_warm))
    pre, dec = router.engines
    health = router.health()
    snap = router.snapshot()
    leaked = sum(e.batcher.alloc.stats()["blocks_in_use"]
                 for e in router.engines)
    router.shutdown(drain=False)

    if toks != base:
        bad = [i for i, (a, b) in enumerate(zip(toks, base)) if a != b]
        raise RuntimeError(
            f"disagg leg {leg}: streams {bad} diverged from the "
            f"monolithic reference — the KV hop is not bit-exact")
    if dec.batcher.prefill_chunk_calls:
        raise RuntimeError(
            f"disagg leg {leg}: decode replica ran "
            f"{dec.batcher.prefill_chunk_calls} prefill chunks — KV "
            f"arrived by re-prefill, not by snapshot import")
    # a prefill-role engine surrenders at the first step boundary
    # after the first token, by which point the fused step has already
    # run one decode chunk — so a request holds min(budget, 1 + chunk)
    # tokens at surrender and only budgets past that ever migrate
    # (short requests legitimately finish on the prefill replica)
    expect = sum(1 for b in budgets if b > 1 + kw["chunk"])
    if dec.batcher.imported_kv != expect \
            or health["migrations"] != expect:
        raise RuntimeError(
            f"disagg leg {leg}: {dec.batcher.imported_kv} imports / "
            f"{health['migrations']} migrations, expected {expect} "
            f"(budgets past the surrender boundary) — some hop fell "
            f"back to re-prefill or double-migrated")
    if recompiles:
        raise RuntimeError(
            f"disagg leg {leg}: {recompiles} post-warmup recompiles "
            f"across replicas — imports left the warmed ladder")
    if leaked:
        raise RuntimeError(
            f"disagg leg {leg}: {leaked} KV blocks still in use after "
            f"drain — the export/import hop leaked pool blocks")
    handoffs = [e["handoff_s"] for e in snap["migration_log"]]
    ntok = sum(len(t) for t in toks)
    return {
        "tokens": toks,
        "tok_s": ntok / wall,
        "shapes_warmed": warmed,
        "migrations": health["migrations"],
        "migration_bytes": health["migration_bytes"],
        "handoff_ms_mean": (round(1e3 * sum(handoffs) / len(handoffs), 3)
                            if handoffs else None),
        "handoff_ms_max": (round(1e3 * max(handoffs), 3)
                           if handoffs else None),
        "prefill_chunks_prefill_replica": pre.batcher.prefill_chunk_calls,
        "recompiles": recompiles,
    }


def _disagg_gates(params, cfg, prompts, budgets, **kw) -> dict:
    """The --disagg matrix: the fp leg and the w8+int8-KV leg, each
    individually hard-gated (bit-identity vs its own monolithic
    reference, zero decode-replica prefill chunks, one migration per
    request, zero recompiles), plus the cross-leg accuracy gate — the
    quantized disaggregated output must match the fp reference at
    least as well as the documented quantization floor (the snapshot
    hop must not add divergence on top of int8 rounding)."""
    fp = _disagg_leg(params, cfg, prompts, budgets,
                     weight_dtype="fp", kv_dtype="fp", **kw)
    q = _disagg_leg(params, cfg, prompts, budgets,
                    weight_dtype="int8", kv_dtype="int8", **kw)
    m = _prefix_match(fp["tokens"], q["tokens"])
    if m < QUANT_MATCH_FLOOR:
        raise RuntimeError(
            f"disagg gate: int8 disaggregated output matches only "
            f"{m:.3f} of the fp run (documented floor "
            f"{QUANT_MATCH_FLOOR}) — the snapshot hop amplified "
            f"quantization error")
    return {
        "disagg_replicas": 2,
        "disagg_tok_s": round(fp["tok_s"], 1),
        "disagg_tok_s_int8": round(q["tok_s"], 1),
        "disagg_shapes_warmed": fp["shapes_warmed"],
        "disagg_migrations": fp["migrations"],
        "disagg_migration_bytes": fp["migration_bytes"],
        "disagg_migration_bytes_int8": q["migration_bytes"],
        "disagg_handoff_ms_mean": fp["handoff_ms_mean"],
        "disagg_handoff_ms_max": fp["handoff_ms_max"],
        "disagg_token_match_int8": round(m, 4),
        "disagg_recompiles_after_warmup": 0,      # each leg hard-gated
    }


def _slo_breach_leg(params, cfg, prompts, budgets, **kw) -> dict:
    """The SLO-engine gate, e2e over the whole surface: a 1-replica
    Router + HttpFrontend serve the mixed workload while a seeded
    `FaultInjector` hangs several device steps for 4 s each — SHORT of
    the 30 s watchdog (latency degradation, not a dead replica). The
    leg HARD-FAILS unless the injected latency drives an
    `itl_ms_p99` BREACH that is visible end-to-end — engine
    `health()["slo"]`, the router rollup, the `/health` JSON detail
    (still HTTP 200: SLOs degrade, supervision decides), and
    `slo_breaches_total >= 1` for BOTH the replica and the router
    rollup in the merged `/metrics` exposition — AND the verdict
    clears back to OK after the fault heals, with zero post-warmup
    recompiles. A `POST /debug/profile` capture window during the
    recovery traffic must also complete and land device-wall spans in
    the merged trace (the device-time-attribution half of the PR)."""
    import threading

    from paddle_tpu import serving
    from paddle_tpu.serving.faults import FaultInjector

    inj = FaultInjector(seed=0)
    router = serving.Router(
        params, cfg, replicas=1, max_batch=kw["max_batch"],
        block_size=kw["block_size"], max_total_len=64,
        max_new_tokens=kw["max_new"], chunk=kw["chunk"],
        max_queue_depth=2 * len(prompts),
        prefix_cache=kw["prefix_cache"],
        max_prefill_bucket=kw["max_prefill_bucket"],
        attention_impl=kw["attention_impl"],
        fused_units=kw["fused_units"],
        # the hang must stay SHORT of the watchdog: this is the
        # latency-degradation shape, not the dead-replica one
        watchdog_s=30.0,
        slo_objectives={"itl_ms_p99": 2000.0, "error_rate": 0.5},
        slo_opts={"fast_window_s": 1.0, "slow_window_s": 3.0,
                  "eval_every_s": 0.05},
        per_replica=[{"fault_injector": inj}],
        start=False)
    router.warmup()
    router.start()
    eng = router.engines[0]
    router.generate(prompts[0], timeout=600)
    compiles_warm = eng.batcher.compile_count
    fe = serving.HttpFrontend(router, port=0, shutdown_router=False)
    host, port = fe.start()

    # arm: the next few device calls each stall 4 s — far past the
    # 2000 ms itl objective, far short of the 30 s watchdog
    c = inj.stats()["calls"]
    for k in range(1, 4):
        inj.hang_on_step(c + k, 4.0)
    reqs = [router.submit(p, max_new_tokens=mn)
            for p, mn in zip(prompts, budgets)]
    breach_seen = None
    deadline = time.perf_counter() + 300
    while time.perf_counter() < deadline:
        h = eng.health()
        if h["slo"]["verdict"] == "BREACH":
            breach_seen = h["slo"]
            break
        if all(r.done for r in reqs):
            break
        time.sleep(0.05)
    for r in reqs:
        r.result(600)
    if breach_seen is None:
        raise RuntimeError(
            "slo gate: the injected 4s step hangs never drove an SLO "
            "BREACH — the tracker is not watching the latency the "
            "engine serves")
    if breach_seen["objectives"]["itl_ms_p99"]["verdict"] != "BREACH":
        raise RuntimeError(
            f"slo gate: breach fired on the wrong objective — "
            f"{breach_seen['objectives']}")
    rh = router.health()
    if rh["slo"]["verdict"] not in ("BREACH", "WARN"):
        raise RuntimeError(
            f"slo gate: router rollup says {rh['slo']['verdict']} "
            f"while the replica breached — fleet aggregation is blind")
    if rh["slo"]["breaches_total"] < 1:
        raise RuntimeError("slo gate: rollup lost the breach count")

    # the HTTP surface: /health keeps its 200 (SLOs degrade,
    # supervision decides) while carrying the verdict detail, and the
    # merged /metrics exposition counts the breach for the replica AND
    # the router rollup
    import http.client
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/health")
    resp = conn.getresponse()
    health_body = json.loads(resp.read())
    conn.close()
    if resp.status != 200:
        raise RuntimeError(
            f"slo gate: /health flipped to {resp.status} on an SLO "
            f"breach — breaches are detail, not outage")
    if "slo" not in health_body or "objectives" not in health_body["slo"]:
        raise RuntimeError("slo gate: /health carries no slo detail")
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/metrics")
    prom = conn.getresponse().read().decode()
    conn.close()
    counts = {}
    for ln in prom.splitlines():
        if ln.startswith("paddle_tpu_slo_breaches_total{"):
            label = ln.split("{")[1].split("}")[0]
            counts[label] = float(ln.split()[-1])
    if counts.get('replica="r0"', 0) < 1 \
            or counts.get('replica="router"', 0) < 1:
        raise RuntimeError(
            f"slo gate: slo_breaches_total missing from the merged "
            f"exposition (saw {counts})")

    # heal → the verdict must CLEAR once the windows forget the spike
    inj.heal()
    clear_deadline = time.perf_counter() + 120
    post_rng = np.random.RandomState(123)
    while time.perf_counter() < clear_deadline:
        router.generate(
            list(map(int, post_rng.randint(1, 200, 6))),
            max_new_tokens=2, timeout=600)
        if eng.health()["slo"]["verdict"] == "OK":
            break
        time.sleep(0.1)
    final = eng.health()["slo"]
    if final["verdict"] != "OK":
        raise RuntimeError(
            f"slo gate: verdict stuck at {final['verdict']} after the "
            f"fault healed — breach→recover hysteresis never released")

    # device-time capture through the frontend while traffic flows
    done = threading.Event()

    def burst():
        for _ in range(4):
            router.generate(
                list(map(int, post_rng.randint(1, 200, 8))),
                max_new_tokens=kw["max_new"], timeout=600)
        done.set()

    t = threading.Thread(target=burst)
    t.start()
    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", "/debug/profile",
                 json.dumps({"steps": 3, "timeout_s": 60}),
                 {"Content-Type": "application/json"})
    profile = json.loads(conn.getresponse().read())
    conn.close()
    t.join(600)
    cap = profile["r0"]["capture"]
    if not cap["complete"] or cap["steps_captured"] < 3:
        raise RuntimeError(
            f"slo gate: the /debug/profile capture window never "
            f"completed under live traffic ({cap})")
    dev_spans = sum(
        1 for e in router.to_chrome_trace()["traceEvents"]
        if str(e.get("name", "")).startswith("device."))
    if dev_spans < 3:
        raise RuntimeError(
            f"slo gate: only {dev_spans} device-wall spans in the "
            f"merged trace — capture fences are not reaching the "
            f"timelines")
    recompiles = eng.batcher.compile_count - compiles_warm
    if recompiles:
        raise RuntimeError(
            f"slo gate: {recompiles} post-warmup recompiles — the SLO "
            f"tracker or the capture fences touched the compiled-shape "
            f"memo")
    breaches_total = final["breaches_total"]
    fe.shutdown(drain=True)
    router.shutdown(drain=False)
    return {
        "slo_breaches_total": breaches_total,
        "slo_breach_objective": "itl_ms_p99",
        "slo_breach_burn_rate_fast":
            breach_seen["objectives"]["itl_ms_p99"]["burn_rate_fast"],
        "slo_verdict_peak": "BREACH",
        "slo_verdict_final": final["verdict"],
        "slo_injected_hangs": inj.stats()["injected"].get("hang", 0),
        "slo_recompiles_after_warmup": recompiles,
        "slo_profile_steps_captured": cap["steps_captured"],
        "slo_device_spans": dev_spans,
    }


def _load_leg(params, cfg, *, sessions: int, turns: int, rate_hz: float,
              deadline_s: float, router_replicas: int = 0, **kw) -> dict:
    """The closed-loop load generator: `sessions` clients arrive as a
    Poisson process (`rate_hz`), each runs `turns` multi-turn rounds
    (turn N+1's prompt is turn N's prompt + generated tokens + fresh
    user tokens — the prefix-cache steady state), and the population
    shares a small set of system prompts. Closed-loop: a session
    blocks on its own previous turn, so offered load self-limits the
    way real clients do. Emits goodput (tokens of requests that
    completed within `deadline_s`, over the wall) and request-latency
    percentiles under load — the tracked direction-3 numbers.

    `router_replicas > 0` (the `--load --router` combination) runs the
    SAME generator through a `serving.Router` over that many replicas
    instead of one engine — the multi-replica goodput-scaling view the
    ROADMAP's "load-leg router mode" follow-on asked for (prefix
    affinity keeps a session's turns on the replica already holding
    its history, so the per-replica caches stay warm)."""
    import threading

    from paddle_tpu import serving

    common = dict(
        max_batch=kw["max_batch"], block_size=kw["block_size"],
        max_total_len=64, max_new_tokens=kw["max_new"],
        chunk=kw["chunk"], max_queue_depth=max(64, sessions * turns),
        prefix_cache=kw["prefix_cache"],
        max_prefill_bucket=kw["max_prefill_bucket"],
        attention_impl=kw["attention_impl"],
        fused_units=kw["fused_units"], start=False)
    if router_replicas:
        eng = serving.Router(params, cfg, replicas=router_replicas,
                             **common)
    else:
        eng = serving.ServingEngine(params, cfg, **common)
    eng.warmup()
    eng.start()

    def pc_stats():
        # aggregated prefix-cache counters (summed across replicas in
        # router mode — hit attribution per replica lives in snapshot)
        snap = eng.snapshot()
        if router_replicas:
            out = {"prompt_tokens": 0, "hit_tokens": 0}
            for s in snap["replicas"].values():
                pc = s["prefix_cache"]
                out["prompt_tokens"] += pc.get("prompt_tokens", 0)
                out["hit_tokens"] += pc.get("hit_tokens", 0)
            return out
        return snap["prefix_cache"]

    rng = np.random.RandomState(7)
    system_prompts = [list(map(int, rng.randint(1, 200, 12)))
                      for _ in range(2)]
    eng.generate(system_prompts[0] + [1, 2, 3], timeout=600)
    pc0 = pc_stats()
    arrivals = np.cumsum(rng.exponential(1.0 / rate_hz, sessions))
    lock = threading.Lock()
    samples = []          # (latency_s, ntok, within_deadline)

    def session(si):
        srng = np.random.RandomState(100 + si)
        t_arrive = t0 + arrivals[si]
        delay = t_arrive - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        history = list(system_prompts[si % len(system_prompts)])
        for _ in range(turns):
            history = history + list(map(int, srng.randint(1, 200, 4)))
            t_s = time.perf_counter()
            req = eng.submit(history, max_new_tokens=kw["max_new"])
            toks = req.result(timeout=600)
            lat = time.perf_counter() - t_s
            with lock:
                samples.append((lat, len(toks), lat <= deadline_s))
            history = history + toks

    t0 = time.perf_counter()
    threads = [threading.Thread(target=session, args=(i,))
               for i in range(sessions)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    wall = time.perf_counter() - t0
    pc = pc_stats()
    routed_per_replica = None
    if router_replicas:
        h = eng.health()
        routed_per_replica = {
            rid: eng.metrics.counter(f"routed_{rid}").value
            for rid in h["replicas"]}
    eng.shutdown()
    lats = sorted(s[0] for s in samples)
    good_tok = sum(n for _, n, ok in samples if ok)
    total_tok = sum(n for _, n, _ in samples)
    lookups = pc["prompt_tokens"] - pc0["prompt_tokens"]
    saved = pc["hit_tokens"] - pc0["hit_tokens"]
    pct = lambda q: (round(lats[min(len(lats) - 1,
                                    int(round(q * (len(lats) - 1))))], 4)
                     if lats else None)
    out = {
        "metric": "serving_load_goodput_tok_s",
        "value": round(good_tok / wall, 1),
        "unit": "tokens/s",
        "workload": "load",
        "goodput_tok_s": round(good_tok / wall, 1),
        "tok_s_total": round(total_tok / wall, 1),
        "sessions": sessions,
        "turns": turns,
        "arrival_rate_hz": rate_hz,
        "deadline_s": deadline_s,
        "requests_total": len(samples),
        "requests_in_deadline": sum(1 for s in samples if s[2]),
        "latency_s_p50_load": pct(0.50),
        "latency_s_p99_load": pct(0.99),
        "wall_s": round(wall, 3),
        "prefix_cache_hit_rate": (round(saved / lookups, 4)
                                  if lookups else 0.0),
        "max_batch": kw["max_batch"],
        "max_new_tokens": kw["max_new"],
    }
    if router_replicas:
        out["load_router_replicas"] = router_replicas
        out["load_routed_per_replica"] = routed_per_replica
    return out


def main(n_requests: int = 16, max_new: int = 8, max_batch: int = 4,
         block_size: int = 8, chunk: int = 4, workload: str = "random",
         prefix_len: int = 24, suffix_len: int = 6,
         prefix_cache: bool = True,
         max_prefill_bucket: int = 512,
         attention_impl: str = "auto", fused_units: int = 1,
         sessions: int = 6, turns: int = 3, rate_hz: float = 8.0,
         deadline_s: float = 5.0, load_router_replicas: int = 0,
         spec_tree=(2, 1, 1, 1), tp_speculative: bool = False,
         trace_path=None, trace_overhead: bool = False) -> dict:
    import jax
    from paddle_tpu.nlp import llama

    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    prompts = _make_prompts(rng, n_requests, workload,
                            prefix_len, suffix_len)
    kw = dict(max_new=max_new, max_batch=max_batch,
              block_size=block_size, chunk=chunk,
              prefix_cache=prefix_cache,
              max_prefill_bucket=max_prefill_bucket,
              attention_impl=attention_impl, fused_units=fused_units)
    if workload == "load":
        # the closed-loop generator builds its own session workload —
        # none of the offline result assembly below applies
        return _load_leg(params, cfg, sessions=sessions, turns=turns,
                         rate_hz=rate_hz, deadline_s=deadline_s,
                         router_replicas=load_router_replicas, **kw)

    base = None
    if workload in ("fused", "prefix-share", "chaos", "quantized",
                    "router", "restart", "slo", "disagg", "tp"):
        # staggered per-request budgets so slots retire at DIFFERENT
        # steps — equal budgets would march the whole batch in lockstep
        # waves and no admission would ever land mid-decode. The fused
        # comparison needs that overlap for stalls to exist at all; the
        # prefix-share trace artifact needs it so cached-prefix
        # requests visibly piggyback (fused prefill_chunk events next
        # to their cached_tokens skip)
        kw["budgets"] = [1 + (i % max_new) for i in range(len(prompts))]
    if workload == "tp":
        # TP=4 splits on the kv-head axis and the bench default model
        # has 2 kv heads — the tp gate gets its own 4-kv-head tiny
        # config (same layers/geometry otherwise) and assembles its
        # own JSON line, gates included
        cfg = llama.LlamaConfig.tiny(use_flash=False,
                                     num_hidden_layers=2,
                                     num_key_value_heads=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        return _tp_leg(params, cfg, prompts, kw["budgets"],
                       speculative=tp_speculative,
                       spec_tree=spec_tree if tp_speculative else None,
                       **{k: v for k, v in kw.items()
                          if k != "budgets"})
    if workload == "fused":
        # unfused first: the SAME prompts through the PR4 path give the
        # decode_stall_steps / ITL baseline the fused run must beat
        base = _serve(params, cfg, prompts, fused_prefill=False, **kw)
    spec = None
    if workload == "speculative":
        # plain reference first (its numbers double as this
        # workload's base JSON), then the spec run with the
        # bit-identical / tokens-per-step / zero-recompile gates
        spec = _spec_leg(params, cfg, prompts, spec_tree=spec_tree,
                         **kw)
        r0 = spec.pop("_ref")
    quant = None
    if workload == "quantized":
        # the fp/w8/int8-KV/w8+int8-KV matrix with its warm==cold,
        # recompile, gather-bytes and divergence gates; the plain
        # fp _serve below still provides the base JSON numbers
        quant = _quantized_gates(
            params, cfg, prompts, kw["budgets"],
            **{k: v for k, v in kw.items() if k != "budgets"})
    disagg = None
    if workload == "disagg":
        # the disaggregated prefill/decode matrix (fp + w8/int8-KV)
        # with its bit-identity / zero-decode-prefill / one-migration-
        # per-request / zero-recompile gates; the plain fp _serve
        # below still provides the base JSON numbers
        disagg = _disagg_gates(
            params, cfg, prompts, kw["budgets"],
            **{k: v for k, v in kw.items() if k != "budgets"})
    slo = None
    if workload == "slo":
        # sampled device timing must be nearly free: a discarded leg
        # burns process warm-up, then an ABBA sequence — sampling off,
        # on, on, off — so each side runs once early and once late and
        # first-order warm-state drift cancels from the pooled tok/s
        # (the --trace-overhead methodology, applied to the fence)
        kw_on = dict(kw, profile_sample_every=4)
        _serve(params, cfg, prompts, fused_prefill=True, **kw)
        u1 = _serve(params, cfg, prompts, fused_prefill=True, **kw)
        s1 = _serve(params, cfg, prompts, fused_prefill=True, **kw_on)
        s2 = _serve(params, cfg, prompts, fused_prefill=True, **kw_on)
        u2 = _serve(params, cfg, prompts, fused_prefill=True, **kw)
        tok_off = (u1["tok_s"] + u2["tok_s"]) / 2
        tok_on = (s1["tok_s"] + s2["tok_s"]) / 2
        ratio = tok_on / tok_off
        samples = s1["profile_samples"] + s2["profile_samples"]
        recompiles = sum(x["recompiles"] for x in (u1, s1, s2, u2))
        if samples < 1:
            raise RuntimeError(
                "slo gate: the sampled legs fenced ZERO steps — the "
                "overhead comparison is vacuous (sample_every too "
                "large for this workload?)")
        if recompiles:
            raise RuntimeError(
                f"slo gate: {recompiles} post-warmup recompiles across "
                f"the sampling legs — the fence touched the "
                f"compiled-shape memo")
        if ratio < 0.97:
            raise RuntimeError(
                f"slo gate: sampled run at {ratio:.3f}x the "
                f"sampling-off tok/s (floor 0.97x) — the device-time "
                f"fence is no longer cheap enough to leave on")
        slo = {
            "slo_tok_s_sampling_off": round(tok_off, 1),
            "slo_tok_s_sampling_on": round(tok_on, 1),
            "slo_sampling_overhead_ratio": round(ratio, 4),
            "slo_profile_samples": samples,
        }
        slo.update(_slo_breach_leg(
            params, cfg, prompts, kw["budgets"],
            **{k: v for k, v in kw.items() if k != "budgets"}))
        r0 = u1           # the first clean leg doubles as the numbers
    routed = None
    if workload in ("router", "restart"):
        # single-engine leg first: its per-request tokens are the
        # parity reference the 2-replica HTTP run must reproduce
        # bit-identically (and it provides this workload's base JSON
        # numbers); then the router+frontend leg with its failover
        # gate — or, for --restart, the self-healing leg that also
        # demands the dead slot respawn, rejoin and serve
        r0 = _serve(params, cfg, prompts, fused_prefill=True, **kw)
        base_tokens = [q.result() for q in r0["reqs"]]
        leg = _restart_leg if workload == "restart" else _router_leg
        routed = leg(
            params, cfg, prompts, kw["budgets"], base_tokens,
            **{k: v for k, v in kw.items() if k != "budgets"})
    chaos = None
    if workload == "chaos":
        # fault-free leg first: its per-request tokens are the parity
        # baseline the chaos engine's survivors must reproduce bit-
        # identically (and it doubles as this workload's JSON numbers)
        r0 = _serve(params, cfg, prompts, fused_prefill=True, **kw)
        base_tokens = [q.result() for q in r0["reqs"]]
        # the culprit must still be DECODING when its first-token
        # poison arms, or the fault can never fire mid-stream — pick
        # the request with the largest decode budget
        culprit = max(range(len(prompts)), key=lambda i: kw["budgets"][i])
        chaos = _chaos_leg(
            params, cfg, prompts, kw["budgets"], culprit, base_tokens,
            **{k: v for k, v in kw.items() if k != "budgets"})
    untraced = None
    if trace_overhead:
        # the tracing-overhead gate needs BIAS-FREE legs: the first
        # engine lifecycle in a process absorbs one-time warm state
        # (jax platform init, compilation cache) and later lifecycles
        # keep getting gradually warmer, so any fixed leg order hands
        # one side a systematic advantage bigger than the 3% floor.
        # Burn the one-time warm-up on a DISCARDED run, then measure
        # an ABBA sequence (untraced, traced, traced, untraced) and
        # compare pooled tok/s — first-order drift cancels because
        # each side runs once early and once late.
        _serve(params, cfg, prompts, fused_prefill=True,
               trace=False, **kw)
        u1 = _serve(params, cfg, prompts, fused_prefill=True,
                    trace=False, **kw)
        t1 = _serve(params, cfg, prompts, fused_prefill=True, **kw)
        t2 = _serve(params, cfg, prompts, fused_prefill=True, **kw)
        u2 = _serve(params, cfg, prompts, fused_prefill=True,
                    trace=False, **kw)
        untraced = u1
        untraced["tok_s"] = (u1["tok_s"] + u2["tok_s"]) / 2
        untraced["recompiles"] = u1["recompiles"] + u2["recompiles"]
        r = t1
        r["tok_s"] = (t1["tok_s"] + t2["tok_s"]) / 2
        r["recompiles"] = t1["recompiles"] + t2["recompiles"]
    elif chaos is not None or routed is not None or slo is not None \
            or spec is not None:
        r = r0            # the reference leg doubles as the numbers
    else:
        r = _serve(params, cfg, prompts, fused_prefill=True, **kw)

    reqs, snap = r["reqs"], r["snap"]
    ttft = np.asarray([q.first_token_time - q.submit_time for q in reqs])
    wait = np.asarray([q.admit_time - q.submit_time for q in reqs])
    pct = lambda a, q: round(float(np.percentile(a, q)), 4)
    result = {
        "metric": "serving_offline_tok_s",
        "value": round(r["tok_s"], 1),
        "unit": "tokens/s",
        "workload": workload,
        "attention_impl": r["attention_impl"],
        "decode_tok_s": (round(r["decode_tok_s"], 1)
                         if r["decode_tok_s"] else None),
        "fused_units": fused_units,
        "fused_unit_count": r["fused_unit_count"],
        "n_requests": n_requests,
        "max_batch": max_batch,
        "max_new_tokens": max_new,
        "wall_s": round(r["wall_s"], 3),
        "warmup_s": round(r["warmup_s"], 3),
        "ttft_s_p50": pct(ttft, 50),
        "ttft_s_p90": pct(ttft, 90),
        "ttft_s_p99": pct(ttft, 99),
        "queue_wait_s_p50": pct(wait, 50),
        "queue_wait_s_p90": pct(wait, 90),
        "queue_wait_s_p99": pct(wait, 99),
        "itl_ms_p50": r["itl_ms_p50"],
        "itl_ms_p99": r["itl_ms_p99"],
        "step_s_p50": snap["histograms"]["serving.step_s"].get("p50"),
        "per_token_s_p50": snap["histograms"]["per_token_s"].get("p50"),
        "requests_completed": snap["counters"]["requests_completed"]
        - r["completed0"],
        "kv_high_water_blocks": snap["allocator"]["high_water_blocks"],
        "kv_reused_blocks": snap["allocator"]["reused_blocks"],
        "prefill_buckets": r["buckets"],
        "prefill_shapes_warmed": r["warmed"],
        "prefill_compile_count": r["compile_count"],
        "compile_count": r["compile_count_total"],
        "prefill_recompiles_after_warmup": r["recompiles"],
        "prefill_pad_tokens": r["pad_tokens"],
        "prefill_suffix_hist": r["suffix_hist"],
        "fused_steps": r["fused_steps"],
        "decode_stall_steps": r["decode_stall_steps"],
        # resolved quantization config + byte accounting (bucket_tuner
        # reads kv_bytes_per_token to price pad tokens in gather bytes)
        "weight_dtype": snap["quantization"]["weight_dtype"],
        "kv_dtype": snap["quantization"]["kv_dtype"],
        "kv_bytes_per_token": snap["quantization"]["kv_bytes_per_token"],
        "kv_pool_bytes": snap["quantization"]["kv_pool_bytes"],
    }
    pc = snap["prefix_cache"]
    if pc.get("enabled"):
        # deltas over the timed window (the warmup request primed the
        # cache but must not count as a hit)
        lookups = pc["prompt_tokens"] - r["pc0"]["prompt_tokens"]
        saved = pc["hit_tokens"] - r["pc0"]["hit_tokens"]
        result.update({
            "prefix_cache_hit_rate": round(saved / lookups, 4)
            if lookups else 0.0,
            "prefill_tokens_saved": saved,
            "prefix_cache_evictions": pc["evicted_blocks"],
            "prefix_cache_cached_blocks": pc["cached_blocks"],
        })
    if base is not None:
        result.update({
            "tok_s_unfused": round(base["tok_s"], 1),
            "decode_stall_steps_unfused": base["decode_stall_steps"],
            "itl_ms_p50_unfused": base["itl_ms_p50"],
            "itl_ms_p99_unfused": base["itl_ms_p99"],
        })
        if base["decode_stall_steps"] == 0:
            raise RuntimeError(
                "unfused baseline recorded ZERO decode stalls — the "
                "workload produced no admission-during-decode overlap "
                "(raise n_requests vs max_batch, or lower chunk), so "
                "the fused-vs-unfused comparison is vacuous")
        if not (r["decode_stall_steps"] < base["decode_stall_steps"]):
            raise RuntimeError(
                f"fused run stalled decode {r['decode_stall_steps']} "
                f"times vs {base['decode_stall_steps']} unfused — "
                f"piggybacked admission is not overlapping prefill "
                f"with in-flight decode")
    if trace_path is not None:
        # the Chrome-trace/Perfetto artifact: per-request timelines on
        # slot lanes + the engine step spans, straight off the sink
        chrome = r["trace"].to_chrome_trace()
        with open(trace_path, "w") as f:
            json.dump(chrome, f)
        result["trace_path"] = trace_path
        result["trace_events"] = len(chrome["traceEvents"])
    if untraced is not None:
        ratio = r["tok_s"] / untraced["tok_s"]
        result["tok_s_untraced"] = round(untraced["tok_s"], 1)
        result["trace_overhead_ratio"] = round(ratio, 4)
        if r["recompiles"] or untraced["recompiles"]:
            raise RuntimeError(
                f"tracing-overhead run recompiled after warmup "
                f"(traced {r['recompiles']}, untraced "
                f"{untraced['recompiles']}) — trace emission must not "
                f"touch compiled-shape memo keys")
        if ratio < 0.97:
            raise RuntimeError(
                f"tracing overhead gate: traced run at {ratio:.3f}x "
                f"the untraced tok/s (floor 0.97x) — trace emission "
                f"is no longer always-on-cheap")
    if chaos is not None:
        result.update(chaos)
    if routed is not None:
        result.update(routed)
    if quant is not None:
        result.update(quant)
    if disagg is not None:
        result.update(disagg)
    if slo is not None:
        result.update(slo)
    if spec is not None:
        result.update(spec)
    if workload in ("mixed", "fused", "chaos", "quantized", "router",
                    "restart", "slo", "speculative", "disagg") \
            and r["recompiles"]:
        raise RuntimeError(
            f"bucketed workload recompiled {r['recompiles']} prefill "
            f"shapes after warmup — the bucket ladder no longer covers "
            f"admission (warmed {r['warmed']}, buckets {r['buckets']})")
    return result


def _cli() -> dict:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--prefix-share", action="store_true",
                    help="N requests sharing a common prompt prefix "
                         "(exercises the prefix cache)")
    ap.add_argument("--bucketed", action="store_true",
                    help="mixed-length workload spanning every prefill "
                         "bucket; asserts zero recompiles after warmup")
    ap.add_argument("--fused", action="store_true",
                    help="admission-during-decode workload run fused "
                         "AND unfused; asserts the fused run stalls "
                         "decode less and never recompiles")
    ap.add_argument("--chaos", action="store_true",
                    help="fault-isolation gate: re-serve the workload "
                         "with a seeded mid-stream fail-on-rid poison; "
                         "HARD-FAILS unless the culprit alone FAILS, "
                         "every innocent finishes bit-identical to the "
                         "fault-free run, recompiles stay 0 and the "
                         "pool drains clean")
    ap.add_argument("--router", action="store_true",
                    help="multi-replica failover gate: 2 ServingEngine "
                         "replicas behind Router + HttpFrontend serve "
                         "the mixed workload as concurrent SSE streams "
                         "over a real socket; a seeded chaos hang "
                         "poisons the victim's replica mid-stream; "
                         "HARD-FAILS unless the victim completes on "
                         "the survivor (pre-failover stream a strict "
                         "prefix), every request bit-matches the "
                         "single-engine reference, and recompiles "
                         "stay 0 on both replicas")
    ap.add_argument("--restart", action="store_true",
                    help="self-healing gate: like --router (a chaos "
                         "hang kills the victim's replica mid-stream, "
                         "stranded SSE streams must fail over with "
                         "the strict-prefix invariant) but with "
                         "auto_restart on; HARD-FAILS unless the dead "
                         "slot is respawned through the supervisor's "
                         "readiness gate, rejoins rotation and serves "
                         "a post-restart request with zero recompiles "
                         "on every engine incarnation")
    ap.add_argument("--slo", action="store_true",
                    help="SLO-engine gate: the mixed workload with "
                         "sampled device timing on vs off (HARD-FAILS "
                         "unless sampled tok/s >= 0.97x with zero "
                         "recompiles), then a 1-replica Router + "
                         "frontend leg where injected 4s step hangs "
                         "(short of the watchdog) must drive an "
                         "itl_ms_p99 BREACH visible end-to-end — "
                         "engine health, router rollup, /health "
                         "detail (still 200), slo_breaches_total in "
                         "the merged /metrics — and CLEAR after the "
                         "fault heals; plus a /debug/profile capture "
                         "window landing device-wall spans in the "
                         "merged trace")
    ap.add_argument("--speculative", action="store_true",
                    help="self-speculative decoding gate: the shared-"
                         "prefix workload runs plain, then with a "
                         "chain draft, then with a TREE draft (shape "
                         "from --spec-tree); HARD-FAILS unless both "
                         "spec outputs are bit-identical to the plain "
                         "greedy reference, accepted tokens/step > 1, "
                         "tree accepted/sweep >= chain's, and "
                         "recompiles stay 0; emits spec_accept_rate, "
                         "spec_tree_* and decode_tok_s_spec* fields")
    ap.add_argument("--spec-tree", default="2,1,1,1",
                    help="branching spec for the --speculative tree "
                         "leg, comma-separated per-level factors "
                         "(default 2,1,1,1: two candidates for the "
                         "first token, chains below — depth equals "
                         "the chain leg's k so the acceptance "
                         "comparison is budget-fair)")
    ap.add_argument("--load", action="store_true",
                    help="closed-loop load generator: Poisson session "
                         "arrivals, multi-turn rounds, shared system "
                         "prompts; emits goodput (completed-within-"
                         "deadline tok/s) and latency percentiles "
                         "under load. Combine with --router to run "
                         "the generator through a 2-replica Router "
                         "(multi-replica goodput scaling)")
    ap.add_argument("--sessions", type=int, default=6,
                    help="concurrent client sessions for --load")
    ap.add_argument("--turns", type=int, default=3,
                    help="multi-turn rounds per session for --load")
    ap.add_argument("--arrival-rate", type=float, default=8.0,
                    help="Poisson session arrival rate (1/s) for --load")
    ap.add_argument("--deadline-s", type=float, default=5.0,
                    help="per-request goodput deadline for --load")
    ap.add_argument("--quantized", action="store_true",
                    help="quantized-serving gate: the same workload "
                         "through fp, w8, int8-KV and w8+int8-KV "
                         "engines; HARD-FAILS on any post-warmup "
                         "recompile, any warm-vs-cold token mismatch, "
                         "int8 KV gather bytes > 0.55x fp, or "
                         "quantized-vs-fp greedy divergence below the "
                         "documented floor")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode gate: the "
                         "mixed workload through a monolithic "
                         "reference engine, then through "
                         "Router(disaggregated=True) with one "
                         "prefill-role and one decode-role replica "
                         "(KVSnapshot export/import per request), fp "
                         "AND w8+int8-KV; HARD-FAILS unless the "
                         "disaggregated streams are bit-identical to "
                         "the monolithic run, the decode replica ran "
                         "zero prefill chunks, every request migrated "
                         "exactly once, the int8 leg holds the "
                         "documented fp-match floor and recompiles "
                         "stay 0 on both replicas; emits migration "
                         "count/bytes and handoff latency")
    ap.add_argument("--tp", action="store_true",
                    help="tensor-parallel gate (forces 4 host devices "
                         "at module import): the mixed workload "
                         "single-device, then through a TP=4 mesh "
                         "engine with Megatron-sharded weights and a "
                         "head-sharded paged-KV pool; HARD-FAILS "
                         "unless TP output is bit-identical to "
                         "single-device, post-warmup recompiles stay "
                         "0 on both engines, and a TP=2-sharded "
                         "replica pair survives the --restart chaos "
                         "shape (failover + supervisor respawn of a "
                         "sharded slot). Composes with --speculative "
                         "(tree spec on the sharded engine) and "
                         "--attention-impl pallas (the ragged kernel "
                         "shard_map-wrapped on the mesh)")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="serve with the prefix cache disabled")
    ap.add_argument("--attention-impl", default="auto",
                    choices=("auto", "xla", "pallas"),
                    help="paged-attention backend: xla reference "
                         "gather, pallas ragged kernel (interpret mode "
                         "off-TPU — parity, not speed), or auto "
                         "(pallas on TPU, xla elsewhere); the JSON "
                         "line records the RESOLVED impl")
    ap.add_argument("--fused-units", type=int, default=1,
                    help="max pending prefill units one fused step "
                         "carries (PR 5 follow-on: >1 drains "
                         "admission bursts faster under decode load)")
    ap.add_argument("--trace", metavar="PATH", default=None,
                    help="write the run's per-request trace timelines "
                         "as Chrome-trace/Perfetto JSON to PATH "
                         "(load in ui.perfetto.dev; summarize with "
                         "tools/trace_report.py)")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="run a discarded warm-up leg, then an ABBA "
                         "untraced/traced sequence (order bias "
                         "cancels); HARD-FAIL unless pooled traced "
                         "tok/s >= 0.97x pooled untraced with zero "
                         "post-warmup recompiles (the always-on-"
                         "cheap gate)")
    ap.add_argument("--n-requests", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=None,
                    help="decode chunk length (default 4; 2 for "
                         "--fused so staggered budgets desync the "
                         "batch and admissions land mid-decode)")
    ap.add_argument("--prefix-len", type=int, default=24,
                    help="shared prefix length for --prefix-share")
    ap.add_argument("--suffix-len", type=int, default=6,
                    help="per-request suffix length for --prefix-share")
    ap.add_argument("--max-prefill-bucket", type=int, default=None,
                    help="cap the prefill bucket ladder (default 512; "
                         "16 for --bucketed/--fused so the workload "
                         "chunks)")
    a = ap.parse_args()
    # two legal combinations: --load --router (the load generator
    # through the Router) and --tp --speculative (the fast-path
    # composition gate: tree speculation on the TP=4 mesh engine —
    # add --attention-impl pallas for the full mesh x kernel x spec
    # composition); every other pairing stays exclusive
    load_router = a.load and a.router
    if load_router:
        a.router = False
    tp_spec = a.tp and a.speculative
    if tp_spec:
        a.speculative = False
    if sum((a.prefix_share, a.bucketed, a.fused, a.chaos,
            a.quantized, a.router, a.restart, a.slo, a.speculative,
            a.disagg, a.load, a.tp)) > 1:
        ap.error("--prefix-share, --bucketed, --fused, --chaos, "
                 "--quantized, --router, --restart, --slo, "
                 "--speculative, --disagg, --load and --tp are "
                 "mutually exclusive (except --load --router and "
                 "--tp --speculative)")
    workload = ("prefix-share" if a.prefix_share
                else "mixed" if a.bucketed
                else "fused" if a.fused
                else "chaos" if a.chaos
                else "quantized" if a.quantized
                else "router" if a.router
                else "restart" if a.restart
                else "slo" if a.slo
                else "speculative" if a.speculative
                else "disagg" if a.disagg
                else "tp" if a.tp
                else "load" if a.load else "random")
    bucket_cap = a.max_prefill_bucket
    if bucket_cap is None:
        # the mixed/fused/chaos/quantized/router/restart/slo workloads
        # should also exercise CHUNKED prefill, so cap the ladder below
        # their longest prompts (load's multi-turn histories chunk too)
        bucket_cap = (16 if workload in ("mixed", "fused", "chaos",
                                         "quantized", "router",
                                         "restart", "slo", "load",
                                         "speculative", "disagg",
                                         "tp")
                      else 512)
    chunk = (a.chunk if a.chunk is not None
             else 2 if workload in ("fused", "prefix-share", "chaos",
                                    "quantized", "router", "restart",
                                    "slo", "speculative", "disagg",
                                    "tp")
             else 4)
    return main(n_requests=a.n_requests, max_new=a.max_new,
                max_batch=a.max_batch, block_size=a.block_size,
                chunk=chunk, workload=workload,
                prefix_len=a.prefix_len, suffix_len=a.suffix_len,
                prefix_cache=not a.no_prefix_cache,
                max_prefill_bucket=bucket_cap,
                attention_impl=a.attention_impl,
                fused_units=a.fused_units,
                sessions=a.sessions, turns=a.turns,
                rate_hz=a.arrival_rate, deadline_s=a.deadline_s,
                load_router_replicas=2 if load_router else 0,
                spec_tree=tuple(int(b) for b in
                                a.spec_tree.split(",") if b.strip()),
                tp_speculative=tp_spec,
                trace_path=a.trace, trace_overhead=a.trace_overhead)


if __name__ == "__main__":
    print(json.dumps(_cli()))
