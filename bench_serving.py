"""Serving benchmark: offline throughput + latency percentiles through
the ServingEngine on the CPU backend.

Prints ONE JSON line (bench.py convention, landed alongside the
BENCH_*.json records): generated tokens/s end-to-end through the full
admission→batcher→channel path, plus TTFT and queue-wait percentiles —
the serving-layer numbers the device-side decode benches in bench.py
cannot see (queueing, scheduling, host fan-out overhead).

Workloads:
  * `random` (default) — independent prompts of random lengths, the
    original scheduling/overhead bench;
  * `prefix-share` (`--prefix-share`) — N requests sharing one common
    prompt prefix (the system-prompt / few-shot pattern), exercising the
    `serving.cache` prefix cache: the JSON line gains
    `prefix_cache_hit_rate` and `prefill_tokens_saved`;
  * `mixed` (`--bucketed`) — prompt lengths spread wide enough to span
    every prefill bucket AND chunk past the largest one, exercising the
    bucketed/chunked prefill path. Asserts ZERO prefill recompiles after
    warmup (the TTFT story: admission dispatches to pre-compiled
    shapes), so a recompile regression fails the bench.

Warmup pre-compiles EVERY prefill bucket shape via `engine.warmup()`
(AOT lowering — no device compute) plus one served request for the
decode chunk fn; before it, the first timed request of each new prompt
length ate a fresh XLA trace+compile and TTFT p99 measured the compiler,
not the server.

Deliberately a tiny model on CPU: this measures the HOST serving layer's
overhead and scheduling behavior deterministically; device-side decode
throughput is bench.py's `decode_tok_s`.
"""
from __future__ import annotations

import argparse
import json
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def _make_prompts(rng, n_requests: int, workload: str,
                  prefix_len: int, suffix_len: int):
    if workload == "prefix-share":
        common = list(map(int, rng.randint(1, 200, prefix_len)))
        return [common + list(map(int, rng.randint(1, 200, suffix_len)))
                for _ in range(n_requests)]
    if workload == "mixed":
        # lengths spanning the whole ladder, incl. past the largest
        # bucket (chunked prefill) — every request a different length
        return [list(map(int, rng.randint(1, 200, int(L))))
                for L in rng.randint(3, 41, n_requests)]
    return [list(map(int, rng.randint(1, 200, int(L))))
            for L in rng.randint(4, 16, n_requests)]


def main(n_requests: int = 16, max_new: int = 8, max_batch: int = 4,
         block_size: int = 8, chunk: int = 4, workload: str = "random",
         prefix_len: int = 24, suffix_len: int = 6,
         prefix_cache: bool = True,
         max_prefill_bucket: int = 512) -> dict:
    import jax
    from paddle_tpu.nlp import llama
    from paddle_tpu import serving

    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    prompts = _make_prompts(rng, n_requests, workload,
                            prefix_len, suffix_len)

    eng = serving.ServingEngine(
        params, cfg, max_batch=max_batch, block_size=block_size,
        max_total_len=64, max_new_tokens=max_new, chunk=chunk,
        max_queue_depth=n_requests, prefix_cache=prefix_cache,
        max_prefill_bucket=max_prefill_bucket, start=False)
    # warmup: AOT-compile EVERY prefill bucket shape (group ladder x
    # bucket ladder x cold/cached) before the loop starts, then serve
    # one request to compile the decode chunk fn (for prefix-share it
    # also PRIMES the cache — the steady-state view a shared system
    # prompt actually serves under)
    t_w = time.perf_counter()
    warmed = eng.warmup()
    eng.start()
    eng.generate(prompts[0], timeout=600)
    warmup_s = time.perf_counter() - t_w
    completed0 = eng.metrics.counter("requests_completed").value
    pc0 = eng.snapshot()["prefix_cache"]
    compiles_warm = eng.batcher.prefill_compile_count

    t0 = time.perf_counter()
    reqs = [eng.submit(p) for p in prompts]
    if not eng.drain(timeout=600):
        raise RuntimeError("drain timed out — benchmark invalid")
    wall = time.perf_counter() - t0
    eng.shutdown()

    toks = sum(len(r.result()) for r in reqs)
    ttft = np.asarray([r.first_token_time - r.submit_time for r in reqs])
    wait = np.asarray([r.admit_time - r.submit_time for r in reqs])
    snap = eng.snapshot()
    recompiles = eng.batcher.prefill_compile_count - compiles_warm
    pct = lambda a, q: round(float(np.percentile(a, q)), 4)
    result = {
        "metric": "serving_offline_tok_s",
        "value": round(toks / wall, 1),
        "unit": "tokens/s",
        "workload": workload,
        "n_requests": n_requests,
        "max_batch": max_batch,
        "max_new_tokens": max_new,
        "wall_s": round(wall, 3),
        "warmup_s": round(warmup_s, 3),
        "ttft_s_p50": pct(ttft, 50),
        "ttft_s_p90": pct(ttft, 90),
        "ttft_s_p99": pct(ttft, 99),
        "queue_wait_s_p50": pct(wait, 50),
        "queue_wait_s_p90": pct(wait, 90),
        "queue_wait_s_p99": pct(wait, 99),
        "step_s_p50": snap["histograms"]["serving.step_s"].get("p50"),
        "per_token_s_p50": snap["histograms"]["per_token_s"].get("p50"),
        "requests_completed": snap["counters"]["requests_completed"]
        - completed0,
        "kv_high_water_blocks": snap["allocator"]["high_water_blocks"],
        "kv_reused_blocks": snap["allocator"]["reused_blocks"],
        "prefill_buckets": list(eng.batcher.prefill_buckets),
        "prefill_shapes_warmed": warmed,
        "prefill_compile_count": eng.batcher.prefill_compile_count,
        "prefill_recompiles_after_warmup": recompiles,
        "prefill_pad_tokens": eng.batcher.prefill_pad_tokens,
    }
    pc = snap["prefix_cache"]
    if pc.get("enabled"):
        # deltas over the timed window (the warmup request primed the
        # cache but must not count as a hit)
        lookups = pc["prompt_tokens"] - pc0["prompt_tokens"]
        saved = pc["hit_tokens"] - pc0["hit_tokens"]
        result.update({
            "prefix_cache_hit_rate": round(saved / lookups, 4)
            if lookups else 0.0,
            "prefill_tokens_saved": saved,
            "prefix_cache_evictions": pc["evicted_blocks"],
            "prefix_cache_cached_blocks": pc["cached_blocks"],
        })
    if workload == "mixed" and recompiles:
        raise RuntimeError(
            f"bucketed workload recompiled {recompiles} prefill shapes "
            f"after warmup — the bucket ladder no longer covers "
            f"admission (warmed {warmed}, buckets "
            f"{list(eng.batcher.prefill_buckets)})")
    return result


def _cli() -> dict:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--prefix-share", action="store_true",
                    help="N requests sharing a common prompt prefix "
                         "(exercises the prefix cache)")
    ap.add_argument("--bucketed", action="store_true",
                    help="mixed-length workload spanning every prefill "
                         "bucket; asserts zero recompiles after warmup")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="serve with the prefix cache disabled")
    ap.add_argument("--n-requests", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--prefix-len", type=int, default=24,
                    help="shared prefix length for --prefix-share")
    ap.add_argument("--suffix-len", type=int, default=6,
                    help="per-request suffix length for --prefix-share")
    ap.add_argument("--max-prefill-bucket", type=int, default=None,
                    help="cap the prefill bucket ladder (default 512; "
                         "16 for --bucketed so the workload chunks)")
    a = ap.parse_args()
    if a.prefix_share and a.bucketed:
        ap.error("--prefix-share and --bucketed are mutually exclusive")
    workload = ("prefix-share" if a.prefix_share
                else "mixed" if a.bucketed else "random")
    bucket_cap = a.max_prefill_bucket
    if bucket_cap is None:
        # the mixed workload should also exercise CHUNKED prefill, so
        # cap the ladder below its longest prompts by default
        bucket_cap = 16 if a.bucketed else 512
    return main(n_requests=a.n_requests, max_new=a.max_new,
                max_batch=a.max_batch, block_size=a.block_size,
                chunk=a.chunk, workload=workload,
                prefix_len=a.prefix_len, suffix_len=a.suffix_len,
                prefix_cache=not a.no_prefix_cache,
                max_prefill_bucket=bucket_cap)


if __name__ == "__main__":
    print(json.dumps(_cli()))
