"""Bucketed / chunked / jit-cached prefill in the continuous batcher.

Three layers, cheapest first:

  * ladder/chunking units — bucket selection, chunk splitting, group
    padding (pure host logic on a built batcher, no model compute);
  * token-level parity — bucketed == unbucketed and chunked == whole,
    incl. the prefix-cache interplay (suffix chunking after a cached
    chain, the COW full-hit whose padded bucket crosses a block
    boundary) and co-batched neighbors staying uncorrupted;
  * compile-count accounting — admissions draw from a FIXED shape set:
    repeat lengths in the same bucket add zero compiles, warmup_prefill
    pre-compiles the whole ladder so serving never traces, and a
    same-bucket burst prefills in ONE batched call.
"""
import numpy as np
import pytest
import jax

from paddle_tpu.nlp import llama, paged


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _batcher(params, cfg, max_new=6, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_total_len", 32)
    kw.setdefault("chunk", 3)
    return paged.ContinuousBatcher(params, cfg, max_new_tokens=max_new,
                                   **kw)


def _run(params, cfg, prompts, max_new=6, **kw):
    cb = _batcher(params, cfg, max_new=max_new, **kw)
    rids = [cb.submit(p) for p in prompts]
    out = cb.run()
    return [out[r] for r in rids], cb


def _prompts(seed, lengths):
    rng = np.random.RandomState(seed)
    return [list(map(int, rng.randint(1, 200, n))) for n in lengths]


class TestBucketLadder:
    def test_auto_ladder_and_bucket_for(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg)                    # max_total_len=32
        assert cb.prefill_buckets == (8, 16, 32)
        assert cb._bucket_for(1) == 8
        assert cb._bucket_for(8) == 8
        assert cb._bucket_for(9) == 16
        assert cb._bucket_for(32) == 32

    def test_explicit_and_disabled_ladder(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg, prefill_buckets=(4, 12))
        assert cb.prefill_buckets == (4, 12)
        off = _batcher(params, cfg, prefill_buckets=())
        assert off.prefill_buckets == ()
        assert off._bucket_for(13) == 13              # exact shapes
        with pytest.raises(ValueError, match="positive"):
            _batcher(params, cfg, prefill_buckets=(0, 4))

    def test_ladder_caps_at_max_prefill_bucket(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg, max_prefill_bucket=16)
        assert cb.prefill_buckets == (8, 16)
        # non-pow2 table span: the top bucket is the span itself, never
        # a power of two PAST it (33..47-token suffixes would only buy
        # pad tokens from a 64 bucket)
        cb = _batcher(params, cfg, max_total_len=48, block_size=8)
        assert cb.prefill_buckets == (8, 16, 32, 48)

    def test_suffix_chunking_rule(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg, max_prefill_bucket=8)   # ladder (8,)
        # 20-token cold suffix → two full 8-chunks + a bucketed tail
        assert cb._suffix_chunks(0, 20) == [(0, 8, 8), (8, 16, 8),
                                            (16, 20, 8)]
        # warm suffix starts at the cached length
        assert cb._suffix_chunks(8, 13) == [(8, 13, 8)]
        # disabled bucketing: one exact-shape pass, never chunks
        off = _batcher(params, cfg, prefill_buckets=())
        assert off._suffix_chunks(0, 20) == [(0, 20, 20)]

    def test_group_padding_ladder(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg, max_batch=4)
        assert [cb._group_pad(g) for g in (1, 2, 3, 4)] == [1, 2, 4, 4]


class TestPrefillParity:
    """Acceptance: the bucketed/chunked pipeline is token-identical to
    the exact-shape path — padding and chunk seams must be invisible."""

    def test_bucketed_matches_unbucketed(self, setup):
        cfg, params = setup
        prompts = _prompts(31, (3, 5, 9, 13))         # two buckets
        base, _ = _run(params, cfg, prompts, prefill_buckets=())
        buck, cb = _run(params, cfg, prompts)
        assert buck == base
        assert cb.prefill_pad_tokens > 0              # padding happened

    def test_chunked_matches_whole(self, setup):
        cfg, params = setup
        prompts = _prompts(32, (18, 21))              # > largest bucket 4
        base, _ = _run(params, cfg, prompts, prefill_buckets=())
        chunked, cb = _run(params, cfg, prompts, prefill_buckets=(4,))
        assert chunked == base
        # 18 cold tokens = 4 full chunks + a 2-token tail → ≥ 5 calls
        assert cb.prefill_compile_count >= 1

    def test_padded_bucket_crossing_block_boundary(self, setup):
        """A 5-token prompt pads to bucket 8 with block_size 4: the pad
        region spans the first block's tail AND the whole second block.
        The dropped pad writes must not corrupt either the request's own
        later decode or its co-batched neighbor."""
        cfg, params = setup
        prompts = _prompts(33, (5, 11))
        base, _ = _run(params, cfg, prompts, prefill_buckets=())
        buck, _ = _run(params, cfg, prompts, prefill_buckets=(8, 16))
        assert buck == base

    def test_chunked_suffix_after_cached_prefix(self, setup):
        """Warm path x chunking: a prompt whose prefix chain is cached
        (including blocks a COW admission produced) and whose LONG
        suffix chunks through the paged per-query-causal path."""
        cfg, params = setup
        rng = np.random.RandomState(34)
        head = list(map(int, rng.randint(1, 200, 8)))   # 2 full blocks
        long_tail = list(map(int, rng.randint(1, 200, 14)))
        cold, _ = _run(params, cfg, [head + long_tail],
                       prefill_buckets=())
        cb = _batcher(params, cfg, max_batch=1, prefill_buckets=(4,),
                      prefix_cache=True)
        r0 = cb.submit(head)          # seeds the cache with head's blocks
        cb.run()
        r1 = cb.submit(head)          # full hit → COW tail clone
        cb.run()
        r2 = cb.submit(head + long_tail)   # cached prefix + chunked tail
        out = cb.run()
        assert out[r2] == cold[0]
        st = cb.prefix_stats()
        assert st["hit_tokens"] >= 7 + 8       # r1 COW (P-1) + r2 chain
        cold_head, _ = _run(params, cfg, [head], prefill_buckets=())
        assert out[r0] == cold_head[0] and out[r1] == cold_head[0]

    def test_cow_full_hit_padded_across_block_boundary(self, setup):
        """The COW full-hit recomputes ONE token at position P-1
        (mid-block); its bucket pads past the block boundary into the
        next block. Output must match cold, and the pool must drain."""
        cfg, params = setup
        rng = np.random.RandomState(35)
        p = list(map(int, rng.randint(1, 200, 8)))    # exactly 2 blocks
        cold, _ = _run(params, cfg, [p], prefill_buckets=())
        cb = _batcher(params, cfg, max_batch=1, prefill_buckets=(4, 8),
                      prefix_cache=True)
        r1 = cb.submit(p)
        cb.run()
        r2 = cb.submit(p)                             # full hit → COW
        out = cb.run()
        assert out[r1] == cold[0] and out[r2] == cold[0]
        assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_same_burst_cow_on_inflight_sibling(self, setup):
        """Regression: two IDENTICAL prompts admitted in ONE burst — the
        second full-hits on blocks the first registered moments earlier
        with its prefill still pending. The COW clone must wait until
        the source's unit has written the pool (it once cloned zeros and
        corrupted the second request's decode context)."""
        cfg, params = setup
        for seed in (52, 53, 56, 63):     # seeds that caught the bug
            rng = np.random.RandomState(seed)
            p = list(map(int, rng.randint(1, 200, 12)))  # 3 full blocks
            cold, _ = _run(params, cfg, [p], max_batch=1)
            cb = _batcher(params, cfg, prefix_cache=True)
            ra, rb = cb.submit(p), cb.submit(p)
            cb.step()                     # one burst admits both
            out = cb.run()
            assert out[ra] == cold[0]
            assert out[rb] == cold[0], f"seed {seed}: COW read stale KV"
            assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_failed_prefill_rolls_back_whole_burst(self, setup,
                                                   monkeypatch):
        """A prefill failure mid-burst must return EVERY prepared
        request's blocks (none of the slots activated) — the engine's
        exception boundary relies on it."""
        cfg, params = setup
        cb = _batcher(params, cfg, prefix_cache=True)
        monkeypatch.setattr(
            paged, "forward_paged",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
        for p in _prompts(36, (5, 7)):
            cb.submit(p)
        with pytest.raises(RuntimeError, match="boom"):
            cb.run()
        assert cb.alloc.stats()["blocks_in_use"] == 0
        assert cb.active == [False, False]
        # undoing never-written registrations is NOT pool pressure:
        # neither the index's eviction counter nor the allocator's moves
        assert cb.prefix_stats()["evicted_blocks"] == 0
        assert cb.prefix_stats()["evictions"] == 0


class TestCompileAccounting:
    def test_same_bucket_lengths_share_one_compile(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg, max_batch=1)       # ladder (8, 16, 32)
        for p in _prompts(41, (3,)):
            cb.submit(p)
        cb.run()
        assert cb.prefill_compile_count == 1          # (G=1, 8, cold)
        for p in _prompts(42, (5, 7, 8)):             # same bucket
            cb.submit(p)
            cb.run()
        assert cb.prefill_compile_count == 1          # zero recompiles
        cb.submit(_prompts(43, (9,))[0])              # next bucket
        cb.run()
        assert cb.prefill_compile_count == 2

    def test_warmup_prefill_covers_all_admission_shapes(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg, max_batch=2, prefix_cache=True)
        warmed = cb.warmup_prefill()
        # standalone: ladder (8,16,32) x groups {1,2} x {cold, cached};
        # fused decode+prefill: ladder x REACHABLE row counts (phase-
        # free — prefill rows always ride the per-query-causal paged
        # path): at max_batch=2 a fused step needs 1 active slot,
        # leaving 1 for pending records, so only rows=1 can ever run;
        # plus the standalone-decode chunk executable
        assert warmed == 3 * 2 * 2 + 3 * 1 + 1
        # fusion off: the standalone ladder + the decode chunk
        off = _batcher(params, cfg, max_batch=2, fused_prefill=False)
        assert off.warmup_prefill() == 3 * 2 * 2 + 1
        c0 = cb.compile_count
        for p in _prompts(44, (3, 9, 17, 4, 10, 3)):  # span the ladder
            cb.submit(p)
        cb.run()
        for p in _prompts(44, (3, 9, 17)):            # warm repeats (hits)
            cb.submit(p)
        cb.run()
        assert cb.compile_count == c0                 # NEVER recompiled

    def test_unbucketed_compiles_per_length(self, setup):
        """The pre-bucketing behavior, kept reachable for comparison:
        every distinct suffix length is its own compiled shape."""
        cfg, params = setup
        cb = _batcher(params, cfg, max_batch=1, prefill_buckets=())
        for p in _prompts(45, (3, 5, 7)):
            cb.submit(p)
            cb.run()
        assert cb.prefill_compile_count == 3

    def test_same_bucket_burst_prefills_in_one_call(self, setup):
        """Batched admission: a burst landing in one bucket runs ONE
        compiled prefill (group-padded), and outputs match solo runs."""
        cfg, params = setup
        prompts = _prompts(46, (5, 6, 7))
        solo = [_run(params, cfg, [p], max_batch=1)[0][0]
                for p in prompts]
        cb = _batcher(params, cfg, max_batch=3)
        rids = [cb.submit(p) for p in prompts]
        cb.step()                                     # one admission burst
        assert cb.active == [True, True, True]
        assert cb.prefill_compile_count == 1          # (G=3→3, 8, cold)
        out = cb.run()
        assert [out[r] for r in rids] == solo

    def test_pad_tokens_accounting(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg, max_batch=1)
        cb.submit(_prompts(47, (5,))[0])              # 5 → bucket 8
        cb.run()
        assert cb.prefill_pad_tokens == 3
