"""End-to-end LLM lifecycle: pretrain (sharded) → checkpoint → serve
(TP/DP-sharded decode through inference.Predictor).

Reference analog: the PaddleNLP llm/ flow — run_pretrain.py under fleet
hybrid parallel, save .pdparams, then predict with
--tensor_parallel_degree (SURVEY.md §1 Lx row, §3.5). This is the
integration test tying the round-3 serving path (inference/llm.py) to
the training stack on the 8-virtual-device mesh.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, train, generation
from paddle_tpu.parallel.topology import build_mesh


class TestLlmLifecycle:
    def test_train_save_serve_roundtrip(self, tmp_path):
        from paddle_tpu import inference
        from paddle_tpu.inference import llm as illm

        # -- pretrain a few sharded steps (ZeRO + TP on 8 devices) --------
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2,
                                     num_key_value_heads=4)
        mesh = build_mesh(dp=2, sharding=2, mp=2)
        tx = train.make_optimizer(3e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=mesh)
        step = train.make_train_step(cfg, tx, mesh=mesh)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 32)),
            jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(4):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])

        # -- save the trained params as a serving checkpoint --------------
        prefix = str(tmp_path / "pretrained")
        host_params = jax.tree.map(np.asarray, state.params)
        illm.save_llm(prefix, host_params, cfg)

        # -- serve with TP=2 x DP=2: decode must match single-device ------
        config = inference.Config(prefix)
        config.enable_llm_generation(max_new_tokens=6)
        config.set_llm_parallel(mp=2, dp=2)
        pred = inference.create_predictor(config)
        prompt = np.asarray(toks[:2, :8])
        pred.get_input_handle("input_ids").copy_from_cpu(prompt)
        (out,) = pred.run()

        ref = generation.generate(
            jax.tree.map(jnp.asarray, host_params),
            jnp.asarray(prompt), cfg, max_new_tokens=6)
        np.testing.assert_array_equal(out, np.asarray(ref))

    def test_sharded_sampling_and_eos(self, tmp_path):
        """Sampling + eos padding behave identically under the mesh."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2,
                                     num_key_value_heads=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 8)),
            jnp.int32)
        mesh = build_mesh(mp=2, devices=jax.devices()[:2])
        sp = jax.tree.map(lambda s: NamedSharding(mesh, s),
                          llama.infer_param_specs(cfg),
                          is_leaf=lambda x: not isinstance(x, dict))
        p_sh = jax.tree.map(jax.device_put, params, sp)

        kw = dict(max_new_tokens=5, greedy=False, temperature=0.9,
                  top_k=20, top_p=0.9, key=jax.random.PRNGKey(5))
        a = generation.generate(params, prompt, cfg, **kw)
        b = jax.jit(lambda p, t: generation.generate(
            p, t, cfg, mesh=mesh, **kw))(p_sh, prompt)
        # identical keys + identical (bf16-rounded) logits -> identical
        # sampled ids in practice for the tiny config
        assert a.shape == b.shape == (2, 5)
        assert int(jnp.min(b)) >= 0 and int(jnp.max(b)) < cfg.vocab_size

        greedy = generation.generate(params, prompt, cfg, max_new_tokens=6)
        eos = int(greedy[0, 1])
        out = jax.jit(lambda p, t: generation.generate(
            p, t, cfg, max_new_tokens=6, eos_token_id=eos, pad_token_id=-1,
            mesh=mesh))(p_sh, prompt)
        row = out[0].tolist()
        assert eos in row
        assert all(t == -1 for t in row[row.index(eos) + 1:]), row
