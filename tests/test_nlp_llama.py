"""Flagship Llama model tests — single-device correctness + sharded step.

Mirrors the reference's hybrid-parallel test pattern (SURVEY.md §4 fleet
tests): TP/sharded runs must match single-card numerics."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, train
from paddle_tpu.parallel.topology import build_mesh


def tiny(**over):
    return llama.LlamaConfig.tiny(**over)


def toks(cfg, b=4, s=32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)


class TestForward:
    def test_shapes_and_dtype(self):
        cfg = tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        logits = llama.forward(params, toks(cfg), cfg)
        assert logits.shape == (4, 32, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not change past logits."""
        cfg = tiny(remat=False)
        params = llama.init_params(jax.random.key(0), cfg)
        t1 = toks(cfg)
        t2 = t1.at[:, -1].set((t1[:, -1] + 1) % cfg.vocab_size)
        l1 = llama.forward(params, t1, cfg)
        l2 = llama.forward(params, t2, cfg)
        np.testing.assert_allclose(np.asarray(l1[:, :-1]),
                                   np.asarray(l2[:, :-1]), rtol=2e-2, atol=2e-2)
        assert not np.allclose(np.asarray(l1[:, -1]), np.asarray(l2[:, -1]))

    def test_remat_matches_no_remat(self):
        cfg = tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        import dataclasses
        l1 = llama.forward(params, toks(cfg), cfg)
        l2 = llama.forward(params, toks(cfg),
                           dataclasses.replace(cfg, remat=False))
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-5, atol=1e-5)

    def test_gqa_vs_mha_reference(self):
        """GQA (kv heads < heads) must equal expanded-head attention."""
        cfg = tiny(num_key_value_heads=2)
        params = llama.init_params(jax.random.key(1), cfg)
        logits = llama.forward(params, toks(cfg), cfg)
        assert np.isfinite(np.asarray(logits)).all()

    def test_tied_embeddings(self):
        cfg = tiny(tie_word_embeddings=True)
        params = llama.init_params(jax.random.key(0), cfg)
        assert "lm_head" not in params
        logits = llama.forward(params, toks(cfg), cfg)
        assert logits.shape[-1] == cfg.vocab_size


class TestTrain:
    def test_loss_decreases_single_device(self):
        cfg = tiny()
        tx = train.make_optimizer(1e-2, warmup_steps=0)
        state = train.init_state(jax.random.key(0), cfg, tx)
        step = train.make_train_step(cfg, tx)
        t = toks(cfg)
        losses = []
        for _ in range(5):
            state, m = step(state, t)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        assert int(state.step) == 5

    def test_sharded_step_matches_single_device(self):
        """SURVEY.md §4: TP/hybrid numerics must equal single-card."""
        cfg = tiny(num_key_value_heads=4)
        tx = train.make_optimizer(1e-2)
        t = toks(cfg, b=8, s=32)

        state1 = train.init_state(jax.random.key(0), cfg, tx)
        step1 = train.make_train_step(cfg, tx, donate=False)
        _, m1 = step1(state1, t)

        mesh = build_mesh(dp=2, sharding=2, pp=1, sep=1, mp=2)
        state8 = train.init_state(jax.random.key(0), cfg, tx, mesh)
        step8 = train.make_train_step(cfg, tx, mesh, donate=False)
        _, m8 = step8(state8, t)

        np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                                   rtol=2e-5)
        # bf16 compute: cross-sharding reduction order shifts the norm
        np.testing.assert_allclose(float(m1["grad_norm"]),
                                   float(m8["grad_norm"]), rtol=2e-3)

    def test_sep_context_parallel_step(self):
        """Sequence dim sharded over sep axis (context parallel) runs."""
        cfg = tiny(num_key_value_heads=4)
        tx = train.make_optimizer(1e-2)
        mesh = build_mesh(dp=1, sharding=2, pp=1, sep=2, mp=2)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh)
        step = train.make_train_step(cfg, tx, mesh)
        state, m = step(state, toks(cfg, b=4, s=64))
        assert np.isfinite(float(m["loss"]))

    def test_param_specs_cover_params(self):
        cfg = tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        specs = llama.param_specs(cfg)
        assert jax.tree.structure(params) == jax.tree.structure(
            specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))

    def test_num_params_matches(self):
        cfg = tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        assert n == llama.num_params(cfg)


class TestGraftEntry:
    def test_entry_jits(self):
        import __graft_entry__ as g
        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == args[1].shape[0]

    def test_dryrun_multichip(self):
        import __graft_entry__ as g
        g.dryrun_multichip(8)
