"""Flagship Llama model tests — single-device correctness + sharded step.

Mirrors the reference's hybrid-parallel test pattern (SURVEY.md §4 fleet
tests): TP/sharded runs must match single-card numerics."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, train
from paddle_tpu.parallel.topology import build_mesh


def tiny(**over):
    return llama.LlamaConfig.tiny(**over)


def toks(cfg, b=4, s=32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)


class TestForward:
    def test_shapes_and_dtype(self):
        cfg = tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        logits = llama.forward(params, toks(cfg), cfg)
        assert logits.shape == (4, 32, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not change past logits."""
        cfg = tiny(remat=False)
        params = llama.init_params(jax.random.key(0), cfg)
        t1 = toks(cfg)
        t2 = t1.at[:, -1].set((t1[:, -1] + 1) % cfg.vocab_size)
        l1 = llama.forward(params, t1, cfg)
        l2 = llama.forward(params, t2, cfg)
        np.testing.assert_allclose(np.asarray(l1[:, :-1]),
                                   np.asarray(l2[:, :-1]), rtol=2e-2, atol=2e-2)
        assert not np.allclose(np.asarray(l1[:, -1]), np.asarray(l2[:, -1]))

    def test_remat_matches_no_remat(self):
        cfg = tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        import dataclasses
        l1 = llama.forward(params, toks(cfg), cfg)
        l2 = llama.forward(params, toks(cfg),
                           dataclasses.replace(cfg, remat=False))
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-5, atol=1e-5)

    def test_gqa_vs_mha_reference(self):
        """GQA (kv heads < heads) must equal expanded-head attention."""
        cfg = tiny(num_key_value_heads=2)
        params = llama.init_params(jax.random.key(1), cfg)
        logits = llama.forward(params, toks(cfg), cfg)
        assert np.isfinite(np.asarray(logits)).all()

    def test_tied_embeddings(self):
        cfg = tiny(tie_word_embeddings=True)
        params = llama.init_params(jax.random.key(0), cfg)
        assert "lm_head" not in params
        logits = llama.forward(params, toks(cfg), cfg)
        assert logits.shape[-1] == cfg.vocab_size


class TestTrain:
    def test_loss_decreases_single_device(self):
        cfg = tiny()
        tx = train.make_optimizer(1e-2, warmup_steps=0)
        state = train.init_state(jax.random.key(0), cfg, tx)
        step = train.make_train_step(cfg, tx)
        t = toks(cfg)
        losses = []
        for _ in range(5):
            state, m = step(state, t)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        assert int(state.step) == 5

    def test_sharded_step_matches_single_device(self):
        """SURVEY.md §4: TP/hybrid numerics must equal single-card."""
        cfg = tiny(num_key_value_heads=4)
        tx = train.make_optimizer(1e-2)
        t = toks(cfg, b=8, s=32)

        state1 = train.init_state(jax.random.key(0), cfg, tx)
        step1 = train.make_train_step(cfg, tx, donate=False)
        _, m1 = step1(state1, t)

        mesh = build_mesh(dp=2, sharding=2, pp=1, sep=1, mp=2)
        state8 = train.init_state(jax.random.key(0), cfg, tx, mesh)
        step8 = train.make_train_step(cfg, tx, mesh, donate=False)
        _, m8 = step8(state8, t)

        np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                                   rtol=2e-5)
        # bf16 compute: cross-sharding reduction order shifts the norm
        np.testing.assert_allclose(float(m1["grad_norm"]),
                                   float(m8["grad_norm"]), rtol=2e-3)

    def test_sep_context_parallel_step(self):
        """Sequence dim sharded over sep axis (context parallel) runs."""
        cfg = tiny(num_key_value_heads=4)
        tx = train.make_optimizer(1e-2)
        mesh = build_mesh(dp=1, sharding=2, pp=1, sep=2, mp=2)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh)
        step = train.make_train_step(cfg, tx, mesh)
        state, m = step(state, toks(cfg, b=4, s=64))
        assert np.isfinite(float(m["loss"]))

    def test_param_specs_cover_params(self):
        cfg = tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        specs = llama.param_specs(cfg)
        assert jax.tree.structure(params) == jax.tree.structure(
            specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))

    def test_num_params_matches(self):
        cfg = tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        assert n == llama.num_params(cfg)


class TestGraftEntry:
    def test_entry_jits(self):
        import __graft_entry__ as g
        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == args[1].shape[0]

    def test_dryrun_multichip(self):
        import __graft_entry__ as g
        g.dryrun_multichip(8)


class TestGradAccumulation:
    def test_accum_matches_full_batch(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.nlp import llama, train
        cfg = llama.LlamaConfig.tiny()
        tx = train.make_optimizer(1e-3)
        state1 = train.init_state(jax.random.key(0), cfg, tx, mesh=None)
        state2 = train.init_state(jax.random.key(0), cfg, tx, mesh=None)
        step1 = train.make_train_step(cfg, tx, mesh=None, donate=False)
        step4 = train.make_train_step(cfg, tx, mesh=None, donate=False,
                                      grad_accum_steps=4)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 64)),
            jnp.int32)
        s1, m1 = step1(state1, tokens)
        s2, m2 = step4(state2, tokens)
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(m1["grad_norm"]),
                                   float(m2["grad_norm"]), rtol=1e-3)
        # bf16 forward rounding differs between chunked and full batches;
        # Adam turns near-zero grad sign flips into ~lr-sized param deltas,
        # so params match to ~2*lr, not machine precision
        flat1 = jax.tree.leaves(s1.params)
        flat2 = jax.tree.leaves(s2.params)
        for a, b in zip(flat1, flat2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=2.5e-3)

    def test_bad_divisor_and_pp_combination(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import pytest
        from paddle_tpu.nlp import llama, train
        cfg = llama.LlamaConfig.tiny()
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=None)
        step3 = train.make_train_step(cfg, tx, mesh=None, donate=False,
                                      grad_accum_steps=3)
        tokens = jnp.asarray(np.zeros((8, 64)), jnp.int32)
        with pytest.raises(ValueError, match="divisible"):
            step3(state, tokens)
        with pytest.raises(ValueError, match=">= 1"):
            train.make_train_step(cfg, tx, mesh=None, grad_accum_steps=0)
        from paddle_tpu.parallel import topology
        pp_mesh = topology.build_mesh(dp=4, pp=2)
        with pytest.raises(ValueError, match="num_microbatches"):
            train.make_train_step(cfg, tx, mesh=pp_mesh, grad_accum_steps=2)

    def test_accum_on_sharded_mesh(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.nlp import llama, train
        from paddle_tpu.parallel import topology
        mesh = topology.build_mesh(dp=2, sharding=2, mp=2)
        cfg = llama.LlamaConfig.tiny()
        tx = train.make_optimizer(3e-4)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=mesh)
        step = train.make_train_step(cfg, tx, mesh=mesh, grad_accum_steps=2)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 128)),
            jnp.int32)
        l0 = None
        for _ in range(4):
            state, m = step(state, tokens)
            l0 = l0 or float(m["loss"])
        assert float(m["loss"]) < l0


class TestFusedHeadCE:
    """fused_head_ce custom VJP pinned against the materializing
    _final_head + _mb_loss reference (review finding: no direct test)."""

    @pytest.mark.parametrize("tie", [False, True])
    def test_value_and_grads_match_reference(self, tie):
        cfg = llama.LlamaConfig.tiny(num_hidden_layers=2,
                                     tie_word_embeddings=tie,
                                     fused_ce=True)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 24)),
            jnp.int32)

        def ref(p):
            return llama._mb_loss(llama.forward(p, toks, cfg), toks)

        def fused(p):
            return llama.loss_fn(p, toks, cfg)

        lr, lf = float(ref(params)), float(fused(params))
        assert abs(lr - lf) < 1e-4, (lr, lf)
        gr = jax.grad(ref)(params)
        gf = jax.grad(fused)(params)
        errs = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(
                a.astype(jnp.float32) - b.astype(jnp.float32)))), gr, gf)
        assert max(jax.tree.leaves(errs)) < 2e-2, errs

    def test_odd_seq_never_single_chunk(self):
        """Seq lengths not divisible by 8 pick the largest divisor, never
        the full-logits single chunk (unless S is prime)."""
        x = jnp.zeros((1, 20, 8), jnp.float32)
        toks = jnp.zeros((1, 20), jnp.int32)
        xs, tg, nc, c = llama._ce_scan_chunks(x, toks)
        assert nc == 5 and c == 4
