"""Fused-backward RMSNorm (kernels.rms_norm.rms_norm_train) parity.

The training stacks route their norms through rms_norm_train, whose
hand-written backward (Pallas on TPU, jnp twin elsewhere) must match
jax.grad of the reference formulation.
"""
import numpy as np
import pytest


class TestRmsNormTrain:
    def _setup(self):
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 6, 256) * 2.0, jnp.float32)
        w = jnp.asarray(1.0 + 0.1 * rng.randn(256), jnp.float32)
        return x, w

    @pytest.mark.parametrize("interpret", [False, True])
    def test_value_and_grads_match_ref(self, interpret):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core import flags as F
        from paddle_tpu.kernels.rms_norm import rms_norm_ref, rms_norm_train
        x, w = self._setup()
        if interpret:
            F.set_flags({"FLAGS_pallas_interpret": True})
        try:
            out = rms_norm_train(x, w, 1e-6, True)
            np.testing.assert_allclose(np.asarray(out),
                                       np.asarray(rms_norm_ref(x, w, 1e-6)),
                                       rtol=1e-5, atol=1e-5)

            def loss_f(fn):
                return lambda x, w: jnp.sum(jnp.sin(fn(x, w)))

            gx, gw = jax.grad(
                loss_f(lambda x, w: rms_norm_train(x, w, 1e-6, True)),
                argnums=(0, 1))(x, w)
            gx_r, gw_r = jax.grad(
                loss_f(lambda x, w: rms_norm_ref(x, w, 1e-6)),
                argnums=(0, 1))(x, w)
            np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_r),
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_r),
                                       rtol=1e-4, atol=1e-4)
        finally:
            if interpret:
                F.set_flags({"FLAGS_pallas_interpret": False})

    def test_bf16_and_padded_rows(self):
        """Non-multiple-of-block row counts and bf16 inputs round-trip."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core import flags as F
        from paddle_tpu.kernels.rms_norm import rms_norm_ref, rms_norm_train
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(3, 7, 128), jnp.bfloat16)
        w = jnp.asarray(1.0 + 0.1 * rng.randn(128), jnp.bfloat16)
        F.set_flags({"FLAGS_pallas_interpret": True})
        try:
            out = rms_norm_train(x, w, 1e-6, True)
            ref = rms_norm_ref(x, w, 1e-6)
            np.testing.assert_allclose(np.asarray(out, np.float32),
                                       np.asarray(ref, np.float32),
                                       rtol=2e-2, atol=2e-2)
            gx = jax.grad(lambda x: jnp.sum(
                rms_norm_train(x, w, 1e-6, True).astype(jnp.float32)))(x)
            gx_r = jax.grad(lambda x: jnp.sum(
                rms_norm_ref(x, w, 1e-6).astype(jnp.float32)))(x)
            np.testing.assert_allclose(np.asarray(gx, np.float32),
                                       np.asarray(gx_r, np.float32),
                                       rtol=5e-2, atol=5e-2)
        finally:
            F.set_flags({"FLAGS_pallas_interpret": False})


class TestLayerNormTrain:
    @pytest.mark.parametrize("affine", [True, False])
    @pytest.mark.parametrize("interpret", [False, True])
    def test_value_and_grads_match_ref(self, affine, interpret):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core import flags as F
        from paddle_tpu.kernels.layer_norm import (layer_norm_ref,
                                                   layer_norm_train)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 6, 256) * 2.0, jnp.float32)
        w = jnp.asarray(1.0 + 0.1 * rng.randn(256),
                        jnp.float32) if affine else None
        b = jnp.asarray(0.1 * rng.randn(256),
                        jnp.float32) if affine else None
        if interpret:
            F.set_flags({"FLAGS_pallas_interpret": True})
        try:
            out = layer_norm_train(x, w, b, 1e-5, True)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(layer_norm_ref(x, w, b, 1e-5)),
                rtol=1e-5, atol=1e-5)

            if affine:
                def loss_t(x, w, b):
                    return jnp.sum(jnp.sin(layer_norm_train(x, w, b, 1e-5,
                                                            True)))

                def loss_r(x, w, b):
                    return jnp.sum(jnp.sin(layer_norm_ref(x, w, b, 1e-5)))

                gt = jax.grad(loss_t, argnums=(0, 1, 2))(x, w, b)
                gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, w, b)
            else:
                gt = (jax.grad(lambda x: jnp.sum(jnp.sin(
                    layer_norm_train(x, None, None, 1e-5, True))))(x),)
                gr = (jax.grad(lambda x: jnp.sum(jnp.sin(
                    layer_norm_ref(x, None, None, 1e-5))))(x),)
            for a, r in zip(gt, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                           rtol=1e-4, atol=1e-4)
        finally:
            if interpret:
                F.set_flags({"FLAGS_pallas_interpret": False})


class TestRmsNormSharded:
    """rms_norm_train_sharded (VERDICT r4 next-3): the fused kernel under
    a mesh via shard_map — value/grad parity with the ref path."""

    def test_sharded_matches_ref(self):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.core import flags
        from paddle_tpu.parallel.topology import build_mesh
        from paddle_tpu.kernels.rms_norm import (rms_norm_ref,
                                                 rms_norm_train_sharded)
        mesh = build_mesh(dp=2, sharding=2, mp=2)
        spec = P(("dp", "sharding"), None, None)
        x = jnp.asarray(np.random.RandomState(0).randn(8, 16, 128),
                        jnp.float32)
        w = jnp.asarray(np.random.RandomState(1).rand(128), jnp.float32)

        def loss(fn):
            def f(x_, w_):
                return jnp.sum(fn(x_, w_) ** 2)
            return jax.value_and_grad(f, (0, 1))

        ref_v, ref_g = loss(lambda a, b: rms_norm_ref(a, b, 1e-6))(x, w)
        flags.set_flags({"FLAGS_pallas_interpret": True})
        try:
            got_v, got_g = loss(lambda a, b: rms_norm_train_sharded(
                a, b, 1e-6, mesh, spec))(x, w)
        finally:
            flags.set_flags({"FLAGS_pallas_interpret": False})
        np.testing.assert_allclose(float(got_v), float(ref_v), rtol=1e-5)
        for a, b in zip(got_g, ref_g):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestNormDoubleGrad:
    """ADVICE r4 item 2: double-grad/HVPs through the fused norm
    backwards must not hit a bare pallas_call — the second-order rule
    rides the jnp twin. Verified in interpret mode vs the pure-ref HVP."""

    def test_rms_hvp_matches_ref(self):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core import flags
        from paddle_tpu.kernels.rms_norm import rms_norm_ref, rms_norm_train
        x = jnp.asarray(np.random.RandomState(0).randn(8, 128), jnp.float32)
        w = jnp.asarray(np.random.RandomState(1).rand(128), jnp.float32)
        v = jnp.asarray(np.random.RandomState(2).randn(8, 128), jnp.float32)

        def loss(fn, x_):
            return jnp.sum(fn(x_, w) ** 2)

        def hvp_of(fn):
            # reverse-over-reverse (the tape's double-grad formulation)
            g = jax.grad(lambda a: loss(fn, a))
            return jax.grad(lambda a: jnp.vdot(g(a), v))(x)

        flags.set_flags({"FLAGS_pallas_interpret": True})
        try:
            hvp = hvp_of(lambda p, q: rms_norm_train(p, q, 1e-6, True))
        finally:
            flags.set_flags({"FLAGS_pallas_interpret": False})
        ref = hvp_of(lambda p, q: rms_norm_ref(p, q, 1e-6))
        np.testing.assert_allclose(np.asarray(hvp), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_ln_hvp_matches_ref(self):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core import flags
        from paddle_tpu.kernels.layer_norm import (layer_norm_ref,
                                                   layer_norm_train)
        x = jnp.asarray(np.random.RandomState(3).randn(8, 128), jnp.float32)
        w = jnp.asarray(np.random.RandomState(4).rand(128), jnp.float32)
        b = jnp.asarray(np.random.RandomState(5).randn(128), jnp.float32)
        v = jnp.asarray(np.random.RandomState(6).randn(8, 128), jnp.float32)

        def hvp_of(fn):
            g = jax.grad(lambda a: jnp.sum(fn(a, w, b) ** 2))
            return jax.grad(lambda a: jnp.vdot(g(a), v))(x)

        flags.set_flags({"FLAGS_pallas_interpret": True})
        try:
            hvp = hvp_of(layer_norm_train)
        finally:
            flags.set_flags({"FLAGS_pallas_interpret": False})
        ref = hvp_of(layer_norm_ref)
        np.testing.assert_allclose(np.asarray(hvp), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


class TestAdaLN:
    """Fused adaLN (LN + per-sample modulate) kernel — the r5 DiT lever:
    interpret-mode value/grad parity vs the jnp reference."""

    def _case(self, seed=0, B=2, N=256, D=128):
        import jax.numpy as jnp
        rng = np.random.RandomState(seed)
        x = jnp.asarray(rng.randn(B, N, D), jnp.float32)
        sh = jnp.asarray(rng.randn(B, D) * 0.1, jnp.float32)
        sc = jnp.asarray(rng.randn(B, D) * 0.1, jnp.float32)
        return x, sh, sc

    def test_value_and_grads_match_ref(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core import flags
        from paddle_tpu.kernels.adaln import adaln_modulate, adaln_ref
        x, sh, sc = self._case()

        def loss(fn):
            return jax.value_and_grad(
                lambda a, b, c: jnp.sum(fn(a, b, c) ** 2), (0, 1, 2))

        rv, rg = loss(lambda a, b, c: adaln_ref(a, b, c))(x, sh, sc)
        flags.set_flags({"FLAGS_pallas_interpret": True})
        try:
            gv, gg = loss(lambda a, b, c: adaln_modulate(a, b, c))(x, sh, sc)
        finally:
            flags.set_flags({"FLAGS_pallas_interpret": False})
        np.testing.assert_allclose(float(gv), float(rv), rtol=1e-5)
        for a, b in zip(gg, rg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_double_grad(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core import flags
        from paddle_tpu.kernels.adaln import adaln_modulate, adaln_ref
        x, sh, sc = self._case(seed=1, N=128)
        v = jnp.ones_like(x)

        def hvp_of(fn):
            g = jax.grad(lambda a: jnp.sum(fn(a, sh, sc) ** 2))
            return jax.grad(lambda a: jnp.vdot(g(a), v))(x)

        flags.set_flags({"FLAGS_pallas_interpret": True})
        try:
            hvp = hvp_of(adaln_modulate)
        finally:
            flags.set_flags({"FLAGS_pallas_interpret": False})
        ref = hvp_of(adaln_ref)
        np.testing.assert_allclose(np.asarray(hvp), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)
