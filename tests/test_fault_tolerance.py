"""Fault-isolated serving: poisoned-step quarantine, retry with warm
re-prefill, the hung-step watchdog, and the deterministic chaos harness.

The PR's acceptance matrix:

  * poison one rid in a fused batch → the culprit alone reaches FAILED,
    every innocent finishes with BIT-identical tokens to a fault-free
    run (no re-emitted or lost streamed tokens) and zero post-warmup
    recompiles (quarantine re-execution stays on the warmed ladder);
  * a transient fault → the retry succeeds with `retries == 1` and
    token parity; an exhausted retry budget → terminal FAILED with a
    `retried` trace event trail;
  * an injected hang trips the watchdog within the configured deadline,
    `health()` reports UNHEALTHY, the flight dump names the hung tick,
    and `shutdown(drain=False)` returns instead of blocking;
  * chaos under deadline/cancel races leaks no slots or blocks
    (allocator stats clean after drain).
"""
import threading
import time

import numpy as np
import pytest
import jax

from paddle_tpu.nlp import llama
from paddle_tpu import serving
from paddle_tpu.serving import AdmissionQueue, RequestState, TraceSink
from paddle_tpu.serving.faults import FaultInjector, InjectedFault


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


_RNG = np.random.RandomState(11)
PROMPTS = [list(map(int, _RNG.randint(1, 200, L))) for L in (5, 7, 6, 9)]
BUDGETS = [8, 5, 7, 6]


def _kinds(tl):
    return [e["kind"] for e in tl["events"]]


# ---- injector units (no engine, no device) -----------------------------
class TestFaultInjector:
    def test_fail_on_step_fires_once_at_exact_call(self):
        inj = FaultInjector().fail_on_step(2)
        inj.check("decode", [0])                       # call 1: clean
        with pytest.raises(InjectedFault):
            inj.check("decode", [0])                   # call 2: fires
        inj.check("decode", [0])                       # consumed
        assert inj.stats()["injected"] == {"error": 1}

    def test_fail_on_rid_matches_probes_but_step_rules_do_not(self):
        inj = FaultInjector().fail_on_rid(7).fail_on_step(1, times=5)
        with pytest.raises(InjectedFault):
            inj.check("probe", [7], probe=True)        # rid rule fires
        inj.check("probe", [3], probe=True)            # other rid clean
        assert inj.stats()["calls"] == 0               # probes don't count
        with pytest.raises(InjectedFault):
            inj.check("decode", [3])                   # step rule, call 1

    def test_after_step_delays_rid_poison(self):
        inj = FaultInjector().fail_on_rid(1, after_step=2)
        inj.check("decode", [1])                       # call 1 <= 2
        inj.check("decode", [1])                       # call 2 <= 2
        with pytest.raises(InjectedFault):
            inj.check("decode", [1])                   # call 3 fires

    def test_exhaust_is_transient_resource_exhausted(self):
        inj = FaultInjector().exhaust_on_step(1)
        with pytest.raises(InjectedFault) as ei:
            inj.check("prefill", [0])
        assert ei.value.transient is True
        assert "RESOURCE_EXHAUSTED" in str(ei.value)
        assert ei.value.kind == "oom"

    def test_fail_rate_is_seed_deterministic(self):
        def pattern(seed):
            inj = FaultInjector(seed=seed).fail_rate(0.4, times=None)
            out = []
            for _ in range(32):
                try:
                    inj.check("decode", [0])
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        assert pattern(3) == pattern(3)
        assert pattern(3) != pattern(4)
        assert sum(pattern(3)) > 0

    def test_hang_sleeps_and_heal_disarms(self):
        inj = FaultInjector().hang_on_step(1, seconds=0.05)
        t0 = time.perf_counter()
        inj.check("decode", [0])
        assert time.perf_counter() - t0 >= 0.05
        inj.fail_on_rid(9).heal()
        inj.check("decode", [9])                       # healed: clean
        assert inj.stats()["armed_rules"] == 0


# ---- scheduler: front-of-queue requeue ---------------------------------
class TestAdmissionRequeue:
    def test_requeue_beats_every_priority_and_keeps_order(self):
        q = AdmissionQueue(max_depth=8, aging_interval_s=0)
        q.push("low", priority=5)
        q.push("high", priority=0)
        q.requeue(["v1", "v2"])
        assert [q.pop() for _ in range(4)] == ["v1", "v2", "high", "low"]

    def test_requeue_bypasses_max_depth(self):
        q = AdmissionQueue(max_depth=1)
        q.push("a")
        q.requeue(["v"])                # full queue must not bounce it
        assert len(q) == 2
        assert q.pop() == "v"

    def test_later_requeue_batch_goes_in_front(self):
        q = AdmissionQueue(max_depth=8)
        q.requeue(["r1"])
        q.requeue(["r2a", "r2b"])
        assert [q.pop() for _ in range(3)] == ["r2a", "r2b", "r1"]


# ---- quarantine: the acceptance parity gate ----------------------------
class TestQuarantine:
    def _engine(self, setup, inj=None, **kw):
        cfg, params = setup
        # one-bucket ladder keeps warmup() cheap (longer resume
        # prompts chunk through it — more path coverage, not less)
        return serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=64,
            max_new_tokens=16, chunk=2, prefill_buckets=(8,),
            start=False, fault_injector=inj, **kw)

    def _serve_all(self, eng, culprit_idx=None, inj=None):
        """Warmed engine lifecycle over PROMPTS/BUDGETS; arms a
        persistent fail-on-rid poison at the culprit's FIRST streamed
        token when asked. Returns (requests, post-warmup recompiles)."""
        eng.warmup()
        eng.start()
        eng.generate(PROMPTS[0], timeout=300)
        warm = eng.batcher.compile_count
        armed = threading.Event()

        def arm(tok):
            if not armed.is_set():
                armed.set()
                inj.fail_on_rid(culprit_req.request_id)

        # pre-built handle: the engine-thread callback must never race
        # the submit loop's list append
        culprit_req = None if culprit_idx is None else \
            serving.GenerationRequest(PROMPTS[culprit_idx],
                                      max_new_tokens=BUDGETS[culprit_idx],
                                      on_token=arm)
        reqs = []
        for i, (p, mn) in enumerate(zip(PROMPTS, BUDGETS)):
            reqs.append(eng.submit(culprit_req) if i == culprit_idx
                        else eng.submit(p, max_new_tokens=mn))
        assert eng.drain(timeout=300)
        return reqs, eng.batcher.compile_count - warm

    def test_poisoned_rid_in_fused_batch_isolates_culprit(self, setup):
        """The headline gate: a mid-stream poison on one request kills
        only that request; innocents are requeued, resume from
        prompt + streamed tokens and finish BIT-identical to the
        fault-free run — with zero post-warmup recompiles and a clean
        pool."""
        eng0 = self._engine(setup)
        base, _ = self._serve_all(eng0)
        base_toks = [r.result(timeout=5) for r in base]
        eng0.shutdown()

        inj = FaultInjector(seed=0)
        eng = self._engine(setup, inj)
        reqs, recompiles = self._serve_all(eng, culprit_idx=1, inj=inj)
        # the culprit alone reaches FAILED, mid-stream (it streamed)
        assert [r.state for r in reqs].count(RequestState.FAILED) == 1
        culprit = reqs[1]
        assert culprit.state is RequestState.FAILED
        assert culprit.finish_reason == "quarantine_culprit"
        with pytest.raises(serving.RequestFailed):
            culprit.result(timeout=5)
        # streamed tokens were neither lost nor re-emitted: a strict
        # non-empty prefix of the fault-free output
        assert culprit.tokens
        assert culprit.tokens == base_toks[1][:len(culprit.tokens)]
        # innocents: bit-identical token parity with the clean run
        for i in (0, 2, 3):
            assert reqs[i].state is RequestState.FINISHED
            assert reqs[i].result(timeout=5) == base_toks[i], \
                f"innocent {i} lost token parity"
        # quarantine re-execution stayed on the warmed ladder
        assert recompiles == 0
        assert eng.batcher.alloc.stats()["blocks_in_use"] == 0
        h = eng.health()
        assert h["status"] == "DEGRADED"
        # slot-in-place recovery: the failed call committed nothing, so
        # innocents keep their KV via export/import ("restored") instead
        # of requeueing through a full re-prefill
        assert h["quarantines"] >= 1 and h["requests_restored"] >= 1
        restored = [r for i, r in enumerate(reqs) if i != 1
                    and "restored" in _kinds(eng.trace.timeline(r.trace_id))]
        assert restored, "no innocent timeline recorded its restore"
        tl = eng.trace.timeline(culprit.trace_id)
        assert _kinds(tl)[-1] == "failed"
        assert "injected fault" in tl["events"][-1]["attrs"]["error"]
        eng.shutdown()

    def test_transient_fault_retries_once_and_succeeds(self, setup):
        """fail-once-then-heal: no probe reproduces the failure, the
        lone suspect is charged one backoff retry and completes with
        token parity and retries == 1."""
        eng0 = self._engine(setup).start()
        base = eng0.generate(PROMPTS[0], timeout=300)
        eng0.shutdown()

        # call 3: the decode tick after warmup prefill+decode of the
        # single request — a mid-stream transient
        inj = FaultInjector().fail_on_step(3, transient=True)
        eng = self._engine(setup, inj, retry_backoff_s=0.01)
        r = eng.submit(PROMPTS[0])
        eng.start()
        assert r.result(timeout=300) == base
        assert r.retries == 1
        tl = eng.trace.timeline(r.trace_id)
        assert "retried" in _kinds(tl)
        assert eng.metrics.counter("requests_retried").value == 1
        assert eng.health()["status"] == "DEGRADED"
        eng.shutdown()

    def test_retry_budget_exhausted_fails_terminally(self, setup):
        """A persistently-poisoned request burns its whole retry budget
        (trace shows each retry) and then FAILS with a terminal event —
        it never livelocks the engine."""
        inj = FaultInjector()
        eng = self._engine(setup, inj, max_retries=2,
                           retry_backoff_s=0.01)
        armed = set()

        def arm(tok):
            # re-arm on every re-admission: the rid changes, the
            # request-level poison must follow it
            rid = r.request_id
            if rid not in armed:
                armed.add(rid)
                inj.fail_on_rid(rid, transient=True)

        r = eng.submit(PROMPTS[0], on_token=arm)
        eng.start()
        with pytest.raises(serving.RequestFailed):
            r.result(timeout=300)
        assert r.retries == 2
        assert r.finish_reason == "retries_exhausted"
        tl = eng.trace.timeline(r.trace_id)
        assert _kinds(tl).count("retried") == 2
        assert _kinds(tl)[-1] == "failed"
        # the engine itself stays serviceable for other traffic
        inj.heal()
        assert eng.generate(PROMPTS[2], timeout=300)
        assert eng.batcher.alloc.stats()["blocks_in_use"] == 0
        eng.shutdown()

    def test_resource_exhausted_is_retried_by_default(self, setup):
        """RESOURCE_EXHAUSTED-style allocator pressure is transient by
        default: the suspects recover instead of failing."""
        inj = FaultInjector().exhaust_on_step(3)
        eng = self._engine(setup, inj, retry_backoff_s=0.01)
        r = eng.submit(PROMPTS[0])
        eng.start()
        assert r.result(timeout=300)
        assert r.retries == 1
        eng.shutdown()

    def test_quarantine_off_restores_fail_all(self, setup):
        """The escape hatch: quarantine=False reverts to the PR 7
        boundary — every in-flight request fails on a step fault."""
        inj = FaultInjector().fail_on_step(3)
        eng = self._engine(setup, inj, quarantine=False)
        r1 = eng.submit(PROMPTS[0], max_new_tokens=8)
        r2 = eng.submit(PROMPTS[1], max_new_tokens=8)
        eng.start()
        for r in (r1, r2):
            with pytest.raises(serving.RequestFailed):
                r.result(timeout=300)
        assert eng.last_flight_dump is not None
        eng.shutdown()


# ---- watchdog ----------------------------------------------------------
class TestWatchdog:
    def test_hung_step_trips_watchdog_and_shutdown_returns(self, setup):
        """The acceptance bar: an injected hang trips the watchdog
        within the deadline, health() goes UNHEALTHY, the flight dump
        names the hung tick's mode + units, every stranded request
        fails with a clear error, and shutdown(drain=False) returns
        instead of blocking forever."""
        cfg, params = setup
        inj = FaultInjector()
        # warmed + fusion off + one full served request before the
        # victim, so every serving-path executable has already RUN: a
        # first-call compile or cold-dispatch overrun would trip the
        # watchdog before the injected hang (the documented deploy
        # guidance: warm up before serving under a tight deadline)
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=8, chunk=2, prefill_buckets=(8,),
            fused_prefill=False, watchdog_s=2.0,
            fault_injector=inj, start=False)
        eng.warmup()
        eng.start()
        assert eng.generate(PROMPTS[1], timeout=300)
        armed = threading.Event()

        def arm(tok):
            # first streamed token: hang this rid's NEXT device call —
            # a mid-stream decode tick, deterministically
            if not armed.is_set():
                armed.set()
                inj.hang_on_rid(r.request_id, seconds=8.0)

        # handle built before submission: the callback fires on the
        # engine thread and must not race this frame's assignment
        r = serving.GenerationRequest(PROMPTS[0], on_token=arm)
        eng.submit(r)
        deadline = time.monotonic() + 15.0
        while (eng.health()["status"] != "UNHEALTHY"
               and time.monotonic() < deadline):
            time.sleep(0.02)
        h = eng.health()
        assert h["status"] == "UNHEALTHY" and h["watchdog_trips"] == 1
        # the stranded request's handle unblocked with a clear error
        assert r.state is RequestState.FAILED
        assert r.finish_reason == "watchdog_hung_step"
        with pytest.raises(serving.RequestFailed) as ei:
            r.result(timeout=5)
        assert "watchdog" in repr(ei.value.request.error)
        # the dump names the hung tick (recorded BEFORE its device call)
        dump = eng.last_flight_dump
        assert "watchdog" in dump["error"]
        assert dump["failing_record"]["mode"] == "decode"
        assert dump["failing_record"]["rids"] == [r.request_id]
        # drain and shutdown return promptly (engine thread still
        # asleep inside the injected hang)
        assert eng.drain(timeout=1.0)
        t0 = time.monotonic()
        eng.shutdown(drain=False)
        assert time.monotonic() - t0 < 2.0
        # post-shutdown: submissions are refused, not queued forever
        with pytest.raises(serving.EngineStopped):
            eng.submit(PROMPTS[1])

    def test_healthy_run_never_trips(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2, watchdog_s=30.0)
        assert eng.generate(PROMPTS[0], timeout=300)
        h = eng.health()
        assert h["status"] == "HEALTHY" and h["watchdog_trips"] == 0
        assert eng.shutdown() is True

    def test_first_step_grace_covers_unwarmed_compile(self, setup):
        """Arming watchdog_s WITHOUT a prior warmup() used to let the
        first step's trace+compile masquerade as a hung device call.
        The first-step grace multiplier covers exactly that window:
        a deadline far below any compile time still serves, no trips."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2, prefill_buckets=(8,),
            fused_prefill=False, watchdog_s=0.05,
            watchdog_compile_grace=2400.0)      # 0.05s * grace = 120s
        assert eng.generate(PROMPTS[0], timeout=300)
        h = eng.health()
        assert h["status"] == "HEALTHY" and h["watchdog_trips"] == 0
        # a WARMED engine gets no grace at all: a genuinely hung step
        # trips at the plain deadline even with a huge grace factor
        inj_late = FaultInjector()
        eng2 = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=8, chunk=2, prefill_buckets=(8,),
            fused_prefill=False, watchdog_s=2.0,
            watchdog_compile_grace=2400.0, fault_injector=inj_late,
            start=False)
        eng2.warmup()     # warmed: the grace is OFF from step one
        eng2.start()
        assert eng2.generate(PROMPTS[1], timeout=300)
        armed = threading.Event()

        def arm(tok):
            if not armed.is_set():
                armed.set()
                inj_late.hang_on_rid(r2.request_id, seconds=30.0)

        r2 = serving.GenerationRequest(PROMPTS[0], on_token=arm)
        eng2.submit(r2)
        deadline = time.monotonic() + 20.0
        while (eng2.health()["status"] != "UNHEALTHY"
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert eng2.health()["watchdog_trips"] == 1
        eng.shutdown()
        eng2.shutdown(drain=False)

    def test_no_grace_trips_on_unwarmed_first_step(self, setup):
        """The regression half: grace forced to 1.0 on an UNWARMED
        engine with a deadline below compile time reproduces the old
        misfire — proving the grace multiplier (not luck) is what
        keeps test_first_step_grace_covers_unwarmed_compile green."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2, prefill_buckets=(8,),
            fused_prefill=False, watchdog_s=0.05,
            watchdog_compile_grace=1.0)
        r = eng.submit(PROMPTS[0])
        with pytest.raises(serving.RequestFailed) as ei:
            r.result(timeout=300)
        assert "watchdog" in repr(ei.value.request.error)
        assert eng.health()["status"] == "UNHEALTHY"
        assert eng.health()["watchdog_trips"] == 1
        eng.shutdown(drain=False)


# ---- chaos under races: no leaks ---------------------------------------
class TestChaosRaces:
    def test_chaos_with_cancel_and_deadline_races_leaks_nothing(
            self, setup):
        """Seeded background fault noise + deadline expiries + a
        mid-flight cancel: every request reaches a terminal state, the
        allocator drains clean, and the engine still serves afterwards."""
        cfg, params = setup
        inj = FaultInjector(seed=5).fail_rate(0.25, times=6,
                                              transient=True)
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=64,
            max_new_tokens=16, chunk=2, prefill_buckets=(8,),
            retry_backoff_s=0.01, max_retries=3, start=False,
            fault_injector=inj)
        eng.warmup()
        eng.start()
        reqs = []
        for i, (p, mn) in enumerate(zip(PROMPTS * 2, BUDGETS * 2)):
            kw = {"max_new_tokens": mn}
            if i % 4 == 3:
                kw["timeout_s"] = 0.05        # doomed to expire
            reqs.append(eng.submit(p, **kw))
        reqs[1].cancel()
        assert eng.drain(timeout=300)
        for r in reqs:
            assert r.done, f"request {r} never reached a terminal state"
        assert eng.batcher.alloc.stats()["blocks_in_use"] == 0
        assert not eng.batcher._pending and not eng.batcher.queue
        # heal and serve: the pool and slots survived the churn
        inj.heal()
        assert eng.generate(PROMPTS[0], timeout=300)
        assert eng.batcher.alloc.stats()["blocks_in_use"] == 0
        eng.shutdown()


# ---- satellites --------------------------------------------------------
class TestSatellites:
    def test_flight_dump_write_failure_is_counted(self, setup, tmp_path):
        """Satellite bugfix: a failed flight-dump disk write is counted
        in flight_dump_errors and surfaced in snapshot(), instead of
        vanishing in a silent except."""
        cfg, params = setup
        inj = FaultInjector().fail_on_step(3)
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=8, chunk=2, fault_injector=inj,
            flight_dump_path=str(tmp_path))     # a DIRECTORY: open fails
        r = eng.submit(PROMPTS[0])
        with pytest.raises(serving.RequestFailed):
            r.result(timeout=300)
        snap = eng.snapshot()
        assert snap["counters"]["flight_dump_errors"] == 1
        assert snap["last_flight_dump_error"] is not None
        assert eng.health()["flight_dump_errors"] == 1
        # the in-memory dump still landed (the write failure never
        # masks the forensics themselves)
        assert eng.last_flight_dump_json is not None
        eng.shutdown()

    def test_requeue_poisoned_cascade_is_traced(self, setup):
        """Satellite: the `_requeue_poisoned` cascade (aborting a
        pending admission rolls back siblings that leaned on its
        blocks) emits `requeued` trace events, so the timeline explains
        the second `prepared` instead of showing silent churn."""
        cfg, params = setup
        from paddle_tpu.nlp.paged import ContinuousBatcher
        sink = TraceSink()
        cb = ContinuousBatcher(
            params, cfg, max_batch=4, block_size=4, max_total_len=64,
            max_new_tokens=8, chunk=3, prefix_cache=True,
            prefill_buckets=(4,), fused_prefill=True, trace=sink)
        w = PROMPTS[0]
        long_p = list(map(int, _RNG.randint(1, 200, 20)))
        shared = list(map(int, _RNG.randint(1, 200, 8)))
        cb.submit(w)
        cb.step()                         # w decoding
        cb.submit(long_p)                 # chunked pending head
        ra = cb.submit(shared + [3, 5])
        rb = cb.submit(shared + [7, 11])
        cb.step()                         # a + b pending behind long_p
        assert cb.abort(ra) is True
        tl = sink.timeline(rb)
        assert tl is not None
        ev = next(e for e in tl["events"] if e["kind"] == "requeued")
        assert ev["attrs"]["reason"] == "poisoned_sibling"
        cb.run()
        assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_trace_report_counts_requeues(self, tmp_path):
        """Satellite: tools/trace_report.py reports the requeued phase
        (per-request counts + totals) from an exported artifact."""
        import json
        import sys
        sys.path.insert(0, "tools")
        try:
            import trace_report
        finally:
            sys.path.pop(0)
        sink = TraceSink()
        t = sink.start()
        sink.emit(t, "enqueued", prompt_len=4)
        sink.emit(t, "admitted", rid=0)
        sink.emit(t, "requeued", reason="quarantine_victim")
        sink.emit(t, "retried", retries=1, backoff_s=0.05)
        sink.emit(t, "admitted", rid=1, resumed=True)
        sink.finish(t, "finished", reason="length")
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(sink.to_chrome_trace()))
        summary = trace_report.summarize(trace_report.load_events(
            str(path)))
        assert summary["total"]["requeued_events"] == 1
        assert summary["total"]["retried_events"] == 1
        row = summary["requests"][0]
        assert row["requeues"] == 1 and row["retries"] == 1
        assert "requeues" in trace_report.render(summary)

    def test_prometheus_exports_fault_counters(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=16,
            max_new_tokens=2, chunk=2, start=False)
        text = eng.metrics.to_prometheus()
        for name in ("step_faults", "quarantines", "requests_requeued",
                     "requests_retried", "watchdog_trips",
                     "flight_dump_errors"):
            assert f"paddle_tpu_{name}_total 0.0" in text
        eng.shutdown()
