"""ptlint (paddle_tpu.analysis) — rule unit tests on purpose-built
fixtures (a true positive AND a true negative per rule), suppression
comments, the baseline ratchet, the CLI, and the whole-package gate:
`paddle_tpu/` must be clean beyond the committed baseline.

These tests exercise the AST engine only — no jax tracing happens, so
the file is cheap even inside the tier-1 budget."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.analysis import (
    ALL_RULES, RULES_BY_ID, analyze_source, apply_baseline,
    load_baseline, load_project, run_rules, save_baseline,
)
from paddle_tpu.analysis.runner import main as ptlint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_src(src, rule=None, relpath="snippet.py"):
    fs = analyze_source(textwrap.dedent(src), relpath=relpath)
    return [f for f in fs if rule is None or f.rule == rule]


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# TRACE001
# ---------------------------------------------------------------------------

def test_trace_print_in_decorated_jit():
    fs = run_src("""
        import jax
        @jax.jit
        def f(x):
            print("tracing!", x)
            return x
    """, "TRACE001")
    assert len(fs) == 1 and "print()" in fs[0].message


def test_trace_closure_append_in_wrapped_fn():
    fs = run_src("""
        import jax
        log = []
        def f(x):
            log.append(x)
            return x
        g = jax.jit(f)
    """, "TRACE001")
    assert len(fs) == 1 and "log.append" in fs[0].message


def test_trace_global_statement_and_attr_store():
    fs = run_src("""
        from jax import jit
        state = {}
        class Holder: pass
        h = Holder()
        @jit
        def f(x):
            global counter
            counter = 1
            h.field = x
            return x
    """, "TRACE001")
    msgs = " | ".join(f.message for f in fs)
    assert "global" in msgs and "attribute 'field'" in msgs


def test_trace_scan_body_flagged():
    fs = run_src("""
        from jax import lax
        def body(carry, x):
            print(carry)
            return carry, x
        out = lax.scan(body, 0, None)
    """, "TRACE001")
    assert len(fs) == 1 and "body of jax.lax.scan" in fs[0].message


def test_trace_fori_and_while_bodies_flagged():
    # fori_loop's body is args[2], while_loop's cond/body are args[0:2]
    fs = run_src("""
        from jax import lax
        def body(i, carry):
            print(i)
            return carry
        out = lax.fori_loop(0, 10, body, 0)
        def cond(c):
            print(c)
            return True
        out2 = lax.while_loop(cond, lambda c: c, 0)
    """, "TRACE001")
    assert len(fs) == 2


def test_trace_negative_eager_fn_and_local_mutation():
    fs = run_src("""
        import jax
        def eager(x):
            print(x)          # not traced: fine
            return x
        @jax.jit
        def f(x):
            acc = []
            acc.append(x)     # local list: fine
            return acc
    """, "TRACE001")
    assert fs == []


def test_trace_same_name_method_not_confused_with_jitted_inner():
    # LLMEngine.run regression: the HOST-side method shares the name of
    # the nested traced fn; only the inner one is traced
    fs = run_src("""
        import jax
        class Engine:
            def run(self):
                print("host side, fine")
                def run(params):
                    return params
                return jax.jit(run)
    """, "TRACE001")
    assert fs == []


# ---------------------------------------------------------------------------
# SYNC001
# ---------------------------------------------------------------------------

def test_sync_hot_path_flags_syncs():
    fs = run_src("""
        import numpy as np
        import jax.numpy as jnp
        class Batcher:
            def step(self):
                active = jnp.asarray(self.active)     # re-upload
                toks = np.asarray(self.toks)          # host copy
                loss = self.metrics.item()            # blocking sync
                return int(jnp.argmax(self.logits))   # blocking cast
    """, "SYNC001", relpath="paddle_tpu/nlp/paged.py")
    assert len(fs) == 4
    msgs = " | ".join(f.message for f in fs)
    assert "re-uploads" in msgs and ".item()" in msgs


def test_sync_negative_cold_path_and_host_values():
    # same code in a non-hot file: silent; host-only casts in a hot
    # file: silent
    assert run_src("""
        import numpy as np
        class Batcher:
            def step(self):
                return np.asarray(self.toks)
    """, "SYNC001", relpath="paddle_tpu/other/module.py") == []
    assert run_src("""
        class Batcher:
            def step(self):
                n = int(len(self.queue))    # host int: fine
                return n
    """, "SYNC001", relpath="paddle_tpu/nlp/paged.py") == []


def test_sync_item_in_traced_fn_any_file():
    fs = run_src("""
        import jax
        @jax.jit
        def f(x):
            return x.item()
    """, "SYNC001")
    assert len(fs) == 1


# ---------------------------------------------------------------------------
# LOCK001
# ---------------------------------------------------------------------------

def test_lock_bare_acquire():
    fs = run_src("""
        import threading
        _lock = threading.Lock()
        def f():
            _lock.acquire()
            _lock.release()
    """, "LOCK001")
    assert len(fs) == 1 and "bare" in fs[0].message


def test_lock_blocking_calls_under_lock():
    fs = run_src("""
        import queue
        import threading
        import time
        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._chan = queue.Queue()
            def bad_sleep(self):
                with self._lock:
                    time.sleep(0.1)
            def bad_get(self):
                with self._lock:
                    return self._chan.get()
    """, "LOCK001")
    msgs = " | ".join(f.message for f in fs)
    assert len(fs) == 2 and "sleeps" in msgs and "blocking" in msgs


def test_lock_timeout_none_still_blocking():
    fs = run_src("""
        import queue
        import threading
        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._chan = queue.Queue()
            def bad(self):
                with self._lock:
                    return self._chan.get(timeout=None)   # blocks forever
    """, "LOCK001")
    assert len(fs) == 1


def test_lock_negatives_with_condition_and_timeouts():
    fs = run_src("""
        import queue
        import threading
        import time
        class Engine:
            def __init__(self):
                self._lock = threading.RLock()
                self._work = threading.Condition(self._lock)
                self._chan = queue.Queue()
            def ok(self):
                with self._work:
                    self._work.wait()           # releases the lock
                    self._chan.get(timeout=1)   # bounded
                    self._chan.get_nowait()
                time.sleep(0.1)                 # outside the lock
    """, "LOCK001")
    assert fs == []


def test_lock_order_inconsistency_nested_with():
    fs = run_src("""
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def f():
            with a_lock:
                with b_lock:
                    pass
        def g():
            with b_lock:
                with a_lock:
                    pass
    """, "LOCK001")
    assert len(fs) == 2
    assert all("inconsistent lock order" in f.message for f in fs)


def test_lock_order_inconsistency_cross_class():
    # the ServingEngine <-> AdmissionQueue shape: holding my lock while
    # calling a method of a typed attribute that takes ITS lock
    fs = run_src("""
        import threading
        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()
            def m(self):
                with self._lock:
                    self.b.n()          # A._lock -> B._lock
        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self.a = A()
            def n(self):
                with self._lock:
                    pass
            def p(self):
                with self._lock:
                    self.a.m()          # B._lock -> A._lock: conflict
    """, "LOCK001")
    assert len(fs) == 2
    assert all("inconsistent lock order" in f.message for f in fs)


def test_lock_order_consistent_is_clean():
    fs = run_src("""
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def f():
            with a_lock:
                with b_lock:
                    pass
        def g():
            with a_lock:
                with b_lock:
                    pass
    """, "LOCK001")
    assert fs == []


# ---------------------------------------------------------------------------
# EXC001
# ---------------------------------------------------------------------------

def test_exc_broad_swallow_flagged():
    fs = run_src("""
        def f():
            try:
                work()
            except Exception:
                pass
        def g():
            try:
                work()
            except:
                return None
    """, "EXC001")
    assert len(fs) == 2


def test_exc_log_substring_names_do_not_count_as_logging():
    # catalog/dialog contain 'log' but are NOT logging calls
    fs = run_src("""
        def f(self):
            try:
                work()
            except Exception as e:
                self.catalog.append(e)
        def g(self):
            try:
                work()
            except Exception:
                self.dialog.close()
    """, "EXC001")
    assert len(fs) == 2


def test_exc_negatives():
    fs = run_src("""
        import logging
        import warnings
        def a():
            try:
                work()
            except ValueError:        # narrow: fine
                pass
        def b():
            try:
                work()
            except Exception:
                raise                 # re-raise: fine
        def c():
            try:
                work()
            except Exception as e:
                logging.warning(e)    # logged: fine
        def d():
            try:
                work()
            except Exception as e:
                warnings.warn(str(e))
    """, "EXC001")
    assert fs == []


# ---------------------------------------------------------------------------
# API001 (multi-file: needs a real project on disk)
# ---------------------------------------------------------------------------

_mini_count = [0]


def _mini_project(tmp_path, init_src, mod_src):
    _mini_count[0] += 1
    pkg = tmp_path / f"pkg{_mini_count[0]}"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(textwrap.dedent(init_src))
    (pkg / "mod.py").write_text(textwrap.dedent(mod_src))
    project, errs = load_project([str(pkg)], str(tmp_path))
    assert errs == []
    return [f for f in run_rules(project, ALL_RULES) if f.rule == "API001"]


def test_api_missing_docstring_across_modules(tmp_path):
    fs = _mini_project(
        tmp_path,
        """
        from .mod import documented, bare
        __all__ = ["documented", "bare", "local_bare"]
        def local_bare():
            return 1
        """,
        '''
        def documented():
            """Has one."""
        def bare():
            return 2
        ''')
    names = sorted(f.message.split("'")[1] for f in fs)
    assert names == ["bare", "local_bare"]


def test_api_negative_all_documented_or_no_all(tmp_path):
    assert _mini_project(
        tmp_path,
        """
        from .mod import documented
        __all__ = ["documented"]
        """,
        '''
        def documented():
            """Yes."""
        ''') == []
    # no __all__: implicit surface, skipped entirely
    assert _mini_project(
        tmp_path,
        "from .mod import bare\n",
        "def bare():\n    return 2\n") == []


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

def test_suppression_inline_and_standalone():
    clean = run_src("""
        def f():
            try:
                work()
            except Exception:  # ptlint: disable=EXC001 — justified here
                pass
        def g():
            try:
                work()
            # ptlint: disable=EXC001 — two-line justification, the
            # comment block carries to the handler line below
            except Exception:
                pass
    """, "EXC001")
    assert clean == []


def test_suppression_survives_blank_line():
    assert run_src("""
        def f():
            try:
                work()
            # ptlint: disable=EXC001 — justified

            except Exception:
                pass
    """, "EXC001") == []


def test_suppression_disable_all_and_wrong_rule():
    assert run_src("""
        def f():
            try:
                work()
            except Exception:  # ptlint: disable=all
                pass
    """, "EXC001") == []
    # disabling a DIFFERENT rule does not silence this one
    fs = run_src("""
        def f():
            try:
                work()
            except Exception:  # ptlint: disable=SYNC001
                pass
    """, "EXC001")
    assert len(fs) == 1


# ---------------------------------------------------------------------------
# baseline ratchet
# ---------------------------------------------------------------------------

_VIOLATION = ("def f():\n    try:\n        g()\n"
              "    except Exception:\n        pass\n")


def _write_pkg(tmp_path, n_violations):
    src = "".join(_VIOLATION.replace("def f", f"def f{i}")
                  for i in range(n_violations))
    p = tmp_path / "code.py"
    p.write_text(src or "x = 1\n")
    return p


def test_baseline_absorbs_then_ratchets(tmp_path):
    p = _write_pkg(tmp_path, 1)
    bl = tmp_path / "baseline.json"
    args = [str(p), "--root", str(tmp_path), "--baseline", str(bl)]
    assert ptlint_main(args + ["--update-baseline"]) == 0
    assert ptlint_main(args) == 0                 # baselined: clean
    # adding a NEW violation fails even though the old one is baselined
    _write_pkg(tmp_path, 2)
    assert ptlint_main(args) == 1


def test_baseline_shrinks_cleanly(tmp_path, capsys):
    p = _write_pkg(tmp_path, 2)
    bl = tmp_path / "baseline.json"
    args = [str(p), "--root", str(tmp_path), "--baseline", str(bl)]
    assert ptlint_main(args + ["--update-baseline"]) == 0
    # identical handler lines share one fingerprint with count 2
    assert sum(load_baseline(str(bl)).values()) == 2
    # burn one down: the run stays green and reports the stale entry
    _write_pkg(tmp_path, 1)
    capsys.readouterr()
    assert ptlint_main(args) == 0
    assert "stale" in capsys.readouterr().out
    # --update-baseline shrinks the file to the surviving violation
    assert ptlint_main(args + ["--update-baseline"]) == 0
    assert sum(load_baseline(str(bl)).values()) == 1


def test_baseline_apply_counts():
    fs = analyze_source(_VIOLATION + _VIOLATION.replace("def f", "def h"))
    assert len(fs) == 2
    base = {fs[0].fingerprint: 1}
    res = apply_baseline(fs, base)
    assert len(res.new) == 1 and len(res.baselined) == 1 and not res.stale


def test_baseline_save_load_roundtrip(tmp_path):
    fs = analyze_source(_VIOLATION)
    path = tmp_path / "b.json"
    saved = save_baseline(str(path), fs)
    assert load_baseline(str(path)) == saved
    assert apply_baseline(fs, saved).new == []


# ---------------------------------------------------------------------------
# CLI / integration
# ---------------------------------------------------------------------------

def test_cli_json_format(tmp_path, capsys):
    p = _write_pkg(tmp_path, 1)
    rc = ptlint_main([str(p), "--root", str(tmp_path), "--no-baseline",
                      "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["exit"] == 1
    assert out["new"][0]["rule"] == "EXC001"
    assert out["new"][0]["path"] == "code.py"


def test_cli_select_and_list_rules(tmp_path, capsys):
    p = _write_pkg(tmp_path, 1)
    rc = ptlint_main([str(p), "--root", str(tmp_path), "--no-baseline",
                      "--select", "SYNC001"])
    assert rc == 0                                # EXC001 not selected
    assert ptlint_main(["--list-rules"]) == 0
    assert "TRACE001" in capsys.readouterr().out
    assert ptlint_main([str(p), "--select", "NOPE"]) == 2


def test_parse_error_reported_not_crash(tmp_path, capsys):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    rc = ptlint_main([str(p), "--root", str(tmp_path), "--no-baseline"])
    assert rc == 1
    assert "PARSE" in capsys.readouterr().out


def test_ptlint_script_runs_standalone():
    # the CI entry point: must work WITHOUT importing the framework
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ptlint.py"),
         "--list-rules"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    for rid in RULES_BY_ID:
        assert rid in out.stdout


def test_repo_clean_beyond_committed_baseline():
    """The acceptance gate: paddle_tpu/ has no findings beyond the
    committed baseline, and the baseline has no stale entries."""
    project, errs = load_project([os.path.join(REPO, "paddle_tpu")], REPO)
    assert errs == []
    findings = run_rules(project, ALL_RULES)
    base = load_baseline(os.path.join(REPO, "tools",
                                      "ptlint_baseline.json"))
    res = apply_baseline(findings, base)
    assert res.new == [], "\n".join(
        f"{f.location} {f.rule} {f.message}" for f in res.new)
    assert res.stale == {}, res.stale


@pytest.mark.slow
def test_module_entrypoint_exits_zero():
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "paddle_tpu/",
         "--root", REPO],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
