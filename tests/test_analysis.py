"""ptlint (paddle_tpu.analysis) — rule unit tests on purpose-built
fixtures (a true positive AND a true negative per rule), suppression
comments, the baseline ratchet, the CLI, and the whole-package gate:
`paddle_tpu/` must be clean beyond the committed baseline.

These tests exercise the AST engine only — no jax tracing happens, so
the file is cheap even inside the tier-1 budget."""
import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.analysis import (
    ALL_RULES, RULES_BY_ID, analyze_source, apply_baseline,
    load_baseline, load_project, run_rules, save_baseline,
)
from paddle_tpu.analysis.callgraph import build_callgraph
from paddle_tpu.analysis.core import FileContext, Project
from paddle_tpu.analysis.rules.memo import discover_memo_caches
from paddle_tpu.analysis.rules.sync import derive_hot_paths
from paddle_tpu.analysis.runner import main as ptlint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def graph_of(src, relpath="paddle_tpu/mod.py"):
    ctx = FileContext(relpath, textwrap.dedent(src), relpath)
    project = Project([ctx])
    return build_callgraph(project), ctx


_real_tree_cache = []


def real_tree():
    """The whole-package Project, loaded once per test session: the
    clean-gate and the hot-set superset test share it (and its cached
    call graph) so the tier-1 wall-clock pays one parse, not three."""
    if not _real_tree_cache:
        project, errs = load_project(
            [os.path.join(REPO, "paddle_tpu")], REPO)
        assert errs == []
        _real_tree_cache.append(project)
    return _real_tree_cache[0]


def run_src(src, rule=None, relpath="snippet.py"):
    fs = analyze_source(textwrap.dedent(src), relpath=relpath)
    return [f for f in fs if rule is None or f.rule == rule]


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# TRACE001
# ---------------------------------------------------------------------------

def test_trace_print_in_decorated_jit():
    fs = run_src("""
        import jax
        @jax.jit
        def f(x):
            print("tracing!", x)
            return x
    """, "TRACE001")
    assert len(fs) == 1 and "print()" in fs[0].message


def test_trace_closure_append_in_wrapped_fn():
    fs = run_src("""
        import jax
        log = []
        def f(x):
            log.append(x)
            return x
        g = jax.jit(f)
    """, "TRACE001")
    assert len(fs) == 1 and "log.append" in fs[0].message


def test_trace_global_statement_and_attr_store():
    fs = run_src("""
        from jax import jit
        state = {}
        class Holder: pass
        h = Holder()
        @jit
        def f(x):
            global counter
            counter = 1
            h.field = x
            return x
    """, "TRACE001")
    msgs = " | ".join(f.message for f in fs)
    assert "global" in msgs and "attribute 'field'" in msgs


def test_trace_scan_body_flagged():
    fs = run_src("""
        from jax import lax
        def body(carry, x):
            print(carry)
            return carry, x
        out = lax.scan(body, 0, None)
    """, "TRACE001")
    assert len(fs) == 1 and "body of jax.lax.scan" in fs[0].message


def test_trace_fori_and_while_bodies_flagged():
    # fori_loop's body is args[2], while_loop's cond/body are args[0:2]
    fs = run_src("""
        from jax import lax
        def body(i, carry):
            print(i)
            return carry
        out = lax.fori_loop(0, 10, body, 0)
        def cond(c):
            print(c)
            return True
        out2 = lax.while_loop(cond, lambda c: c, 0)
    """, "TRACE001")
    assert len(fs) == 2


def test_trace_negative_eager_fn_and_local_mutation():
    fs = run_src("""
        import jax
        def eager(x):
            print(x)          # not traced: fine
            return x
        @jax.jit
        def f(x):
            acc = []
            acc.append(x)     # local list: fine
            return acc
    """, "TRACE001")
    assert fs == []


def test_trace_same_name_method_not_confused_with_jitted_inner():
    # LLMEngine.run regression: the HOST-side method shares the name of
    # the nested traced fn; only the inner one is traced
    fs = run_src("""
        import jax
        class Engine:
            def run(self):
                print("host side, fine")
                def run(params):
                    return params
                return jax.jit(run)
    """, "TRACE001")
    assert fs == []


# ---------------------------------------------------------------------------
# SYNC001
# ---------------------------------------------------------------------------

def test_sync_hot_path_flags_syncs():
    fs = run_src("""
        import numpy as np
        import jax.numpy as jnp
        class Batcher:
            def step(self):
                active = jnp.asarray(self.active)     # re-upload
                toks = np.asarray(self.toks)          # host copy
                loss = self.metrics.item()            # blocking sync
                return int(jnp.argmax(self.logits))   # blocking cast
    """, "SYNC001", relpath="paddle_tpu/nlp/paged.py")
    assert len(fs) == 4
    msgs = " | ".join(f.message for f in fs)
    assert "re-uploads" in msgs and ".item()" in msgs


def test_sync_negative_cold_path_and_host_values():
    # same code in a non-hot file: silent; host-only casts in a hot
    # file: silent
    assert run_src("""
        import numpy as np
        class Batcher:
            def step(self):
                return np.asarray(self.toks)
    """, "SYNC001", relpath="paddle_tpu/other/module.py") == []
    assert run_src("""
        class Batcher:
            def step(self):
                n = int(len(self.queue))    # host int: fine
                return n
    """, "SYNC001", relpath="paddle_tpu/nlp/paged.py") == []


def test_sync_item_in_traced_fn_any_file():
    fs = run_src("""
        import jax
        @jax.jit
        def f(x):
            return x.item()
    """, "SYNC001")
    assert len(fs) == 1


# ---------------------------------------------------------------------------
# call graph (analysis.callgraph): the engine under SYNC001's closure
# and GUARD001's thread attribution
# ---------------------------------------------------------------------------

def test_callgraph_resolves_through_self_attr_types():
    # the constructor-assignment type map: self.q = Queue() makes
    # self.q.push() an edge to Queue.push
    graph, ctx = graph_of("""
        class Queue:
            def push(self, item):
                pass
        class Engine:
            def __init__(self):
                self.q = Queue()
            def admit(self):
                self.q.push(1)
    """)
    mod = ctx.module_name
    assert (mod, "Queue", "push") in graph.edges[(mod, "Engine", "admit")]


def test_callgraph_resolves_local_ctor_then_self_assign():
    # the normalize-an-optional-arg idiom: a local built from a ctor
    # (possibly inside an `if`) then stored on self still types the attr
    graph, ctx = graph_of("""
        class Sink:
            def emit(self):
                pass
        class Engine:
            def __init__(self, sink=None):
                if sink is None:
                    sink = Sink()
                self._sink = sink
            def tick(self):
                self._sink.emit()
    """)
    mod = ctx.module_name
    assert (mod, "Sink", "emit") in graph.edges[(mod, "Engine", "tick")]


def test_callgraph_cross_module_resolution(tmp_path):
    # imports + the class index resolve edges across files
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "sched.py").write_text(textwrap.dedent("""
        class Queue:
            def pop(self):
                pass
    """))
    (pkg / "eng.py").write_text(textwrap.dedent("""
        from .sched import Queue
        class Engine:
            def __init__(self):
                self.q = Queue()
            def tick(self):
                self.q.pop()
    """))
    project, errs = load_project([str(pkg)], str(tmp_path))
    assert errs == []
    graph = build_callgraph(project)
    assert ("pkg.sched", "Queue", "pop") in \
        graph.edges[("pkg.eng", "Engine", "tick")]


def test_callgraph_thread_entrypoint_discovery():
    graph, ctx = graph_of("""
        import asyncio
        import threading
        from concurrent.futures import ThreadPoolExecutor
        class Engine:
            def __init__(self):
                self._pool = ThreadPoolExecutor(2)
            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()
                threading.Timer(1.0, self._tick).start()
                self._pool.submit(self._work, 1)
                asyncio.run_coroutine_threadsafe(self._serve(), loop)
            def _loop(self): pass
            def _tick(self): pass
            def _work(self, n): pass
            async def _serve(self): pass
    """)
    mod = ctx.module_name
    roots = {(r.key, r.kind) for r in graph.thread_roots}
    assert ((mod, "Engine", "_loop"), "Thread(target=)") in roots
    assert ((mod, "Engine", "_tick"), "Timer") in roots
    assert ((mod, "Engine", "_work"), "executor.submit") in roots
    assert ((mod, "Engine", "_serve"), "run_coroutine_threadsafe") in roots
    # spawning is NOT calling: start() gets no edge to the targets
    assert (mod, "Engine", "_loop") not in graph.edges[(mod, "Engine",
                                                        "start")]


def test_callgraph_closure_propagates_and_cycles_terminate():
    graph, ctx = graph_of("""
        def a():
            b()
        def b():
            c()
        def c():
            a()        # cycle
        def lonely():
            pass
    """)
    mod = ctx.module_name
    reach = graph.reachable([(mod, None, "a")])
    assert reach == {(mod, None, "a"), (mod, None, "b"), (mod, None, "c")}
    prov = graph.closure_provenance([(mod, None, "a")])
    assert prov[(mod, None, "c")] == (mod, None, "a")


def test_callgraph_function_reference_args_make_edges():
    # callbacks run on the caller's thread: pop(fits=self._fits) must
    # put _fits inside pop's caller's closure
    graph, ctx = graph_of("""
        class Engine:
            def admit(self):
                self.q.pop(fits=self._fits, prefer=best)
            def _fits(self, r):
                return True
        def best(r):
            return False
    """)
    mod = ctx.module_name
    out = graph.edges[(mod, "Engine", "admit")]
    assert (mod, "Engine", "_fits") in out
    assert (mod, None, "best") in out


# ---------------------------------------------------------------------------
# LOCK001
# ---------------------------------------------------------------------------

def test_lock_bare_acquire():
    fs = run_src("""
        import threading
        _lock = threading.Lock()
        def f():
            _lock.acquire()
            _lock.release()
    """, "LOCK001")
    assert len(fs) == 1 and "bare" in fs[0].message


def test_lock_blocking_calls_under_lock():
    fs = run_src("""
        import queue
        import threading
        import time
        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._chan = queue.Queue()
            def bad_sleep(self):
                with self._lock:
                    time.sleep(0.1)
            def bad_get(self):
                with self._lock:
                    return self._chan.get()
    """, "LOCK001")
    msgs = " | ".join(f.message for f in fs)
    assert len(fs) == 2 and "sleeps" in msgs and "blocking" in msgs


def test_lock_timeout_none_still_blocking():
    fs = run_src("""
        import queue
        import threading
        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._chan = queue.Queue()
            def bad(self):
                with self._lock:
                    return self._chan.get(timeout=None)   # blocks forever
    """, "LOCK001")
    assert len(fs) == 1


def test_lock_negatives_with_condition_and_timeouts():
    fs = run_src("""
        import queue
        import threading
        import time
        class Engine:
            def __init__(self):
                self._lock = threading.RLock()
                self._work = threading.Condition(self._lock)
                self._chan = queue.Queue()
            def ok(self):
                with self._work:
                    self._work.wait()           # releases the lock
                    self._chan.get(timeout=1)   # bounded
                    self._chan.get_nowait()
                time.sleep(0.1)                 # outside the lock
    """, "LOCK001")
    assert fs == []


def test_lock_order_inconsistency_nested_with():
    fs = run_src("""
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def f():
            with a_lock:
                with b_lock:
                    pass
        def g():
            with b_lock:
                with a_lock:
                    pass
    """, "LOCK001")
    assert len(fs) == 2
    assert all("inconsistent lock order" in f.message for f in fs)


def test_lock_order_inconsistency_cross_class():
    # the ServingEngine <-> AdmissionQueue shape: holding my lock while
    # calling a method of a typed attribute that takes ITS lock
    fs = run_src("""
        import threading
        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()
            def m(self):
                with self._lock:
                    self.b.n()          # A._lock -> B._lock
        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self.a = A()
            def n(self):
                with self._lock:
                    pass
            def p(self):
                with self._lock:
                    self.a.m()          # B._lock -> A._lock: conflict
    """, "LOCK001")
    assert len(fs) == 2
    assert all("inconsistent lock order" in f.message for f in fs)


def test_lock_order_consistent_is_clean():
    fs = run_src("""
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def f():
            with a_lock:
                with b_lock:
                    pass
        def g():
            with a_lock:
                with b_lock:
                    pass
    """, "LOCK001")
    assert fs == []


# ---------------------------------------------------------------------------
# GUARD001: cross-thread access to lock-guarded fields
# ---------------------------------------------------------------------------

_RACY_ENGINE = """
    import threading
    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
        def start(self):
            threading.Thread(target=self._loop, daemon=True).start()
        def _loop(self):
            with self._lock:
                self.count += 1
        def peek(self):
            return self.count
"""


def test_guard_true_race_flagged():
    fs = run_src(_RACY_ENGINE, "GUARD001")
    assert len(fs) == 1
    f = fs[0]
    assert "count" in f.message and "Engine._lock" in f.message
    assert "Engine.peek" in f.message
    assert f.snippet == "return self.count"


def test_guard_with_lock_access_clean():
    fs = run_src(_RACY_ENGINE.replace(
        "        def peek(self):\n            return self.count",
        "        def peek(self):\n"
        "            with self._lock:\n"
        "                return self.count"), "GUARD001")
    assert fs == []


def test_guard_single_thread_class_clean():
    # no thread entry points anywhere: every access is one context,
    # thread-confined de facto — even unlocked reads stay silent
    fs = run_src("""
        import threading
        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
            def bump(self):
                with self._lock:
                    self.count += 1
            def peek(self):
                return self.count
    """, "GUARD001")
    assert fs == []


def test_guard_locked_suffix_convention_clean():
    # *_locked methods document "caller holds my lock": their bodies
    # are checked as if the class's guard locks were held
    fs = run_src(_RACY_ENGINE.replace(
        "        def peek(self):\n            return self.count",
        "        def peek(self):\n"
        "            with self._lock:\n"
        "                return self._peek_locked()\n"
        "        def _peek_locked(self):\n"
        "            return self.count"), "GUARD001")
    assert fs == []


def test_guard_suppression_guarded_by_and_disable():
    fs = run_src(_RACY_ENGINE.replace(
        "            return self.count",
        "            # ptlint: guarded-by(_lock) — callers hold it\n"
        "            return self.count"), "GUARD001")
    assert fs == []
    fs = run_src(_RACY_ENGINE.replace(
        "            return self.count",
        "            return self.count"
        "  # ptlint: disable=GUARD001 — stats-only read"), "GUARD001")
    assert fs == []


def test_guard_thread_confined_field_annotation():
    # thread-confined on the defining assignment exempts the FIELD:
    # both the unlocked read and any other access stay silent
    fs = run_src(_RACY_ENGINE.replace(
        "            self.count = 0",
        "            # ptlint: thread-confined — engine-thread stats\n"
        "            self.count = 0"), "GUARD001")
    assert fs == []


def test_guard_cross_class_field_via_type_map():
    # the AdmissionQueue shape: another class reaches into a typed
    # attr's guarded internals without that class's lock
    src = """
        import threading
        class Queue:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
            def push(self, x):
                with self._lock:
                    self._items.append(x)
        class Engine:
            def __init__(self):
                self.q = Queue()
            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()
            def _loop(self):
                self.q.push(1)
            def depth(self):
                return len(self.q._items){LOCK}
    """
    fs = run_src(src.replace("{LOCK}", ""), "GUARD001")
    assert len(fs) == 1
    assert "_items" in fs[0].message and "Queue._lock" in fs[0].message
    # holding the OWNER's lock through the typed attr is clean
    locked = src.replace(
        "                return len(self.q._items){LOCK}",
        "                with self.q._lock:\n"
        "                    return len(self.q._items)")
    assert run_src(locked, "GUARD001") == []


def test_guard_inherited_field_shares_storage():
    # Base writes the field under its lock; a Derived-only method
    # reads it unlocked from another thread. Same instance storage,
    # same actual lock — the chain is one component, still a race
    src = """
        import threading
        class Base:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
            def bump(self):
                with self._lock:
                    self.count += 1
        class Derived(Base):
            def start(self):
                threading.Thread(target=self.bump).start()
            def peek(self):
                return self.count
    """
    fs = run_src(src, "GUARD001")
    assert len(fs) == 1 and "count" in fs[0].message
    # holding the (inherited) lock in the derived method is clean:
    # 'Derived._lock' and 'Base._lock' canonicalize to one lock
    locked = src.replace(
        "            def peek(self):\n                return self.count",
        "            def peek(self):\n"
        "                with self._lock:\n"
        "                    return self.count")
    assert run_src(locked, "GUARD001") == []


def test_guard_mutating_call_counts_as_guarded_write():
    # a field only ever .append()ed under the lock is still guarded
    fs = run_src("""
        import threading
        class Log:
            def __init__(self):
                self._lock = threading.Lock()
                self._events = []
            def start(self):
                threading.Thread(target=self._loop).start()
            def _loop(self):
                with self._lock:
                    self._events.append(1)
            def dump(self):
                return list(self._events)
    """, "GUARD001")
    assert len(fs) == 1 and "_events" in fs[0].message


# ---------------------------------------------------------------------------
# SYNC001 closure: seed roots derive their transitive callees
# ---------------------------------------------------------------------------

def test_sync_closure_derives_new_helper():
    # the whole point of the refactor: a helper step() calls is hot the
    # day it's written, with no hand-list entry
    fs = run_src("""
        class Batcher:
            def step(self):
                self._new_helper()
            def _new_helper(self):
                return self.metrics.item()
    """, "SYNC001", relpath="paddle_tpu/nlp/paged.py")
    assert len(fs) == 1
    assert "_new_helper" in fs[0].message
    assert "via" in fs[0].message          # provenance names the root


def test_sync_closure_follows_inherited_helper():
    # a helper defined only on a base class is still on the hot path
    # when a hot root calls it through self — method resolution walks
    # the in-tree base chain, so 'covered the day it's written' holds
    # for mixin/base refactors too
    fs = run_src("""
        class Base:
            def _helper(self):
                return self.metrics.item()
        class Batcher(Base):
            def step(self):
                self._helper()
    """, "SYNC001", relpath="paddle_tpu/nlp/paged.py")
    assert len(fs) == 1 and "_helper" in fs[0].message


def test_callgraph_method_resolves_through_base_chain():
    graph, _ctx = graph_of("""
        class Base:
            def helper(self):
                pass
        class Mid(Base):
            pass
        class Leaf(Mid):
            def run(self):
                self.helper()
    """)
    key = graph.method("Leaf", "helper")
    assert key is not None and key[1] == "Base"
    run_key = graph.method("Leaf", "run")
    assert key in graph.edges[run_key]


def test_sync_closure_crosses_files(tmp_path):
    # a hot root in one module pulls a callee in ANOTHER module into
    # the hot set — the hand list could never say this
    pkg = tmp_path / "nlp"
    pkg.mkdir()
    (pkg / "util.py").write_text(textwrap.dedent("""
        class Sink:
            def emit(self):
                return self.buf.item()
    """))
    (pkg / "paged.py").write_text(textwrap.dedent("""
        from .util import Sink
        class Batcher:
            def __init__(self):
                self._sink = Sink()
            def step(self):
                self._sink.emit()
    """))
    project, errs = load_project([str(pkg)], str(tmp_path))
    assert errs == []
    fs = [f for f in run_rules(project, ALL_RULES) if f.rule == "SYNC001"]
    assert len(fs) == 1 and fs[0].path.endswith("util.py")


def test_sync_dead_root_reported():
    # a root pattern matching nothing in its file is DEAD — the report
    # that stops a rename from silently shrinking coverage
    ctx = FileContext("paddle_tpu/nlp/paged.py",
                      "class Batcher:\n    def step(self):\n        pass\n",
                      "paddle_tpu/nlp/paged.py")
    hot, dead = derive_hot_paths(Project([ctx]))
    assert ("nlp/paged.py", "run") in dead
    assert all(name != "run" for _, node, _ in hot.values()
               for name in [node.name])


# the hand-maintained HOT_PATHS list as it stood before the call-graph
# closure replaced it (PR 14 state, verbatim): the derived hot set must
# remain a SUPERSET of everything this list matched, forever — deleting
# a hand entry is only legal because the closure provably covers it
_OLD_HOT_PATHS = (
    ("nlp/paged.py",
     r"^(step|run|_step_fused|_prefill_pending|_run_standalone_unit"
     r"|_paged_gqa_attention|forward_paged|_write_pool|_write_pool_int8"
     r"|_trace_emit|_trace_chunks|_record_tick"
     r"|_step_spec|_emit_spec|_spec_any|_drain_emitted"
     r"|_forward_spec|_spec_gqa_attention|_profile_t0|_profile_commit)$"),
    ("nlp/ragged_attention.py",
     r"^(ragged_paged_attention|_rpa_kernel|resolve_attention_impl)$"),
    ("quantization/kv.py",
     r"^(quantize|dequantize|rescale_codes|scale_of)$"),
    ("serving/engine.py", r"^(_loop|_dispatch|step|load|_slo_eval)$"),
    ("serving/slo.py",
     r"^(record_ttft|record_itl|record_queue_wait|record_tokens"
     r"|record_request|_record|evaluate|pop_transitions)$"),
    ("serving/profiling.py",
     r"^(should_fence|record|arm_capture|capture_active)$"),
    ("serving/speculative.py",
     r"^(record_step|accept_rate|tokens_per_step)$"),
    ("serving/router.py",
     r"^(submit|_place|_views|_bridge|_monitor_loop|_sweep_locked"
     r"|_handle_terminal|_failover)$"),
    ("serving/frontend.py",
     r"^(_handle|_generate|_stream_sse|_submit|_read_request)$"),
    ("serving/supervisor.py",
     r"^(_loop|_restart_slot|_probe|slot_serving|info)$"),
    ("serving/trace.py",
     r"^(emit|finish|start|alias|span|now|record)$"),
)


def test_sync_derived_hot_set_superset_of_old_list():
    """No silent coverage loss: every function the old hand list
    matched on the REAL tree is in the derived hot set."""
    import ast
    import re
    project = real_tree()
    hot, dead = derive_hot_paths(project)
    derived = {}
    for ctx, node, _reason in hot.values():
        derived.setdefault(ctx.relpath, set()).add(node.name)
    missing = []
    for suffix, rx in _OLD_HOT_PATHS:
        pat = re.compile(rx)
        for ctx in project.files:
            if ctx.tree is None or not ctx.relpath.endswith(suffix):
                continue
            for n in ast.walk(ctx.tree):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and pat.match(n.name) \
                        and n.name not in derived.get(ctx.relpath, set()):
                    missing.append(f"{ctx.relpath}::{n.name}")
    assert missing == [], f"hot-set coverage lost vs the old hand list: " \
                          f"{missing}"
    # and the live seed roots are all alive on the real tree
    assert dead == [], f"dead HOT_ROOTS entries on the real tree: {dead}"


# ---------------------------------------------------------------------------
# EXC001
# ---------------------------------------------------------------------------

def test_exc_broad_swallow_flagged():
    fs = run_src("""
        def f():
            try:
                work()
            except Exception:
                pass
        def g():
            try:
                work()
            except:
                return None
    """, "EXC001")
    assert len(fs) == 2


def test_exc_log_substring_names_do_not_count_as_logging():
    # catalog/dialog contain 'log' but are NOT logging calls
    fs = run_src("""
        def f(self):
            try:
                work()
            except Exception as e:
                self.catalog.append(e)
        def g(self):
            try:
                work()
            except Exception:
                self.dialog.close()
    """, "EXC001")
    assert len(fs) == 2


def test_exc_negatives():
    fs = run_src("""
        import logging
        import warnings
        def a():
            try:
                work()
            except ValueError:        # narrow: fine
                pass
        def b():
            try:
                work()
            except Exception:
                raise                 # re-raise: fine
        def c():
            try:
                work()
            except Exception as e:
                logging.warning(e)    # logged: fine
        def d():
            try:
                work()
            except Exception as e:
                warnings.warn(str(e))
    """, "EXC001")
    assert fs == []


# ---------------------------------------------------------------------------
# API001 (multi-file: needs a real project on disk)
# ---------------------------------------------------------------------------

_mini_count = [0]


def _mini_project(tmp_path, init_src, mod_src):
    _mini_count[0] += 1
    pkg = tmp_path / f"pkg{_mini_count[0]}"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(textwrap.dedent(init_src))
    (pkg / "mod.py").write_text(textwrap.dedent(mod_src))
    project, errs = load_project([str(pkg)], str(tmp_path))
    assert errs == []
    return [f for f in run_rules(project, ALL_RULES) if f.rule == "API001"]


def test_api_missing_docstring_across_modules(tmp_path):
    fs = _mini_project(
        tmp_path,
        """
        from .mod import documented, bare
        __all__ = ["documented", "bare", "local_bare"]
        def local_bare():
            return 1
        """,
        '''
        def documented():
            """Has one."""
        def bare():
            return 2
        ''')
    names = sorted(f.message.split("'")[1] for f in fs)
    assert names == ["bare", "local_bare"]


def test_api_negative_all_documented_or_no_all(tmp_path):
    assert _mini_project(
        tmp_path,
        """
        from .mod import documented
        __all__ = ["documented"]
        """,
        '''
        def documented():
            """Yes."""
        ''') == []
    # no __all__: implicit surface, skipped entirely
    assert _mini_project(
        tmp_path,
        "from .mod import bare\n",
        "def bare():\n    return 2\n") == []


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

def test_suppression_inline_and_standalone():
    clean = run_src("""
        def f():
            try:
                work()
            except Exception:  # ptlint: disable=EXC001 — justified here
                pass
        def g():
            try:
                work()
            # ptlint: disable=EXC001 — two-line justification, the
            # comment block carries to the handler line below
            except Exception:
                pass
    """, "EXC001")
    assert clean == []


def test_suppression_survives_blank_line():
    assert run_src("""
        def f():
            try:
                work()
            # ptlint: disable=EXC001 — justified

            except Exception:
                pass
    """, "EXC001") == []


def test_suppression_disable_all_and_wrong_rule():
    assert run_src("""
        def f():
            try:
                work()
            except Exception:  # ptlint: disable=all
                pass
    """, "EXC001") == []
    # disabling a DIFFERENT rule does not silence this one
    fs = run_src("""
        def f():
            try:
                work()
            except Exception:  # ptlint: disable=SYNC001
                pass
    """, "EXC001")
    assert len(fs) == 1


# ---------------------------------------------------------------------------
# baseline ratchet
# ---------------------------------------------------------------------------

_VIOLATION = ("def f():\n    try:\n        g()\n"
              "    except Exception:\n        pass\n")


def _write_pkg(tmp_path, n_violations):
    src = "".join(_VIOLATION.replace("def f", f"def f{i}")
                  for i in range(n_violations))
    p = tmp_path / "code.py"
    p.write_text(src or "x = 1\n")
    return p


def test_baseline_absorbs_then_ratchets(tmp_path):
    p = _write_pkg(tmp_path, 1)
    bl = tmp_path / "baseline.json"
    args = [str(p), "--root", str(tmp_path), "--baseline", str(bl)]
    assert ptlint_main(args + ["--update-baseline"]) == 0
    assert ptlint_main(args) == 0                 # baselined: clean
    # adding a NEW violation fails even though the old one is baselined
    _write_pkg(tmp_path, 2)
    assert ptlint_main(args) == 1


def test_baseline_shrinks_cleanly(tmp_path, capsys):
    p = _write_pkg(tmp_path, 2)
    bl = tmp_path / "baseline.json"
    args = [str(p), "--root", str(tmp_path), "--baseline", str(bl)]
    assert ptlint_main(args + ["--update-baseline"]) == 0
    # identical handler lines share one fingerprint with count 2
    assert sum(load_baseline(str(bl)).values()) == 2
    # burn one down: the run stays green and reports the stale entry
    _write_pkg(tmp_path, 1)
    capsys.readouterr()
    assert ptlint_main(args) == 0
    assert "stale" in capsys.readouterr().out
    # --update-baseline shrinks the file to the surviving violation
    assert ptlint_main(args + ["--update-baseline"]) == 0
    assert sum(load_baseline(str(bl)).values()) == 1


def test_baseline_apply_counts():
    fs = analyze_source(_VIOLATION + _VIOLATION.replace("def f", "def h"))
    assert len(fs) == 2
    base = {fs[0].fingerprint: 1}
    res = apply_baseline(fs, base)
    assert len(res.new) == 1 and len(res.baselined) == 1 and not res.stale


def test_baseline_save_load_roundtrip(tmp_path):
    fs = analyze_source(_VIOLATION)
    path = tmp_path / "b.json"
    saved = save_baseline(str(path), fs)
    assert load_baseline(str(path)) == saved
    assert apply_baseline(fs, saved).new == []


# ---------------------------------------------------------------------------
# CLI / integration
# ---------------------------------------------------------------------------

def test_cli_json_format(tmp_path, capsys):
    p = _write_pkg(tmp_path, 1)
    rc = ptlint_main([str(p), "--root", str(tmp_path), "--no-baseline",
                      "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["exit"] == 1
    assert out["new"][0]["rule"] == "EXC001"
    assert out["new"][0]["path"] == "code.py"


def test_cli_select_and_list_rules(tmp_path, capsys):
    p = _write_pkg(tmp_path, 1)
    rc = ptlint_main([str(p), "--root", str(tmp_path), "--no-baseline",
                      "--select", "SYNC001"])
    assert rc == 0                                # EXC001 not selected
    assert ptlint_main(["--list-rules"]) == 0
    assert "TRACE001" in capsys.readouterr().out
    assert ptlint_main([str(p), "--select", "NOPE"]) == 2


def test_cli_github_format_annotations(tmp_path, capsys):
    p = _write_pkg(tmp_path, 1)
    rc = ptlint_main([str(p), "--root", str(tmp_path), "--no-baseline",
                      "--format", "github"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "::error file=code.py,line=" in out
    assert "title=ptlint EXC001::" in out
    # clean tree: no ::error lines, summary still printed
    (tmp_path / "clean.py").write_text("x = 1\n")
    rc = ptlint_main([str(tmp_path / "clean.py"), "--root", str(tmp_path),
                      "--no-baseline", "--format", "github"])
    out = capsys.readouterr().out
    assert rc == 0 and "::error" not in out and "0 new finding" in out


def test_cli_hot_report_nonblocking(tmp_path, capsys):
    pkg = tmp_path / "nlp"
    pkg.mkdir()
    (pkg / "paged.py").write_text(
        "class Batcher:\n"
        "    def step(self):\n"
        "        self._helper()\n"
        "    def _helper(self):\n"
        "        pass\n")
    rc = ptlint_main([str(pkg), "--root", str(tmp_path), "--hot-report"])
    out = capsys.readouterr().out
    assert rc == 0                      # informational: never fails
    assert "derived hot set" in out
    assert "_helper" in out and "via" in out
    assert "DEAD hot-path roots" in out     # `run` has no match here


def test_cli_hot_report_warns_on_parse_error(tmp_path, capsys):
    # a file that fails to parse contributes no functions: the report
    # must lead with the gap, not present a silently shrunken hot set
    pkg = tmp_path / "nlp"
    pkg.mkdir()
    (pkg / "paged.py").write_text("def step(:\n")
    rc = ptlint_main([str(pkg), "--root", str(tmp_path), "--hot-report"])
    out = capsys.readouterr().out
    assert rc == 0                      # still informational
    assert "WARNING" in out and "incomplete" in out
    assert "paged.py" in out


def test_cli_time_budget_exceeded(tmp_path, capsys):
    p = tmp_path / "ok.py"
    p.write_text("x = 1\n")
    args = [str(p), "--root", str(tmp_path), "--no-baseline"]
    assert ptlint_main(args + ["--time-budget", "600"]) == 0
    capsys.readouterr()
    # a zero budget always trips: clean findings still fail the run
    rc = ptlint_main(args + ["--time-budget", "0"])
    err = capsys.readouterr().err
    assert rc == 1 and "TIME BUDGET EXCEEDED" in err


def test_parse_error_reported_not_crash(tmp_path, capsys):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    rc = ptlint_main([str(p), "--root", str(tmp_path), "--no-baseline"])
    assert rc == 1
    assert "PARSE" in capsys.readouterr().out


def test_ptlint_script_runs_standalone():
    # the CI entry point: must work WITHOUT importing the framework
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ptlint.py"),
         "--list-rules"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    for rid in RULES_BY_ID:
        assert rid in out.stdout


def test_repo_clean_beyond_committed_baseline():
    """The acceptance gate: paddle_tpu/ has no findings beyond the
    committed baseline, and the baseline has no stale entries."""
    findings = run_rules(real_tree(), ALL_RULES)
    base = load_baseline(os.path.join(REPO, "tools",
                                      "ptlint_baseline.json"))
    res = apply_baseline(findings, base)
    assert res.new == [], "\n".join(
        f"{f.location} {f.rule} {f.message}" for f in res.new)
    assert res.stale == {}, res.stale


@pytest.mark.slow
def test_module_entrypoint_exits_zero():
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "paddle_tpu/",
         "--root", REPO],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr


# ---------------------------------------------------------------------------
# KEY001 — memo-key soundness
# ---------------------------------------------------------------------------

# the paged idiom in miniature: one key helper feeding get/set/member
# sites, a _build_* closure that bakes `self.cfg` into the lowered
# program, and the two declared-mandatory config tuples
_MEMO_OK = """
    import jax

    class Batcher:
        def __init__(self, cfg, impl, wq, kq):
            self.cfg = cfg
            # ptlint: trace-config
            self.impl = impl
            # ptlint: trace-config
            self._qkey = (wq, kq)
            self._step_cache = {}

        def _key(self, n):
            return (n, self.cfg, self.impl) + self._qkey

        def _build_step(self):
            cfg = self.cfg

            def step(x):
                return x * cfg.scale

            return jax.jit(step)

        def _step_exe(self, n):
            key = self._key(n)
            exe = self._step_cache.get(key)
            if exe is None:
                exe = self._build_step()
                self._step_cache[key] = exe
            return exe

        def warmed(self, n):
            return self._key(n) in self._step_cache
"""


def test_key_clean_paged_idiom():
    assert run_src(_MEMO_OK, "KEY001") == []


def test_key_mutation_deleting_qkey_yields_exactly_one_finding():
    """The teeth test: drop `+ self._qkey` from the key helper (the
    PR 9 bug shape) — exactly one finding, of the stale-executable
    kind, because `_qkey` is declared trace-config (key-mandatory)."""
    mutated = _MEMO_OK.replace(
        "return (n, self.cfg, self.impl) + self._qkey",
        "return (n, self.cfg, self.impl)")
    assert mutated != _MEMO_OK
    fs = run_src(mutated, "KEY001")
    assert len(fs) == 1, [f.message for f in fs]
    assert "_qkey" in fs[0].message and "STALE" in fs[0].message


def test_key_mutation_deleting_impl_yields_exactly_one_finding():
    mutated = _MEMO_OK.replace(
        "return (n, self.cfg, self.impl) + self._qkey",
        "return (n, self.cfg) + self._qkey")
    fs = run_src(mutated, "KEY001")
    assert len(fs) == 1
    assert "impl" in fs[0].message and "trace-config" in fs[0].message


def test_key_missing_config_read_under_trace():
    """Finding kind 1: the builder bakes `self.depth` in, the key
    doesn't carry it — a depth change serves a stale executable."""
    fs = run_src("""
        import jax

        class B:
            def __init__(self, cfg, depth):
                self.cfg = cfg
                self.depth = depth
                self._c_cache = {}

            def _build_c(self):
                c, d = self.cfg, self.depth

                def f(x):
                    return x * c.scale + d

                return jax.jit(f)

            def _c_exe(self, n):
                key = (n, self.cfg)
                exe = self._c_cache.get(key)
                if exe is None:
                    exe = self._build_c()
                    self._c_cache[key] = exe
                return exe
    """, "KEY001")
    assert len(fs) == 1, [f.message for f in fs]
    assert "depth" in fs[0].message and "STALE" in fs[0].message


def test_key_spurious_element_never_read():
    """Finding kind 2: `self.tag` rides the key but nothing traced
    reads it — every distinct tag recompiles an identical program."""
    fs = run_src("""
        import jax

        class B:
            def __init__(self, cfg, tag):
                self.cfg = cfg
                self.tag = tag
                self._c_cache = {}

            def _build_c(self):
                c = self.cfg

                def f(x):
                    return x * c.scale

                return jax.jit(f)

            def _c_exe(self, n):
                key = (n, self.cfg, self.tag)
                exe = self._c_cache.get(key)
                if exe is None:
                    exe = self._build_c()
                    self._c_cache[key] = exe
                return exe
    """, "KEY001")
    assert len(fs) == 1, [f.message for f in fs]
    assert "tag" in fs[0].message and "never read" in fs[0].message


def test_key_membership_check_drift():
    """Finding kind 3: the warmup `in`-check forgot an element the
    `.get` key carries — the PR 9/14 warmup-assertion bug shape."""
    fs = run_src("""
        import jax

        class B:
            def __init__(self, cfg, depth):
                self.cfg = cfg
                self.depth = depth
                self._c_cache = {}

            def _build_c(self):
                c, d = self.cfg, self.depth

                def f(x):
                    return x * c.scale + d

                return jax.jit(f)

            def _c_exe(self, n):
                key = (n, self.cfg, self.depth)
                exe = self._c_cache.get(key)
                if exe is None:
                    exe = self._build_c()
                    self._c_cache[key] = exe
                return exe

            def warmed(self, n):
                return (n, self.cfg) in self._c_cache
    """, "KEY001")
    assert len(fs) == 1, [f.message for f in fs]
    assert "membership check" in fs[0].message
    assert "not term-identical" in fs[0].message


def test_key_wildcard_locals_do_not_drift():
    """Shape locals named differently at different sites (`n` vs `m`)
    and different constant tags are NOT drift — only attr structure."""
    fs = run_src("""
        import jax

        class B:
            def __init__(self, cfg):
                self.cfg = cfg
                self._c_cache = {}

            def _build_c(self):
                c = self.cfg

                def f(x):
                    return x * c.scale

                return jax.jit(f)

            def _c_exe(self, n, phase):
                key = (n, "draft", self.cfg)
                exe = self._c_cache.get(key)
                if exe is None:
                    exe = self._build_c()
                    self._c_cache[key] = exe
                return exe

            def warmed(self, m):
                return (m, "verify", self.cfg) in self._c_cache
    """, "KEY001")
    assert fs == [], [f.message for f in fs]


def test_key_memo_invariant_class_wide_suppression():
    """`# ptlint: memo-invariant(...)` on the __init__ assignment
    exempts the attr's keyless reads component-wide."""
    fs = run_src("""
        import jax

        class B:
            def __init__(self, cfg, eos):
                self.cfg = cfg
                # ptlint: memo-invariant(eos id fixed at construction)
                self.eos = eos
                self._c_cache = {}

            def _build_c(self):
                c, e = self.cfg, self.eos

                def f(x):
                    return x * c.scale + e

                return jax.jit(f)

            def _c_exe(self, n):
                key = (n, self.cfg)
                exe = self._c_cache.get(key)
                if exe is None:
                    exe = self._build_c()
                    self._c_cache[key] = exe
                return exe
    """, "KEY001")
    assert fs == [], [f.message for f in fs]


def test_key_memo_invariant_per_read_line_suppression():
    """The per-read form: annotating the read line inside the builder
    exempts that site without declaring the attr class-wide."""
    fs = run_src("""
        import jax

        class B:
            def __init__(self, cfg, eos):
                self.cfg = cfg
                self.eos = eos
                self._c_cache = {}

            def _build_c(self):
                c = self.cfg
                e = self.eos  # ptlint: memo-invariant(fixed at ctor)

                def f(x):
                    return x * c.scale + e

                return jax.jit(f)

            def _c_exe(self, n):
                key = (n, self.cfg)
                exe = self._c_cache.get(key)
                if exe is None:
                    exe = self._build_c()
                    self._c_cache[key] = exe
                return exe
    """, "KEY001")
    assert fs == [], [f.message for f in fs]


def test_key_inheritance_through_base_chain():
    """The builder lives on the base class, the memo method on the
    derived one — the component walk still derives the traced reads."""
    fs = run_src("""
        import jax

        class Base:
            def __init__(self, cfg, gamma):
                self.cfg = cfg
                self.gamma = gamma
                self._c_cache = {}

            def _build_c(self):
                c, g = self.cfg, self.gamma

                def f(x):
                    return x * c.scale + g

                return jax.jit(f)

        class Derived(Base):
            def _c_exe(self, n):
                key = (n, self.cfg)
                exe = self._c_cache.get(key)
                if exe is None:
                    exe = self._build_c()
                    self._c_cache[key] = exe
                return exe
    """, "KEY001")
    assert len(fs) == 1, [f.message for f in fs]
    assert "gamma" in fs[0].message and "STALE" in fs[0].message


def test_key_disable_comment_works():
    mutated = _MEMO_OK.replace(
        "            exe = self._step_cache.get(key)",
        "            # ptlint: disable=KEY001 — fixture justification\n"
        "            exe = self._step_cache.get(key)").replace(
        "return (n, self.cfg, self.impl) + self._qkey",
        "return (n, self.cfg, self.impl)")
    assert run_src(mutated, "KEY001") == []


def test_key_bookkeeping_dicts_not_policed():
    """A dict that only stores (a metrics gauge, a result log) is not
    the memo idiom — no get/member pairing, no findings."""
    fs = run_src("""
        class B:
            def __init__(self, cfg):
                self.cfg = cfg
                self._log_cache = {}

            def record(self, n, v):
                self._log_cache[(n, self.cfg)] = v
    """, "KEY001")
    assert fs == []


def test_key001_discovers_every_paged_cache():
    """Coverage floor, same idiom as the SYNC001 superset pin: every
    `self._*_cache` attribute in nlp/paged.py must be discovered (and
    qualify as a memo cache) — a refactor that renames a cache out of
    the rule's sight fails here, not three PRs later."""
    project = real_tree()
    graph = build_callgraph(project)
    caches = discover_memo_caches(graph)
    qualified = set()
    for (_canon, name), entry in caches.items():
        kinds = {s.kind for s in entry["sites"]}
        if "set" in kinds and ({"get", "member"} & kinds):
            qualified.add(name)
    src = open(os.path.join(REPO, "paddle_tpu", "nlp", "paged.py"),
               encoding="utf-8").read()
    in_source = set(re.findall(r"self\.(_\w+_cache)\b", src))
    # the four compiled-shape caches the rule was built for are the
    # floor — pinned by name so a silent discovery regression is loud
    assert {"_prefill_cache", "_fused_cache", "_chunk_cache",
            "_spec_cache"} <= in_source
    assert in_source <= qualified, (
        f"caches in paged.py not discovered by KEY001: "
        f"{sorted(in_source - qualified)}")


# ---------------------------------------------------------------------------
# ASYNC001 — blocking calls in async bodies
# ---------------------------------------------------------------------------

def test_async_time_sleep_flagged():
    fs = run_src("""
        import time

        async def handler():
            time.sleep(1)
    """, "ASYNC001")
    assert len(fs) == 1 and "time.sleep" in fs[0].message


def test_async_future_result_and_acquire_flagged():
    fs = run_src("""
        async def handler(fut, lock):
            fut.result()
            lock.acquire()
    """, "ASYNC001")
    assert len(fs) == 2
    assert any("result" in f.message for f in fs)
    assert any("acquire" in f.message for f in fs)


def test_async_router_call_flagged():
    fs = run_src("""
        class Frontend:
            def __init__(self, router):
                self.router = router

            async def handle(self, prompt):
                return self.router.submit(prompt)
    """, "ASYNC001")
    assert len(fs) == 1 and "serving-tier" in fs[0].message


def test_async_getattr_bound_router_local_flagged():
    fs = run_src("""
        class Frontend:
            def __init__(self, router):
                self.router = router

            async def handle(self, slot):
                reset = getattr(self.router, "reset_breaker", None)
                return reset(slot)
    """, "ASYNC001")
    assert len(fs) == 1 and "getattr" in fs[0].message


def test_async_callgraph_resolved_blocking_helper():
    """The `self._submit` -> `router.submit` shape: the async body
    calls a sync helper whose closure blocks — flagged at the call."""
    fs = run_src("""
        class Frontend:
            def __init__(self, router):
                self.router = router

            def _submit(self, prompt):
                return self.router.submit(prompt)

            async def handle(self, prompt):
                return self._submit(prompt)
    """, "ASYNC001")
    assert len(fs) == 1
    assert "_submit" in fs[0].message
    assert "run_in_executor" in fs[0].message


def test_async_negatives():
    """awaited calls, run_in_executor-routed work, sync functions'
    own bodies, and nested sync defs are all fine."""
    fs = run_src("""
        import asyncio
        import time

        class Frontend:
            def __init__(self, router):
                self.router = router

            async def handle(self, prompt):
                loop = asyncio.get_running_loop()
                text = await loop.run_in_executor(
                    None, lambda: self.router.to_prometheus())
                data = await self.read(prompt)
                return text, data

            async def read(self, prompt):
                await asyncio.sleep(0.01)
                return prompt

            def shutdown(self, fut):
                # sync: blocks the CALLER's thread, not the loop
                time.sleep(0.1)
                return fut.result()

            async def spawn(self):
                def worker():
                    return self.router.submit("x")
                return worker
    """, "ASYNC001")
    assert fs == [], [f.message for f in fs]


def test_async_disable_comment_works():
    fs = run_src("""
        class Frontend:
            def __init__(self, router):
                self.router = router

            async def health(self):
                # ptlint: disable=ASYNC001 — short-lock snapshot
                return self.router.health()
    """, "ASYNC001")
    assert fs == []


def test_real_frontend_async_clean():
    """serving/frontend.py is burned down: the real fixes + inline
    justifications hold (a new blocking call in a handler fails)."""
    fs = [f for f in run_rules(real_tree(), ALL_RULES)
          if f.rule == "ASYNC001"]
    assert fs == [], [f"{f.location} {f.message}" for f in fs]


# ---------------------------------------------------------------------------
# --changed-only / --fail-dead-roots / parse memo
# ---------------------------------------------------------------------------

_BROAD_EXCEPT = "try:\n    work()\nexcept Exception:\n    pass\n"


def _git(args, cwd):
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t"]
                   + args, cwd=cwd, check=True, capture_output=True)


def test_cli_changed_only_scopes_to_git_diff(tmp_path, capsys):
    """a.py (committed, has a finding) is invisible; b.py (untracked,
    same finding) reports — the pre-commit loop only sees the diff."""
    (tmp_path / "a.py").write_text(_BROAD_EXCEPT)
    _git(["init", "-q"], tmp_path)
    _git(["add", "a.py"], tmp_path)
    _git(["commit", "-qm", "seed"], tmp_path)
    (tmp_path / "b.py").write_text(_BROAD_EXCEPT)
    rc = ptlint_main([str(tmp_path), "--root", str(tmp_path),
                      "--no-baseline", "--changed-only",
                      "--format", "json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["path"] for f in data["new"]} == {"b.py"}
    assert data["focused_files"] == 1
    # full run still sees both — the scoping is opt-in
    rc = ptlint_main([str(tmp_path), "--root", str(tmp_path),
                      "--no-baseline", "--format", "json"])
    data = json.loads(capsys.readouterr().out)
    assert {f["path"] for f in data["new"]} == {"a.py", "b.py"}


def test_cli_changed_only_clean_tree_reports_nothing(tmp_path, capsys):
    (tmp_path / "a.py").write_text(_BROAD_EXCEPT)
    _git(["init", "-q"], tmp_path)
    _git(["add", "a.py"], tmp_path)
    _git(["commit", "-qm", "seed"], tmp_path)
    rc = ptlint_main([str(tmp_path), "--root", str(tmp_path),
                      "--no-baseline", "--changed-only",
                      "--format", "json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0 and data["new"] == [] and data["focused_files"] == 0


def test_cli_fail_dead_roots_gates(tmp_path, capsys):
    """On a tree with none of the hot-root files, every HOT_ROOTS
    pattern is dead: the flag turns that into exit 1 (without it the
    same run passes — the report alone never gated)."""
    (tmp_path / "ok.py").write_text("x = 1\n")
    args = [str(tmp_path / "ok.py"), "--root", str(tmp_path),
            "--no-baseline"]
    assert ptlint_main(args) == 0
    capsys.readouterr()
    rc = ptlint_main(args + ["--fail-dead-roots"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "DEAD hot-path root" in captured.err


def test_focus_scopes_run_rules():
    bad = textwrap.dedent("""
        def f():
            try:
                work()
            except Exception:
                pass
    """)
    a = FileContext("a.py", bad, "a.py")
    b = FileContext("b.py", bad, "b.py")
    project = Project([a, b])
    assert {f.path for f in run_rules(project, ALL_RULES)} == \
        {"a.py", "b.py"}
    project.focus = {"b.py"}
    assert {f.path for f in run_rules(project, ALL_RULES)} == {"b.py"}


def test_parse_memo_reuses_tree_for_unchanged_source():
    src = "def f():\n    return 1\n"
    a = FileContext("m.py", src, "m.py")
    b = FileContext("m.py", src, "m.py")
    assert a.tree is b.tree
    c = FileContext("m.py", src + "\nx = 2\n", "m.py")
    assert c.tree is not a.tree
