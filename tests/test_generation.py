"""KV-cache generation (nlp.generation) — VERDICT r1 missing item 10.

Reference analog: PaddleNLP llm/ predict recipes' model.generate
(greedy_search/sampling over a KV cache); SURVEY.md §3.5's serving story.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, generation


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (2, 8)), jnp.int32)
    return cfg, params, prompt


class TestKVCache:
    def test_prefill_matches_full_forward(self, setup):
        cfg, params, prompt = setup
        cache = generation.init_cache(cfg, 2, 16)
        lc, cache = generation.forward_cached(params, prompt, cache, 0, cfg)
        lf = llama.forward(params, prompt, cfg)
        np.testing.assert_allclose(np.asarray(lc), np.asarray(lf),
                                   rtol=1e-5, atol=1e-5)

    def test_decode_matches_full_forward(self, setup):
        """Single-token cached decode logits == full-forward last-position
        logits at every step."""
        cfg, params, prompt = setup
        T = prompt.shape[1] + 4
        cache = generation.init_cache(cfg, 2, T)
        _, cache = generation.forward_cached(params, prompt, cache, 0, cfg)
        seq = prompt
        for i in range(3):
            nxt = jnp.argmax(llama.forward(params, seq, cfg)[:, -1],
                             axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
            lc, cache = generation.forward_cached(
                params, nxt[:, None], cache, seq.shape[1] - 1, cfg)
            lf = llama.forward(params, seq, cfg)[:, -1:]
            np.testing.assert_allclose(np.asarray(lc), np.asarray(lf),
                                       rtol=1e-4, atol=1e-4)


class TestGenerate:
    def test_greedy_matches_rolling_forward(self, setup):
        cfg, params, prompt = setup
        out = jax.jit(lambda p, t: generation.generate(
            p, t, cfg, max_new_tokens=6))(params, prompt)
        seq, ref = prompt, []
        for _ in range(6):
            nxt = jnp.argmax(llama.forward(params, seq, cfg)[:, -1],
                             axis=-1).astype(jnp.int32)
            ref.append(nxt)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        assert bool(jnp.all(out == jnp.stack(ref, axis=1)))

    def test_sampling_shapes_and_determinism(self, setup):
        cfg, params, prompt = setup
        kw = dict(max_new_tokens=5, greedy=False, temperature=0.8,
                  top_k=16, top_p=0.9, key=jax.random.PRNGKey(1))
        a = generation.generate(params, prompt, cfg, **kw)
        b = generation.generate(params, prompt, cfg, **kw)
        assert a.shape == (2, 5) and bool(jnp.all(a == b))
        assert int(jnp.min(a)) >= 0 and int(jnp.max(a)) < cfg.vocab_size

    def test_eos_pads_tail(self, setup):
        cfg, params, prompt = setup
        greedy = generation.generate(params, prompt, cfg, max_new_tokens=6)
        eos = int(greedy[0, 1])  # force an eos hit at step 2 for row 0
        out = generation.generate(params, prompt, cfg, max_new_tokens=6,
                                  eos_token_id=eos, pad_token_id=-1)
        row = out[0].tolist()
        assert eos in row
        after = row[row.index(eos) + 1:]
        assert all(t == -1 for t in after), row

    def test_topk_topp_sequential_filter(self, setup):
        """Combined top_k+top_p applies top-k FIRST, then top-p over the
        survivors (the reference's TopKProcess → TopPProcess order), and
        top_k >= vocab_size is clamped, not an IndexError (ADVICE r2)."""
        cfg, params, prompt = setup
        # top_k=1 + any top_p must degenerate to greedy regardless of how
        # much mass top_p would have kept from the unfiltered distribution
        out = generation.generate(params, prompt, cfg, max_new_tokens=4,
                                  greedy=False, top_k=1, top_p=0.99,
                                  key=jax.random.PRNGKey(3))
        ref = generation.generate(params, prompt, cfg, max_new_tokens=4)
        assert bool(jnp.all(out == ref))
        big = generation.generate(params, prompt, cfg, max_new_tokens=3,
                                  greedy=False, top_k=10 * cfg.vocab_size,
                                  key=jax.random.PRNGKey(4))
        assert big.shape == (2, 3)


class TestShardedGeneration:
    """VERDICT r2 missing item 1 / next-round item 1: TP/DP-sharded
    KV-cache generation (PaddleNLP llm/ predict mp>1; SURVEY.md §3.5)."""

    def test_tp_dp_greedy_matches_single_device(self, setup):
        from jax.sharding import NamedSharding
        from paddle_tpu.parallel.topology import build_mesh
        cfg, params, prompt = setup
        ref = generation.generate(params, prompt, cfg, max_new_tokens=6)
        mesh = build_mesh(dp=2, mp=2, devices=jax.devices()[:4])
        sp = jax.tree.map(lambda s: NamedSharding(mesh, s),
                          llama.infer_param_specs(cfg),
                          is_leaf=lambda x: not isinstance(x, dict))
        p_sh = jax.tree.map(jax.device_put, params, sp)
        ids = jax.device_put(prompt, NamedSharding(
            mesh, jax.sharding.PartitionSpec(("dp", "sharding"), None)))
        out = jax.jit(lambda p, t: generation.generate(
            p, t, cfg, max_new_tokens=6, mesh=mesh))(p_sh, ids)
        assert bool(jnp.all(out == ref)), (np.asarray(out), np.asarray(ref))

    def test_tp_prefill_logits_match(self, setup):
        from jax.sharding import NamedSharding
        from paddle_tpu.parallel.topology import build_mesh
        cfg, params, prompt = setup
        cache = generation.init_cache(cfg, 2, 16)
        ref, _ = generation.forward_cached(params, prompt, cache, 0, cfg)
        mesh = build_mesh(mp=2, devices=jax.devices()[:2])
        sp = jax.tree.map(lambda s: NamedSharding(mesh, s),
                          llama.infer_param_specs(cfg),
                          is_leaf=lambda x: not isinstance(x, dict))
        p_sh = jax.tree.map(jax.device_put, params, sp)
        cache_sh = generation.init_cache(cfg, 2, 16, mesh)
        got, _ = jax.jit(lambda p, t, c: generation.forward_cached(
            p, t, c, 0, cfg, mesh))(p_sh, prompt, cache_sh)
        # bf16 compute: the row-parallel all-reduce changes the matmul
        # reduction order, so parity is to bf16-ulp, not f32 exactness
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)

    def test_decode_step_hlo_no_full_weight_allgather(self, setup):
        """HLO-golden: a compiled TP decode step must not all-gather any
        full weight matrix — TP weights are consumed as shards (the whole
        point of infer_param_specs having no ZeRO axis)."""
        import re
        from jax.sharding import NamedSharding
        from paddle_tpu.parallel.topology import build_mesh
        cfg, params, prompt = setup
        mesh = build_mesh(mp=2, devices=jax.devices()[:2])
        sp = jax.tree.map(lambda s: NamedSharding(mesh, s),
                          llama.infer_param_specs(cfg),
                          is_leaf=lambda x: not isinstance(x, dict))
        cache = generation.init_cache(cfg, 2, 16, mesh)
        tok = jnp.zeros((2, 1), jnp.int32)
        txt = jax.jit(
            lambda p, t, c: generation.forward_cached(p, t, c, 8, cfg, mesh),
            in_shardings=(sp, None, None),
        ).lower(params, tok, cache).compile().as_text()
        weight_shapes = set()
        for leaf in jax.tree.leaves(params["layers"]):
            if leaf.ndim >= 2:
                weight_shapes.add(",".join(map(str, leaf.shape[-2:])))
        weight_shapes.add(",".join(map(str, params["lm_head"].shape)))
        for m in re.finditer(r"\w+\[([\d,]+)\][^\n]*\ball-gather\b", txt):
            dims = m.group(1)
            for ws in weight_shapes:
                assert not dims.endswith(ws), (
                    f"decode all-gathers a full weight [{dims}]")


class TestLLMPredictor:
    """inference.Predictor serving path over the .pdllm artifact."""

    def test_save_load_roundtrip_and_parallel_decode(self, setup, tmp_path):
        from paddle_tpu import inference
        from paddle_tpu.inference import llm as illm
        cfg, params, prompt = setup
        prefix = str(tmp_path / "tiny_llama")
        illm.save_llm(prefix, params, cfg)

        config = inference.Config(prefix)
        config.enable_llm_generation(max_new_tokens=5)
        config.set_llm_parallel(mp=2, dp=2)
        pred = inference.create_predictor(config)
        assert pred.get_input_names() == ["input_ids"]
        h = pred.get_input_handle("input_ids")
        h.copy_from_cpu(np.asarray(prompt))
        (out,) = pred.run()
        ref = generation.generate(params, prompt, cfg, max_new_tokens=5)
        assert out.shape == (2, 5)
        np.testing.assert_array_equal(out, np.asarray(ref))
        got = pred.get_output_handle("generated_ids").copy_to_cpu()
        np.testing.assert_array_equal(got, out)

    def test_dispatch_prefers_llm_artifact(self, setup, tmp_path):
        from paddle_tpu import inference
        from paddle_tpu.inference import llm as illm
        cfg, params, prompt = setup
        prefix = str(tmp_path / "auto")
        illm.save_llm(prefix, params, cfg)
        pred = inference.create_predictor(inference.Config(prefix))
        assert isinstance(pred, illm.LLMPredictor)


class TestFlashPrefill:
    """VERDICT r3 missing 2: prefill must run the pad-to-block flash
    kernel over the prompt, not mha_ref over the full cache with a
    materialized [P, T] visibility mask."""

    def test_flash_prefill_parity(self):
        """Interpret-mode Pallas prefill == masked-cache reference for a
        prompt long enough to take the flash path (P >= 128)."""
        from paddle_tpu.core import flags as F
        cfg = llama.LlamaConfig.tiny(use_flash=True, num_hidden_layers=2)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(0, 256, (1, 130)), jnp.int32)
        cache = generation.init_cache(cfg, 1, 140)
        F.set_flags({"FLAGS_pallas_interpret": True})
        try:
            lf, cf = generation.forward_cached(params, prompt, cache, 0, cfg)
        finally:
            F.set_flags({"FLAGS_pallas_interpret": False})
        cfg_ref = llama.LlamaConfig.tiny(use_flash=False,
                                         num_hidden_layers=2)
        lr, cr = generation.forward_cached(params, prompt, cache, 0, cfg_ref)
        # bf16 activations: the two reduction orders round differently on
        # a handful of elements
        np.testing.assert_allclose(np.asarray(lf), np.asarray(lr),
                                   rtol=2e-2, atol=1e-2)
        # layer-2 cache entries inherit layer-1's bf16 rounding divergence
        np.testing.assert_allclose(np.asarray(cf.k), np.asarray(cr.k),
                                   rtol=2e-2, atol=1e-2)

    def test_prefill_hlo_has_no_pt_mask(self):
        """The compiled prefill (flash path) must not materialize any
        [.., P, T]-shaped attention buffer; the non-flash path does."""
        cfg = llama.LlamaConfig.tiny(use_flash=True, num_hidden_layers=2)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        P_, T_ = 256, 384
        prompt = jnp.zeros((1, P_), jnp.int32)
        cache = generation.init_cache(cfg, 1, T_)

        from paddle_tpu.core import flags as F
        F.set_flags({"FLAGS_pallas_interpret": True})
        try:
            txt = jax.jit(
                lambda p, t, c: generation.forward_cached(p, t, c, 0, cfg)
            ).lower(params, prompt, cache).as_text()
        finally:
            F.set_flags({"FLAGS_pallas_interpret": False})
        assert f"{P_}x{T_}" not in txt, "prefill still builds a [P, T] mask"


class TestTopPNoFullSort:
    """VERDICT r3 weak 5: pure top-p must not lower to an O(V log V)
    full-vocab sort; it thresholds over a bounded lax.top_k candidate
    set with full-vocab softmax normalization."""

    def test_no_sort_in_hlo(self):
        V = 8192  # > _TOPP_CANDIDATES so the bounded path is exercised
        logits = jnp.asarray(np.random.RandomState(0).randn(2, V),
                             jnp.float32)
        f = jax.jit(lambda l, k: generation._sample(
            l, k, 1.0, 0, 0.9, False))
        txt = f.lower(logits, jax.random.PRNGKey(0)).compile().as_text()
        assert " sort(" not in txt, "pure top-p still lowers to a sort"

    def test_no_sort_in_full_generate_hlo(self):
        """The whole compiled generate() (prefill + decode scan) is
        sort-free for any vocab above the candidate cap (tiny 256-vocab
        configs legitimately full-sort: top_k(V, V) is a sort)."""
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2,
                                     vocab_size=8192)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 8), jnp.int32)
        txt = jax.jit(lambda p, t: generation.generate(
            p, t, cfg, max_new_tokens=4, greedy=False, top_p=0.9,
            key=jax.random.PRNGKey(0))).lower(
                params, prompt).compile().as_text()
        assert " sort(" not in txt, "generate() decode loop contains a sort"

    def test_matches_full_sort_semantics(self):
        """Bounded-candidate cutoff == full-sort cutoff whenever the
        candidates cover the top-p mass (any peaked distribution)."""
        rng = np.random.RandomState(2)
        V = 8192
        logits = jnp.asarray(rng.randn(8, V) * 4.0, jnp.float32)

        def ref_keep_mask(l, p):
            s = np.sort(np.asarray(l), axis=-1)[:, ::-1]
            probs = np.exp(s - s.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            cum = np.cumsum(probs, -1)
            idx = np.maximum((cum - probs < p).sum(-1) - 1, 0)
            cut = np.take_along_axis(s, idx[:, None], -1)
            return np.asarray(l) >= cut

        keys = jax.random.split(jax.random.PRNGKey(0), 512)
        toks = jax.vmap(lambda k: generation._sample(
            logits, k, 1.0, 0, 0.7, False))(keys)
        keep = ref_keep_mask(logits, 0.7)
        picked = np.asarray(toks)  # [512, 8]
        for row in range(8):
            assert keep[row, picked[:, row]].all(), (
                "sampled a token outside the exact top-p set")

    def test_flat_distribution_falls_back_to_untruncated(self):
        """When the candidate set cannot cover top_p (near-uniform logits,
        V > candidates), the row samples untruncated instead of silently
        truncating at the candidate cap."""
        V = 8192
        logits = jnp.zeros((1, V), jnp.float32)  # uniform
        toks = jax.vmap(lambda k: generation._sample(
            logits, k, 1.0, 0, 0.999, False))(
                jax.random.split(jax.random.PRNGKey(1), 256))
        # tokens beyond the candidate cap must be reachable
        assert int(jnp.max(toks)) >= generation._TOPP_CANDIDATES


class TestWeightOnly:
    """Weight-only-quantized serving decode (VERDICT r4 next-2): the
    reference ecosystem's default LLM serving mode — PaddleNLP predict
    --quant_type weight_only_int8 over paddle.nn.quant.weight_quantize."""

    def test_int8_logits_close_and_greedy_decodes(self, setup):
        cfg, params, prompt = setup
        qp = generation.quantize_for_serving(params)
        # structure: codes are int8, scales ride '<name>:scale'
        assert qp["layers"]["q_proj"].dtype == jnp.int8
        assert qp["layers"]["q_proj:scale"].shape[1] == 1
        cache = generation.init_cache(cfg, 2, 8)
        lb, _ = generation.forward_cached(params, prompt, cache, 0, cfg)
        cache = generation.init_cache(cfg, 2, 8)
        lq, _ = generation.forward_cached(qp, prompt, cache, 0, cfg)
        # int8 per-channel weight error ~0.4% -> small logits error
        err = float(jnp.max(jnp.abs(lb - lq)) / jnp.max(jnp.abs(lb)))
        assert err < 0.05, err
        out = generation.generate(qp, prompt, cfg, max_new_tokens=4,
                                  greedy=True)
        assert out.shape == (2, 4)

    def test_int4_decodes(self, setup):
        cfg, params, prompt = setup
        qp = generation.quantize_for_serving(params, bits=4)
        out = generation.generate(qp, prompt, cfg, max_new_tokens=3,
                                  greedy=True)
        assert out.shape == (2, 3)

    def test_quantized_specs_tree_matches(self, setup):
        cfg, params, _ = setup
        qp = generation.quantize_for_serving(params)
        specs = generation.quantized_specs(llama.infer_param_specs(cfg), qp)
        # every quantized leaf has a spec; tree_map must not raise
        jax.tree.map(lambda a, b: None, qp, specs,
                     is_leaf=lambda x: x is None or not isinstance(x, dict))

    def test_weight_only_linear_api(self):
        import paddle_tpu as paddle
        rng = np.random.default_rng(0)
        w = paddle.to_tensor(rng.standard_normal((64, 32)).astype("float32"))
        x = paddle.to_tensor(rng.standard_normal((4, 64)).astype("float32"))
        ref = np.asarray(x._data @ w._data)
        for algo, gs, tol in (("weight_only_int8", -1, 0.02),
                              ("weight_only_int8", 16, 0.02),
                              ("weight_only_int4", 16, 0.2)):
            codes, scale = paddle.nn.quant.weight_quantize(
                w, algo=algo, group_size=gs)
            y = paddle.nn.quant.weight_only_linear(
                x, codes, weight_scale=scale,
                weight_dtype="int4" if "int4" in algo else "int8",
                group_size=gs)
            err = float(np.max(np.abs(np.asarray(y._data) - ref))
                        / np.max(np.abs(ref)))
            assert err < tol, (algo, gs, err)
        # dequantize roundtrip
        codes, scale = paddle.nn.quant.weight_quantize(w)
        wd = paddle.nn.quant.weight_dequantize(codes, scale)
        err = float(np.max(np.abs(np.asarray(wd._data) -
                                  np.asarray(w._data))))
        assert err < 0.05

    def test_predictor_enable_weight_only(self, setup, tmp_path):
        from paddle_tpu import inference
        from paddle_tpu.inference.llm import save_llm
        cfg, params, prompt = setup
        prefix = str(tmp_path / "m")
        save_llm(prefix, params, cfg)
        config = inference.Config(prefix)
        config.enable_llm_generation(max_new_tokens=4)
        config.enable_weight_only("int8")
        pred = inference.create_predictor(config)
        out = pred.run([np.asarray(prompt)])[0]
        assert out.shape == (2, 4)
        assert pred._params["layers"]["q_proj"].dtype == jnp.int8
