"""KV-cache generation (nlp.generation) — VERDICT r1 missing item 10.

Reference analog: PaddleNLP llm/ predict recipes' model.generate
(greedy_search/sampling over a KV cache); SURVEY.md §3.5's serving story.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, generation


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (2, 8)), jnp.int32)
    return cfg, params, prompt


class TestKVCache:
    def test_prefill_matches_full_forward(self, setup):
        cfg, params, prompt = setup
        cache = generation.init_cache(cfg, 2, 16)
        lc, cache = generation.forward_cached(params, prompt, cache, 0, cfg)
        lf = llama.forward(params, prompt, cfg)
        np.testing.assert_allclose(np.asarray(lc), np.asarray(lf),
                                   rtol=1e-5, atol=1e-5)

    def test_decode_matches_full_forward(self, setup):
        """Single-token cached decode logits == full-forward last-position
        logits at every step."""
        cfg, params, prompt = setup
        T = prompt.shape[1] + 4
        cache = generation.init_cache(cfg, 2, T)
        _, cache = generation.forward_cached(params, prompt, cache, 0, cfg)
        seq = prompt
        for i in range(3):
            nxt = jnp.argmax(llama.forward(params, seq, cfg)[:, -1],
                             axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
            lc, cache = generation.forward_cached(
                params, nxt[:, None], cache, seq.shape[1] - 1, cfg)
            lf = llama.forward(params, seq, cfg)[:, -1:]
            np.testing.assert_allclose(np.asarray(lc), np.asarray(lf),
                                       rtol=1e-4, atol=1e-4)


class TestGenerate:
    def test_greedy_matches_rolling_forward(self, setup):
        cfg, params, prompt = setup
        out = jax.jit(lambda p, t: generation.generate(
            p, t, cfg, max_new_tokens=6))(params, prompt)
        seq, ref = prompt, []
        for _ in range(6):
            nxt = jnp.argmax(llama.forward(params, seq, cfg)[:, -1],
                             axis=-1).astype(jnp.int32)
            ref.append(nxt)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        assert bool(jnp.all(out == jnp.stack(ref, axis=1)))

    def test_sampling_shapes_and_determinism(self, setup):
        cfg, params, prompt = setup
        kw = dict(max_new_tokens=5, greedy=False, temperature=0.8,
                  top_k=16, top_p=0.9, key=jax.random.PRNGKey(1))
        a = generation.generate(params, prompt, cfg, **kw)
        b = generation.generate(params, prompt, cfg, **kw)
        assert a.shape == (2, 5) and bool(jnp.all(a == b))
        assert int(jnp.min(a)) >= 0 and int(jnp.max(a)) < cfg.vocab_size

    def test_eos_pads_tail(self, setup):
        cfg, params, prompt = setup
        greedy = generation.generate(params, prompt, cfg, max_new_tokens=6)
        eos = int(greedy[0, 1])  # force an eos hit at step 2 for row 0
        out = generation.generate(params, prompt, cfg, max_new_tokens=6,
                                  eos_token_id=eos, pad_token_id=-1)
        row = out[0].tolist()
        assert eos in row
        after = row[row.index(eos) + 1:]
        assert all(t == -1 for t in after), row
