"""Spec-driven sweep over the hand-registered op surface (VERDICT r3
weak 3 / task 6): every ops/refspecs.py row gets the same OpTest-style
numpy-reference forward check as the optable rows, and grad rows a
finite-difference check — lifting per-op verification from 42 table ops
to 250+ without rewriting the hand modules."""
import numpy as np
import pytest

import paddle_tpu as paddle  # noqa: F401  (registers the op surface)
from paddle_tpu.ops.refspecs import (RTABLE, LIST_ARG_OPS, INT_IDX_OPS,
                                     SORTED_INPUT_OPS, INPUT_TRANSFORMS)
from paddle_tpu.ops._registry import REGISTRY

import optest

_BY_NAME = {s.name: s for s in RTABLE}
_FWD = sorted(_BY_NAME)
_GRAD = sorted(n for n, s in _BY_NAME.items()
               if s.grad and not s.int_op)


def _inputs(spec, seed=11):
    rng = np.random.RandomState(seed)
    if spec.n_in == 0:
        return []
    shapes = spec.shapes or ((3, 4),) * max(spec.n_in, 1)
    if len(shapes) < spec.n_in:
        shapes = tuple(shapes) * spec.n_in
    lo, hi = spec.domain
    out = []
    for i, sh in enumerate(shapes):
        if spec.int_op:
            out.append(rng.randint(0, 5, sh).astype(np.int64))
        elif spec.name == "where" and i == 0:
            out.append(rng.uniform(-1, 1, sh) > 0)
        elif spec.name in INT_IDX_OPS and i == 1:
            out.append(rng.randint(0, INT_IDX_OPS[spec.name], sh)
                       .astype(np.int64))
        else:
            out.append(rng.uniform(lo, hi, sh).astype(np.float32))
    if spec.name in SORTED_INPUT_OPS:
        j = SORTED_INPUT_OPS[spec.name]
        out[j] = np.sort(out[j].reshape(-1)).astype(out[j].dtype)
    for j, fn in INPUT_TRANSFORMS.get(spec.name, {}).items():
        out[j] = fn(out[j])
    return out


def _call(name):
    """List-argument ops take their tensors as ONE list."""
    op = REGISTRY[name]
    if name in LIST_ARG_OPS:
        return lambda *ts, **kw: op(list(ts), **kw)
    return op


@pytest.mark.parametrize("name", _FWD)
def test_forward_matches_numpy(name):
    spec = _BY_NAME[name]
    optest.check_output(_call(name), spec.ref, _inputs(spec),
                        kwargs=spec.kwargs, rtol=spec.rtol)


@pytest.mark.parametrize("name", _GRAD)
def test_grad_matches_finite_difference(name):
    spec = _BY_NAME[name]
    optest.check_grad(_call(name), _inputs(spec), kwargs=spec.kwargs)


def test_row_names_unique_and_registered():
    names = [s.name for s in RTABLE]
    assert len(names) == len(set(names))
    for n in names:
        assert n in REGISTRY, n
