"""Child worker for the two-process jax.distributed tests (NOT a test
module — spawned by tests/test_dist_multiprocess.py).

Reference analog: the collective_*_api.py child scripts of
test/collective/ that TestDistBase launches as real processes on
127.0.0.1 (SURVEY.md §4 — 'multi-node is simulated as multi-process on
one node'). Argv: coordinator_address process_id result_path.
"""
import json
import sys

import jax

coordinator, pid, result_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(coordinator_address=coordinator,
                           num_processes=2, process_id=pid)

import jax.numpy as jnp  # noqa: E402
import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402

rank = jax.process_index()
out = {"rank": rank, "process_count": jax.process_count()}

# all_reduce (SUM then AVG) — the eager multi-process branch
t = paddle.to_tensor(jnp.asarray([float(rank + 1), 10.0 * (rank + 1)]))
dist.all_reduce(t)
out["sum"] = [float(v) for v in t.numpy()]          # [3, 30]
t2 = paddle.to_tensor(jnp.asarray([float(rank)]))
dist.all_reduce(t2, op=dist.ReduceOp.AVG)
out["avg"] = float(t2.numpy()[0])                   # 0.5

# all_gather
lst = []
dist.all_gather(lst, paddle.to_tensor(jnp.asarray([float(rank), -1.0])))
out["gather"] = [[float(v) for v in x.numpy()] for x in lst]

# broadcast from rank 0
b = paddle.to_tensor(jnp.asarray([float(rank * 7 + 3)]))
dist.broadcast(b, src=0)
out["bcast"] = float(b.numpy()[0])                  # rank0's 3.0

# barrier — both processes must pass
dist.barrier()
out["barrier"] = True

with open(result_path, "w") as f:
    json.dump(out, f)
