"""paddle_tpu.serving.router + frontend — the multi-replica tier.

Deterministic CPU coverage of the "millions of users" layer: routing
policy units (health exclusion, occupancy tie-break, prefix-affinity
stickiness) against stub replicas, 2-replica e2e token parity vs a
single engine under mixed priorities/cancel/timeout, cross-replica
failover with the strict-prefix stream invariant, SSE round-trips over
a real socket through the asyncio HTTP frontend, all-replica
backpressure → 429, graceful drain shutdown, per-replica Prometheus
labels, and replica-grouped trace reporting.
"""
import http.client
import importlib.util
import json
import pathlib
import threading
import time

import numpy as np
import pytest
import jax

from paddle_tpu.nlp import llama
from paddle_tpu import serving
from paddle_tpu.serving import RequestState
from paddle_tpu.serving.faults import FaultInjector
from paddle_tpu.serving.router import (
    Router, NoReplicaAvailable, default_policy, _AffinityIndex)

REPO = pathlib.Path(__file__).resolve().parent.parent

_RNG = np.random.RandomState(11)
PROMPTS = [list(map(int, _RNG.randint(1, 200, n)))
           for n in (5, 7, 9, 6, 11, 4)]
MAX_NEW = 6


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def baselines(setup):
    """Single-engine reference tokens (greedy — replica-invariant)."""
    cfg, params = setup
    eng = serving.ServingEngine(
        params, cfg, max_batch=2, block_size=4, max_total_len=48,
        max_new_tokens=MAX_NEW, chunk=3)
    out = [eng.generate(p, timeout=300) for p in PROMPTS]
    eng.shutdown()
    return out


def _router(setup, *, replicas=2, per_replica=None, **kw):
    cfg, params = setup
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_total_len", 48)
    kw.setdefault("max_new_tokens", MAX_NEW)
    kw.setdefault("chunk", 3)
    kw.setdefault("max_queue_depth", 32)
    kw.setdefault("max_prefill_bucket", 16)     # small warmable ladder
    return Router(params, cfg, replicas=replicas,
                  per_replica=per_replica, start=False, **kw)


class _StubEngine:
    """Policy-unit stand-in for a ServingEngine: canned health/load
    plus a submit() that records what the router sent it."""

    def __init__(self, replica_id, status="HEALTHY", queue_depth=0,
                 in_flight=0, util=0.0, accepting=True, full=False,
                 slo=None):
        self.replica_id = replica_id
        self.trace = None
        self._status = status
        self._slo = slo            # worst-of SLO verdict ("OK"/...)
        self._load = {"replica_id": replica_id, "queue_depth": queue_depth,
                      "in_flight": in_flight, "parked_retries": 0,
                      "kv_utilization": util, "accepting": accepting}
        self._full = full
        self.submitted = []

    def health(self):
        h = {"status": self._status, "replica_id": self.replica_id}
        if self._slo is not None:
            h["slo"] = {"verdict": self._slo}
        return h

    def load(self):
        return dict(self._load)

    def submit(self, req):
        if self._full:
            raise serving.QueueFullError("stub full")
        req.max_new_tokens = req.max_new_tokens or MAX_NEW
        self.submitted.append(req)
        return req

    def start(self):
        return self

    def cancel(self, req):
        pass

    def shutdown(self, drain=True, timeout=None):
        return True


class TestRoutingPolicy:
    def _route_once(self, router, prompt):
        req = router.submit(prompt)
        return req.replica_id

    def test_unhealthy_replica_excluded(self):
        stubs = [_StubEngine("r0", status="UNHEALTHY"),
                 _StubEngine("r1")]
        r = Router(engines=stubs, affinity_block_size=4, start=True)
        for _ in range(3):
            assert self._route_once(r, [1, 2, 3, 4]) == "r1"
        assert not stubs[0].submitted and len(stubs[1].submitted) == 3
        r.shutdown(drain=False)

    def test_occupancy_tie_break(self):
        stubs = [_StubEngine("r0", queue_depth=4, in_flight=2),
                 _StubEngine("r1", queue_depth=0, in_flight=0)]
        r = Router(engines=stubs, affinity_block_size=4, start=True)
        assert self._route_once(r, [9, 9, 9, 9]) == "r1"
        r.shutdown(drain=False)

    def test_degraded_penalized_but_still_serves(self):
        healthy_busy = _StubEngine("r0", queue_depth=3)
        degraded_idle = _StubEngine("r1", status="DEGRADED")
        r = Router(engines=[healthy_busy, degraded_idle],
                   affinity_block_size=4, start=True)
        # DEGRADED_PENALTY outweighs a small queue: traffic prefers the
        # busier healthy replica...
        assert self._route_once(r, [1, 1, 1, 1]) == "r0"
        r.shutdown(drain=False)
        # ...but a DEGRADED replica alone still serves
        r2 = Router(engines=[_StubEngine("r0", status="DEGRADED")],
                    affinity_block_size=4, start=True)
        assert self._route_once(r2, [1, 1, 1, 1]) == "r0"
        r2.shutdown(drain=False)

    def test_prefix_affinity_stickiness(self):
        # r1 is slightly busier; a shared full-block prefix routed
        # there first must keep pulling its siblings there anyway
        stubs = [_StubEngine("r0"),
                 _StubEngine("r1", in_flight=1)]
        r = Router(engines=stubs, affinity_block_size=4, start=True)
        shared = [7, 7, 7, 7, 1]
        first = self._route_once(r, shared)
        assert first == "r0"                 # idle replica wins cold
        # warm the OTHER replica's affinity by hand (as if r0 died and
        # the chain re-pointed) — siblings must follow the index
        r._affinity.observe(shared, 1)
        assert self._route_once(r, [7, 7, 7, 7, 2]) == "r1"
        # a different prefix is cold: occupancy decides again
        assert self._route_once(r, [8, 8, 8, 8, 1]) == "r0"
        r.shutdown(drain=False)

    def test_default_policy_scores(self):
        base = {"status": "HEALTHY", "queue_depth": 0, "in_flight": 0,
                "parked_retries": 0, "kv_utilization": 0.0,
                "affinity_blocks": 0, "affinity_tokens": 0}
        idle = default_policy(dict(base))
        busy = default_policy(dict(base, queue_depth=4))
        warm = default_policy(dict(base, affinity_blocks=2,
                                   affinity_tokens=8))
        degraded = default_policy(dict(base, status="DEGRADED",
                                       affinity_blocks=8,
                                       affinity_tokens=32))
        assert warm > idle > busy
        assert idle > degraded      # health outweighs full affinity cap

    def test_slo_breach_penalized_but_still_serves(self):
        """SLO-aware routing (PR 13 follow-on): a BREACHing replica
        loses to a busier OK one (the policy sheds load off the burn
        before supervision acts), but still serves when alone."""
        burning_idle = _StubEngine("r0", slo="BREACH")
        healthy_busy = _StubEngine("r1", queue_depth=4, in_flight=2,
                                   slo="OK")
        r = Router(engines=[burning_idle, healthy_busy],
                   affinity_block_size=4, start=True)
        # SLO_BREACH_PENALTY 10 > 6 requests * QUEUE_PENALTY 0.5
        assert self._route_once(r, [1, 2, 3, 4]) == "r1"
        r.shutdown(drain=False)
        alone = Router(engines=[_StubEngine("r0", slo="BREACH")],
                       affinity_block_size=4, start=True)
        assert self._route_once(alone, [1, 2, 3, 4]) == "r0"
        alone.shutdown(drain=False)

    def test_slo_warn_between_occupancy_and_degraded(self):
        """The penalty ladder: WARN > a small queue, BREACH > WARN,
        DEGRADED > BREACH — and a replica without SLO tracking scores
        as OK (no penalty)."""
        from paddle_tpu.serving.router import (
            SLO_WARN_PENALTY, SLO_BREACH_PENALTY, DEGRADED_PENALTY,
            QUEUE_PENALTY)
        assert QUEUE_PENALTY * 4 < SLO_WARN_PENALTY \
            < SLO_BREACH_PENALTY < DEGRADED_PENALTY
        base = {"status": "HEALTHY", "queue_depth": 0, "in_flight": 0,
                "parked_retries": 0, "kv_utilization": 0.0,
                "affinity_blocks": 0, "affinity_tokens": 0}
        ok = default_policy(dict(base, slo_verdict="OK"))
        untracked = default_policy(dict(base))
        warn = default_policy(dict(base, slo_verdict="WARN"))
        breach = default_policy(dict(base, slo_verdict="BREACH"))
        degraded = default_policy(dict(base, status="DEGRADED",
                                       slo_verdict="OK"))
        busy = default_policy(dict(base, queue_depth=4))
        assert ok == untracked
        assert ok > busy > warn > breach > degraded

    def test_views_carry_slo_verdict(self):
        """_views feeds the policy the replica's worst-of verdict
        ("OK" when the stub reports no slo dict)."""
        stubs = [_StubEngine("r0", slo="WARN"), _StubEngine("r1")]
        r = Router(engines=stubs, affinity_block_size=4, start=False)
        views = {i: v for _, i, v in r._views([1, 2, 3, 4], ())}
        assert views[0]["slo_verdict"] == "WARN"
        assert views[1]["slo_verdict"] == "OK"
        r.shutdown(drain=False)

    def test_affinity_index_bound_and_repoint(self):
        idx = _AffinityIndex(block_size=2, cap=4)
        idx.observe([1, 2, 3, 4], replica=0)
        assert idx.match([1, 2, 3, 4]) == {0: 4}
        idx.observe([1, 2, 3, 4], replica=1)      # last writer wins
        assert idx.match([1, 2, 3, 4]) == {1: 4}
        for i in range(10, 20, 2):                # overflow the cap
            idx.observe([i, i + 1], replica=0)
        assert len(idx) <= 4
        assert idx.match([1, 2]) == {}            # oldest evicted


class TestRouterE2E:
    def test_two_replica_parity_mixed_lifecycle(self, setup, baselines):
        """2 replicas serve the full mixed workload (priorities, one
        cancel, one timeout) with tokens identical to the single-engine
        reference; both replicas saw traffic; pools drain clean."""
        r = _router(setup)
        r.warmup()
        r.start()
        served = [r.submit(p, priority=i % 3)
                  for i, p in enumerate(PROMPTS)]
        victim_cancel = r.submit(PROMPTS[0])
        r.cancel(victim_cancel)
        victim_timeout = r.submit(PROMPTS[1], timeout_s=0.0001)
        outs = [q.result(300) for q in served]
        assert outs == baselines
        with pytest.raises(serving.RequestCancelled):
            victim_cancel.result(60)
        with pytest.raises(serving.RequestTimedOut):
            victim_timeout.result(60)
        routed = {q.replica_id for q in served}
        assert routed == {"r0", "r1"}
        assert r.drain(30)
        for eng in r.engines:
            assert eng.batcher.alloc.stats()["blocks_in_use"] == 0
        h = r.health()
        assert h["status"] == "HEALTHY" and h["serving_replicas"] == 2
        assert r.shutdown()

    def test_streaming_and_trace_routed_events(self, setup, baselines):
        r = _router(setup)
        r.start()
        got = list(r.stream(PROMPTS[2]))
        assert got == baselines[2]
        # the routed event landed on the serving replica's timeline
        merged = r.to_chrome_trace()
        routed = [e for e in merged["traceEvents"]
                  if e.get("name") == "routed"]
        assert routed and all(
            e["args"]["replica"] in ("r0", "r1") and
            e["args"]["trace_id"].split(":")[0] in ("r0", "r1")
            for e in routed)
        r.shutdown()

    def test_snapshot_and_prometheus_labels(self, setup):
        r = _router(setup)
        r.start()
        r.generate(PROMPTS[0], timeout=300)
        snap = r.snapshot()
        assert set(snap["replicas"]) == {"r0", "r1"}
        for rid, s in snap["replicas"].items():
            assert s["replica_id"] == rid
        prom = r.to_prometheus()
        assert 'replica="router"' in prom
        assert 'replica="r0"' in prom and 'replica="r1"' in prom
        # families stay grouped: each TYPE line appears exactly once
        types = [ln for ln in prom.splitlines()
                 if ln.startswith("# TYPE ")]
        assert len(types) == len(set(types))
        q = ('paddle_tpu_requests_completed_total'
             '{replica="r0"}')
        assert any(ln.startswith(q) for ln in prom.splitlines())
        r.shutdown()

    def test_backpressure_when_all_replicas_full(self, setup):
        """Every replica's admission queue rejecting surfaces as
        NoReplicaAvailable (the frontend's 429) — and the engines
        never see the overflow request."""
        r = _router(setup, max_queue_depth=1)
        # NOT started: requests pile into the admission queues
        fill = [r.submit(PROMPTS[0]) for _ in range(2)]
        with pytest.raises(NoReplicaAvailable):
            r.submit(PROMPTS[1])
        assert r.metrics.counter(
            "requests_rejected_all_replicas").value == 1
        r.start()
        assert [q.result(300) for q in fill]
        r.shutdown()


class TestRouterFailover:
    def test_failover_strict_prefix_and_parity(self, setup, baselines):
        """Hang replica r-victim mid-stream: the watchdog flips it
        UNHEALTHY, stranded requests re-admit on the survivor, every
        stream ends bit-identical to the single-engine reference with
        the pre-failover part a strict prefix (nothing re-emitted or
        lost), zero post-warmup recompiles."""
        injs = [FaultInjector(seed=0), FaultInjector(seed=1)]
        r = _router(setup, watchdog_s=0.3,
                    per_replica=[{"fault_injector": injs[0]},
                                 {"fault_injector": injs[1]}])
        r.warmup()
        r.start()
        compiles0 = [e.batcher.compile_count for e in r.engines]
        armed = threading.Event()
        ready = threading.Event()     # all submits landed (the engine-
        reqs = []                     # thread cb must not race the list)
        streamed = {i: [] for i in range(len(PROMPTS))}

        def cb(i):
            def on_token(t):
                streamed[i].append(t)
                if i == 0 and not armed.is_set():
                    armed.set()
                    ready.wait(30)
                    inj = injs[int(reqs[0].replica_id[1:])]
                    c = inj.stats()["calls"]
                    for k in range(1, 6):
                        inj.hang_on_step(c + k, 1.5)
            return on_token

        for i, p in enumerate(PROMPTS):
            reqs.append(r.submit(p, on_token=cb(i)))
        ready.set()
        outs = [q.result(300) for q in reqs]
        assert outs == baselines           # parity incl. the victims
        assert armed.is_set()
        h = r.health()
        assert h["failovers"] >= 1 and h["serving_replicas"] == 1
        snap = r.snapshot()
        by_rid = {e["router_rid"]: e for e in snap["failover_log"]}
        kept = by_rid[reqs[0].request_id]["tokens_kept"]
        assert 0 < kept < len(baselines[0])     # strict prefix resumed
        assert reqs[0].router_failovers == 1
        assert by_rid[reqs[0].request_id]["from_replica"] != \
            by_rid[reqs[0].request_id]["to_replica"]
        # nothing re-emitted: the client-side streams saw each token once
        assert streamed[0] == baselines[0]
        recompiles = sum(e.batcher.compile_count - c0
                         for e, c0 in zip(r.engines, compiles0))
        assert recompiles == 0
        # failover trace event landed on the new replica's timeline
        merged = r.to_chrome_trace()
        fo = [e for e in merged["traceEvents"]
              if e.get("name") == "failover"]
        assert fo and fo[0]["args"]["tokens_kept"] == kept
        r.shutdown(drain=False)

    def test_failover_disabled_fails_terminal(self, setup):
        injs = [FaultInjector(seed=0), FaultInjector(seed=1)]
        r = _router(setup, watchdog_s=0.3, failover=False,
                    per_replica=[{"fault_injector": injs[0]},
                                 {"fault_injector": injs[1]}])
        # warmed: the tight 0.3s deadline must not be stretched by the
        # unwarmed-engine compile grace (the injected hang is 1.5s)
        r.warmup()
        r.start()
        armed = threading.Event()
        ready = threading.Event()
        holder = []

        def on_token(t):
            if not armed.is_set():
                armed.set()
                ready.wait(30)
                inj = injs[int(holder[0].replica_id[1:])]
                c = inj.stats()["calls"]
                for k in range(1, 6):
                    inj.hang_on_step(c + k, 1.5)

        holder.append(r.submit(PROMPTS[4], on_token=on_token))
        ready.set()
        with pytest.raises(serving.RequestFailed):
            holder[0].result(300)
        assert r.health()["failovers"] == 0
        r.shutdown(drain=False)


@pytest.fixture(scope="module")
def frontend(setup):
    """Shared router + HTTP frontend on an ephemeral port."""
    r = _router(setup, max_queue_depth=32)
    r.start()
    fe = serving.HttpFrontend(r, port=0, shutdown_router=False)
    host, port = fe.start()
    yield host, port, r
    fe.shutdown()
    r.shutdown()


def _http(host, port, method, path, payload=None, timeout=300):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = None if payload is None else json.dumps(payload)
        conn.request(method, path, body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class TestHttpFrontend:
    def test_generate_roundtrip(self, frontend, baselines):
        host, port, _ = frontend
        status, body = _http(host, port, "POST", "/v1/generate",
                             {"prompt": PROMPTS[0]})
        out = json.loads(body)
        assert status == 200
        assert out["tokens"] == baselines[0]
        assert out["state"] == "FINISHED"
        assert out["replica"] in ("r0", "r1")
        assert out["request_id"].startswith("req")

    def test_sse_round_trip_over_real_socket(self, frontend, baselines):
        """POST /v1/stream: routed event first, one data event per
        token in order, a terminal done event — parsed off the raw
        socket exactly as a browser's EventSource would."""
        host, port, _ = frontend
        conn = http.client.HTTPConnection(host, port, timeout=300)
        conn.request("POST", "/v1/stream",
                     json.dumps({"prompt": PROMPTS[1]}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "text/event-stream"
        events, cur = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.decode().rstrip("\n")
            if line.startswith("event: "):
                cur = line[7:]
            elif line.startswith("data: "):
                events.append((cur or "data", json.loads(line[6:])))
                cur = None
        conn.close()
        assert events[0][0] == "routed"
        assert events[0][1]["replica"] in ("r0", "r1")
        toks = [d["token"] for k, d in events if k == "data"]
        assert toks == baselines[1]
        kind, final = events[-1]
        assert kind == "done" and final["state"] == "FINISHED"
        assert final["tokens_generated"] == len(toks)

    def test_health_and_metrics_endpoints(self, frontend):
        host, port, _ = frontend
        status, body = _http(host, port, "GET", "/health")
        h = json.loads(body)
        assert status == 200
        assert h["status"] in ("HEALTHY", "DEGRADED")
        assert set(h["replicas"]) == {"r0", "r1"}
        status, body = _http(host, port, "GET", "/metrics")
        assert status == 200
        text = body.decode()
        assert 'replica="r0"' in text and 'replica="r1"' in text

    def test_bad_requests(self, frontend):
        host, port, _ = frontend
        for payload, want in [(None, 400), ({"prompt": []}, 400),
                              ({"prompt": "abc"}, 400),
                              ({"prompt": [1], "max_new_tokens": "x"},
                               400)]:
            status, _ = _http(host, port, "POST", "/v1/generate", payload)
            assert status == want
        assert _http(host, port, "GET", "/nope")[0] == 404
        assert _http(host, port, "GET", "/v1/generate")[0] == 405

    def test_backpressure_429(self, setup):
        """Both replicas' queues full → POST answers 429."""
        r = _router(setup, max_queue_depth=1)   # parked: never started
        fe = serving.HttpFrontend(r, port=0, shutdown_router=False)
        host, port = fe.start()
        fill = [r.submit(PROMPTS[0]) for _ in range(2)]
        status, body = _http(host, port, "POST", "/v1/generate",
                             {"prompt": PROMPTS[1]})
        assert status == 429, body
        r.start()
        [q.result(300) for q in fill]
        assert fe.shutdown(drain=True)   # router stays up (ours to stop)
        r.shutdown()

    def test_drain_shutdown_completes_inflight(self, setup, baselines):
        """shutdown(drain=True) finishes the in-flight SSE stream
        before the listener dies; a late request gets refused."""
        r = _router(setup)
        r.start()
        fe = serving.HttpFrontend(r, port=0, shutdown_router=True)
        host, port = fe.start()
        result = {}

        def consume():
            conn = http.client.HTTPConnection(host, port, timeout=300)
            conn.request("POST", "/v1/stream",
                         json.dumps({"prompt": PROMPTS[3]}))
            resp = conn.getresponse()
            toks = []
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.decode().rstrip("\n")
                if line.startswith("data: "):
                    d = json.loads(line[6:])
                    if "token" in d:
                        toks.append(d["token"])
                    elif "state" in d:
                        result["final"] = d
            result["tokens"] = toks
            conn.close()

        t = threading.Thread(target=consume)
        t.start()
        deadline = time.monotonic() + 30     # stream reached the router
        while r.metrics.gauge("router_inflight").value == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert fe.shutdown(drain=True, timeout=120)
        t.join(120)
        assert result["tokens"] == baselines[3]
        assert result["final"]["state"] == "FINISHED"
        with pytest.raises((ConnectionError, OSError)):
            _http(host, port, "POST", "/v1/generate",
                  {"prompt": PROMPTS[0]}, timeout=5)
        # router was drained and stopped by the frontend
        with pytest.raises(RuntimeError):
            r.submit(PROMPTS[0])


class TestTraceReportReplicas:
    def test_report_groups_by_replica_and_failovers(self, setup,
                                                    baselines, tmp_path):
        """The merged 2-replica artifact summarizes with a replica
        column, a per-replica request breakdown and failover churn."""
        spec = importlib.util.spec_from_file_location(
            "trace_report", REPO / "tools" / "trace_report.py")
        tr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tr)
        r = _router(setup)
        r.start()
        outs = [r.generate(p, timeout=300) for p in PROMPTS[:4]]
        assert outs == baselines[:4]
        path = tmp_path / "router_trace.json"
        path.write_text(json.dumps(r.to_chrome_trace()))
        r.shutdown()
        summary = tr.summarize(tr.load_events(str(path)))
        t = summary["total"]
        assert set(t["replicas"]) <= {"r0", "r1"}
        assert sum(t["replicas"].values()) >= 4
        assert t["failover_events"] == 0
        for row in summary["requests"]:
            if row["terminal"] == "finished":
                assert row["replica"] in ("r0", "r1")
        txt = tr.render(summary)
        assert "replicas:" in txt and "failovers" in txt
