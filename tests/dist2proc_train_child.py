"""Child trainer for the launch-CLI end-to-end test (NOT a test module).

Bootstraps via paddle_tpu.distributed.init_parallel_env from the env the
launch CLI sets (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID — the TCPStore-rendezvous analog, SURVEY.md §3.2), beats
the heartbeat, and all_reduces one value so the run proves real
cross-process communication.
"""
import json
import os
import sys

import paddle_tpu.distributed as dist
from paddle_tpu.distributed.launch.main import heartbeat

dist.init_parallel_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import paddle_tpu as paddle  # noqa: E402

rank = jax.process_index()
for _ in range(3):  # fake train steps with heartbeats
    heartbeat()
t = paddle.to_tensor(jnp.asarray([float(rank + 1)]))
dist.all_reduce(t)
with open(sys.argv[1] + f".{rank}", "w") as f:
    json.dump({"rank": rank, "world": jax.process_count(),
               "sum": float(t.numpy()[0])}, f)
