"""COVERAGE.md's numbers are measured claims — this test IS the
measurement, so the audit can never silently drift from the package
(VERDICT r3 missing 1)."""
import inspect

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import Layer
from paddle_tpu.ops._registry import REGISTRY


def _layer_classes(mod):
    out = set()
    for nm in dir(mod):
        if nm.startswith("_"):
            continue
        o = getattr(mod, nm)
        if inspect.isclass(o) and issubclass(o, Layer) and o is not Layer:
            out.add(o)
    return out


def test_registry_floor():
    assert len(REGISTRY) >= 850, len(REGISTRY)


def test_tensor_method_floor():
    pub = [m for m in dir(Tensor) if not m.startswith("_")]
    assert len(pub) >= 570, len(pub)
    # the in-place wave + dtype casts + samplers are present
    for m in ("normal_", "uniform_", "exponential_", "silu_", "int",
              "long", "bfloat16", "is_sparse", "strides"):
        assert hasattr(Tensor, m), m


def test_layer_census_floor():
    from paddle_tpu.distributed.fleet import mpu
    import paddle_tpu.audio as audio
    import paddle_tpu.vision.models as vm
    import paddle_tpu.incubate.distributed.models.moe as moe_layers
    from paddle_tpu import text
    census = set()
    for mod in (paddle.nn, paddle.nn.quant, paddle.incubate.nn,
                paddle.sparse.nn, mpu, audio.features, vm, moe_layers,
                text):
        census |= _layer_classes(mod)
    assert len(census) >= 190, len(census)


def test_ref_verified_ops_floor():
    from paddle_tpu.ops.optable import SPECS
    from paddle_tpu.ops.refspecs import RTABLE
    covered = {s.name for s in RTABLE} | {
        n for n, s in SPECS.items() if s.ref is not None}
    assert len(covered) >= 320, len(covered)


def test_text_dataset_surface():
    from paddle_tpu import text
    for cls in ("Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14",
                "WMT16", "Conll05st"):
        assert hasattr(text.datasets, cls), cls
    assert hasattr(paddle.vision.datasets, "Flowers")
    assert hasattr(paddle.vision.datasets, "VOC2012")
