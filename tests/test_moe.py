"""MoE: gating, capacity dispatch, expert parallelism, model family, and the
incubate MoELayer facade.

Reference test analog: the incubate moe tests + DeepSeekMoE/Qwen2-MoE
BASELINE config 4 (SURVEY.md §4, §6).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nlp import moe, llama, train
from paddle_tpu.parallel.topology import build_mesh, set_mesh


class TestTopKGating:
    def test_each_token_routed_at_most_k(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(16, 4), jnp.float32)
        d, c, aux = moe.top_k_gating(logits, 2, 8)
        per_tok = np.asarray(d.sum(axis=(1, 2)))
        assert per_tok.max() <= 2.0 + 1e-6
        comb = np.asarray(c.sum(axis=(1, 2)))
        assert comb.max() <= 1.0 + 1e-5

    def test_capacity_enforced(self):
        # all tokens prefer expert 0 → only C fit
        logits = jnp.tile(jnp.asarray([[10.0, 0.0, 0.0, 0.0]]), (16, 1))
        d, c, aux = moe.top_k_gating(logits, 1, 4)
        per_e = np.asarray(d.sum(axis=(0, 2)))
        assert per_e[0] == 4.0  # capacity, not 16
        # dropped tokens have zero combine weight
        assert np.asarray(c.sum(axis=(1, 2))).sum() == pytest.approx(4.0, abs=1e-4)

    def test_load_balance_loss_uniform_is_one(self):
        # perfectly uniform router → loss ≈ 1 (E · E⁻¹·E⁻¹ · E)
        logits = jnp.zeros((64, 8), jnp.float32)
        _, _, aux = moe.top_k_gating(logits, 1, 64)
        assert float(aux["load_balance_loss"]) == pytest.approx(1.0, rel=1e-3)


class TestMoeBlock:
    def test_identical_experts_equals_dense(self):
        cfg = moe.MoeConfig.tiny(num_shared_experts=0, capacity_factor=8.0)
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        lp = jax.tree.map(lambda p: p[0], params["layers"])
        for nm in ("expert_gate_proj", "expert_up_proj", "expert_down_proj"):
            lp[nm] = jnp.broadcast_to(lp[nm][0:1], lp[nm].shape)
        x = jnp.asarray(np.random.RandomState(1).randn(2, 8, cfg.hidden_size),
                        jnp.float32).astype(jnp.bfloat16)
        y, _ = moe.moe_block(x, lp, cfg)
        xt = x.reshape(-1, cfg.hidden_size)
        g = xt @ lp["expert_gate_proj"][0].astype(x.dtype)
        u = xt @ lp["expert_up_proj"][0].astype(x.dtype)
        ref = ((jax.nn.silu(g) * u)
               @ lp["expert_down_proj"][0].astype(x.dtype)).reshape(x.shape)
        np.testing.assert_allclose(
            np.asarray(y, jnp.float32), np.asarray(ref, jnp.float32),
            atol=0.05)

    def test_shared_expert_added(self):
        cfg = moe.MoeConfig.tiny(num_shared_experts=1)
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        assert "shared_gate_proj" in params["layers"]
        lp = jax.tree.map(lambda p: p[0], params["layers"])
        x = jnp.ones((1, 4, cfg.hidden_size), jnp.bfloat16)
        y, _ = moe.moe_block(x, lp, cfg)
        assert y.shape == x.shape


class TestMoeModel:
    def test_loss_and_grad_finite(self):
        cfg = moe.MoeConfig.tiny()
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (4, 32)), jnp.int32)
        l = moe.loss_fn(params, toks, cfg)
        assert np.isfinite(float(l))
        g = jax.grad(moe.loss_fn)(params, toks, cfg)
        assert jax.tree_util.tree_all(
            jax.tree.map(lambda a: bool(jnp.all(jnp.isfinite(a))), g))

    def test_expert_parallel_train_step(self):
        """EP×TP×DP sharded MoE train step on the 8-device mesh."""
        mesh = build_mesh(dp=2, ep=2, mp=2)
        set_mesh(mesh)
        cfg = moe.MoeConfig.tiny()
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh,
                                 model=moe)
        step = train.make_train_step(cfg, tx, mesh, model=moe)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        toks = jax.device_put(toks, NamedSharding(mesh, llama.batch_spec()))
        state, m0 = step(state, toks)
        for _ in range(3):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])
        assert np.isfinite(float(m["grad_norm"]))

    def test_sharded_matches_unsharded(self):
        mesh = build_mesh(dp=2, ep=4)
        cfg = moe.MoeConfig.tiny(remat=False)
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (4, 32)), jnp.int32)
        ref = moe.loss_fn(params, toks, cfg, mesh=None)
        sh = jax.jit(lambda p, t: moe.loss_fn(p, t, cfg, mesh))(params, toks)
        assert abs(float(ref) - float(sh)) < 1e-3

    def test_param_counts(self):
        cfg = moe.MoeConfig.tiny()
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        assert total == moe.num_params(cfg)
        assert moe.active_params(cfg) < moe.num_params(cfg)


class TestMoELayerFacade:
    def test_forward_backward_train(self):
        from paddle_tpu.incubate.distributed.models.moe import (
            MoELayer, GShardGate)
        d = 16
        experts = [nn.Sequential(nn.Linear(d, 32), nn.GELU(),
                                 nn.Linear(32, d)) for _ in range(4)]
        layer = MoELayer(d_model=d, experts=experts,
                         gate=GShardGate(d, 4, top_k=2, capacity=(8.0, 8.0)))
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 8, d).astype("float32"),
            stop_gradient=False)
        y = layer(x)
        assert list(y.shape) == [2, 8, d]
        assert layer.l_aux is not None
        loss = (y * y).mean() + layer.l_aux * 0.01
        loss.backward()
        assert layer.gate.weight.grad is not None
        assert experts[0][0].weight.grad is not None

        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=layer.parameters())
        l0 = None
        for _ in range(5):
            opt.clear_grad()
            y = layer(x)
            loss = ((y - 1.0) ** 2).mean()
            loss.backward()
            opt.step()
            l0 = l0 if l0 is not None else float(loss.numpy())
        assert float(loss.numpy()) < l0


class TestIndexDispatch:
    """VERDICT r1 item 4: index-form routing + gather dispatch must not
    materialize O(T*E*C) tensors, and the Pallas ragged-gather kernel must
    match the jnp path in both directions."""

    def test_gather_rows_pallas_matches_jnp(self):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from paddle_tpu.kernels import moe_dispatch as md
        from paddle_tpu.core import flags as F
        rng = np.random.RandomState(0)
        src = jnp.asarray(rng.randn(2, 16, 128), jnp.float32)
        idx = jnp.asarray(rng.randint(-1, 16, (2, 24)), jnp.int32)
        ref = md._gather_rows_jnp(src, idx)
        F.set_flags({"FLAGS_pallas_interpret": True})
        try:
            out = md.gather_rows(src, idx, use_pallas=True)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
            gp = jax.grad(lambda s: jnp.sum(
                md.gather_rows(s, idx, use_pallas=True) ** 2))(src)
            gr = jax.grad(lambda s: jnp.sum(
                md._gather_rows_jnp(s, idx) ** 2))(src)
            np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                       rtol=1e-6, atol=1e-6)
        finally:
            F.set_flags({"FLAGS_pallas_interpret": False})

    def test_dispatch_gather_pallas_matches_jnp(self):
        """The conditional-free Pallas dispatch forward (k=1 gather_wsum
        with clipped indices + zero weights) must match the masked jnp
        path in value and x-gradient (interpret mode — the TPU kernel is
        otherwise only exercised on the real chip)."""
        import numpy as np
        import jax
        import jax.numpy as jnp
        from paddle_tpu.kernels import moe_dispatch as md
        from paddle_tpu.core import flags as F
        rng = np.random.RandomState(2)
        B, S, M, D, k = 1, 12, 16, 128, 2
        x = jnp.asarray(rng.randn(B, S, D), jnp.float32)
        inv_tok = jnp.asarray(rng.randint(-1, S, (B, M)), jnp.int32)
        flat = np.full((B, S * k), -1, np.int32)
        flat[0, :10] = rng.permutation(M)[:10]
        flat = jnp.asarray(flat)
        F.set_flags({"FLAGS_pallas_interpret": True})
        try:
            out_p = md.dispatch_gather(x, inv_tok, flat, k, True)
            out_j = md.dispatch_gather(x, inv_tok, flat, k, False)
            np.testing.assert_allclose(np.asarray(out_p),
                                       np.asarray(out_j),
                                       rtol=1e-6, atol=1e-6)
            gp = jax.grad(lambda x: jnp.sum(
                md.dispatch_gather(x, inv_tok, flat, k, True) ** 2))(x)
            gj = jax.grad(lambda x: jnp.sum(
                md.dispatch_gather(x, inv_tok, flat, k, False) ** 2))(x)
            np.testing.assert_allclose(np.asarray(gp), np.asarray(gj),
                                       rtol=1e-5, atol=1e-6)
        finally:
            F.set_flags({"FLAGS_pallas_interpret": False})

    def test_combine_wsum_matches_einsum_formulation(self):
        """Fused weighted combine (kernel + jnp fallback) must match the
        unfused gather-to-[B,T,k,D] + einsum path in value AND in the
        eout/probs gradients (the fused backward gathers dy rows once for
        both d_eout and d_probs)."""
        import numpy as np
        import jax
        import jax.numpy as jnp
        from paddle_tpu.kernels import moe_dispatch as md
        from paddle_tpu.core import flags as F
        rng = np.random.RandomState(1)
        B, T, k, M, D = 2, 16, 2, 24, 128
        eout = jnp.asarray(rng.randn(B, M, D), jnp.float32)
        # a consistent routing: injective (t, j) -> slot map with drops
        flat = np.full((B, T * k), -1, np.int32)
        inv = np.full((B, M), -1, np.int32)
        for b in range(B):
            perm = rng.permutation(M)
            for i, pos in enumerate(rng.permutation(T * k)[:20]):
                flat[b, pos] = perm[i]
                inv[b, perm[i]] = pos
        flat_j, inv_j = jnp.asarray(flat), jnp.asarray(inv)
        probs = jnp.asarray(rng.rand(B, T, k), jnp.float32)
        idx_tk = jnp.clip(flat_j, 0).reshape(B, T, k)
        w = jnp.where(flat_j >= 0, probs.reshape(B, T * k),
                      0.0).reshape(B, T, k)

        def ref(eo, pw):
            got = md._gather_rows_jnp(eo, flat_j).reshape(B, T, k, D)
            wv = jnp.where(flat_j.reshape(B, T, k) >= 0, pw, 0.0)
            return jnp.einsum("btkd,btk->btd", got, wv)

        def fused(eo, pw, use_pallas):
            wv = jnp.where(flat_j.reshape(B, T, k) >= 0, pw, 0.0)
            return md.combine_wsum(eo, idx_tk, wv, inv_j, use_pallas)

        for use_pallas in (False, True):
            if use_pallas:
                F.set_flags({"FLAGS_pallas_interpret": True})
            try:
                y = fused(eout, probs, use_pallas)
                np.testing.assert_allclose(np.asarray(y),
                                           np.asarray(ref(eout, probs)),
                                           rtol=1e-5, atol=1e-5)
                ge_f, gp_f = jax.grad(
                    lambda eo, pw: jnp.sum(fused(eo, pw, use_pallas) ** 2),
                    argnums=(0, 1))(eout, probs)
                ge_r, gp_r = jax.grad(
                    lambda eo, pw: jnp.sum(ref(eo, pw) ** 2),
                    argnums=(0, 1))(eout, probs)
                np.testing.assert_allclose(np.asarray(ge_f),
                                           np.asarray(ge_r),
                                           rtol=1e-5, atol=1e-5)
                np.testing.assert_allclose(np.asarray(gp_f),
                                           np.asarray(gp_r),
                                           rtol=1e-5, atol=1e-5)
            finally:
                F.set_flags({"FLAGS_pallas_interpret": False})

    def test_routing_matches_onehot_gating(self):
        """top_k_gating (one-hot facade) is derived from top_k_routing —
        dispatch/combine rebuilt from indices must satisfy the GShard
        invariants: each slot filled once, combine weights at dispatch
        positions."""
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.nlp import moe
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(32, 8), jnp.float32)
        d, c, _ = moe.top_k_gating(logits, 2, 6)
        eidx, slot, probs, valid, inv, _ = moe.top_k_routing(logits, 2, 6)
        # one-hot dispatch total == number of valid index assignments
        assert int(jnp.sum(d)) == int(jnp.sum(valid))
        # inverse map round-trips: inv[e, c] = t implies dispatch[t, e, c],
        # and combine there carries that token's gate prob for that choice
        invn = np.asarray(inv)
        dn, cn = np.asarray(d), np.asarray(c)
        en, sn = np.asarray(eidx), np.asarray(slot)
        pn, vn = np.asarray(probs), np.asarray(valid)
        for e in range(8):
            for s in range(6):
                t = invn[e, s]
                if t >= 0:
                    assert dn[t, e, s] == 1.0
                    (j,) = np.where((en[t] == e) & (sn[t] == s) & vn[t])
                    np.testing.assert_allclose(cn[t, e, s], pn[t, j[0]],
                                               rtol=1e-6)

    def test_dispatch_memory_linear_not_quadratic(self):
        """The round-1 one-hot dispatch materialized [B,S,E,C] with
        C ~ S·k/E — quadratic in sequence length. The index+gather block
        must stay linear: measured (CPU, isolated block grad) old vs new is
        6x at S=512 growing to 47x at S=4096; assert the 2048-vs-512 growth
        of the new block is ~linear (x4 tokens -> well under x8 memory,
        where the einsum block grew x15)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.nlp import moe

        def block_mem(S, B=4):
            cfg = moe.MoeConfig.tiny(num_experts=8, hidden_size=64,
                                     num_hidden_layers=1,
                                     num_shared_experts=0)
            params = moe.init_params(jax.random.PRNGKey(0), cfg)
            lp = jax.tree.map(lambda p: p[0], params["layers"])

            def blk(x):
                y, _ = moe.moe_block(x, lp, cfg, mesh=None)
                return jnp.sum(y.astype(jnp.float32) ** 2)

            x = jnp.zeros((B, S, cfg.hidden_size), cfg.dtype)
            c = jax.jit(jax.grad(blk)).lower(x).compile()
            return c.memory_analysis().temp_size_in_bytes

        m512, m2048 = block_mem(512), block_mem(2048)
        assert m2048 < m512 * 8, (m512, m2048)


class TestMoePipeline:
    """MoE through the compiled GPipe schedule (pp x ep composition —
    DeepSeek-class recipes; router aux losses ride the pipe as pytree
    buffer channels)."""

    def test_pp_loss_matches_unpipelined(self):
        from paddle_tpu.parallel.topology import build_mesh
        mesh = build_mesh(dp=2, pp=2, ep=2)
        cfg = moe.MoeConfig.tiny(num_experts=4, attn_impl="exact",
                                 remat=False)
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref = float(moe.loss_fn(params, toks, cfg, mesh=None))
        got = float(jax.jit(lambda p, t: moe.loss_fn(
            p, t, cfg, mesh, pp_microbatches=4))(params, toks))
        assert abs(ref - got) < 2e-3, (ref, got)

    def test_pp_ep_train_step_loss_decreases(self):
        from paddle_tpu.parallel.topology import build_mesh
        from paddle_tpu.nlp import train
        mesh = build_mesh(dp=2, pp=2, ep=2)
        cfg = moe.MoeConfig.tiny(num_experts=4, attn_impl="exact")
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=mesh,
                                 model=moe)
        step = train.make_train_step(cfg, tx, mesh=mesh, model=moe)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(3):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])


class TestMoe1F1B:
    """MoE under the fused 1F1B schedules (VERDICT r2 missing 5): the
    router aux-loss accumulators ride one_f_one_b's pytree activation
    contract, so DeepSeek-class MoE trains under 1F1B/interleaved with
    aux-loss gradients intact — no silent GPipe fallback."""

    def test_1f1b_pp_ep_loss_and_grad_parity(self):
        from paddle_tpu.parallel.topology import build_mesh
        mesh = build_mesh(dp=2, pp=2, ep=2)
        cfg = moe.MoeConfig.tiny(num_experts=4, attn_impl="exact",
                                 remat=False, num_hidden_layers=4)
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref_l, ref_g = jax.value_and_grad(
            lambda p: moe.loss_fn(p, toks, cfg, None))(params)
        l, g = jax.jit(lambda p, t: moe.loss_and_grad_pp(
            p, t, cfg, mesh, 4))(params, toks)
        assert abs(float(ref_l) - float(l)) < 2e-3
        errs = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                            ref_g, g)
        assert max(jax.tree.leaves(errs)) < 2e-3
        # the router gate grads specifically must be nonzero — the aux-loss
        # cotangents flowed back up the pipe
        assert float(jnp.max(jnp.abs(g["layers"]["gate"]))) > 0

    def test_interleaved_1f1b_matches(self):
        from paddle_tpu.parallel.topology import build_mesh
        mesh = build_mesh(dp=2, pp=2, ep=2)
        cfg = moe.MoeConfig.tiny(num_experts=4, attn_impl="exact",
                                 remat=False, num_hidden_layers=4)
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref_l = float(moe.loss_fn(params, toks, cfg, None))
        l, g = jax.jit(lambda p, t: moe.loss_and_grad_pp(
            p, t, cfg, mesh, 4, virtual_pp=2))(params, toks)
        assert abs(ref_l - float(l)) < 2e-3
        assert all(bool(jnp.all(jnp.isfinite(x)))
                   for x in jax.tree.leaves(g))

    def test_train_step_uses_1f1b_for_moe(self):
        """make_train_step's default schedule must route MoE through
        loss_and_grad_pp now that it exists (no GPipe fallback)."""
        from paddle_tpu.parallel.topology import build_mesh
        from paddle_tpu.nlp import train
        mesh = build_mesh(dp=2, pp=2, ep=2)
        cfg = moe.MoeConfig.tiny(num_experts=4, attn_impl="exact")
        assert hasattr(moe, "loss_and_grad_pp")
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=mesh,
                                 model=moe)
        step = train.make_train_step(cfg, tx, mesh=mesh, model=moe,
                                     pp_schedule="1f1b")
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(3):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])


class TestPairedTransposeGathers:
    """VERDICT r3 weak 1: dispatch/combine gradients are gathers via the
    inverse index map (slot assignment is injective) — parity against the
    generic scatter-add VJP of the plain jnp gather."""

    def _maps(self, rng, B, S, k, E, C):
        """Random injective slot assignment + its inverse."""
        import numpy as np
        flat = np.full((B, S * k), -1, np.int32)
        inv_pos = np.full((B, E * C), -1, np.int32)
        for b in range(B):
            n = min(S * k, E * C) - 3   # leave some dropped/empty
            slots = rng.choice(E * C, size=n, replace=False)
            poss = rng.choice(S * k, size=n, replace=False)
            flat[b, poss] = slots
            inv_pos[b, slots] = poss
        return flat, inv_pos

    def test_grads_match_scatter_reference(self):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from paddle_tpu.kernels import moe_dispatch as md
        rng = np.random.RandomState(0)
        B, S, k, E, C, D = 2, 8, 2, 4, 5, 128
        flat_np, inv_pos_np = self._maps(rng, B, S, k, E, C)
        flat = jnp.asarray(flat_np)
        inv_pos = jnp.asarray(inv_pos_np)
        inv_tok = jnp.where(inv_pos >= 0, inv_pos // k, -1)
        x = jnp.asarray(rng.randn(B, S, D), jnp.float32)
        eout = jnp.asarray(rng.randn(B, E * C, D), jnp.float32)

        # dispatch: value + grad vs plain jnp gather (autodiff scatter-add)
        f = lambda xx: jnp.sum(md.dispatch_gather(  # noqa: E731
            xx, inv_tok, flat, k, False) ** 2)
        r = lambda xx: jnp.sum(md._gather_rows_jnp(xx, inv_tok) ** 2)  # noqa: E731
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(r(x)),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(jax.grad(f)(x)),
                                   np.asarray(jax.grad(r)(x)),
                                   rtol=1e-5, atol=1e-5)

        # combine: value + grad
        g = lambda ee: jnp.sum(md.combine_gather(  # noqa: E731
            ee, flat, inv_pos, False) ** 3)
        s = lambda ee: jnp.sum(md._gather_rows_jnp(ee, flat) ** 3)  # noqa: E731
        np.testing.assert_allclose(np.asarray(g(eout)), np.asarray(s(eout)),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(jax.grad(g)(eout)),
                                   np.asarray(jax.grad(s)(eout)),
                                   rtol=1e-5, atol=1e-5)

    def test_moe_block_grads_vs_scatter_path(self):
        """Whole moe_block gradient with the paired-transpose gathers
        matches finite differences through the loss."""
        import numpy as np
        import jax
        import jax.numpy as jnp
        from paddle_tpu.nlp import moe
        cfg = moe.MoeConfig.tiny()
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        lp = {kk: v[0] for kk, v in params["layers"].items()}
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(2, 16, cfg.hidden_size) * 0.3, jnp.float32)

        def loss(xx):
            y, _ = moe.moe_block(xx.astype(jnp.float32), lp, cfg, None)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        g = jax.grad(loss)(x)
        eps = 1e-3
        idxs = [(0, 3, 5), (1, 10, 17), (0, 15, 2)]
        for i in idxs:
            d = jnp.zeros_like(x).at[i].set(eps)
            fd = (loss(x + d) - loss(x - d)) / (2 * eps)
            np.testing.assert_allclose(np.asarray(g[i]), np.asarray(fd),
                                       rtol=2e-2, atol=2e-3)


class TestMeshFusedKernels:
    """VERDICT r4 next-3: EP/TP meshes run the SAME fused Pallas kernels
    as the single-chip bench, shard_mapped over the batch shards — with
    parity against the jnp path and lowering evidence."""

    def _setup(self):
        from paddle_tpu.parallel.topology import build_mesh
        mesh = build_mesh(dp=2, ep=2, mp=2)
        cfg = moe.MoeConfig.tiny(hidden_size=128, moe_intermediate_size=128,
                                 intermediate_size=256)
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (4, 32)), jnp.int32)
        return mesh, cfg, params, toks

    def test_fused_mesh_path_matches_jnp(self):
        from paddle_tpu.core import flags
        mesh, cfg, params, toks = self._setup()

        def run():
            loss, grads = jax.value_and_grad(
                lambda p: moe.loss_fn(p, toks, cfg, mesh))(params)
            return loss, grads

        ref_loss, ref_grads = run()   # jnp path (CPU gate)
        flags.set_flags({"FLAGS_pallas_interpret": True})
        try:
            got_loss, got_grads = run()   # fused shard_map path, interpret
        finally:
            flags.set_flags({"FLAGS_pallas_interpret": False})
        np.testing.assert_allclose(float(got_loss), float(ref_loss),
                                   rtol=2e-4)
        for a, b in zip(jax.tree.leaves(got_grads),
                        jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=2e-3)

    def test_mesh_module_contains_pallas_custom_call(self):
        """Lowering for platforms=('tpu',) with FLAGS_pallas_force must put
        the Mosaic custom-call INSIDE the sharded module — the r4 mesh
        branch silently dropped to jnp, which this would catch."""
        import jax.export
        from paddle_tpu.core import flags
        mesh, cfg, params, toks = self._setup()
        fn = jax.jit(lambda p, t: moe.loss_fn(p, t, cfg, mesh))
        flags.set_flags({"FLAGS_pallas_force": True})
        jax.clear_caches()  # earlier CPU-lowered inner jits poison the
        try:                # cross-platform lowering cache (closed_call)
            txt = jax.export.export(fn, platforms=["tpu"])(
                params, toks).mlir_module()
        finally:
            flags.set_flags({"FLAGS_pallas_force": False})
            jax.clear_caches()
        assert "tpu_custom_call" in txt
        # without the force flag the CPU lowering has no pallas calls
        txt_cpu = fn.lower(params, toks).as_text()
        assert "tpu_custom_call" not in txt_cpu


class TestGatherMlp:
    """Fused dispatch-gather + gate/up GEMM kernel (r5, VERDICT r4 next-4):
    interpret-mode parity vs the jnp formulation, values and grads."""

    def _case(self, seed=0, T=32, D=128, E=4, M=16, F=128, k=2):
        from paddle_tpu.kernels import moe_dispatch as md
        rng = np.random.RandomState(seed)
        src = jnp.asarray(rng.randn(T, D), jnp.float32)
        wg = jnp.asarray(rng.randn(E, D, F) * 0.05, jnp.float32)
        wu = jnp.asarray(rng.randn(E, D, F) * 0.05, jnp.float32)
        # a routing-shaped index set: each token's k choices land in
        # distinct slots; some slots stay empty (-1)
        perm = rng.permutation(E * M)[: T * k]
        idx = np.full((E * M,), -1, np.int64)
        idx[perm] = np.arange(T * k) // k     # choice i sits at slot perm[i]
        inv_flat = np.zeros((T, k), np.int64)
        w_flat = np.zeros((T, k), np.float32)
        for i, s in enumerate(perm):          # forward map (token, choice)→slot
            inv_flat[i // k, i % k] = s
            w_flat[i // k, i % k] = 1.0
        return (md, src, jnp.asarray(idx.reshape(E, M), jnp.int32),
                jnp.asarray(inv_flat, jnp.int32), jnp.asarray(w_flat),
                wg, wu)

    def test_pallas_matches_jnp(self):
        from paddle_tpu.core import flags
        md, src, idx, inv_flat, w_flat, wg, wu = self._case()
        g_ref, u_ref, xin_ref = md._gather_mlp_jnp(src, idx, wg, wu)
        flags.set_flags({"FLAGS_pallas_interpret": True})
        try:
            g, u, xin = md.gather_mlp_pallas(src, idx, wg, wu,
                                             interpret=True)
        finally:
            flags.set_flags({"FLAGS_pallas_interpret": False})
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(u), np.asarray(u_ref),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(xin), np.asarray(xin_ref))

    def test_grads_match_unfused(self):
        from paddle_tpu.core import flags
        md, src, idx, inv_flat, w_flat, wg, wu = self._case(seed=3)

        def fused(s, a, b):
            g, u = md.gather_mlp(s, idx, inv_flat, w_flat, a, b, True)
            return jnp.sum((jax.nn.silu(g) * u) ** 2)

        def unfused(s, a, b):
            g, u, _ = md._gather_mlp_jnp(s, idx, a, b)
            return jnp.sum((jax.nn.silu(g) * u) ** 2)

        for interp in (False, True):
            flags.set_flags({"FLAGS_pallas_interpret": interp})
            try:
                v, gr = jax.value_and_grad(fused, (0, 1, 2))(src, wg, wu)
            finally:
                flags.set_flags({"FLAGS_pallas_interpret": False})
            rv, rgr = jax.value_and_grad(unfused, (0, 1, 2))(src, wg, wu)
            np.testing.assert_allclose(float(v), float(rv), rtol=1e-5)
            for a, b in zip(gr, rgr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5)
