"""Pipeline parallelism: compiled GPipe schedule + fleet facade.

Reference analog: test/collective/fleet/test_parallel_dygraph_pipeline_
parallel.py (SURVEY.md §4) — theirs spawns NCCL processes per stage; ours
runs the one compiled schedule on 8 host-platform devices and checks parity
against the unpipelined model.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.parallel.topology import build_mesh, set_mesh
from paddle_tpu.parallel.pipeline import (
    gpipe_apply, pipelined, stack_stages, unstack_stages)
from paddle_tpu.nlp import llama, train


@pytest.fixture
def pp_mesh():
    mesh = build_mesh(dp=2, pp=4)
    set_mesh(mesh)
    return mesh


class TestGpipePrimitive:
    def test_stacked_linear_stages_match_sequential(self, pp_mesh):
        """4 stages, each y = x @ w_i: pipeline == sequential product."""
        rng = np.random.RandomState(0)
        ws = jnp.asarray(rng.randn(4, 1, 8, 8) * 0.5, jnp.float32)
        mb = jnp.asarray(rng.randn(6, 2, 8), jnp.float32)  # [M=6, mb=2, d]

        def stage_fn(w, x):
            return x @ w[0]

        out = jax.jit(pipelined(stage_fn, pp_mesh))(ws, mb)
        ref = mb
        for i in range(4):
            ref = ref @ ws[i, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_flows_through_pipeline(self, pp_mesh):
        rng = np.random.RandomState(1)
        ws = jnp.asarray(rng.randn(4, 1, 4, 4) * 0.5, jnp.float32)
        mb = jnp.asarray(rng.randn(4, 2, 4), jnp.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w[0])

        def loss_pipe(ws):
            return jnp.sum(pipelined(stage_fn, pp_mesh)(ws, mb) ** 2)

        def loss_ref(ws):
            x = mb
            for i in range(4):
                x = jnp.tanh(x @ ws[i, 0])
            return jnp.sum(x ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(ws)
        g_ref = jax.grad(loss_ref)(ws)
        np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_stack_unstack_roundtrip(self):
        p = {"w": jnp.arange(24.0).reshape(8, 3)}
        s = stack_stages(p, 4)
        assert s["w"].shape == (4, 2, 3)
        r = unstack_stages(s)
        np.testing.assert_array_equal(np.asarray(r["w"]),
                                      np.asarray(p["w"]))

    def test_indivisible_layers_raise(self):
        with pytest.raises(ValueError):
            stack_stages({"w": jnp.zeros((6, 2))}, 4)


class TestLlamaPipeline:
    @pytest.mark.slow
    def test_pp_loss_and_grad_parity(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                     num_hidden_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref = llama.loss_fn(params, toks, cfg, mesh=None)
        pp = jax.jit(lambda p, t: llama.loss_fn(p, t, cfg, pp_mesh,
                                                pp_microbatches=4))(params, toks)
        assert abs(float(ref) - float(pp)) < 1e-3

        g_ref = jax.grad(lambda p: llama.loss_fn(p, toks, cfg, None))(params)
        g_pp = jax.jit(jax.grad(
            lambda p: llama.loss_fn(p, toks, cfg, pp_mesh, 4)))(params)
        errs = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                            g_ref, g_pp)
        assert max(jax.tree.leaves(errs)) < 1e-3

    def test_pp_train_step_loss_decreases(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=4)
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=pp_mesh)
        step = train.make_train_step(cfg, tx, mesh=pp_mesh)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(4):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_pp_composes_with_context_parallel(self, impl):
        """PP (manual pp axis) nesting the sep-axis attention shard_map."""
        mesh = build_mesh(pp=2, sep=4)
        cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                     num_hidden_layers=4, attn_impl=impl)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref_cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                         num_hidden_layers=4)
        ref = llama.loss_fn(params, toks, ref_cfg, mesh=None)
        pp = jax.jit(lambda p, t: llama.loss_fn(
            p, t, cfg, mesh, pp_microbatches=4))(params, toks)
        assert abs(float(ref) - float(pp)) < 1e-3

    def test_1f1b_loss_and_grad_parity(self, pp_mesh):
        """The fused 1F1B schedule (one_f_one_b) matches the unpipelined
        reference — loss and every grad leaf."""
        cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                     num_hidden_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref_l, ref_g = jax.value_and_grad(
            lambda p: llama.loss_fn(p, toks, cfg, None))(params)
        l, g = jax.jit(lambda p, t: llama.loss_and_grad_pp(
            p, t, cfg, pp_mesh, 8))(params, toks)
        assert abs(float(ref_l) - float(l)) < 1e-3
        errs = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                            ref_g, g)
        assert max(jax.tree.leaves(errs)) < 1e-3

    def test_1f1b_memory_beats_gpipe(self, pp_mesh):
        """The 1F1B claim (VERDICT r1 item 2): stage activation residency is
        O(pp), not O(M). At M=32 microbatches / pp=4 stages the compiled
        temp memory of the fused schedule must be several times below the
        GPipe-under-jax.grad path (whose scan transpose keeps all M
        microbatch activations live)."""
        cfg = llama.LlamaConfig.tiny(remat=True, use_flash=False,
                                     num_hidden_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.zeros((32, 32), jnp.int32)
        M = 32
        gpipe = jax.jit(jax.grad(
            lambda p: llama.loss_fn(p, toks, cfg, pp_mesh, M)))
        f1b = jax.jit(lambda p, t: llama.loss_and_grad_pp(
            p, t, cfg, pp_mesh, M))
        m_gpipe = gpipe.lower(params).compile().memory_analysis()
        m_1f1b = f1b.lower(params, toks).compile().memory_analysis()
        assert m_1f1b.temp_size_in_bytes * 3 < m_gpipe.temp_size_in_bytes

    def test_1f1b_train_step_loss_decreases(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=4)
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=pp_mesh)
        step = train.make_train_step(cfg, tx, mesh=pp_mesh,
                                     pp_schedule="1f1b")
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(4):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])

    def test_layers_not_divisible_by_stages_raises(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(num_hidden_layers=2, use_flash=False)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.zeros((8, 16), jnp.int32)
        with pytest.raises(ValueError, match="not divisible"):
            llama.forward_pp(params, toks, cfg, pp_mesh, 4)


class TestFleetPipelineFacade:
    def test_pipeline_layer_forward_and_train_batch(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import (
            LayerDesc, PipelineLayer, PipelineParallel)

        set_mesh(build_mesh(dp=8))
        layers = [
            LayerDesc(nn.Linear, 8, 16),
            LayerDesc(nn.ReLU),
            LayerDesc(nn.Linear, 16, 4),
        ]
        pl = PipelineLayer(layers, num_stages=1,
                           loss_fn=nn.CrossEntropyLoss())
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype("float32"))
        out = pl(x)
        assert list(out.shape) == [4, 4]

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"pp_configs": {"accumulate_steps": 2}}
        pp = PipelineParallel(pl, strategy=strategy)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=pl.parameters())
        label = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 4, (4,)).astype("int64"))
        l0 = float(pp.train_batch((x, label), opt).numpy())
        l_last = l0
        for _ in range(5):
            l_last = float(pp.train_batch((x, label), opt).numpy())
        assert l_last < l0

    def test_fleet_init_builds_mesh(self):
        from paddle_tpu.distributed import fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        from paddle_tpu.parallel.topology import get_mesh
        mesh = get_mesh()
        assert mesh.shape["dp"] == 2 and mesh.shape["mp"] == 2 \
            and mesh.shape["pp"] == 2
        hcg = fleet.fleet.get_hybrid_communicate_group()
        assert hcg.get_pipe_parallel_world_size() == 2

    def test_seg_method_layer(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer

        set_mesh(build_mesh(dp=8))
        layers = []
        for _ in range(4):
            layers.append(LayerDesc(nn.Linear, 4, 4))
            layers.append(LayerDesc(nn.ReLU))
        pl = PipelineLayer(layers, num_stages=2, seg_method="layer:Linear")
        assert pl.get_num_stages() == 2
        s0 = pl.stage_layers(0)
        s1 = pl.stage_layers(1)
        assert len(s0) + len(s1) == 8


class TestInterleavedVirtualPP:
    """Circular virtual-pp schedule (reference: PipelineParallel's
    interleaved mode — SURVEY.md §2.3 PP row, the round-1 gap's second
    half after 1F1B)."""

    def test_circular_matches_sequential(self, pp_mesh):
        from paddle_tpu.parallel.pipeline import (
            interleaved, stack_virtual_chunks)
        rng = np.random.RandomState(0)
        L, d = 8, 8
        ws = jnp.asarray(rng.randn(L, d, d) * 0.3, jnp.float32)
        mb = jnp.asarray(rng.randn(8, 2, d), jnp.float32)

        def stage_fn(w, x):
            def body(x, wl):
                return jnp.tanh(x @ wl), None
            x, _ = jax.lax.scan(body, x, w)
            return x

        chunks = stack_virtual_chunks(ws, 4, 2)
        out = jax.jit(interleaved(stage_fn, pp_mesh, v=2,
                                  remat=False))(chunks, mb)
        ref = mb
        for l in range(L):
            ref = jnp.tanh(ref @ ws[l])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_flow_through_circular_schedule(self, pp_mesh):
        from paddle_tpu.parallel.pipeline import (
            interleaved, stack_virtual_chunks)
        rng = np.random.RandomState(1)
        ws = jnp.asarray(rng.randn(8, 4, 4) * 0.3, jnp.float32)
        mb = jnp.asarray(rng.randn(4, 2, 4), jnp.float32)

        def stage_fn(w, x):
            def body(x, wl):
                return jnp.tanh(x @ wl), None
            x, _ = jax.lax.scan(body, x, w)
            return x

        def loss_i(ws):
            return jnp.sum(interleaved(stage_fn, pp_mesh, v=2, remat=False)(
                stack_virtual_chunks(ws, 4, 2), mb) ** 2)

        def loss_r(ws):
            x = mb
            for l in range(8):
                x = jnp.tanh(x @ ws[l])
            return jnp.sum(x ** 2)

        gi = jax.jit(jax.grad(loss_i))(ws)
        gr = jax.grad(loss_r)(ws)
        np.testing.assert_allclose(np.asarray(gi), np.asarray(gr),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_llama_interleaved_loss_parity(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                     num_hidden_layers=8)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref = llama.loss_fn(params, toks, cfg, mesh=None)
        got = jax.jit(lambda p, t: llama.loss_fn(
            p, t, cfg, pp_mesh, pp_microbatches=4, pp_virtual=2))(
            params, toks)
        assert abs(float(ref) - float(got)) < 1e-3

    def test_interleaved_train_step(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=8)
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=pp_mesh)
        step = train.make_train_step(cfg, tx, mesh=pp_mesh,
                                     pp_schedule="interleaved",
                                     virtual_pp_degree=2)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(3):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])

    def test_microbatches_not_divisible_by_stages_raises(self, pp_mesh):
        from paddle_tpu.parallel.pipeline import (
            interleaved, stack_virtual_chunks)
        ws = jnp.zeros((8, 4, 4), jnp.float32)
        mb = jnp.zeros((6, 2, 4), jnp.float32)  # 6 % 4 != 0
        with pytest.raises(ValueError, match="groups of p"):
            jax.jit(interleaved(lambda w, x: x, pp_mesh, v=2))(
                stack_virtual_chunks(ws, 4, 2), mb)


class TestInterleaved1F1B:
    """Interleaved (virtual-pp) 1F1B — VERDICT r2 missing 2: the fused
    explicit-vjp schedule with v chunks/device and O(v·pp) activation
    residency, replacing the circular-GPipe-under-grad transpose."""

    def test_matches_unpipelined_and_plain_1f1b(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                     num_hidden_layers=8)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref_l, ref_g = jax.value_and_grad(
            lambda p: llama.loss_fn(p, toks, cfg, None))(params)
        l, g = jax.jit(lambda p, t: llama.loss_and_grad_pp(
            p, t, cfg, pp_mesh, 8, virtual_pp=2))(params, toks)
        assert abs(float(ref_l) - float(l)) < 1e-3
        errs = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                            ref_g, g)
        assert max(jax.tree.leaves(errs)) < 1e-3

    def test_residency_independent_of_microbatch_count(self, pp_mesh):
        """The 1F1B memory property under virtual-pp: compiled temp memory
        must NOT scale with M (the saved-activation ring is 2·v·p slots).
        The circular-GPipe transpose keeps O(v·M) activations — at M=32 it
        must cost several times more temp than interleaved 1F1B."""
        cfg = llama.LlamaConfig.tiny(remat=True, use_flash=False,
                                     num_hidden_layers=8)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)

        def temp(M):
            toks = jnp.zeros((M, 32), jnp.int32)
            fn = jax.jit(lambda p, t: llama.loss_and_grad_pp(
                p, t, cfg, pp_mesh, M, virtual_pp=2))
            return fn.lower(params, toks).compile(
                ).memory_analysis().temp_size_in_bytes

        t8, t32 = temp(8), temp(32)
        assert t32 < 1.5 * t8, (t8, t32)

        toks32 = jnp.zeros((32, 32), jnp.int32)
        circ = jax.jit(jax.grad(lambda p: llama.loss_fn(
            p, toks32, cfg, pp_mesh, pp_microbatches=32, pp_virtual=2)))
        t_circ = circ.lower(params).compile(
            ).memory_analysis().temp_size_in_bytes
        assert t32 * 2 < t_circ, (t32, t_circ)

    def test_interleaved_schedule_in_train_step(self, pp_mesh):
        """make_train_step(pp_schedule='interleaved') now routes through
        interleaved_one_f_one_b (llama has loss_and_grad_pp)."""
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=8)
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=pp_mesh)
        step = train.make_train_step(cfg, tx, mesh=pp_mesh,
                                     pp_schedule="interleaved",
                                     virtual_pp_degree=2)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(3):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])


class TestPytreeActivations1F1B:
    """VERDICT r2 weak 2: the 1F1B activation contract is a pytree — a
    stage boundary may carry side channels beside the activation."""

    def test_dict_activation_with_scalar_channel(self, pp_mesh):
        """Stages y = relu(x @ w) with a scalar accumulator channel
        s += mean(y); last_fn consumes both. Grads must match the
        sequential (no-pipeline) autodiff of the same composite."""
        from paddle_tpu.parallel.pipeline import one_f_one_b, stack_stages
        n, M, mb, D = 4, 8, 2, 8
        f32 = jnp.float32
        ws = (jax.random.normal(jax.random.PRNGKey(0), (n, D, D)) * 0.5
              ).astype(f32)
        inp = jax.random.normal(jax.random.PRNGKey(1), (M, mb, D)).astype(f32)
        w_first = (jax.random.normal(jax.random.PRNGKey(2), (D, D)) * 0.5
                   ).astype(f32)
        w_last = jax.random.normal(jax.random.PRNGKey(3), (D,)).astype(f32)

        def stage_fn(w, buf):
            y = jax.nn.relu(buf["x"] @ w[0])
            return {"x": y, "s": buf["s"] + jnp.mean(y)}

        def first_fn(wf, z):
            return {"x": z @ wf, "s": jnp.zeros((), jnp.float32)}

        def last_fn(wl, buf, z):
            return jnp.sum(buf["x"] * wl) + buf["s"]

        def seq_loss(stages, wf, wl):
            def one(z):
                buf = first_fn(wf, z)
                for i in range(n):
                    buf = stage_fn(stages[i:i + 1, 0], buf)
                return last_fn(wl, buf, z)
            return jnp.mean(jax.vmap(one)(inp))

        sp = stack_stages(ws, n)
        l, g_s, g_f, g_l = jax.jit(
            lambda s, f, la, x: one_f_one_b(
                stage_fn, first_fn, last_fn, pp_mesh, n_stages=n)(
                    s, f, la, x))(sp, w_first, w_last, inp)
        stages_ref = sp.reshape(n, 1, D, D)
        ref_l, (rg_s, rg_f, rg_l) = jax.value_and_grad(
            lambda s, f, la: seq_loss(s, f, la), argnums=(0, 1, 2))(
                stages_ref, w_first, w_last)
        assert abs(float(l) - float(ref_l)) < 1e-4
        np.testing.assert_allclose(np.asarray(g_s).reshape(rg_s.shape),
                                   np.asarray(rg_s), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(g_f), np.asarray(rg_f),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(g_l), np.asarray(rg_l),
                                   rtol=1e-4, atol=1e-4)


class TestVirtualChunkRelayout:
    """stack/unstack_virtual_chunks mesh staging (VERDICT r3 weak 2): the
    storage→chunk relayout must compile without GSPMD's involuntary-
    replication fallback in BOTH regimes (p | v all-to-all, v < p voluntary
    replicate) and land on the contract shardings."""

    @pytest.mark.parametrize("v", [2, 4])  # pp=4: v=2 replicate, v=4 a2a
    def test_round_trip_and_shardings(self, pp_mesh, v):
        from jax.sharding import NamedSharding
        from paddle_tpu.parallel.pipeline import (
            stack_virtual_chunks, unstack_virtual_chunks)
        p = pp_mesh.shape["pp"]
        L, d = p * v, 8
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(L, d) * 0.3, jnp.float32)
        w = jax.device_put(w, NamedSharding(pp_mesh, P("pp")))

        stack = jax.jit(lambda x: stack_virtual_chunks(
            {"w": x}, p, v, mesh=pp_mesh))
        chunks = stack(w)["w"]
        # values: identical to the plain reshape (constraints are layout-only)
        np.testing.assert_array_equal(
            np.asarray(chunks), np.asarray(w).reshape(v, p, 1, d))
        # layout: chunk dim 1 sharded over pp — the interleaved contract
        assert chunks.sharding.spec == P(None, "pp"), chunks.sharding

        back = jax.jit(lambda c: unstack_virtual_chunks(
            {"w": c}, mesh=pp_mesh))(chunks)["w"]
        np.testing.assert_array_equal(np.asarray(back), np.asarray(w))
        # inverse lands back on contiguous-P('pp') storage
        assert back.sharding.spec == P("pp"), back.sharding

    def test_stage_count_mismatch_raises(self, pp_mesh):
        from paddle_tpu.parallel.pipeline import stack_virtual_chunks
        w = jnp.zeros((8, 4), jnp.float32)
        with pytest.raises(ValueError, match="one stage per"):
            stack_virtual_chunks({"w": w}, 2, 4, mesh=pp_mesh)

    @pytest.mark.parametrize("v", [2, 4])
    def test_trailing_tp_zero_axes_survive(self, v):
        """Finding from review: the staging pins must move ONLY the pp
        axis — a TP/ZeRO-sharded weight leaf keeps its mp/'sharding'
        trailing-dim sharding through the relayout (pinning them None
        would all-gather every weight)."""
        from jax.sharding import NamedSharding
        from paddle_tpu.parallel.pipeline import (
            stack_virtual_chunks, unstack_virtual_chunks)
        mesh = build_mesh(pp=2, sharding=2, mp=2)
        p = mesh.shape["pp"]
        L, d1, d2 = p * v, 8, 8
        w = jnp.asarray(np.random.RandomState(0).randn(L, d1, d2),
                        jnp.float32)
        w = jax.device_put(
            w, NamedSharding(mesh, P("pp", "sharding", "mp")))

        chunks = jax.jit(lambda x: stack_virtual_chunks(
            {"w": x}, p, v, mesh=mesh))(w)["w"]
        np.testing.assert_array_equal(
            np.asarray(chunks), np.asarray(w).reshape(v, p, L // (p * v),
                                                      d1, d2))
        cspec = chunks.sharding.spec
        assert cspec[1] == "pp", cspec
        assert "sharding" in cspec and "mp" in cspec, (
            f"TP/ZeRO axes stripped by the relayout: {cspec}")

        back = jax.jit(lambda c: unstack_virtual_chunks(
            {"w": c}, mesh=mesh))(chunks)["w"]
        np.testing.assert_array_equal(np.asarray(back), np.asarray(w))
        bspec = back.sharding.spec
        assert bspec[0] == "pp", bspec
        assert "sharding" in bspec and "mp" in bspec, (
            f"TP/ZeRO axes stripped on the grad path: {bspec}")
