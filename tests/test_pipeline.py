"""Pipeline parallelism: compiled GPipe schedule + fleet facade.

Reference analog: test/collective/fleet/test_parallel_dygraph_pipeline_
parallel.py (SURVEY.md §4) — theirs spawns NCCL processes per stage; ours
runs the one compiled schedule on 8 host-platform devices and checks parity
against the unpipelined model.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.parallel.topology import build_mesh, set_mesh
from paddle_tpu.parallel.pipeline import (
    gpipe_apply, pipelined, stack_stages, unstack_stages)
from paddle_tpu.nlp import llama, train


@pytest.fixture
def pp_mesh():
    mesh = build_mesh(dp=2, pp=4)
    set_mesh(mesh)
    return mesh


class TestGpipePrimitive:
    def test_stacked_linear_stages_match_sequential(self, pp_mesh):
        """4 stages, each y = x @ w_i: pipeline == sequential product."""
        rng = np.random.RandomState(0)
        ws = jnp.asarray(rng.randn(4, 1, 8, 8) * 0.5, jnp.float32)
        mb = jnp.asarray(rng.randn(6, 2, 8), jnp.float32)  # [M=6, mb=2, d]

        def stage_fn(w, x):
            return x @ w[0]

        out = jax.jit(pipelined(stage_fn, pp_mesh))(ws, mb)
        ref = mb
        for i in range(4):
            ref = ref @ ws[i, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_flows_through_pipeline(self, pp_mesh):
        rng = np.random.RandomState(1)
        ws = jnp.asarray(rng.randn(4, 1, 4, 4) * 0.5, jnp.float32)
        mb = jnp.asarray(rng.randn(4, 2, 4), jnp.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w[0])

        def loss_pipe(ws):
            return jnp.sum(pipelined(stage_fn, pp_mesh)(ws, mb) ** 2)

        def loss_ref(ws):
            x = mb
            for i in range(4):
                x = jnp.tanh(x @ ws[i, 0])
            return jnp.sum(x ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(ws)
        g_ref = jax.grad(loss_ref)(ws)
        np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_stack_unstack_roundtrip(self):
        p = {"w": jnp.arange(24.0).reshape(8, 3)}
        s = stack_stages(p, 4)
        assert s["w"].shape == (4, 2, 3)
        r = unstack_stages(s)
        np.testing.assert_array_equal(np.asarray(r["w"]),
                                      np.asarray(p["w"]))

    def test_indivisible_layers_raise(self):
        with pytest.raises(ValueError):
            stack_stages({"w": jnp.zeros((6, 2))}, 4)


class TestLlamaPipeline:
    @pytest.mark.slow
    def test_pp_loss_and_grad_parity(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                     num_hidden_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref = llama.loss_fn(params, toks, cfg, mesh=None)
        pp = jax.jit(lambda p, t: llama.loss_fn(p, t, cfg, pp_mesh,
                                                pp_microbatches=4))(params, toks)
        assert abs(float(ref) - float(pp)) < 1e-3

        g_ref = jax.grad(lambda p: llama.loss_fn(p, toks, cfg, None))(params)
        g_pp = jax.jit(jax.grad(
            lambda p: llama.loss_fn(p, toks, cfg, pp_mesh, 4)))(params)
        errs = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                            g_ref, g_pp)
        assert max(jax.tree.leaves(errs)) < 1e-3

    def test_pp_train_step_loss_decreases(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=4)
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=pp_mesh)
        step = train.make_train_step(cfg, tx, mesh=pp_mesh)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(4):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_pp_composes_with_context_parallel(self, impl):
        """PP (manual pp axis) nesting the sep-axis attention shard_map."""
        mesh = build_mesh(pp=2, sep=4)
        cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                     num_hidden_layers=4, attn_impl=impl)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref_cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                         num_hidden_layers=4)
        ref = llama.loss_fn(params, toks, ref_cfg, mesh=None)
        pp = jax.jit(lambda p, t: llama.loss_fn(
            p, t, cfg, mesh, pp_microbatches=4))(params, toks)
        assert abs(float(ref) - float(pp)) < 1e-3

    def test_1f1b_loss_and_grad_parity(self, pp_mesh):
        """The fused 1F1B schedule (one_f_one_b) matches the unpipelined
        reference — loss and every grad leaf."""
        cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                     num_hidden_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref_l, ref_g = jax.value_and_grad(
            lambda p: llama.loss_fn(p, toks, cfg, None))(params)
        l, g = jax.jit(lambda p, t: llama.loss_and_grad_pp(
            p, t, cfg, pp_mesh, 8))(params, toks)
        assert abs(float(ref_l) - float(l)) < 1e-3
        errs = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                            ref_g, g)
        assert max(jax.tree.leaves(errs)) < 1e-3

    def test_1f1b_memory_beats_gpipe(self, pp_mesh):
        """The 1F1B claim (VERDICT r1 item 2): stage activation residency is
        O(pp), not O(M). At M=32 microbatches / pp=4 stages the compiled
        temp memory of the fused schedule must be several times below the
        GPipe-under-jax.grad path (whose scan transpose keeps all M
        microbatch activations live)."""
        cfg = llama.LlamaConfig.tiny(remat=True, use_flash=False,
                                     num_hidden_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.zeros((32, 32), jnp.int32)
        M = 32
        gpipe = jax.jit(jax.grad(
            lambda p: llama.loss_fn(p, toks, cfg, pp_mesh, M)))
        f1b = jax.jit(lambda p, t: llama.loss_and_grad_pp(
            p, t, cfg, pp_mesh, M))
        m_gpipe = gpipe.lower(params).compile().memory_analysis()
        m_1f1b = f1b.lower(params, toks).compile().memory_analysis()
        assert m_1f1b.temp_size_in_bytes * 3 < m_gpipe.temp_size_in_bytes

    def test_1f1b_train_step_loss_decreases(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=4)
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=pp_mesh)
        step = train.make_train_step(cfg, tx, mesh=pp_mesh,
                                     pp_schedule="1f1b")
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(4):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])

    def test_layers_not_divisible_by_stages_raises(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(num_hidden_layers=2, use_flash=False)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.zeros((8, 16), jnp.int32)
        with pytest.raises(ValueError, match="not divisible"):
            llama.forward_pp(params, toks, cfg, pp_mesh, 4)


class TestFleetPipelineFacade:
    def test_pipeline_layer_forward_and_train_batch(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import (
            LayerDesc, PipelineLayer, PipelineParallel)

        set_mesh(build_mesh(dp=8))
        layers = [
            LayerDesc(nn.Linear, 8, 16),
            LayerDesc(nn.ReLU),
            LayerDesc(nn.Linear, 16, 4),
        ]
        pl = PipelineLayer(layers, num_stages=1,
                           loss_fn=nn.CrossEntropyLoss())
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype("float32"))
        out = pl(x)
        assert list(out.shape) == [4, 4]

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"pp_configs": {"accumulate_steps": 2}}
        pp = PipelineParallel(pl, strategy=strategy)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=pl.parameters())
        label = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 4, (4,)).astype("int64"))
        l0 = float(pp.train_batch((x, label), opt).numpy())
        l_last = l0
        for _ in range(5):
            l_last = float(pp.train_batch((x, label), opt).numpy())
        assert l_last < l0

    def test_fleet_init_builds_mesh(self):
        from paddle_tpu.distributed import fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        from paddle_tpu.parallel.topology import get_mesh
        mesh = get_mesh()
        assert mesh.shape["dp"] == 2 and mesh.shape["mp"] == 2 \
            and mesh.shape["pp"] == 2
        hcg = fleet.fleet.get_hybrid_communicate_group()
        assert hcg.get_pipe_parallel_world_size() == 2

    def test_seg_method_layer(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer

        set_mesh(build_mesh(dp=8))
        layers = []
        for _ in range(4):
            layers.append(LayerDesc(nn.Linear, 4, 4))
            layers.append(LayerDesc(nn.ReLU))
        pl = PipelineLayer(layers, num_stages=2, seg_method="layer:Linear")
        assert pl.get_num_stages() == 2
        s0 = pl.stage_layers(0)
        s1 = pl.stage_layers(1)
        assert len(s0) + len(s1) == 8


class TestInterleavedVirtualPP:
    """Circular virtual-pp schedule (reference: PipelineParallel's
    interleaved mode — SURVEY.md §2.3 PP row, the round-1 gap's second
    half after 1F1B)."""

    def test_circular_matches_sequential(self, pp_mesh):
        from paddle_tpu.parallel.pipeline import (
            interleaved, stack_virtual_chunks)
        rng = np.random.RandomState(0)
        L, d = 8, 8
        ws = jnp.asarray(rng.randn(L, d, d) * 0.3, jnp.float32)
        mb = jnp.asarray(rng.randn(8, 2, d), jnp.float32)

        def stage_fn(w, x):
            def body(x, wl):
                return jnp.tanh(x @ wl), None
            x, _ = jax.lax.scan(body, x, w)
            return x

        chunks = stack_virtual_chunks(ws, 4, 2)
        out = jax.jit(interleaved(stage_fn, pp_mesh, v=2,
                                  remat=False))(chunks, mb)
        ref = mb
        for l in range(L):
            ref = jnp.tanh(ref @ ws[l])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_flow_through_circular_schedule(self, pp_mesh):
        from paddle_tpu.parallel.pipeline import (
            interleaved, stack_virtual_chunks)
        rng = np.random.RandomState(1)
        ws = jnp.asarray(rng.randn(8, 4, 4) * 0.3, jnp.float32)
        mb = jnp.asarray(rng.randn(4, 2, 4), jnp.float32)

        def stage_fn(w, x):
            def body(x, wl):
                return jnp.tanh(x @ wl), None
            x, _ = jax.lax.scan(body, x, w)
            return x

        def loss_i(ws):
            return jnp.sum(interleaved(stage_fn, pp_mesh, v=2, remat=False)(
                stack_virtual_chunks(ws, 4, 2), mb) ** 2)

        def loss_r(ws):
            x = mb
            for l in range(8):
                x = jnp.tanh(x @ ws[l])
            return jnp.sum(x ** 2)

        gi = jax.jit(jax.grad(loss_i))(ws)
        gr = jax.grad(loss_r)(ws)
        np.testing.assert_allclose(np.asarray(gi), np.asarray(gr),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_llama_interleaved_loss_parity(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(remat=False, use_flash=False,
                                     num_hidden_layers=8)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        ref = llama.loss_fn(params, toks, cfg, mesh=None)
        got = jax.jit(lambda p, t: llama.loss_fn(
            p, t, cfg, pp_mesh, pp_microbatches=4, pp_virtual=2))(
            params, toks)
        assert abs(float(ref) - float(got)) < 1e-3

    def test_interleaved_train_step(self, pp_mesh):
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=8)
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=pp_mesh)
        step = train.make_train_step(cfg, tx, mesh=pp_mesh,
                                     pp_schedule="interleaved",
                                     virtual_pp_degree=2)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(3):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])

    def test_microbatches_not_divisible_by_stages_raises(self, pp_mesh):
        from paddle_tpu.parallel.pipeline import (
            interleaved, stack_virtual_chunks)
        ws = jnp.zeros((8, 4, 4), jnp.float32)
        mb = jnp.zeros((6, 2, 4), jnp.float32)  # 6 % 4 != 0
        with pytest.raises(ValueError, match="groups of p"):
            jax.jit(interleaved(lambda w, x: x, pp_mesh, v=2))(
                stack_virtual_chunks(ws, 4, 2), mb)
