"""Op unit tests: math/elementwise — mirrors the reference's per-op OpTest
files (SURVEY.md §4, test/legacy_test/test_*_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from optest import check_output, check_grad

RNG = np.random.default_rng(7)


def fdata(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


class TestBinaryOps:
    @pytest.mark.parametrize("op,ref", [
        (paddle.add, np.add), (paddle.subtract, np.subtract),
        (paddle.multiply, np.multiply), (paddle.divide, np.divide),
        (paddle.maximum, np.maximum), (paddle.minimum, np.minimum),
        (paddle.atan2, np.arctan2),
    ])
    def test_forward(self, op, ref):
        x, y = fdata(3, 4), fdata(3, 4) + 2.0
        check_output(op, ref, [x, y])

    def test_broadcast(self):
        check_output(paddle.add, np.add, [fdata(3, 1, 4), fdata(2, 4)])

    @pytest.mark.parametrize("op", [paddle.add, paddle.subtract, paddle.multiply, paddle.divide])
    def test_grad(self, op):
        x, y = fdata(2, 3), fdata(2, 3) + 2.0
        check_grad(op, [x, y])

    def test_scalar_rhs(self):
        x = paddle.to_tensor(fdata(2, 2))
        np.testing.assert_allclose((x + 1.5).numpy(), x.numpy() + 1.5, rtol=1e-6)
        np.testing.assert_allclose((2.0 * x).numpy(), 2 * x.numpy(), rtol=1e-6)
        np.testing.assert_allclose((1.0 / (x + 10)).numpy(), 1 / (x.numpy() + 10), rtol=1e-6)

    def test_int_ops(self):
        a = np.array([7, 8, 9]); b = np.array([2, 3, 4])
        check_output(paddle.floor_divide, np.floor_divide, [a, b])
        check_output(paddle.mod, np.mod, [a, b])


class TestUnaryOps:
    @pytest.mark.parametrize("op,ref", [
        (paddle.exp, np.exp), (paddle.log, None), (paddle.sqrt, None),
        (paddle.tanh, np.tanh), (paddle.sin, np.sin), (paddle.cos, np.cos),
        (paddle.abs, np.abs), (paddle.floor, np.floor), (paddle.ceil, np.ceil),
        (paddle.square, np.square), (paddle.sigmoid, None),
    ])
    def test_forward(self, op, ref):
        x = fdata(3, 4)
        if op in (paddle.log, paddle.sqrt):
            x = np.abs(x) + 0.5
            ref = {paddle.log: np.log, paddle.sqrt: np.sqrt}[op]
        if op is paddle.sigmoid:
            ref = lambda v: 1 / (1 + np.exp(-v))
        check_output(op, ref, [x])

    @pytest.mark.parametrize("op", [paddle.exp, paddle.tanh, paddle.sigmoid, paddle.sqrt])
    def test_grad(self, op):
        x = np.abs(fdata(2, 3)) + 0.5
        check_grad(op, [x])

    def test_clip(self):
        x = fdata(4, 4) * 3
        check_output(paddle.clip, lambda v: np.clip(v, -1, 1), [x],
                     kwargs=dict(min=-1.0, max=1.0))
        check_grad(paddle.clip, [x], kwargs=dict(min=-1.0, max=1.0))

    def test_rsqrt(self):
        x = np.abs(fdata(3, 3)) + 0.1
        check_output(paddle.rsqrt, lambda v: 1 / np.sqrt(v), [x])


class TestMatmul:
    def test_2d(self):
        check_output(paddle.matmul, np.matmul, [fdata(3, 4), fdata(4, 5)])

    def test_batched(self):
        check_output(paddle.matmul, np.matmul, [fdata(2, 3, 4), fdata(2, 4, 5)])

    def test_transpose_flags(self):
        x, y = fdata(4, 3), fdata(4, 5)
        check_output(paddle.matmul, lambda a, b: a.T @ b, [x, y],
                     kwargs=dict(transpose_x=True))
        x2, y2 = fdata(3, 4), fdata(5, 4)
        check_output(paddle.matmul, lambda a, b: a @ b.T, [x2, y2],
                     kwargs=dict(transpose_y=True))

    def test_grad(self):
        check_grad(paddle.matmul, [fdata(2, 3), fdata(3, 2)])

    def test_vec(self):
        check_output(paddle.dot, lambda a, b: np.sum(a * b, -1), [fdata(5), fdata(5)])
        check_output(paddle.mv, np.matmul, [fdata(3, 4), fdata(4)])


class TestCumulative:
    def test_cumsum(self):
        x = fdata(3, 4)
        check_output(paddle.cumsum, lambda v: np.cumsum(v, axis=1), [x],
                     kwargs=dict(axis=1))
        check_output(paddle.cumsum, lambda v: np.cumsum(v), [x])
        check_grad(paddle.cumsum, [fdata(2, 3)], kwargs=dict(axis=0))

    def test_cumprod(self):
        x = np.abs(fdata(3, 4)) + 0.5
        check_output(paddle.cumprod, lambda v: np.cumprod(v, axis=1), [x],
                     kwargs=dict(dim=1))

    def test_logsumexp(self):
        from scipy.special import logsumexp as ref  # scipy is available via jax deps
        x = fdata(3, 4)
        check_output(paddle.logsumexp, lambda v: ref(v, axis=1), [x],
                     kwargs=dict(axis=1))

    def test_cummax(self):
        x = fdata(3, 5)
        v, i = paddle.cummax(paddle.to_tensor(x), axis=1)
        np.testing.assert_allclose(v.numpy(), np.maximum.accumulate(x, axis=1), rtol=1e-6)


class TestScale:
    def test_scale(self):
        x = fdata(3, 3)
        check_output(paddle.scale, lambda v: v * 2 + 1, [x],
                     kwargs=dict(scale=2.0, bias=1.0))
        check_output(paddle.scale, lambda v: (v + 1) * 2, [x],
                     kwargs=dict(scale=2.0, bias=1.0, bias_after_scale=False))


class TestBitwise:
    def test_bitwise(self):
        a = np.array([5, 6, 7], dtype=np.int32)
        b = np.array([3, 3, 3], dtype=np.int32)
        check_output(paddle.bitwise_and, np.bitwise_and, [a, b])
        check_output(paddle.bitwise_or, np.bitwise_or, [a, b])
        check_output(paddle.bitwise_xor, np.bitwise_xor, [a, b])


class TestNewOps:
    """renorm/nanquantile/vander/tensordot/histogramdd/igamma/as_strided
    (op-surface widening, SURVEY.md §2.4 tensor-methods row)."""

    def test_renorm(self):
        x = fdata(3, 4)
        out = paddle.renorm(paddle.to_tensor(x), 2.0, 0, 1.0).numpy()
        norms = np.linalg.norm(out.reshape(3, -1), axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        # rows already under the cap are untouched
        small = x / (np.abs(x).sum() + 10)
        out2 = paddle.renorm(paddle.to_tensor(small), 2.0, 0, 1.0).numpy()
        np.testing.assert_allclose(out2, small, rtol=1e-6)

    def test_renorm_grad(self):
        check_grad(lambda t: paddle.renorm(t, 2.0, 0, 1.0), [fdata(3, 4)])

    def test_nanquantile(self):
        x = fdata(4, 5)
        x[0, 0] = np.nan
        out = paddle.nanquantile(paddle.to_tensor(x), 0.5).numpy()
        np.testing.assert_allclose(out, np.nanquantile(x, 0.5), rtol=1e-6)
        out_ax = paddle.nanquantile(paddle.to_tensor(x), 0.25, axis=1).numpy()
        np.testing.assert_allclose(out_ax, np.nanquantile(x, 0.25, axis=1),
                                   rtol=1e-5)

    def test_vander(self):
        x = np.array([1.0, 2.0, 3.0], np.float32)
        check_output(lambda t: paddle.vander(t, 4), lambda a: np.vander(a, 4),
                     [x])
        check_output(lambda t: paddle.vander(t, 3, increasing=True),
                     lambda a: np.vander(a, 3, increasing=True), [x])

    def test_tensordot(self):
        a, b = fdata(3, 4, 5), fdata(4, 5, 6)
        out = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(b),
                               axes=2).numpy()
        np.testing.assert_allclose(out, np.tensordot(a, b, axes=2), rtol=1e-4)
        out2 = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(b),
                                axes=[[1, 2], [0, 1]]).numpy()
        np.testing.assert_allclose(
            out2, np.tensordot(a, b, axes=[[1, 2], [0, 1]]), rtol=1e-4)

    def test_histogramdd(self):
        pts = RNG.random((50, 2)).astype(np.float32)
        h, edges = paddle.histogramdd(paddle.to_tensor(pts), bins=5)
        ref_h, ref_edges = np.histogramdd(pts, bins=5)
        np.testing.assert_allclose(h.numpy(), ref_h)
        assert len(edges) == 2
        np.testing.assert_allclose(edges[0].numpy(), ref_edges[0], rtol=1e-5)

    def test_igamma_igammac(self):
        from scipy import special as sp  # scipy ships with the image? guard
        x = np.array([1.0, 2.0, 4.0], np.float32)
        out = paddle.igamma(paddle.to_tensor(x), 1.5).numpy()
        np.testing.assert_allclose(out, sp.gammaincc(x, 1.5), rtol=1e-5)
        outc = paddle.igammac(paddle.to_tensor(x), 1.5).numpy()
        np.testing.assert_allclose(outc, sp.gammainc(x, 1.5), rtol=1e-5)
        np.testing.assert_allclose(out + outc, np.ones_like(x), rtol=1e-6)

    def test_as_strided(self):
        x = np.arange(12, dtype=np.float32)
        out = paddle.as_strided(paddle.to_tensor(x), [3, 2], [4, 1], 1).numpy()
        ref = np.lib.stride_tricks.as_strided(
            x[1:], shape=(3, 2), strides=(16, 4))
        np.testing.assert_array_equal(out, ref)

    def test_fft_rfftn_irfftn(self):
        x = fdata(4, 8)
        out = paddle.fft.rfftn(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, np.fft.rfftn(x), rtol=1e-4, atol=1e-5)
        back = paddle.fft.irfftn(paddle.to_tensor(out), s=[4, 8]).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)

    def test_tensordot_flat_axes(self):
        # paddle flat-list form: contract the SAME dims of both operands
        a, b = fdata(3, 4, 5), fdata(3, 4, 6)
        out = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(b),
                               axes=[0, 1]).numpy()
        np.testing.assert_allclose(
            out, np.tensordot(a, b, axes=[[0, 1], [0, 1]]), rtol=1e-4)

    def test_histogramdd_flat_ranges(self):
        pts = RNG.random((40, 2)).astype(np.float32)
        h, edges = paddle.histogramdd(paddle.to_tensor(pts), bins=4,
                                      ranges=[0.0, 1.0, 0.0, 1.0])
        ref_h, _ = np.histogramdd(pts, bins=4, range=[(0, 1), (0, 1)])
        np.testing.assert_allclose(h.numpy(), ref_h)
