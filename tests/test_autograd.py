"""Tape autograd tests — parity with the reference's eager backward semantics
(SURVEY.md §2.2 eager autograd engine; §3.1 call stack)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(x, sg=False):
    return paddle.to_tensor(np.asarray(x, dtype=np.float32), stop_gradient=sg)


class TestBackward:
    def test_chain(self):
        x = t([2.0, 3.0])
        y = (x * x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 3 * np.array([4.0, 9.0]), rtol=1e-6)

    def test_fanout_accumulation(self):
        x = t([1.0, 2.0])
        a = x * 2
        b = x * 3
        (a + b).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0], rtol=1e-6)

    def test_grad_accumulates_across_backwards(self):
        x = t([1.0])
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0], rtol=1e-6)

    def test_stop_gradient_blocks(self):
        x = t([1.0, 2.0])
        y = t([3.0, 4.0], sg=True)
        (x * y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 4.0])
        assert y.grad is None

    def test_detach(self):
        x = t([2.0])
        d = (x * 2).detach()
        (d * x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0])  # d treated as constant

    def test_non_scalar_needs_grad_tensor(self):
        x = t([[1.0, 2.0]])
        with pytest.raises(RuntimeError):
            (x * 2).backward()
        (x * 2).backward(paddle.ones([1, 2]))
        np.testing.assert_allclose(x.grad.numpy(), [[2.0, 2.0]])

    def test_retain_graph(self):
        x = t([1.0])
        y = (x * 3).sum()
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_double_backward_without_retain_raises(self):
        x = t([1.0])
        y = (x * 3).sum()
        y.backward()
        with pytest.raises(RuntimeError):
            y.backward()

    def test_multi_output_op(self):
        x = t([[3.0, 1.0, 2.0]])
        v, i = paddle.topk(x, 2)
        v.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1.0, 0.0, 1.0]])

    def test_no_grad(self):
        x = t([1.0])
        with paddle.no_grad():
            y = x * 2
        assert y._grad_node is None and y.stop_gradient

    def test_hooks(self):
        x = t([1.0, 1.0])
        seen = {}

        def hook(g):
            seen["g"] = g.numpy().copy()
            return g * 2

        x.register_hook(hook)
        (x * 3).sum().backward()
        np.testing.assert_allclose(seen["g"], [3.0, 3.0])
        np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])

    def test_retain_grads_intermediate(self):
        x = t([2.0])
        y = x * 3
        y.retain_grads()
        (y * y).sum().backward()
        np.testing.assert_allclose(y.grad.numpy(), [12.0])
        np.testing.assert_allclose(x.grad.numpy(), [36.0])

    def test_setitem_grad_through(self):
        x = t([1.0, 2.0, 3.0])
        y = x * 2
        y[0] = 10.0
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [0.0, 2.0, 2.0])


class TestGradAPI:
    def test_paddle_grad(self):
        x = t([2.0])
        y = (x * x).sum()
        (g,) = paddle.grad(y, x)
        np.testing.assert_allclose(g.numpy(), [4.0])
        assert x.grad is None  # paddle.grad must not clobber .grad

    def test_grad_unused_raises(self):
        x = t([1.0])
        z = t([1.0])
        y = (x * 2).sum()
        with pytest.raises(RuntimeError):
            paddle.grad(y, z)
        y2 = (x * 2).sum()
        (g,) = paddle.grad(y2, [z], allow_unused=True)
        assert g is None


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Double(paddle.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, g):
                (x,) = ctx.saved_tensor()
                return g * 2

        x = t([3.0])
        y = Double.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(y.numpy(), [6.0])
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_pylayer_multiple_inputs(self):
        class MulAdd(paddle.PyLayer):
            @staticmethod
            def forward(ctx, x, y):
                ctx.save_for_backward(x, y)
                return x * y + x

            @staticmethod
            def backward(ctx, g):
                x, y = ctx.saved_tensor()
                return g * (y + 1), g * x

        x, y = t([2.0]), t([5.0])
        MulAdd.apply(x, y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])
        np.testing.assert_allclose(y.grad.numpy(), [2.0])


class TestInplace:
    def test_add_(self):
        x = t([1.0, 2.0], sg=True)
        x.add_(paddle.to_tensor([1.0, 1.0]))
        np.testing.assert_allclose(x.numpy(), [2.0, 3.0])

    def test_scale_(self):
        x = t([2.0], sg=True)
        x.scale_(scale=3.0)
        np.testing.assert_allclose(x.numpy(), [6.0])

    def test_zero_fill(self):
        x = t([1.0, 2.0], sg=True)
        x.zero_()
        np.testing.assert_allclose(x.numpy(), [0.0, 0.0])
        x.fill_(7.0)
        np.testing.assert_allclose(x.numpy(), [7.0, 7.0])


class TestDoubleGrad:
    """create_graph=True: the vjp is re-recorded through eager dispatch so
    grads carry a tape graph (reference: double-grad nodes from backward.yaml,
    paddle/fluid/eager — SURVEY.md §2.4 autograd row)."""

    def test_cubic_second_derivative(self):
        x = t([2.0, -1.5, 0.5])
        y = (x * x * x).sum()
        (g1,) = paddle.grad(y, [x], create_graph=True)
        assert g1.stop_gradient is False
        (g2,) = paddle.grad(g1.sum(), [x])
        np.testing.assert_allclose(
            g2.numpy(), 6 * np.array([2.0, -1.5, 0.5]), rtol=1e-6)

    def test_matches_jax_double_grad(self):
        import jax
        import jax.numpy as jnp

        xv = np.array([0.3, -0.7, 1.2], np.float32)

        def f(v):
            return jnp.tanh(v * v + jnp.sin(v)).sum()

        ref = jax.grad(lambda v: jax.grad(f)(v).sum())(jnp.asarray(xv))
        xt = t(xv)
        yt = (xt * xt + xt.sin()).tanh().sum()
        (g1,) = paddle.grad(yt, [xt], create_graph=True)
        (g2,) = paddle.grad(g1.sum(), [xt])
        np.testing.assert_allclose(g2.numpy(), np.asarray(ref), rtol=1e-5)

    def test_gradient_penalty_backward(self):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        wv = rng.standard_normal((4, 4), dtype=np.float32)
        xv = rng.standard_normal((2, 4), dtype=np.float32)
        w, x = t(wv), t(xv)
        out = (x @ w).tanh().sum()
        (gx,) = paddle.grad(out, [x], create_graph=True)
        (gx * gx).sum().backward()

        def penalty(wa, xa):
            g = jax.grad(lambda xx: jnp.tanh(xx @ wa).sum())(xa)
            return (g * g).sum()

        ref = jax.grad(penalty)(jnp.asarray(wv), jnp.asarray(xv))
        np.testing.assert_allclose(
            w.grad.numpy(), np.asarray(ref), rtol=2e-4, atol=1e-6)

    def test_third_order(self):
        x = t([1.5])
        y = (x ** 4).sum()
        (a,) = paddle.grad(y, [x], create_graph=True)
        (b,) = paddle.grad(a.sum(), [x], create_graph=True)
        (c,) = paddle.grad(b.sum(), [x])
        np.testing.assert_allclose(c.numpy(), [24 * 1.5], rtol=1e-6)

    def test_unused_input_raises_and_allow_unused(self):
        x, z = t([1.0]), t([2.0])
        y = (x * x).sum()
        with pytest.raises(RuntimeError):
            paddle.grad(y, [z], create_graph=True)
        g = paddle.grad(y, [z], create_graph=True, allow_unused=True)
        assert g[0] is None
