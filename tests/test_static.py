"""paddle.static Program/Executor/save-load tests (SURVEY.md §2.4 row
'paddle.static'; reference test style: build program, exe.run feed/fetch,
compare vs dygraph numerics)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


@pytest.fixture(autouse=True)
def reset_static():
    yield
    paddle.disable_static()


def test_enable_disable_static():
    assert paddle.in_dynamic_mode()
    paddle.enable_static()
    assert not paddle.in_dynamic_mode()
    paddle.disable_static()
    assert paddle.in_dynamic_mode()


def test_program_run_matches_eager():
    # build the layer eagerly so weights are real constants
    layer = paddle.nn.Sequential(
        paddle.nn.Linear(4, 8), paddle.nn.ReLU(), paddle.nn.Linear(8, 3))
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((6, 4)).astype(np.float32)
    eager_out = layer(paddle.to_tensor(xs)).numpy()

    paddle.enable_static()
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        y = layer(x)
    exe = static.Executor()
    out, = exe.run(main, feed={"x": xs}, fetch_list=[y])
    np.testing.assert_allclose(out, eager_out, rtol=1e-5, atol=1e-6)


def test_program_shape_polymorphic_refeed():
    paddle.enable_static()
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        y = (x * 2.0 + 1.0).sum(axis=1)
    exe = static.Executor()
    for batch in (2, 5):
        xs = np.ones((batch, 3), np.float32)
        out, = exe.run(main, feed={"x": xs}, fetch_list=[y])
        np.testing.assert_allclose(out, np.full((batch,), 9.0), rtol=1e-6)


def test_parameter_update_visible_between_runs():
    """Parameters are leaves read at run time — mutating them (opt.step /
    set_state_dict) must change the next exe.run without recapture."""
    layer = paddle.nn.Linear(2, 2)
    paddle.enable_static()
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        y = layer(x)
    exe = static.Executor()
    xs = np.eye(2, dtype=np.float32)
    out1, = exe.run(main, feed={"x": xs}, fetch_list=[y])
    import jax.numpy as jnp
    layer.weight._rebind(jnp.zeros_like(layer.weight._data))
    layer.bias._rebind(jnp.ones_like(layer.bias._data))
    out2, = exe.run(main, feed={"x": xs}, fetch_list=[y])
    np.testing.assert_allclose(out2, np.ones((2, 2), np.float32))
    assert not np.allclose(out1, out2)


def test_program_guard_isolation():
    paddle.enable_static()
    p1, p2 = static.Program(), static.Program()
    with static.program_guard(p1):
        a = static.data("a", [2], "float32")
        _ = a + 1.0
    with static.program_guard(p2):
        b = static.data("b", [2], "float32")
        _ = b * 3.0
    assert len(p1.records) == 1 and len(p2.records) == 1
    assert "a" in p1.feed_vars and "a" not in p2.feed_vars


def test_multiple_fetches_and_fetch_by_name():
    paddle.enable_static()
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4], "float32")
        s = x.sum()
        m = x.mean()
    exe = static.Executor()
    xs = np.arange(4, dtype=np.float32)
    outs = exe.run(main, feed={"x": xs}, fetch_list=[s, m])
    np.testing.assert_allclose(outs[0], 6.0)
    np.testing.assert_allclose(outs[1], 1.5)


def test_save_load_inference_model(tmp_path):
    layer = paddle.nn.Linear(4, 2)
    paddle.enable_static()
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        y = layer(x)
    exe = static.Executor()
    prefix = os.path.join(str(tmp_path), "model")
    static.save_inference_model(prefix, [x], [y], exe, program=main)
    assert os.path.exists(prefix + ".pdmodel")

    paddle.disable_static()
    prog, feed_names, fetch_names = static.load_inference_model(prefix, exe)
    for batch in (8, 3):  # dynamic batch survives export (symbolic dims)
        xs = np.random.default_rng(1).standard_normal(
            (batch, 4)).astype(np.float32)
        out, = exe.run(prog, feed={"x": xs}, fetch_list=None)
        expected = layer(paddle.to_tensor(xs)).numpy()
        np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


def test_input_spec():
    spec = static.InputSpec([None, 16], "float32", name="inp")
    assert spec.shape == [None, 16]
    t = paddle.to_tensor(np.zeros((2, 3), np.float32))
    s2 = static.InputSpec.from_tensor(t)
    assert s2.shape == [2, 3]
