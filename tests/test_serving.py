"""paddle_tpu.serving — the async request-serving engine over the
paged-KV continuous batcher.

Deterministic CPU coverage: concurrent requests through ServingEngine
match sequential `paged_generate` token-for-token (greedy), priority
ordering, queue-full backpressure, deadline timeout, mid-decode
cancellation returning KV blocks, per-request stop tokens, and the
step-level exception boundary (one request's callback raises → the
others complete and the engine stays alive).
"""
import threading

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, paged
from paddle_tpu import serving
from paddle_tpu.serving import AdmissionQueue, QueueFullError, \
    MetricsRegistry, RequestState


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


_RNG = np.random.RandomState(42)
PROMPT_A = list(map(int, _RNG.randint(1, 200, 5)))
PROMPT_B = list(map(int, _RNG.randint(1, 200, 7)))
PROMPT_A2 = list(map(int, _RNG.randint(1, 200, 5)))
PROMPT_B2 = list(map(int, _RNG.randint(1, 200, 7)))
MAX_NEW = 6


def _paged_single(params, cfg, prompt, max_new=MAX_NEW):
    """The sequential baseline: one request through paged_generate."""
    out, _, _ = paged.paged_generate(
        params, jnp.asarray([prompt], jnp.int32),
        np.asarray([len(prompt)]), cfg, max_new_tokens=max_new,
        block_size=4)
    return [int(t) for t in np.asarray(out[0])]


@pytest.fixture(scope="module")
def baselines(setup):
    cfg, params = setup
    return {name: _paged_single(params, cfg, p) for name, p in [
        ("A", PROMPT_A), ("B", PROMPT_B),
        ("A2", PROMPT_A2), ("B2", PROMPT_B2)]}


@pytest.fixture(scope="module")
def engine(setup):
    """Shared long-lived engine (stop-token / cancellation / fault tests
    assert deltas or per-request outcomes, never absolute counters)."""
    cfg, params = setup
    eng = serving.ServingEngine(
        params, cfg, max_batch=2, block_size=4, max_total_len=32,
        max_new_tokens=20, chunk=3, max_queue_depth=16)
    yield eng
    eng.shutdown()


class TestServingEngineE2E:
    def test_concurrent_mixed_priorities_match_sequential(
            self, setup, baselines):
        """Acceptance: N=6 submissions (4 served at mixed priorities +
        one cancellation + one deadline timeout) through one engine;
        served outputs are token-identical to sequential paged_generate,
        metrics are consistent, and the pool drains back to zero."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=MAX_NEW, chunk=3, max_queue_depth=16,
            start=False)
        r_lo = eng.submit(PROMPT_A, priority=2)
        r_hi = eng.submit(PROMPT_B, priority=0)
        r_mid = eng.submit(PROMPT_A2, priority=1)
        # greedy decode ⇒ a shorter budget is a strict prefix of the
        # longer run, so the per-request max_new needs no new baseline
        r_short = eng.submit(PROMPT_B2, priority=2, max_new_tokens=4)
        r_timeout = eng.submit(PROMPT_A, timeout_s=0.0)
        r_cancel = eng.submit(PROMPT_B)
        r_cancel.cancel()

        eng.start()
        eng.shutdown(drain=True, timeout=300)   # graceful drain

        assert r_lo.result() == baselines["A"]
        assert r_hi.result() == baselines["B"]
        assert r_mid.result() == baselines["A2"]
        assert r_short.result() == baselines["B2"][:4]
        assert r_timeout.state is RequestState.TIMED_OUT
        assert r_cancel.state is RequestState.CANCELLED
        with pytest.raises(serving.RequestTimedOut):
            r_timeout.result()
        with pytest.raises(serving.RequestCancelled):
            r_cancel.result()

        snap = eng.snapshot()
        c = snap["counters"]
        assert c["requests_submitted"] == 6
        assert c["requests_admitted"] == 4
        assert c["requests_completed"] == 4
        assert c["requests_cancelled"] == 1
        assert c["requests_timed_out"] == 1
        assert c["requests_rejected"] == 0
        assert (c["requests_completed"] + c["requests_cancelled"]
                + c["requests_timed_out"]) == c["requests_submitted"]
        assert c["tokens_generated"] == 3 * MAX_NEW + 4
        # latency surfaces populated
        assert snap["histograms"]["ttft_s"]["count"] == 4
        assert snap["histograms"]["queue_wait_s"]["count"] == 4
        # drained: queue empty, nothing in flight, ALL KV blocks back
        assert snap["gauges"]["queue_depth"] == 0
        assert snap["gauges"]["requests_in_flight"] == 0
        assert snap["gauges"]["kv_blocks_in_use"] == 0
        assert snap["gauges"]["kv_block_utilization"] == 0.0
        assert snap["allocator"]["blocks_in_use"] == 0
        # served requests release their batcher-side output lists (no
        # unbounded growth under a long-lived engine)
        assert eng.batcher.outputs == {}

    def test_priority_over_fifo(self, setup):
        """With one batch slot, a priority-0 late arrival is admitted
        before earlier priority-5 traffic; equal priorities stay FIFO."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=2, chunk=2, aging_interval_s=100.0,
            start=False)
        a = eng.submit(PROMPT_A, priority=5)
        b = eng.submit(PROMPT_B, priority=0)
        c = eng.submit(PROMPT_A2, priority=5)
        eng.start()
        eng.shutdown(drain=True, timeout=300)
        assert all(r.state is RequestState.FINISHED for r in (a, b, c))
        assert b.admitted_index < a.admitted_index < c.admitted_index

    def test_queue_full_rejection_and_validation(self, setup):
        """Backpressure: a full queue REJECTS with QueueFullError; a
        request that can never fit fails at submit. Neither runs the
        model (the engine is never started)."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=MAX_NEW, max_queue_depth=2, start=False)
        q1 = eng.submit(PROMPT_A)
        q2 = eng.submit(PROMPT_B)
        q3 = serving.GenerationRequest(PROMPT_A2)
        with pytest.raises(QueueFullError):
            eng.submit(q3)
        # a backpressure-rejected request stays pristine → retryable
        assert q3.submit_time is None and q3.max_new_tokens is None
        with pytest.raises(ValueError):    # prompt + max_new > max_total
            eng.submit(list(range(1, 41)))
        with pytest.raises(ValueError):    # budget over engine-wide max
            eng.submit(PROMPT_A, max_new_tokens=99)
        # a pre-built request must not silently drop submit() kwargs
        pre = serving.GenerationRequest(PROMPT_A, priority=5)
        with pytest.raises(ValueError, match="not both"):
            eng.submit(pre, timeout_s=5.0)
        assert eng.shutdown() is True      # never started: queued → CANCELLED
        assert q1.state is RequestState.CANCELLED
        assert q2.state is RequestState.CANCELLED
        with pytest.raises(ValueError, match="already submitted"):
            eng.submit(q1)                 # a used request can't resubmit
        with pytest.raises(serving.EngineStopped):
            eng.submit(PROMPT_A)
        c = eng.snapshot()["counters"]
        assert c["requests_submitted"] == 2
        assert c["requests_rejected"] == 3
        assert c["requests_cancelled"] == 2


class TestServingEngineShared:
    def test_stop_token_finishes_early(self, engine, baselines):
        """Per-request stop id (satellite: ContinuousBatcher per-slot
        stop support) truncates at the stop token and frees the slot."""
        stop = baselines["A"][1]
        cut = baselines["A"].index(stop)  # first occurrence wins
        out = engine.generate(PROMPT_A, max_new_tokens=MAX_NEW,
                              stop_token_id=stop, timeout=300)
        assert out == baselines["A"][:cut + 1]
        assert out[-1] == stop
        engine.drain(timeout=60)
        assert engine.snapshot()["gauges"]["kv_blocks_in_use"] == 0

    def test_cancel_mid_decode_frees_blocks(self, engine):
        req = engine.submit(PROMPT_B, max_new_tokens=20)
        it = req.stream()
        first = next(it)                  # guarantees DECODING started
        req.cancel()
        assert req.wait(timeout=300)
        assert req.state is RequestState.CANCELLED
        rest = list(it)                   # cancelled stream ends cleanly
        assert req.tokens == [first] + rest
        assert len(req.tokens) < 20
        with pytest.raises(serving.RequestCancelled):
            req.result()
        assert engine.drain(timeout=300)
        assert engine.snapshot()["allocator"]["blocks_in_use"] == 0

    def test_fault_injection_isolates_request(self, engine, baselines):
        """One request's on_token callback raises → only that request
        FAILS (its blocks freed); the co-batched request completes and
        the engine keeps serving."""
        failed_before = engine.metrics.counter("requests_failed").value
        seen = []

        def boom(tok):
            seen.append(tok)
            if len(seen) == 2:
                raise RuntimeError("injected fault")

        bad = engine.submit(PROMPT_A, max_new_tokens=MAX_NEW,
                            on_token=boom)
        good = engine.submit(PROMPT_B, max_new_tokens=MAX_NEW)
        assert good.result(timeout=300) == baselines["B"]
        assert bad.wait(timeout=300)
        assert bad.state is RequestState.FAILED
        assert isinstance(bad.error, RuntimeError)
        assert len(bad.tokens) == 2
        with pytest.raises(serving.RequestFailed):
            bad.result()
        m = engine.metrics.counter("requests_failed").value
        assert m == failed_before + 1
        # engine survived: serve another request end to end
        again = engine.generate(PROMPT_A, max_new_tokens=MAX_NEW,
                                timeout=300)
        assert again == baselines["A"]
        assert engine.drain(timeout=300)
        assert engine.snapshot()["allocator"]["blocks_in_use"] == 0


@pytest.mark.slow
class TestServingStress:
    def test_many_requests_saturate_and_drain(self, setup):
        """Scale pass (excluded from tier-1): 12 mixed-priority requests
        over 2 slots with interleaved cancellations; every invariant the
        dashboard relies on must hold after the drain."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=8, chunk=3, max_queue_depth=32,
            aging_interval_s=0.1, start=False)
        rng = np.random.RandomState(3)
        reqs = [eng.submit(list(rng.randint(1, 200, int(L))),
                           priority=int(rng.randint(0, 3)))
                for L in rng.randint(3, 12, 12)]
        reqs[4].cancel()
        reqs[9].cancel()
        eng.start()
        eng.shutdown(drain=True, timeout=600)
        states = [r.state for r in reqs]
        assert states.count(RequestState.CANCELLED) == 2
        assert states.count(RequestState.FINISHED) == 10
        assert all(len(r.tokens) == 8
                   for r in reqs if r.state is RequestState.FINISHED)
        snap = eng.snapshot()
        c = snap["counters"]
        assert c["requests_submitted"] == 12
        assert (c["requests_completed"] + c["requests_cancelled"]) == 12
        assert snap["allocator"]["blocks_in_use"] == 0
        assert snap["gauges"]["queue_depth"] == 0
        assert eng.batcher.outputs == {}


class TestServingPrefixCache:
    """serving.cache e2e: the engine's default prefix cache must be
    invisible in outputs (token-identical to a cold engine) and visible
    in metrics — including when one of two requests sharing blocks is
    cancelled mid-decode."""

    def _engine(self, setup, max_new=MAX_NEW, **kw):
        cfg, params = setup
        return serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=max_new, chunk=3, max_queue_depth=16, **kw)

    def test_warm_outputs_match_cold_engine(self, setup):
        rng = np.random.RandomState(21)
        common = list(map(int, rng.randint(1, 200, 8)))  # 2 full blocks
        prompts = [common + [11, 12, 13], common + [14, 15], list(common)]
        cold_eng = self._engine(setup, prefix_cache=False)
        cold = [cold_eng.generate(p, timeout=300) for p in prompts]
        cold_eng.shutdown()
        assert cold_eng.snapshot()["prefix_cache"] == {"enabled": False}

        warm_eng = self._engine(setup)                   # cache on by default
        warm = [warm_eng.generate(p, timeout=300) for p in prompts]
        # serve the shared-prefix set AGAIN: now every prompt hits
        warm += [warm_eng.generate(p, timeout=300) for p in prompts]
        snap = warm_eng.snapshot()
        warm_eng.shutdown()
        assert warm == cold + cold                       # token-identical
        pc = snap["prefix_cache"]
        assert pc["enabled"] and pc["hit_rate"] > 0
        assert pc["hit_tokens"] >= 3 * 8                 # second pass ≥ fully warm
        assert snap["gauges"]["prefix_cache_hit_rate"] == pc["hit_rate"]
        assert snap["gauges"]["prefix_cache_hit_tokens"] == pc["hit_tokens"]
        # drained: no block referenced, prefix blocks parked reclaimable
        assert snap["allocator"]["blocks_in_use"] == 0
        assert snap["allocator"]["cached_blocks"] > 0

    def test_cache_aware_admission_prefers_cached_prefix(self, setup):
        """At equal priority the engine admits the request whose prefix
        is cached BEFORE earlier-queued cold traffic (scheduler `prefer`
        tie-break), so reclaimable blocks turn into skipped prefill
        before eviction can recycle them."""
        rng = np.random.RandomState(23)
        common = list(map(int, rng.randint(1, 200, 8)))  # 2 full blocks
        cold_p = list(map(int, rng.randint(1, 200, 9)))
        eng = self._engine(setup, start=False, aging_interval_s=100.0)
        # prime the cache while the loop is parked (the batcher is ours
        # until start()) and hand the outputs back
        rid = eng.batcher.submit(common + [41, 42])
        eng.batcher.run()
        eng.batcher.release(rid)
        cold = eng.submit(cold_p)                 # queued FIRST
        warm = eng.submit(common + [43])          # cached prefix, later
        eng.start()
        eng.shutdown(drain=True, timeout=300)
        assert warm.state is RequestState.FINISHED
        assert cold.state is RequestState.FINISHED
        assert warm.admitted_index < cold.admitted_index
        snap = eng.snapshot()
        assert snap["prefix_cache"]["hit_tokens"] >= 8
        # bucketed-prefill gauges ride the same snapshot
        assert snap["gauges"]["prefill_compile_count"] >= 1
        assert snap["gauges"]["prefill_pad_tokens"] > 0

    def test_warmup_precompiles_and_refuses_after_start(self, setup):
        eng = self._engine(setup, start=False)
        warmed = eng.warmup()
        assert warmed == eng.batcher.compile_count > 0
        eng.start()
        with pytest.raises(RuntimeError, match="before start"):
            eng.warmup()
        out = eng.generate(PROMPT_A, timeout=300)
        assert eng.batcher.compile_count == warmed  # no retrace
        eng.shutdown()
        cfg, params = setup
        assert out == _paged_single(params, cfg, PROMPT_A)

    def test_cancel_mid_decode_releases_shared_blocks(self, setup):
        """Two in-flight requests share the common prefix's blocks
        (refcount 2). Cancelling one mid-decode must decref — not
        free — the shared blocks: the survivor keeps decoding on them
        and still produces its cold-engine output."""
        rng = np.random.RandomState(22)
        common = list(map(int, rng.randint(1, 200, 8)))
        p_cancel = common + [31, 32]
        p_keep = common + [33, 34, 35]
        cold_eng = self._engine(setup, prefix_cache=False)
        keep_cold = cold_eng.generate(p_keep, timeout=300)
        cold_eng.shutdown()

        # the victim gets a 20-token budget so the cancel lands while it
        # is still decoding; the keeper's budget matches the baseline
        eng = self._engine(setup, max_new=20, start=False)
        victim = eng.submit(p_cancel, max_new_tokens=20)
        keeper = eng.submit(p_keep, max_new_tokens=MAX_NEW)
        eng.start()                     # both admitted together: 2 slots
        it = victim.stream()
        next(it)                        # decode provably started
        victim.cancel()
        assert victim.wait(timeout=300)
        assert victim.state is RequestState.CANCELLED
        assert len(victim.tokens) < 20  # genuinely cut short
        assert keeper.result(timeout=300) == keep_cold   # not corrupted
        assert eng.drain(timeout=300)
        snap = eng.snapshot()
        eng.shutdown()
        assert snap["prefix_cache"]["hit_tokens"] >= 8   # blocks were shared
        assert snap["allocator"]["blocks_in_use"] == 0   # all refs dropped
        # the shared prefix survives the cancel for future requests
        assert snap["allocator"]["cached_blocks"] > 0


class TestFusedServing:
    """Fused prefill+decode through the full engine: admissions landing
    while another request decodes piggyback on the decode chunk (the
    fused_steps gauge proves it) and stay token-identical to the
    sequential baselines; the fusion-off engine is the escape hatch."""

    def _engine(self, setup, **kw):
        cfg, params = setup
        return serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=20, chunk=3, max_queue_depth=16, **kw)

    def _serve_overlapped(self, eng, baselines):
        long_req = eng.submit(PROMPT_A, max_new_tokens=20)
        it = long_req.stream()
        first = next(it)                  # decode provably started
        # lands mid-decode: with fusion on this admission piggybacks
        out_b = eng.generate(PROMPT_B, max_new_tokens=MAX_NEW,
                             timeout=300)
        assert out_b == baselines["B"]
        rest = list(it)
        # greedy ⇒ the 6-token baseline is a strict prefix of 20 tokens
        assert ([first] + rest)[:MAX_NEW] == baselines["A"]
        assert eng.drain(timeout=300)

    def test_fused_engine_parity_and_metrics(self, setup, baselines):
        eng = self._engine(setup)         # fused_prefill on by default
        self._serve_overlapped(eng, baselines)
        snap = eng.snapshot()
        eng.shutdown()
        assert snap["gauges"]["fused_steps"] >= 1
        assert snap["gauges"]["decode_stall_steps"] == 0
        # inter-token latency surfaced (multi-step requests ⇒ gaps)
        assert snap["histograms"]["itl_s"]["count"] >= 1
        assert "p95" in snap["histograms"]["itl_s"]
        assert snap["allocator"]["blocks_in_use"] == 0

    def test_fusion_off_escape_hatch(self, setup, baselines):
        eng = self._engine(setup, fused_prefill=False)
        self._serve_overlapped(eng, baselines)
        snap = eng.snapshot()
        eng.shutdown()
        assert snap["gauges"]["fused_steps"] == 0
        assert snap["gauges"]["decode_stall_steps"] >= 1
        assert snap["allocator"]["blocks_in_use"] == 0


class TestContinuousBatcherStop:
    def test_per_request_stop_token(self, setup, baselines):
        """Batcher-level satellite: a slot with stop_token_id finishes
        the moment it emits that id — not only on global eos/budget —
        and its blocks return to the pool while the OTHER slot keeps
        decoding to its full budget."""
        cfg, params = setup
        stop = baselines["A"][1]
        cut = baselines["A"].index(stop)  # first occurrence wins
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=MAX_NEW, chunk=3)
        r_stop = cb.submit(PROMPT_A, stop_token_id=stop)
        r_full = cb.submit(PROMPT_B)
        out = cb.run()
        assert out[r_stop] == baselines["A"][:cut + 1]
        assert out[r_full] == baselines["B"]
        assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_per_request_max_new(self, setup, baselines):
        cfg, params = setup
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=MAX_NEW, chunk=3)
        r = cb.submit(PROMPT_A, max_new_tokens=3)
        out = cb.run()
        assert out[r] == baselines["A"][:3]
        with pytest.raises(ValueError):
            cb.submit(PROMPT_A, max_new_tokens=MAX_NEW + 1)

    def test_validate_caps_at_configured_total(self, setup):
        """validate() enforces the CONFIGURED max_total_len, not the
        block-rounded table capacity."""
        cfg, params = setup
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=16, max_total_len=30,
            max_new_tokens=4, chunk=2)
        assert cb.validate(26, 4) == 4     # 30 fits exactly
        with pytest.raises(ValueError, match="max_total_len 30"):
            cb.validate(28, 4)             # 32 fits M*bs but not 30

    def test_failed_prefill_does_not_leak_blocks(self, setup,
                                                 monkeypatch):
        """A prefill that raises must return its just-allocated blocks
        to the pool (the engine's exception boundary relies on it)."""
        cfg, params = setup
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2)
        monkeypatch.setattr(
            paged, "forward_paged",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
        cb.submit(PROMPT_A)
        with pytest.raises(RuntimeError, match="boom"):
            cb.run()
        assert cb.alloc.stats()["blocks_in_use"] == 0


class TestGenerationRequestUnit:
    def test_stream_after_terminal_does_not_block(self):
        req = serving.GenerationRequest([1, 2, 3])
        req._deliver(10)
        req._deliver(11)
        req._finish(RequestState.FINISHED, "length")
        assert list(req.stream()) == [10, 11]
        assert list(req.stream()) == []    # second pass: no hang
        assert req.result(0) == [10, 11]

    def test_stream_raises_on_failure(self):
        req = serving.GenerationRequest([1])
        req._deliver(5)
        req._finish(RequestState.FAILED, "boom",
                    error=RuntimeError("boom"))
        it = req.stream()
        assert next(it) == 5
        with pytest.raises(serving.RequestFailed):
            next(it)


class TestAdmissionQueue:
    def test_priority_then_fifo(self):
        q = AdmissionQueue(max_depth=8, aging_interval_s=100.0)
        q.push("a5", priority=5)
        q.push("b0", priority=0)
        q.push("c0", priority=0)
        q.push("d5", priority=5)
        assert [q.pop() for _ in range(4)] == ["b0", "c0", "a5", "d5"]
        assert q.pop() is None

    def test_aging_prevents_starvation(self):
        t = [0.0]
        q = AdmissionQueue(max_depth=8, aging_interval_s=2.0,
                           clock=lambda: t[0])
        q.push("old9", priority=9)
        t[0] = 19.0                       # aged by 9 levels → effective 0
        q.push("new0", priority=0)
        assert q.pop() == "old9"          # FIFO wins the tie at level 0
        assert q.pop() == "new0"

    def test_backpressure_and_defer(self):
        q = AdmissionQueue(max_depth=2)
        q.push("x")
        q.push("y")
        with pytest.raises(QueueFullError):
            q.push("z")
        # defer-on-no-blocks: the BEST item gates the whole queue
        assert q.pop(fits=lambda i: False) is None
        assert len(q) == 2
        assert q.pop(fits=lambda i: True) == "x"

    def test_reap(self):
        q = AdmissionQueue(max_depth=8)
        for i in range(4):
            q.push(i)
        assert q.reap(lambda i: i % 2 == 0) == [0, 2]
        assert [q.pop(), q.pop()] == [1, 3]

    def test_pop_many_batch_defer_and_prefer(self):
        """One admission round under one lock: best-first order, the
        head-of-line item failing `fits` stops the round, `fits` runs
        once per ACCEPTED item (callers debit resources inside it), and
        `prefer` tie-breaks within the round."""
        q = AdmissionQueue(max_depth=8, aging_interval_s=100.0)
        q.push("a1", priority=1)
        q.push("b0", priority=0)
        q.push("c1", priority=1)
        assert q.pop_many(2) == ["b0", "a1"]
        assert q.pop_many(5) == ["c1"]
        assert q.pop_many(3) == []
        q.push("big", priority=0)
        q.push("small", priority=1)
        assert q.pop_many(2, fits=lambda i: i != "big") == []
        assert len(q) == 2                 # defer leaves the queue intact
        calls = []
        got = q.pop_many(2, fits=lambda i: calls.append(i) or True)
        assert got == ["big", "small"] and calls == got
        q.push("cold", priority=1)
        q.push("warm", priority=1)
        assert q.pop_many(2, prefer=lambda i: i == "warm") \
            == ["warm", "cold"]

    def test_prefer_breaks_ties_within_priority(self):
        """Cache-aware ordering: at EQUAL effective priority a preferred
        (cached-prefix) item pops before earlier FIFO traffic, but never
        jumps a strictly better priority level."""
        q = AdmissionQueue(max_depth=8, aging_interval_s=100.0)
        q.push("cold_a", priority=1)
        q.push("warm", priority=1)
        q.push("cold_b", priority=1)
        prefer = lambda item: item == "warm"
        assert q.pop(prefer=prefer) == "warm"          # tie-break wins
        assert q.pop(prefer=prefer) == "cold_a"        # then FIFO
        # a higher-priority cold item still beats a preferred one
        q.push("hot", priority=0)
        q.push("warm2", priority=1)
        assert q.pop(prefer=lambda i: i == "warm2") == "hot"
        # prefer composes with fits-deferral: the PREFERRED head gates
        assert q.pop(fits=lambda i: i != "warm2",
                     prefer=lambda i: i == "warm2") is None
        assert q.pop() == "cold_b"


class TestMetricsRegistry:
    def test_counters_gauges_histograms(self):
        m = MetricsRegistry()
        m.counter("c").inc()
        m.counter("c").inc(2)
        m.gauge("g").set(7.5)
        h = m.histogram("h")
        for v in range(1, 101):
            h.observe(float(v))
        snap = m.snapshot()
        assert snap["counters"]["c"] == 3
        assert snap["gauges"]["g"] == 7.5
        hs = snap["histograms"]["h"]
        assert hs["count"] == 100 and hs["min"] == 1.0 and hs["max"] == 100.0
        assert abs(hs["p50"] - 50.0) <= 2.0
        assert abs(hs["p99"] - 99.0) <= 2.0

    def test_percentile_since_skips_warmup_samples(self):
        # bench emitters rank only the timed window: `since` drops the
        # first N lifetime observations (e.g. a warmup request's
        # compile-tainted gaps)
        m = MetricsRegistry()
        h = m.histogram("h")
        h.observe(1000.0)          # warmup outlier
        for v in range(1, 11):
            h.observe(float(v))
        assert h.percentile(0.99) == 1000.0
        assert h.percentile(0.99, since=1) == 10.0
        assert h.percentile(0.50, since=1) == 5.0
        assert h.percentile(0.99, since=11) is None
        # wrapped ring: samples that already fell off are skipped
        hw = m.histogram("hw")
        hw._cap = 8
        for v in range(16):
            hw.observe(float(v))
        assert hw.percentile(1.0, since=4) == 15.0
        assert hw.percentile(0.0, since=4) == 8.0   # 4..7 fell off

    def test_timer_observes_and_is_thread_safe(self):
        m = MetricsRegistry()

        def work():
            for _ in range(50):
                m.counter("n").inc()
                with m.timer("t", record_event=False):
                    pass

        threads = [threading.Thread(target=work) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        snap = m.snapshot()
        assert snap["counters"]["n"] == 200
        assert snap["histograms"]["t"]["count"] == 200

    def test_timer_emits_profiler_span(self):
        # RecordEvent integration: reusable spans must not raise even
        # when no trace is active
        m = MetricsRegistry()
        for _ in range(3):
            with m.timer("serving.span_s"):
                pass
        assert m.snapshot()["histograms"]["serving.span_s"]["count"] == 3
