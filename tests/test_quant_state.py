"""8-bit blockwise Adam state (optimizer.quant_state) — the single-chip
flagship-bench optimizer (VERDICT r1 item 6)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

from paddle_tpu.optimizer.quant_state import (
    BLOCK, adamw_q, scale_by_adam_q, _quantize, _dequantize)
from paddle_tpu.nlp import llama, train


class TestQuantization:
    def test_roundtrip_precision(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(1000) * np.exp(rng.randn(1000)),
                        jnp.float32)
        q = _quantize(x, False)
        assert q.codes.dtype == jnp.float8_e4m3fn
        back = _dequantize(q, x.shape, False)
        rel = np.abs(np.asarray(back - x)) / (np.abs(np.asarray(x)) + 1e-30)
        assert float(np.median(rel)) < 0.05

    def test_sqrt_space_preserves_small_values(self):
        """A block mixing 1e-9 and 1.0 must keep the small entry nonzero
        after the v (sqrt-space) round trip: f8 codes in sqrt-space span
        ~1e10 of v dynamic range per block, where linear int8 codes
        flushed anything below max/500 to zero — and a zeroed v makes
        m/(sqrt(v)+eps) explode."""
        x = jnp.full((BLOCK,), 1e-9, jnp.float32).at[0].set(1.0)
        back = _dequantize(_quantize(x, True), x.shape, True)
        assert float(back[1]) > 1e-11

    def test_state_bytes_per_param(self):
        p = {"w": jnp.zeros((4096, 256), jnp.float32)}
        st = scale_by_adam_q().init(p)
        n = p["w"].size

        def nbytes(t):
            return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(t))

        per_param = (nbytes(st.m) + nbytes(st.v)) / n
        assert per_param < 2.1, per_param  # ~2 bytes vs f32 Adam's 8


class TestAdamQ:
    def test_update_matches_optax_adam(self, monkeypatch):
        """Per-step update direction within a few percent RMS of f32
        scale_by_adam, through the chunked (lax.map) path."""
        from paddle_tpu.optimizer import quant_state
        monkeypatch.setattr(quant_state, "CHUNK_BLOCKS", 1024)
        rng = np.random.RandomState(0)
        n = 1024 * BLOCK * 8 + 77  # > one chunk: exercises padding + lax.map
        p = {"w": jnp.asarray(rng.randn(n), jnp.float32)}
        tx, ref = scale_by_adam_q(), optax.scale_by_adam(0.9, 0.999, 1e-8)
        st, rst = tx.init(p), ref.init(p)
        for i in range(3):
            g = {"w": jnp.asarray(rng.randn(n) * 0.1, jnp.float32)}
            u, st = tx.update(g, st)
            ru, rst = ref.update(g, rst)
            rms = float(jnp.sqrt(jnp.mean((u["w"] - ru["w"]) ** 2))
                        / jnp.sqrt(jnp.mean(ru["w"] ** 2)))
            assert rms < 0.1, (i, rms)

    def test_llama_loss_trajectory_tracks_f32(self):
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)

        def run(state_quant):
            tx = train.make_optimizer(3e-3, state_quant=state_quant)
            state = train.init_state(jax.random.key(0), cfg, tx, mesh=None)
            step = train.make_train_step(cfg, tx, mesh=None)
            losses = []
            for _ in range(10):
                state, m = step(state, toks)
                losses.append(float(m["loss"]))
            return losses

        f32, q8 = run(None), run("8bit")
        assert q8[-1] < q8[0] * 0.8
        assert abs(q8[-1] - f32[-1]) / f32[-1] < 0.05, (q8[-1], f32[-1])

    def test_bf16_params_8bit_state_trains(self):
        """The exact headline-bench combination — bf16 params +
        state_quant='8bit' + grad_clip=0 — must train, not just the f32
        default (a bf16-specific numerics regression would otherwise only
        surface as a wrong 'loss' field in the TPU bench JSON)."""
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2,
                                     param_dtype=jnp.bfloat16)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        tx = train.make_optimizer(3e-3, state_quant="8bit", grad_clip=0.0)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=None)
        step = train.make_train_step(cfg, tx, mesh=None)
        losses = []
        for _ in range(10):
            state, m = step(state, toks)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.8, losses


class TestStreamedClip:
    """clip_norm fused into the chunked 8-bit update (VERDICT r2 weak 5):
    semantics match ClipGradByGlobalNorm without a second grad tree."""

    def test_clip_matches_prescaled_grads(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.optimizer.quant_state import adamw_q
        params = {"a": jnp.ones((1000,), jnp.float32),
                  "b": jnp.ones((300,), jnp.float32)}
        g = {"a": jnp.full((1000,), 3.0), "b": jnp.full((300,), -4.0)}
        gnorm = float(jnp.sqrt(sum(jnp.sum(x * x)
                                   for x in jax.tree.leaves(g))))
        clip = 1.0
        scale = min(1.0, clip / (gnorm + 1e-6))
        tx_c = adamw_q(1e-2, clip_norm=clip)
        tx_p = adamw_q(1e-2)
        u_c, _ = tx_c.update(g, tx_c.init(params), params)
        u_p, _ = tx_p.update(jax.tree.map(lambda x: x * scale, g),
                             tx_p.init(params), params)
        for k in g:
            np.testing.assert_allclose(np.asarray(u_c[k]),
                                       np.asarray(u_p[k]),
                                       rtol=1e-5, atol=1e-6)

    def test_no_clip_below_threshold(self):
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.optimizer.quant_state import adamw_q
        params = {"a": jnp.ones((256,), jnp.float32)}
        g = {"a": jnp.full((256,), 1e-4)}  # tiny norm, clip must be no-op
        tx_c = adamw_q(1e-2, clip_norm=1.0)
        tx_p = adamw_q(1e-2)
        u_c, _ = tx_c.update(g, tx_c.init(params), params)
        u_p, _ = tx_p.update(g, tx_p.init(params), params)
        np.testing.assert_allclose(np.asarray(u_c["a"]),
                                   np.asarray(u_p["a"]), rtol=1e-6)

    def test_make_optimizer_8bit_uses_streamed_clip(self):
        """make_optimizer(state_quant='8bit', grad_clip=1.0) must NOT chain
        optax.clip_by_global_norm (the second-tree version) — train step
        still runs and decreases loss with clip on."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.nlp import llama, train
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
        tx = train.make_optimizer(3e-3, state_quant="8bit", grad_clip=1.0)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh=None)
        step = train.make_train_step(cfg, tx, mesh=None)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32)), jnp.int32)
        state, m0 = step(state, toks)
        for _ in range(6):
            state, m = step(state, toks)
        assert float(m["loss"]) < float(m0["loss"])

    def test_fused_apply_matches_legacy_update(self):
        """apply_fused (one-pass Pallas kernel, interpret mode here) must
        produce the same new params and requantized moments as the legacy
        update()+apply_updates chain — same math, one HBM pass."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax
        from paddle_tpu.core import flags as F
        from paddle_tpu.optimizer.quant_state import (_dequantize,
                                                      adamw_q_fused)
        F.set_flags({"FLAGS_pallas_interpret": True})
        try:
            self._run_fused_parity(np, jax, jnp, optax, _dequantize,
                                   adamw_q_fused)
        finally:
            F.set_flags({"FLAGS_pallas_interpret": False})

    def _run_fused_parity(self, np, jax, jnp, optax, _dequantize,
                          adamw_q_fused):
        rng = np.random.RandomState(0)
        params = {
            "w": jnp.asarray(rng.normal(size=(8, 256)), jnp.bfloat16),
            "b": jnp.asarray(rng.normal(size=(300,)), jnp.float32),
        }
        grads = jax.tree.map(
            lambda p: jnp.asarray(rng.normal(size=p.shape) * 0.1, p.dtype),
            params)
        sched = optax.cosine_decay_schedule(1e-2, 100)
        tx = adamw_q_fused(sched, weight_decay=0.01, clip_norm=1.0)
        state = tx.init(params)
        # two steps so count/bias-correction handling is exercised
        for _ in range(2):
            upd, new_state_l = tx.update(grads, state, params)
            params_l = optax.apply_updates(params, upd)
            params_f, new_state_f = tx.apply_fused(grads, state, params)
            for k in params:
                np.testing.assert_allclose(
                    np.asarray(params_f[k], np.float32),
                    np.asarray(params_l[k], np.float32),
                    rtol=2e-2, atol=2e-5)
            for tree_l, tree_f, sq in ((new_state_l.m, new_state_f.m, False),
                                       (new_state_l.v, new_state_f.v, True)):
                for k in params:
                    np.testing.assert_allclose(
                        np.asarray(_dequantize(tree_f[k], params[k].shape,
                                               sq)),
                        np.asarray(_dequantize(tree_l[k], params[k].shape,
                                               sq)),
                        rtol=0.15, atol=1e-7)
            assert int(new_state_f.count) == int(new_state_l.count)
            params, state = params_f, new_state_f
