"""Optimizer + AMP tests (SURVEY.md §2.4 optimizer/AMP rows)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def make_problem():
    paddle.seed(0)
    X = np.random.default_rng(0).standard_normal((32, 8)).astype(np.float32)
    w = np.random.default_rng(1).standard_normal((8, 1)).astype(np.float32)
    Y = X @ w
    model = nn.Linear(8, 1)
    return model, paddle.to_tensor(X), paddle.to_tensor(Y)


def train(model, X, Y, opt, steps=40):
    losses = []
    for _ in range(steps):
        loss = ((model(X) - Y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


class TestOptimizers:
    @pytest.mark.parametrize("cls,kw", [
        (paddle.optimizer.SGD, dict(learning_rate=0.1)),
        (paddle.optimizer.Momentum, dict(learning_rate=0.05, momentum=0.9)),
        (paddle.optimizer.Adam, dict(learning_rate=0.05)),
        (paddle.optimizer.AdamW, dict(learning_rate=0.05)),
        (paddle.optimizer.RMSProp, dict(learning_rate=0.01)),
        (paddle.optimizer.Adagrad, dict(learning_rate=0.1)),
        (paddle.optimizer.Adamax, dict(learning_rate=0.05)),
        (paddle.optimizer.Lamb, dict(learning_rate=0.02)),
        (paddle.optimizer.Adadelta, dict(learning_rate=5.0)),
    ])
    def test_converges(self, cls, kw):
        model, X, Y = make_problem()
        opt = cls(parameters=model.parameters(), **kw)
        steps = 120 if cls is paddle.optimizer.Adadelta else 40
        losses = train(model, X, Y, opt, steps=steps)
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_sgd_exact_update(self):
        p = paddle.core.tensor.Parameter(np.array([1.0, 2.0], np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[p])
        p.grad = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
        opt.step()
        np.testing.assert_allclose(p.numpy(), [0.5, 1.5], rtol=1e-6)

    def test_adamw_decoupled_decay(self):
        # with zero grads, AdamW still shrinks weights; Adam does not
        p1 = paddle.core.tensor.Parameter(np.ones(4, np.float32))
        p2 = paddle.core.tensor.Parameter(np.ones(4, np.float32))
        aw = paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[p1])
        ad = paddle.optimizer.Adam(learning_rate=0.1, parameters=[p2])
        for p, o in [(p1, aw), (p2, ad)]:
            p.grad = paddle.zeros([4])
            o.step()
        assert p1.numpy()[0] < 1.0
        np.testing.assert_allclose(p2.numpy(), np.ones(4), rtol=1e-6)

    def test_grad_clip_global_norm(self):
        p = paddle.core.tensor.Parameter(np.zeros(2, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p],
                                   grad_clip=paddle.optimizer.ClipGradByGlobalNorm(1.0))
        p.grad = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
        opt.step()
        np.testing.assert_allclose(np.linalg.norm(p.numpy()), 1.0, rtol=1e-5)

    def test_state_dict_roundtrip(self):
        model, X, Y = make_problem()
        opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=model.parameters())
        train(model, X, Y, opt, steps=3)
        sd = opt.state_dict()
        opt2 = paddle.optimizer.Adam(learning_rate=0.05, parameters=model.parameters())
        opt2.set_state_dict(sd)
        k = model.weight.name + ".moment1"
        np.testing.assert_allclose(opt2._state[id(model.weight)]["moment1"],
                                   opt._state[id(model.weight)]["moment1"], rtol=1e-6)

    def test_lr_mult_per_param(self):
        p = paddle.core.tensor.Parameter(np.ones(2, np.float32))
        p.optimize_attr["learning_rate"] = 0.0  # frozen via lr multiplier
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p])
        p.grad = paddle.ones([2])
        opt.step()
        np.testing.assert_allclose(p.numpy(), np.ones(2), rtol=1e-6)


class TestLRSchedulers:
    def test_step_decay(self):
        sch = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(sch())
            sch.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)

    def test_warmup_then_cosine(self):
        cos = paddle.optimizer.lr.CosineAnnealingDecay(0.1, T_max=10)
        sch = paddle.optimizer.lr.LinearWarmup(cos, warmup_steps=5, start_lr=0.0,
                                               end_lr=0.1)
        first = sch()
        for _ in range(5):
            sch.step()
        assert first == 0.0
        assert abs(sch() - 0.1) < 1e-6

    def test_optimizer_uses_scheduler(self):
        model, X, Y = make_problem()
        sch = paddle.optimizer.lr.StepDecay(0.1, step_size=1, gamma=0.1)
        opt = paddle.optimizer.SGD(learning_rate=sch, parameters=model.parameters())
        assert opt.get_lr() == 0.1
        sch.step()
        assert abs(opt.get_lr() - 0.01) < 1e-9

    def test_noam(self):
        sch = paddle.optimizer.lr.NoamDecay(d_model=64, warmup_steps=10,
                                            learning_rate=1.0)
        for _ in range(9):
            sch.step()
        peak_region = sch()
        for _ in range(100):
            sch.step()
        assert sch() < peak_region

    def test_reduce_on_plateau(self):
        sch = paddle.optimizer.lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        for loss in [1.0, 1.0, 1.0, 1.0]:
            sch.step(loss)
        assert sch() < 0.1


class TestAMP:
    def test_autocast_matmul_bf16(self):
        with paddle.amp.auto_cast():
            out = paddle.matmul(paddle.randn([4, 4]), paddle.randn([4, 4]))
        assert out.dtype == paddle.bfloat16

    def test_autocast_blacklist_stays_fp32(self):
        x = paddle.randn([4, 4])
        with paddle.amp.auto_cast():
            out = paddle.ops.reduction.mean(x)
        assert out.dtype == paddle.float32

    def test_autocast_off_outside(self):
        out = paddle.matmul(paddle.randn([2, 2]), paddle.randn([2, 2]))
        assert out.dtype == paddle.float32

    def test_grad_scaler_skips_on_inf(self):
        p = paddle.core.tensor.Parameter(np.ones(2, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        p.grad = paddle.to_tensor(np.array([np.inf, 1.0], np.float32))
        scaler.step(opt)
        np.testing.assert_allclose(p.numpy(), np.ones(2))  # update skipped
        assert scaler._scale < 4.0  # scale reduced

    def test_grad_scaler_scales(self):
        p = paddle.core.tensor.Parameter(np.ones(2, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
        loss = (paddle.to_tensor(np.array([2.0, 2.0], np.float32)) * p).sum()
        scaler.scale(loss).backward()
        np.testing.assert_allclose(p.grad.numpy(), [16.0, 16.0], rtol=1e-6)
        scaler.step(opt)
        np.testing.assert_allclose(p.numpy(), 1 - 0.5 * 2 * np.ones(2), rtol=1e-6)

    def test_amp_decorate_o2(self):
        model = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, dtype="bfloat16")
        assert model.weight.dtype == paddle.bfloat16
        assert opt._multi_precision
        out = model(paddle.to_tensor(np.ones((2, 4), np.float32)).astype('bfloat16'))
        out.sum().backward()
        opt.step()
        # master weights kept in fp32
        assert opt._state[id(model.weight)]["master"].dtype == np.float32
