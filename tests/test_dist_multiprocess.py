"""TRUE multi-process distributed tests — 2 CPU processes over
jax.distributed on 127.0.0.1.

Reference analog: test/collective/'s TestDistBase pattern — a launcher
spawns real processes that rendezvous and run collectives, results
compared cross-rank (SURVEY.md §4; VERDICT r2 missing 6: every
`jax.process_count() > 1` branch in distributed/collective.py and the
launch CLI's multi-host path had never executed). The in-process
8-virtual-device tests cover the shard_map branches; THESE cover the
eager multihost_utils branches and the coordination-service bootstrap.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_environ():
    """Single CPU device per process; no axon plugin, no 8-device forcing
    (the conftest's XLA_FLAGS would otherwise leak into children)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    return env


class TestTwoProcessCollectives:
    def test_allreduce_allgather_broadcast_barrier(self, tmp_path):
        port = _free_port()
        coord = f"127.0.0.1:{port}"
        env = _child_environ()
        procs, paths = [], []
        for pid in range(2):
            res = str(tmp_path / f"result.{pid}.json")
            paths.append(res)
            procs.append(subprocess.Popen(
                [sys.executable, os.path.join(HERE, "dist2proc_child.py"),
                 coord, str(pid), res],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        outs = [p.communicate(timeout=180)[0] for p in procs]
        for p, o in zip(procs, outs):
            assert p.returncode == 0, o.decode("utf-8", "replace")[-2000:]
        results = [json.load(open(p)) for p in paths]
        for r in results:
            assert r["process_count"] == 2
            assert r["sum"] == [3.0, 30.0]
            assert r["avg"] == 0.5
            assert r["gather"] == [[0.0, -1.0], [1.0, -1.0]]
            assert r["bcast"] == 3.0
            assert r["barrier"] is True


class TestLaunchCliTwoProcess:
    def test_launch_end_to_end(self, tmp_path):
        """One `paddle_tpu.distributed.launch` controller per 'host'
        (rank 0/1), same master — the child trainers bootstrap from the
        env the CLI sets, heartbeat, and all_reduce across processes."""
        port = _free_port()
        master = f"127.0.0.1:{port}"
        res = str(tmp_path / "train_out")
        env = _child_environ()
        procs = []
        for rank in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--nnodes", "2", "--master", master, "--rank", str(rank),
                 "--log_dir", str(tmp_path / f"log{rank}"),
                 "--heartbeat_timeout", "120",
                 os.path.join(HERE, "dist2proc_train_child.py"), res],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        outs = [p.communicate(timeout=180)[0] for p in procs]
        for p, o in zip(procs, outs):
            assert p.returncode == 0, o.decode("utf-8", "replace")[-2000:]
        for rank in range(2):
            r = json.load(open(res + f".{rank}"))
            assert r["world"] == 2 and r["sum"] == 3.0
