"""paddle.text datasets + Flowers/VOC2012 (VERDICT r3 missing 4): each
loader parses a tiny SYNTHETIC archive in the upstream on-disk format —
the zero-egress counterpart of the reference's download-and-parse tests."""
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import text


def _tar_with(path, files):
    with tarfile.open(path, "w:gz") as tf:
        for name, data in files.items():
            b = data.encode() if isinstance(data, str) else data
            info = tarfile.TarInfo(name)
            info.size = len(b)
            tf.addfile(info, io.BytesIO(b))
    return path


class TestTextDatasets:
    def test_imdb(self, tmp_path):
        p = _tar_with(str(tmp_path / "imdb.tgz"), {
            "aclImdb/train/pos/0_9.txt": "a great great movie",
            "aclImdb/train/pos/1_8.txt": "great fun",
            "aclImdb/train/neg/0_2.txt": "a terrible movie",
        })
        ds = text.datasets.Imdb(data_file=p, mode="train", cutoff=1)
        assert len(ds) == 3
        doc, label = ds[0]
        assert doc.dtype == np.int64 and label in (0, 1)
        assert "great" in ds.word_idx

    def test_imikolov(self, tmp_path):
        p = _tar_with(str(tmp_path / "ptb.tgz"), {
            "simple-examples/data/ptb.train.txt":
                "the cat sat on the mat\nthe dog sat on the log\n",
        })
        ds = text.datasets.Imikolov(data_file=p, window_size=3,
                                    min_word_freq=1)
        assert len(ds) > 0 and ds[0].shape == (3,)
        seq = text.datasets.Imikolov(data_file=p, data_type="SEQ",
                                     min_word_freq=1)
        assert seq[0].ndim == 1

    def test_movielens(self, tmp_path):
        p = str(tmp_path / "ml.zip")
        with zipfile.ZipFile(p, "w") as zf:
            zf.writestr("ml-1m/users.dat",
                        "1::M::25::4::55455\n2::F::35::7::55117\n")
            zf.writestr("ml-1m/movies.dat",
                        "10::Toy Story (1995)::Animation|Comedy\n")
            zf.writestr("ml-1m/ratings.dat",
                        "1::10::5::978300760\n2::10::3::978302109\n")
        ds = text.datasets.Movielens(data_file=p, mode="train",
                                     test_ratio=0.0)
        assert len(ds) == 2
        u, m, r = ds[0]
        assert u.shape == (4,) and r.shape == (1,)
        # movie features: id + genre ids (Animation, Comedy)
        assert m.shape == (3,) and m[0] == 10

    def test_ucihousing(self, tmp_path):
        p = str(tmp_path / "housing.data")
        rng = np.random.RandomState(0)
        np.savetxt(p, rng.rand(20, 14))
        tr = text.datasets.UCIHousing(data_file=p, mode="train")
        te = text.datasets.UCIHousing(data_file=p, mode="test")
        assert len(tr) == 16 and len(te) == 4
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        assert float(np.abs(x).max()) <= 0.5 + 1e-6

    def test_wmt14(self, tmp_path):
        p = _tar_with(str(tmp_path / "wmt14.tgz"), {
            "wmt14/src.dict": "<s>\n<e>\n<unk>\nhello\nworld",
            "wmt14/trg.dict": "<s>\n<e>\n<unk>\nbonjour\nmonde",
            "wmt14/train/part-00.src": "hello world\nworld hello",
            "wmt14/train/part-00.trg": "bonjour monde\nmonde bonjour",
        })
        ds = text.datasets.WMT14(data_file=p, mode="train")
        assert len(ds) == 2
        s, t, lab = ds[0]
        assert s.tolist() == [3, 4]
        assert t[0] == 0 and lab[-1] == 1   # <s> prefix, <e> shifted target

    def test_wmt16_and_conll(self, tmp_path):
        p = _tar_with(str(tmp_path / "wmt16.tgz"), {
            "wmt16/src.dict": "<s>\n<e>\n<unk>\nein\nhaus",
            "wmt16/trg.dict": "<s>\n<e>\n<unk>\na\nhouse",
            "wmt16/train/bitext.src": "ein haus",
            "wmt16/train/bitext.trg": "a house",
        })
        ds = text.datasets.WMT16(data_file=p, mode="train")
        assert len(ds) == 1
        c = _tar_with(str(tmp_path / "conll.tgz"), {
            "conll05st/train/words.txt": "The\ncat\nsat\n\nA\ndog\n\n",
            "conll05st/train/props.txt":
                "- B-A0\n- I-A0\n sat B-V\n\n- B-A0\n- I-A0\n\n",
        })
        ds2 = text.datasets.Conll05st(data_file=c)
        assert len(ds2) == 2
        wid, pred, lid = ds2[0]
        assert wid.shape == lid.shape


class TestVisionDatasetAdditions:
    def _jpg_bytes(self, rng, size=(8, 8)):
        from PIL import Image
        buf = io.BytesIO()
        Image.fromarray(rng.randint(0, 255, size + (3,), dtype=np.uint8)
                        ).save(buf, format="JPEG")
        return buf.getvalue()

    def test_flowers(self, tmp_path):
        import scipy.io as sio
        rng = np.random.RandomState(0)
        tarp = str(tmp_path / "102flowers.tgz")
        _tar_with(tarp, {
            f"jpg/image_{i:05d}.jpg": self._jpg_bytes(rng)
            for i in range(1, 5)})
        lab = str(tmp_path / "imagelabels.mat")
        sio.savemat(lab, {"labels": np.array([[1, 2, 1, 2]])})
        sid = str(tmp_path / "setid.mat")
        sio.savemat(sid, {"trnid": np.array([[1, 3]]),
                          "valid": np.array([[2]]),
                          "tstid": np.array([[4]])})
        ds = paddle.vision.datasets.Flowers(
            data_file=tarp, label_file=lab, setid_file=sid, mode="train")
        assert len(ds) == 2
        img, label = ds[0]
        assert img.shape == (8, 8, 3) and int(label) == 1

    def test_voc2012(self, tmp_path):
        from PIL import Image
        rng = np.random.RandomState(1)
        mask = io.BytesIO()
        Image.fromarray(rng.randint(0, 20, (8, 8), dtype=np.uint8)
                        ).save(mask, format="PNG")
        p = _tar_with(str(tmp_path / "voc.tgz"), {
            "VOCdevkit/VOC2012/ImageSets/Segmentation/train.txt":
                "2007_000032\n",
            "VOCdevkit/VOC2012/JPEGImages/2007_000032.jpg":
                self._jpg_bytes(rng),
            "VOCdevkit/VOC2012/SegmentationClass/2007_000032.png":
                mask.getvalue(),
        })
        ds = paddle.vision.datasets.VOC2012(data_file=p, mode="train")
        assert len(ds) == 1
        img, label = ds[0]
        assert img.shape == (8, 8, 3) and label.dtype == np.uint8
