"""io + vision + hapi tests (SURVEY.md §2.4 DataLoader/vision rows; BASELINE
config 0 smoke)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import (DataLoader, TensorDataset, BatchSampler,
                           DistributedBatchSampler, Subset, ConcatDataset,
                           random_split, IterableDataset)
from paddle_tpu.vision import FakeData, models
from paddle_tpu.vision import transforms as T


class TestDatasets:
    def test_tensor_dataset(self):
        ds = TensorDataset([paddle.randn([10, 3]), paddle.arange(10)])
        assert len(ds) == 10
        x, y = ds[3]
        assert x.shape == [3] and int(y.numpy()) == 3

    def test_concat_subset_split(self):
        a = FakeData(size=6, image_shape=(2,), num_classes=2)
        b = FakeData(size=4, image_shape=(2,), num_classes=2)
        cat = ConcatDataset([a, b])
        assert len(cat) == 10
        sub = Subset(a, [0, 2])
        assert len(sub) == 2
        tr, va = random_split(a, [4, 2])
        assert len(tr) == 4 and len(va) == 2
        tr, va = random_split(a, [0.5, 0.5])
        assert len(tr) + len(va) == 6


class TestDataLoader:
    def test_basic_batching(self):
        ds = FakeData(size=10, image_shape=(3, 4, 4), num_classes=3)
        dl = DataLoader(ds, batch_size=4)
        batches = list(dl)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == [4, 3, 4, 4] and x.dtype == np.float32
        assert y.shape == [4] and y.dtype == np.int64
        assert batches[-1][0].shape[0] == 2  # remainder kept

    def test_drop_last_shuffle(self):
        ds = FakeData(size=10, image_shape=(2,), num_classes=2)
        dl = DataLoader(ds, batch_size=4, drop_last=True, shuffle=True)
        assert len(list(dl)) == 2

    def test_iterable_dataset(self):
        class Stream(IterableDataset):
            def __iter__(self):
                for i in range(7):
                    yield np.float32(i)

        dl = DataLoader(Stream(), batch_size=3)
        batches = list(dl)
        assert len(batches) == 3
        np.testing.assert_allclose(batches[0].numpy(), [0, 1, 2])

    def test_multiprocess_workers(self):
        ds = FakeData(size=12, image_shape=(2, 3), num_classes=2)
        dl = DataLoader(ds, batch_size=4, num_workers=2)
        ref = DataLoader(ds, batch_size=4, num_workers=0, use_buffer_reader=False)
        got = [b[0].numpy() for b in dl]
        want = [b[0].numpy() for b in ref]
        assert len(got) == len(want) == 3
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w)

    def test_worker_error_propagates(self):
        class Bad(FakeData):
            def __getitem__(self, idx):
                raise ValueError("boom")

        dl = DataLoader(Bad(size=4, image_shape=(2,)), batch_size=2, num_workers=1)
        with pytest.raises(ValueError):
            list(dl)

    def test_distributed_batch_sampler_shards(self):
        ds = FakeData(size=12, image_shape=(2,), num_classes=2)
        seen = []
        for rank in range(3):
            bs = DistributedBatchSampler(ds, batch_size=2, num_replicas=3,
                                         rank=rank)
            idx = [i for batch in bs for i in batch]
            assert len(idx) == 4
            seen.extend(idx)
        assert sorted(seen) == list(range(12))


class TestTransforms:
    def test_compose_pipeline(self):
        img = (np.random.default_rng(0).uniform(0, 255, (32, 40, 3))).astype(np.uint8)
        tf = T.Compose([T.Resize(36), T.CenterCrop(32), T.ToTensor(),
                        T.Normalize([0.5, 0.5, 0.5], [0.5, 0.5, 0.5])])
        out = tf(img)
        assert out.shape == (3, 32, 32)
        assert out.dtype == np.float32
        assert -1.01 <= out.min() and out.max() <= 1.01

    def test_flip_crop(self):
        img = np.arange(24, dtype=np.uint8).reshape(4, 6)
        assert T.RandomHorizontalFlip(1.0)(img)[0, 0] == img[0, -1]
        out = T.RandomCrop(2)(img)
        assert out.shape == (2, 2)


class TestVisionModels:
    @pytest.mark.slow
    def test_resnet18_forward_backward(self):
        net = models.resnet18(num_classes=4)
        out = net(paddle.randn([2, 3, 32, 32]))
        assert out.shape == [2, 4]
        out.sum().backward()
        assert net.conv1.weight.grad is not None

    def test_resnet50_structure(self):
        net = models.resnet50(num_classes=10)
        n = sum(p.size for p in net.parameters())
        assert 23e6 < n < 26e6
        names = dict(net.named_parameters())
        assert "layer1.0.conv1.weight" in names
        assert "fc.weight" in names

    def test_lenet(self):
        net = models.LeNet()
        assert net(paddle.randn([2, 1, 28, 28])).shape == [2, 10]

    @pytest.mark.slow
    def test_mobilenet_v2(self):
        net = models.mobilenet_v2(num_classes=5)
        assert net(paddle.randn([1, 3, 32, 32])).shape == [1, 5]

    @pytest.mark.slow
    def test_vgg11_tiny(self):
        net = models.vgg11(num_classes=3)
        assert net(paddle.randn([1, 3, 224, 224])).shape == [1, 3]


class TestHapiModel:
    def test_fit_evaluate_predict(self, tmp_path):
        paddle.seed(0)
        net = models.LeNet()
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(learning_rate=0.001,
                                            parameters=net.parameters()),
            loss=paddle.nn.CrossEntropyLoss(),
            metrics=paddle.metric.Accuracy())
        ds = FakeData(size=8, image_shape=(1, 28, 28), num_classes=10)
        model.fit(ds, batch_size=4, epochs=1, verbose=0)
        logs = model.evaluate(ds, batch_size=4, verbose=0)
        assert "eval_acc" in logs
        out = model.predict(ds, batch_size=4)
        assert len(out[0]) == 2
        p = str(tmp_path / "ck")
        model.save(p)
        model.load(p)

    def test_pure_save_load_roundtrip(self, tmp_path):
        net = models.LeNet()
        path = str(tmp_path / "m.pdparams")
        paddle.save(net.state_dict(), path)
        loaded = paddle.load(path)
        net2 = models.LeNet()
        net2.set_state_dict(loaded)
        x = paddle.randn([1, 1, 28, 28])
        np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-6)


class TestMetrics:
    def test_accuracy_topk(self):
        m = paddle.metric.Accuracy(topk=(1, 2))
        pred = paddle.to_tensor(np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]], np.float32))
        label = paddle.to_tensor(np.array([[1], [2]]))
        m.update(m.compute(pred, label))
        top1, top2 = m.accumulate()
        assert abs(top1 - 0.5) < 1e-6
        assert abs(top2 - 0.5) < 1e-6

    def test_functional_accuracy(self):
        pred = paddle.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
        label = paddle.to_tensor(np.array([[1], [1]]))
        acc = paddle.metric.accuracy(pred, label, k=1)
        assert abs(float(acc.numpy()) - 0.5) < 1e-6

    def test_precision_recall(self):
        m = paddle.metric.Precision()
        m.update(np.array([0.9, 0.9, 0.1]), np.array([1, 0, 1]))
        assert abs(m.accumulate() - 0.5) < 1e-6
        r = paddle.metric.Recall()
        r.update(np.array([0.9, 0.9, 0.1]), np.array([1, 0, 1]))
        assert abs(r.accumulate() - 0.5) < 1e-6


class TestJit:
    def test_to_static_function(self):
        calls = []

        @paddle.jit.to_static
        def f(x):
            calls.append(1)
            return x * 2 + 1

        a = f(paddle.to_tensor([1.0, 2.0]))
        b = f(paddle.to_tensor([3.0, 4.0]))
        np.testing.assert_allclose(b.numpy(), [7.0, 9.0])
        assert len(calls) == 1  # traced once, cached second call

    def test_to_static_layer_matches_eager(self):
        paddle.seed(1)
        net = models.LeNet()
        net.eval()
        x = paddle.randn([2, 1, 28, 28])
        eager_out = net(x).numpy()
        jnet = paddle.jit.to_static(net)
        np.testing.assert_allclose(jnet(x).numpy(), eager_out, rtol=1e-5, atol=1e-5)

    def test_translated_layer_updates_buffers(self):
        bn = paddle.nn.BatchNorm1D(4, data_format="NCL")
        jbn = paddle.jit.to_static(bn)
        before = bn._mean.numpy().copy()
        jbn(paddle.randn([8, 4, 5]) + 3.0)
        assert not np.allclose(bn._mean.numpy(), before)

    def test_functional_call_pure(self):
        from paddle_tpu.jit import functional_call, state_of
        lin = paddle.nn.Linear(3, 2)
        st = state_of(lin)
        x = paddle.randn([2, 3])
        out, _ = functional_call(lin, st, x)
        np.testing.assert_allclose(out.numpy(), lin(x).numpy(), rtol=1e-6)
