"""Round-2 functional completions: spectral_norm, margin_cross_entropy,
ctc_greedy_decoder, adaptive_log_softmax_with_loss (functional form),
triplet_margin_with_distance_loss (reference: the last missing
nn.functional entries vs the paddle 2.6 surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

RNG = np.random.RandomState(0)


class TestSpectralNorm:
    def test_functional_normalizes_sigma_to_one(self):
        w = paddle.to_tensor(RNG.randn(6, 4).astype(np.float32) * 3)
        wn = F.spectral_norm(w, power_iters=20)
        s = np.linalg.svd(wn.numpy(), compute_uv=False)
        assert abs(s[0] - 1.0) < 1e-2

    def test_layer_state_persists_and_converges(self):
        w = paddle.to_tensor(RNG.randn(6, 4).astype(np.float32) * 3)
        sn = nn.SpectralNorm((6, 4), power_iters=2)
        assert "weight_u" in sn.state_dict()  # reference's persistable U
        u0 = sn.weight_u.numpy().copy()
        sn(w)
        assert not np.allclose(sn.weight_u.numpy(), u0)  # buffer updated
        out = sn(w)
        s = np.linalg.svd(out.numpy(), compute_uv=False)
        assert abs(s[0] - 1.0) < 5e-2


class TestMarginCrossEntropy:
    def test_zero_margins_equal_plain_ce(self):
        logits = paddle.to_tensor((RNG.randn(4, 10) * 0.1)
                                  .astype(np.float32))
        lab = paddle.to_tensor(np.array([1, 3, 5, 7]))
        mce = F.margin_cross_entropy(logits, lab, margin1=1.0, margin2=0.0,
                                     margin3=0.0, scale=1.0)
        ce = F.cross_entropy(logits, lab)
        np.testing.assert_allclose(float(mce.numpy()), float(ce.numpy()),
                                   rtol=1e-5)

    def test_margin_raises_loss_and_softmax_returned(self):
        logits = paddle.to_tensor((RNG.rand(4, 10) * 0.5)
                                  .astype(np.float32))
        lab = paddle.to_tensor(np.array([0, 1, 2, 3]))
        plain = F.margin_cross_entropy(logits, lab, margin2=0.0, scale=1.0)
        arc, sm = F.margin_cross_entropy(logits, lab, margin2=0.5,
                                         scale=1.0, return_softmax=True)
        assert float(arc.numpy()) > float(plain.numpy())
        np.testing.assert_allclose(sm.numpy().sum(-1), 1.0, rtol=1e-5)


class TestCtcGreedyDecoder:
    def test_collapse_and_blank_removal(self):
        probs = np.zeros((2, 6, 4), np.float32)
        for t, c in enumerate([1, 1, 0, 2, 2, 3]):
            probs[0, t, c] = 1.0
        for t, c in enumerate([0, 0, 0, 0, 0, 0]):
            probs[1, t, c] = 1.0
        dec, lens = F.ctc_greedy_decoder(paddle.to_tensor(probs), blank=0)
        assert dec.numpy()[0, :3].tolist() == [1, 2, 3]
        assert lens.numpy().tolist() == [3, 0]
        assert (dec.numpy()[1] == -1).all()


class TestAdaptiveLogSoftmaxFunctional:
    def test_matches_layer(self):
        layer = nn.AdaptiveLogSoftmaxWithLoss(8, 12, [4, 8])
        x = paddle.to_tensor(RNG.randn(6, 8).astype(np.float32))
        lbl = paddle.to_tensor(np.array([0, 3, 5, 9, 11, 2]))
        out_l, loss_l = layer(x, lbl)
        tails = [[m[0].weight, m[1].weight] for m in layer.tail]
        out_f, loss_f = F.adaptive_log_softmax_with_loss(
            x, lbl, layer.head.weight, tails, [4, 8])
        np.testing.assert_allclose(out_l.numpy(), out_f.numpy(), rtol=1e-5)
        np.testing.assert_allclose(float(loss_l.numpy()),
                                   float(loss_f.numpy()), rtol=1e-5)


class TestTripletWithDistance:
    def test_custom_distance_and_swap(self):
        a, p, n_ = (paddle.to_tensor(RNG.randn(5, 8).astype(np.float32))
                    for _ in range(3))
        l2 = F.triplet_margin_with_distance_loss(a, p, n_)
        l1 = F.triplet_margin_with_distance_loss(
            a, p, n_, distance_function=lambda u, v: (u - v).abs().sum(-1))
        assert float(l1.numpy()) != float(l2.numpy())
        # swap substitutes the harder negative (min of d(a,n), d(p,n)),
        # shrinking dn and thus never DECREASING the hinge loss
        ls = F.triplet_margin_with_distance_loss(a, p, n_, swap=True)
        assert float(ls.numpy()) >= float(l2.numpy()) - 1e-6


class TestWeightNormUtils:
    def test_weight_norm_roundtrip_and_grads(self):
        lin = nn.Linear(4, 6)
        nn.utils.weight_norm(lin, "weight")
        named = dict(lin.named_parameters())
        assert "weight_g" in named and "weight_v" in named
        x = paddle.to_tensor(RNG.randn(2, 4).astype(np.float32))
        y1 = lin(x)
        (y1 ** 2).sum().backward()
        assert lin.weight_g.grad is not None
        assert lin.weight_v.grad is not None
        nn.utils.remove_weight_norm(lin, "weight")
        assert "weight" in dict(lin.named_parameters())
        np.testing.assert_allclose(y1.numpy(), lin(x).numpy(), rtol=1e-5)

    def test_spectral_norm_util_constrains_sigma(self):
        lin = nn.Linear(4, 6)
        nn.utils.spectral_norm(lin, "weight", n_power_iterations=3)
        x = paddle.to_tensor(RNG.randn(2, 4).astype(np.float32))
        for _ in range(4):
            lin(x)
        s = np.linalg.svd(lin.weight.numpy(), compute_uv=False)
        assert abs(s[0] - 1.0) < 0.05
        assert "weight_u" in lin.state_dict()  # persistent buffer
