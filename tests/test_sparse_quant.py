"""paddle.sparse + paddle.quantization tests (SURVEY.md §2.4 sparse /
quantization rows)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse, quantization as Q

RNG = np.random.default_rng(17)


def rand_coo(shape=(4, 6), density=0.3, seed=0):
    rng = np.random.default_rng(seed)
    dense = rng.standard_normal(shape).astype(np.float32)
    dense[rng.random(shape) > density] = 0.0
    nz = np.nonzero(dense)
    return sparse.sparse_coo_tensor(
        np.stack(nz), dense[nz], shape=shape), dense


class TestSparseCoo:
    def test_create_and_to_dense(self):
        s, dense = rand_coo()
        assert s.is_sparse_coo() and not s.is_sparse_csr()
        assert s.shape == [4, 6]
        np.testing.assert_allclose(s.to_dense().numpy(), dense)
        assert s.nnz == int((dense != 0).sum())
        assert s.indices().shape[0] == 2
        assert s.values().shape[0] == s.nnz

    def test_coo_csr_round_trip(self):
        s, dense = rand_coo(seed=1)
        csr = s.to_sparse_csr()
        assert csr.is_sparse_csr()
        np.testing.assert_allclose(csr.to_dense().numpy(), dense)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(back.to_dense().numpy(), dense)

    def test_csr_create(self):
        # [[1, 0, 2], [0, 3, 0]]
        csr = sparse.sparse_csr_tensor(
            [0, 2, 3], [0, 2, 1], [1.0, 2.0, 3.0], shape=[2, 3])
        np.testing.assert_allclose(
            csr.to_dense().numpy(), [[1, 0, 2], [0, 3, 0]])
        np.testing.assert_array_equal(csr.crows().numpy(), [0, 2, 3])

    def test_unary_ops(self):
        s, dense = rand_coo(seed=2)
        np.testing.assert_allclose(sparse.relu(s).to_dense().numpy(),
                                   np.maximum(dense, 0))
        np.testing.assert_allclose(sparse.abs(s).to_dense().numpy(),
                                   np.abs(dense))
        np.testing.assert_allclose(sparse.sin(s).to_dense().numpy(),
                                   np.sin(dense), rtol=1e-6)

    def test_add_subtract_sparse(self):
        a, da = rand_coo(seed=3)
        b, db = rand_coo(seed=4)
        np.testing.assert_allclose(sparse.add(a, b).to_dense().numpy(),
                                   da + db, rtol=1e-6)
        np.testing.assert_allclose(sparse.subtract(a, b).to_dense().numpy(),
                                   da - db, rtol=1e-6)

    def test_multiply_divide(self):
        a, da = rand_coo(seed=5)
        b, db = rand_coo(seed=6)
        np.testing.assert_allclose(sparse.multiply(a, b).to_dense().numpy(),
                                   da * db, rtol=1e-6)

    def test_matmul_sparse_dense(self):
        s, dense = rand_coo((4, 6), seed=7)
        y = RNG.standard_normal((6, 3)).astype(np.float32)
        out = sparse.matmul(s, paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5)

    def test_masked_matmul(self):
        mask, mdense = rand_coo((4, 4), seed=8)
        x = RNG.standard_normal((4, 5)).astype(np.float32)
        y = RNG.standard_normal((5, 4)).astype(np.float32)
        out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                                   mask)
        ref = (x @ y) * (mdense != 0)
        np.testing.assert_allclose(out.to_dense().numpy(), ref, rtol=1e-5)

    def test_transpose_sum(self):
        s, dense = rand_coo(seed=9)
        np.testing.assert_allclose(
            sparse.transpose(s, [1, 0]).to_dense().numpy(), dense.T)
        np.testing.assert_allclose(sparse.sum(s).numpy(), dense.sum(),
                                   rtol=1e-6)
        np.testing.assert_allclose(sparse.sum(s, axis=1).numpy(),
                                   dense.sum(1), rtol=1e-6)


class TestQuantization:
    def test_quant_dequant_values(self):
        x = paddle.to_tensor(np.array([0.0, 0.5, 1.0, -1.0], np.float32))
        out = Q.quant_dequant(x, 1.0, bit_length=8).numpy()
        np.testing.assert_allclose(out, [0.0, 0.5039, 1.0, -1.0], atol=1e-3)

    def test_observers(self):
        obs = Q.AbsmaxObserver()
        obs.observe(paddle.to_tensor(np.array([1.0, -3.0], np.float32)))
        obs.observe(paddle.to_tensor(np.array([2.0], np.float32)))
        assert obs.scales() == 3.0
        mm = Q.MinMaxObserver()
        mm.observe(paddle.to_tensor(np.array([-5.0, 2.0], np.float32)))
        assert mm.scales() == 5.0
        cw = Q.ChannelWiseAbsmaxObserver(channel_axis=-1)
        cw.observe(paddle.to_tensor(
            np.array([[1.0, -2.0], [3.0, 0.5]], np.float32)))
        np.testing.assert_allclose(cw.scales(), [3.0, 2.0])

    def _model(self):
        return paddle.nn.Sequential(
            paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
            paddle.nn.Linear(16, 4))

    def test_qat_swaps_and_trains(self):
        model = self._model()
        cfg = Q.QuantConfig(activation=Q.FakeQuanterWithAbsMax,
                            weight=Q.FakeQuanterWithAbsMax)
        qmodel = Q.QAT(cfg).quantize(model)
        assert isinstance(qmodel[0], Q.QuantedLinear)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=qmodel.parameters())
        xs = RNG.standard_normal((16, 8)).astype(np.float32)
        ys = RNG.integers(0, 4, 16)
        first = last = None
        for _ in range(15):
            loss = paddle.nn.CrossEntropyLoss()(
                qmodel(paddle.to_tensor(xs)), paddle.to_tensor(ys))
            loss.backward()
            opt.step()
            opt.clear_grad()
            v = float(loss.numpy())
            first = first or v
            last = v
        assert last < first  # STE gradients flow through fake-quant

    def test_ptq_calibrate_convert(self):
        model = self._model()
        cfg = Q.QuantConfig(activation=Q.AbsmaxObserver,
                            weight=lambda: Q.ChannelWiseAbsmaxObserver(
                                channel_axis=-1))
        ptq = Q.PTQ(cfg)
        qmodel = ptq.quantize(model)
        xs = paddle.to_tensor(RNG.standard_normal((8, 8)).astype(np.float32))
        qmodel.eval()
        qmodel(xs)  # calibration pass populates observers
        converted = ptq.convert(qmodel)
        out = converted(xs)
        ref = model(xs)
        # int8 QDQ ≈ fp32 within quantization error
        err = np.abs(out.numpy() - ref.numpy()).max()
        assert err < 0.25, err
        assert np.isfinite(out.numpy()).all()

    def test_quanted_conv2d(self):
        conv = paddle.nn.Conv2D(3, 8, 3, padding=1)
        cfg = Q.QuantConfig(activation=None,
                            weight=lambda: Q.ChannelWiseAbsmaxObserver(
                                channel_axis=0))
        q = Q.QAT(cfg).quantize(paddle.nn.Sequential(conv))
        x = paddle.to_tensor(
            RNG.standard_normal((1, 3, 8, 8)).astype(np.float32))
        out = q(x)
        ref = conv(x)
        assert out.shape == ref.shape
        assert np.abs(out.numpy() - ref.numpy()).max() < 0.2

    def test_divide_same_pattern_no_nan(self):
        a, da = rand_coo((3, 3), density=0.4, seed=10)
        out = sparse.divide(a, a)
        o = out.to_dense().numpy()
        assert np.isfinite(o).all()
        np.testing.assert_allclose(o, (da != 0).astype(np.float32))

    def test_quant_bits_respected(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 100, dtype=np.float32))
        q8 = Q.quant_dequant(x, 1.0, 8).numpy()
        q4 = Q.quant_dequant(x, 1.0, 4).numpy()
        assert len(np.unique(q4)) < len(np.unique(q8))
        obs = Q.ChannelWiseAbsmaxObserver(quant_bits=4, channel_axis=-1)
        lin = paddle.nn.Linear(4, 2)
        ql = Q.QuantedLinear(lin, None, obs)
        out = ql(paddle.to_tensor(np.eye(4, dtype=np.float32)))
        # 4-bit grid: at most 15 distinct levels per channel
        w = out.numpy()
        for c in range(2):
            assert len(np.unique(np.round(w[:, c], 6))) <= 15

    def test_fake_quanter_frozen_at_eval(self):
        fq = Q.FakeQuanterWithAbsMax()
        fq.train()
        fq(paddle.to_tensor(np.array([2.0], np.float32)))
        s = fq.scales()
        fq.eval()
        fq(paddle.to_tensor(np.array([100.0], np.float32)))
        assert fq.scales() == s  # eval must not mutate the scale

    def test_adaptive_softmax_2d_label(self):
        m = paddle.nn.AdaptiveLogSoftmaxWithLoss(8, 12, [4], div_value=2.0)
        x = paddle.to_tensor(RNG.standard_normal((5, 8)).astype(np.float32))
        lbl = paddle.to_tensor(RNG.integers(0, 12, (5, 1)))
        out, loss = m(x, lbl)
        assert out.shape == [5]
        np.testing.assert_allclose(-out.numpy().mean(), loss.numpy(),
                                   rtol=1e-5)
