"""Ring attention + Ulysses context parallelism vs exact reference.

Mirrors the reference's collective test pattern (SURVEY.md §4): multi-device
runs simulated with 8 host-platform fake devices; numerics checked against
the single-device exact attention.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.kernels.flash_attention import mha_ref
from paddle_tpu.kernels.ring_attention import sep_attention
from paddle_tpu.parallel.topology import build_mesh


def _qkv(b=2, s=32, h=4, kv=None, hd=8, seed=0):
    kv = kv or h
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, h, hd), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, kv, hd), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, kv, hd), jnp.float32)
    return q, k, v


@pytest.fixture
def sep_mesh():
    return build_mesh(dp=2, sep=4)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_exact(self, sep_mesh, causal):
        q, k, v = _qkv()
        ref = mha_ref(q, k, v, causal=causal)
        out = sep_attention(q, k, v, sep_mesh, impl="ring", causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa(self, sep_mesh):
        q, k, v = _qkv(h=8, kv=2)
        ref = mha_ref(q, k, v, causal=True)
        out = sep_attention(q, k, v, sep_mesh, impl="ring", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_grad_matches_exact(self, sep_mesh):
        q, k, v = _qkv(s=16)

        def loss_ring(q, k, v):
            return jnp.sum(sep_attention(q, k, v, sep_mesh, impl="ring",
                                         causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(mha_ref(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_inside_jit_with_sharded_inputs(self, sep_mesh):
        q, k, v = _qkv()
        sh = NamedSharding(sep_mesh, P(("dp",), "sep", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        f = jax.jit(lambda q, k, v: sep_attention(q, k, v, sep_mesh,
                                                  impl="ring", causal=True))
        out = f(qs, ks, vs)
        ref = mha_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_exact(self, sep_mesh, causal):
        q, k, v = _qkv()
        ref = mha_ref(q, k, v, causal=causal)
        out = sep_attention(q, k, v, sep_mesh, impl="ulysses", causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_fewer_kv_than_sep(self, sep_mesh):
        # kv=2 < sep=4 → expanded before the head swap
        q, k, v = _qkv(h=8, kv=2)
        ref = mha_ref(q, k, v, causal=True)
        out = sep_attention(q, k, v, sep_mesh, impl="ulysses", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grad(self, sep_mesh):
        q, k, v = _qkv(s=16)
        g = jax.grad(lambda q: jnp.sum(
            sep_attention(q, k, v, sep_mesh, impl="ulysses", causal=True)))(q)
        g_ref = jax.grad(lambda q: jnp.sum(
            mha_ref(q, k, v, causal=True)))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-4)


class TestSepFallback:
    def test_sep1_uses_flash(self):
        mesh = build_mesh(dp=8)
        q, k, v = _qkv()
        out = sep_attention(q, k, v, mesh, impl="ring", causal=True)
        ref = mha_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestLlamaSepIntegration:
    def test_llama_forward_ring_matches_flash(self):
        from paddle_tpu.nlp import llama
        mesh = build_mesh(dp=2, sep=4)
        cfg = llama.LlamaConfig.tiny(attn_impl="ring", use_flash=False,
                                     remat=False)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.asarray(
            np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 32)),
            jnp.int32)
        logits_ring = llama.forward(params, tokens, cfg, mesh)
        cfg_ref = llama.LlamaConfig.tiny(attn_impl="flash", use_flash=False,
                                         remat=False)
        logits_ref = llama.forward(params, tokens, cfg_ref, mesh=None)
        np.testing.assert_allclose(np.asarray(logits_ring),
                                   np.asarray(logits_ref),
                                   rtol=5e-4, atol=5e-4)


class TestFlashBackwardPallas:
    """Blocked flash backward kernels vs exact-attention vjp (interpret
    mode on CPU; the TPU bench exercises the compiled path)."""

    def _case(self, causal, b=2, s=256, h=4, d=32, seed=0):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        g = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

        out, lse = fa.flash_attention_pallas(
            q, k, v, causal=causal, interpret=True, return_lse=True,
            block_q=128, block_k=128)
        ref_out, vjp = jax.vjp(
            lambda a, b_, c: fa.mha_ref(a, b_, c, causal=causal), q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=2e-4, atol=2e-4)
        dq, dk, dv = fa.flash_attention_pallas_bwd(
            q, k, v, out, lse, g, causal=causal, interpret=True,
            block_q=128, block_k=128)
        rdq, rdk, rdv = vjp(g)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq),
                                   rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk),
                                   rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv),
                                   rtol=2e-3, atol=2e-3)

    def test_bwd_full(self):
        self._case(causal=False)

    def test_bwd_causal(self):
        self._case(causal=True)

    def test_bwd_rectangular_blocks(self):
        # unequal block_q/block_k exercises the causal start/stop arithmetic
        import numpy as np
        import jax
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.default_rng(3)
        b, s, h, d = 1, 512, 2, 32
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        g = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        out, lse = fa.flash_attention_pallas(
            q, k, v, causal=True, interpret=True, return_lse=True,
            block_q=64, block_k=128)
        ref_out, vjp = jax.vjp(
            lambda a, b_, c: fa.mha_ref(a, b_, c, causal=True), q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=2e-4, atol=2e-4)
        dq, dk, dv = fa.flash_attention_pallas_bwd(
            q, k, v, out, lse, g, causal=True, interpret=True,
            block_q=128, block_k=64)
        rdq, rdk, rdv = vjp(g)
        for a, r in ((dq, rdq), (dk, rdk), (dv, rdv)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=2e-3, atol=2e-3)


class TestRingFlashBlocks:
    """Ring attention's Pallas inner-block path (VERDICT r1 item 5 / weak
    item 2): flash_block with runtime diagonal offsets inside the ring fold,
    asserted ACTIVE via the trace counter, vs the exact reference."""

    def _qkv(self, s=512, hkv=2, seed=0):
        import numpy as np
        import jax.numpy as jnp
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(2, s, 4, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, s, hkv, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, s, hkv, 8), jnp.float32)
        return q, k, v

    @pytest.fixture(autouse=True)
    def _interp(self):
        from paddle_tpu.core import flags as F
        F.set_flags({"FLAGS_pallas_interpret": True})
        yield
        F.set_flags({"FLAGS_pallas_interpret": False})

    @pytest.mark.parametrize("causal", [True, False])
    def test_ring_flash_matches_exact(self, causal):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.parallel.topology import build_mesh
        from paddle_tpu.kernels import ring_attention as ra
        from paddle_tpu.kernels.flash_attention import mha_ref
        mesh = build_mesh(sep=4, dp=2)
        q, k, v = self._qkv()
        ref = mha_ref(q, k, v, causal=causal)
        n0 = ra.FLASH_RING_TRACES
        out = jax.jit(lambda q, k, v: ra.sep_attention(
            q, k, v, mesh, impl="ring", causal=causal))(q, k, v)
        assert ra.FLASH_RING_TRACES > n0, "Pallas ring path not selected"
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-4, atol=5e-4)

    @pytest.mark.slow
    def test_ring_flash_grads(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.parallel.topology import build_mesh
        from paddle_tpu.kernels import ring_attention as ra
        from paddle_tpu.kernels.flash_attention import mha_ref
        mesh = build_mesh(sep=4, dp=2)
        q, k, v = self._qkv()

        def loss_ring(q, k, v):
            return jnp.sum(ra.sep_attention(
                q, k, v, mesh, impl="ring", causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(
                mha_ref(q, k, v, causal=True).astype(jnp.float32) ** 2)

        gf = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, r in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=5e-3, atol=5e-3)

    def test_sq_gt_sk_causal_falls_back_exact(self):
        """The sq > sk causal case stays on the exact path (kernel zeros vs
        softmax-uniform fully-masked rows — the two would diverge)."""
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.RandomState(2)
        q2 = jnp.asarray(rng.randn(1, 256, 2, 8), jnp.float32)
        k2, v2 = (jnp.asarray(rng.randn(1, 128, 2, 8), jnp.float32)
                  for _ in range(2))
        assert not fa._pallas_ok(q2, k2, causal=True)
        out2 = fa.flash_attention_fwd(q2, k2, v2, True, None)
        np.testing.assert_allclose(
            np.asarray(out2),
            np.asarray(fa.mha_ref(q2, k2, v2, causal=True)),
            rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("s", [200, 333, 384])
    def test_padded_kernel_arbitrary_lengths(self, s):
        """VERDICT r2 missing 8: misaligned seq lengths (384 = the classic
        grid floor-drop case; 200/333 = not even lane-aligned) go through
        the PAD-to-block kernel path, not the O(S^2) fallback, and match
        the exact reference."""
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.RandomState(s)
        q = jnp.asarray(rng.randn(1, s, 2, 8), jnp.float32)
        k, v = (jnp.asarray(rng.randn(1, s, 2, 8), jnp.float32)
                for _ in range(2))
        assert fa._pallas_ok(q, k, causal=True)
        out = fa.flash_attention_padded(q, k, v, causal=True,
                                        interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(fa.mha_ref(q, k, v, causal=True)),
            rtol=2e-4, atol=2e-4)

    def test_padded_kernel_grads_match_exact(self):
        """Backward through the padded path: padded rows carry zero dO, so
        dq/dk/dv match the exact-attention vjp at an odd length."""
        import jax
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        s = 200
        rng = np.random.RandomState(7)
        q = jnp.asarray(rng.randn(1, s, 2, 8), jnp.float32)
        k = jnp.asarray(rng.randn(1, s, 1, 8), jnp.float32)  # GQA too
        v = jnp.asarray(rng.randn(1, s, 1, 8), jnp.float32)

        def loss_flash(q, k, v):
            return jnp.sum(fa.flash_attention_fwd(q, k, v, True, None)
                           .astype(jnp.float32) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(fa.mha_ref(q, k, v, causal=True)
                           .astype(jnp.float32) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, r in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=5e-4, atol=5e-4)

    def test_padded_rectangular_prefill(self):
        """Odd-length chunked prefill against a longer odd-length cache:
        the unpadded offset sk-sq keeps padded keys invisible."""
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(1, 100, 2, 8), jnp.float32)
        k = jnp.asarray(rng.randn(1, 390, 2, 8), jnp.float32)
        v = jnp.asarray(rng.randn(1, 390, 2, 8), jnp.float32)
        out = fa.flash_attention_padded(q, k, v, causal=True,
                                        interpret=True)
        ref = fa.mha_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_rectangular_causal_offset(self):
        """Default offset sk-sq == mha_ref's bottom-right diagonal (chunked
        prefill against a longer KV cache)."""
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(2, 128, 2, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 256, 2, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 256, 2, 8), jnp.float32)
        out = fa.flash_attention_pallas(q, k, v, causal=True,
                                        interpret=True)
        ref = fa.mha_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestStreamedBwdKernels:
    """The 3D-grid (streamed) flash backward — the seq>4096 path that keeps
    nothing full-sequence in VMEM (the resident kernels hit Mosaic's 16MB
    scoped-vmem stack at the 8B 8k shape). Forced on via the explicit
    streamed=True static arg so interpret mode covers it at small seq."""

    def test_streamed_matches_exact_vjp(self):
        import jax
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.RandomState(11)
        q = jnp.asarray(rng.randn(1, 512, 2, 8), jnp.float32)
        k = jnp.asarray(rng.randn(1, 512, 2, 8), jnp.float32)
        v = jnp.asarray(rng.randn(1, 512, 2, 8), jnp.float32)
        for causal in (True, False):
            out, lse = fa.flash_attention_pallas(
                q, k, v, causal=causal, interpret=True, return_lse=True)
            g = jnp.ones_like(out)
            dq, dk, dv = fa.flash_attention_pallas_bwd(
                q, k, v, out, lse, g, causal=causal, interpret=True,
                streamed=True)
            _, vjp = jax.vjp(lambda a, b, c: fa.mha_ref(
                a, b, c, causal=causal), q, k, v)
            rq, rk, rv = vjp(g)
            for got, ref in ((dq, rq), (dk, rk), (dv, rv)):
                np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                           rtol=2e-4, atol=2e-4)

    def test_streamed_rectangular_offset(self):
        import jax
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.RandomState(12)
        q = jnp.asarray(rng.randn(1, 128, 2, 8), jnp.float32)
        k = jnp.asarray(rng.randn(1, 384, 2, 8), jnp.float32)
        v = jnp.asarray(rng.randn(1, 384, 2, 8), jnp.float32)
        out, lse = fa.flash_attention_pallas(
            q, k, v, causal=True, interpret=True, return_lse=True)
        g = jnp.ones_like(out)
        dq, dk, dv = fa.flash_attention_pallas_bwd(
            q, k, v, out, lse, g, causal=True, interpret=True,
            streamed=True)
        _, vjp = jax.vjp(lambda a, b, c: fa.mha_ref(
            a, b, c, causal=True), q, k, v)
        for got, ref in zip((dq, dk, dv), vjp(g)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-4, atol=2e-4)


class TestMaskedFlash:
    """Key-padding-mask flash path (VERDICT r4 next-1: the bidirectional
    encoder needs flash with padding masks). Interpret mode on CPU; parity
    vs mha_ref with the same mask across ALL backward formulations —
    resident, combined streamed, and the split kernels (the split-forcing
    also covers ADVICE r4 item 5: the sq==sk split fallback had no direct
    coverage)."""

    def _qkvg(self, b=2, s=256, h=2, d=32, seed=0):
        import numpy as np
        import jax.numpy as jnp
        rng = np.random.default_rng(seed)
        mk = lambda: jnp.asarray(rng.standard_normal((b, s, h, d)),
                                 jnp.float32)
        q, k, v, g = mk(), mk(), mk(), mk()
        # per-row prefix padding lengths (>=1 valid key), plus one row with
        # a NON-prefix mask — the kernel takes arbitrary key visibility
        lengths = rng.integers(1, s + 1, b)
        mask = np.arange(s)[None, :] < lengths[:, None]
        mask[0, : s // 4] = False
        mask[0, 0] = True   # keep >= 1 visible key
        return q, k, v, g, jnp.asarray(mask)

    def test_fwd_matches_ref(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        q, k, v, g, mask = self._qkvg()
        out = fa.flash_attention_pallas(q, k, v, key_mask=mask,
                                        interpret=True, block_q=128,
                                        block_k=128)
        ref = fa.mha_ref(q, k, v, mask=mask[:, None, None, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_bwd_all_paths_match_ref(self, monkeypatch):
        import jax
        import numpy as np
        from paddle_tpu.kernels import flash_attention as fa
        q, k, v, g, mask = self._qkvg(seed=1)
        out, lse = fa.flash_attention_pallas(
            q, k, v, key_mask=mask, interpret=True, return_lse=True,
            block_q=128, block_k=128)
        _, vjp = jax.vjp(lambda a, b_, c: fa.mha_ref(
            a, b_, c, mask=mask[:, None, None, :]), q, k, v)
        refs = vjp(g)

        def check(streamed, split=False):
            if split:  # force the split dq/dkv kernels at sq == sk
                monkeypatch.setattr(fa, "_COMBINED_STREAMED_DQ_BYTES", 0)
            grads = fa.flash_attention_pallas_bwd(
                q, k, v, out, lse, g, key_mask=mask, interpret=True,
                streamed=streamed, block_q=128, block_k=128)
            monkeypatch.undo()
            for got, ref in zip(grads, refs):
                np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                           rtol=2e-3, atol=2e-3)

        check(streamed=False)              # resident combined
        check(streamed=True)               # combined streamed
        check(streamed=True, split=True)   # split dq + dkv

    def test_split_path_causal_unmasked_sq_eq_sk(self, monkeypatch):
        # ADVICE r4 item 5: the sq==sk SPLIT streamed path (production's
        # fallback at extreme seq) verified directly, causal, no mask
        import jax
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.default_rng(7)
        mk = lambda: jnp.asarray(rng.standard_normal((1, 256, 2, 16)),
                                 jnp.float32)
        q, k, v, g = mk(), mk(), mk(), mk()
        out, lse = fa.flash_attention_pallas(
            q, k, v, causal=True, interpret=True, return_lse=True)
        monkeypatch.setattr(fa, "_COMBINED_STREAMED_DQ_BYTES", 0)
        dq, dk, dv = fa.flash_attention_pallas_bwd(
            q, k, v, out, lse, g, causal=True, interpret=True,
            streamed=True)
        _, vjp = jax.vjp(lambda a, b_, c: fa.mha_ref(
            a, b_, c, causal=True), q, k, v)
        for got, ref in zip((dq, dk, dv), vjp(g)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-3, atol=2e-3)

    def test_masked_entry_grads_and_gqa(self):
        # flash_attention_masked end-to-end: custom_vjp grads vs mha_ref
        # autodiff, GQA head reduction, unaligned seq (pad-with-masked-keys)
        import jax
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.core import flags
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.default_rng(3)
        b, s, h, hkv, d = 2, 200, 4, 2, 16   # s=200: unaligned
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
        lengths = np.array([s, s // 2])
        mask = jnp.asarray(np.arange(s)[None, :] < lengths[:, None])

        def loss_flash(q_, k_, v_):
            return jnp.sum(fa.flash_attention_masked(q_, k_, v_, mask, None)
                           ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(fa.mha_ref(q_, k_, v_,
                                      mask=mask[:, None, None, :]) ** 2)

        old = flags.flag("FLAGS_pallas_interpret")
        flags.set_flags({"FLAGS_pallas_interpret": True})
        try:
            val, grads = jax.value_and_grad(loss_flash, (0, 1, 2))(q, k, v)
        finally:
            flags.set_flags({"FLAGS_pallas_interpret": old})
        rval, rgrads = jax.value_and_grad(loss_ref, (0, 1, 2))(q, k, v)
        np.testing.assert_allclose(float(val), float(rval), rtol=1e-4)
        for got, ref in zip(grads, rgrads):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-3, atol=2e-3)


class TestGqaNativeKernels:
    """r5 GQA-native flash: forward maps q heads onto kv groups via
    BlockSpec indexing; resident backward grids over KV heads and
    accumulates dk/dv across the group in-kernel — parity vs the
    expanded-and-reduced formulation."""

    @pytest.mark.parametrize("h,hkv", [(4, 2), (8, 2)])
    def test_gqa_fwd_bwd_match_ref(self, h, hkv):
        import jax
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels import flash_attention as fa
        rng = np.random.default_rng(h * 10 + hkv)
        b, s, d = 2, 256, 32
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
        g = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        out, lse = fa.flash_attention_pallas(
            q, k, v, causal=True, interpret=True, return_lse=True,
            block_q=128, block_k=128)
        ref = fa.mha_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
        dq, dk, dv = fa.flash_attention_pallas_bwd(
            q, k, v, out, lse, g, causal=True, interpret=True,
            block_q=128, block_k=128)
        assert dk.shape == k.shape and dv.shape == v.shape
        _, vjp = jax.vjp(
            lambda a, b_, c: fa.mha_ref(a, b_, c, causal=True), q, k, v)
        for got, want in zip((dq, dk, dv), vjp(g)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-3, atol=2e-3)


class TestRopeBhsd:
    def test_matches_bshd_on_transposed_inputs(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.kernels.rope import (rope_freqs, apply_rope_half,
                                             apply_rope_half_bhsd)
        rng = np.random.default_rng(0)
        b, s, h, d = 2, 16, 4, 8
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        cos, sin = rope_freqs(d, 32)
        rq, rk = apply_rope_half(q, k, cos, sin)
        t = lambda x: x.transpose(0, 2, 1, 3)
        bq, bk = apply_rope_half_bhsd(t(q), t(k), cos, sin)
        np.testing.assert_allclose(np.asarray(bq), np.asarray(t(rq)),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(bk), np.asarray(t(rk)),
                                   rtol=1e-6, atol=1e-6)
