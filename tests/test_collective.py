"""paddle.distributed collective facade — shard_map-backed semantics.

Reference test analog: test/collective/test_collective_*_api.py (SURVEY.md
§4) — theirs spawn NCCL processes; ours run the one SPMD program on 8
host-platform devices.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import collective as C
from paddle_tpu.parallel.topology import build_mesh, set_mesh


@pytest.fixture
def dp8():
    mesh = build_mesh(dp=8)
    set_mesh(mesh)
    return mesh


def _run(body, mesh, x, in_spec=P("dp"), out_spec=P("dp")):
    return shard_map(body, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                     check_vma=False)(x)


class TestAllReduce:
    def test_sum(self, dp8):
        x = jnp.arange(8.0)

        def body(x):
            t = paddle.Tensor(x)
            C.all_reduce(t)
            return t._data

        out = _run(body, dp8, x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_prod_with_negatives(self, dp8):
        x = jnp.arange(8.0) - 3.0  # contains negatives and zero

        def body(x):
            t = paddle.Tensor(x)
            C.all_reduce(t, op=C.ReduceOp.PROD)
            return t._data

        out = _run(body, dp8, x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.full(8, np.prod(np.asarray(x))))

    def test_avg(self, dp8):
        x = jnp.arange(8.0)

        def body(x):
            t = paddle.Tensor(x)
            C.all_reduce(t, op=C.ReduceOp.AVG)
            return t._data

        out = _run(body, dp8, x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))


class TestReduceScatter:
    @pytest.mark.parametrize("op,npfn", [
        (C.ReduceOp.SUM, np.sum), (C.ReduceOp.MAX, np.max),
        (C.ReduceOp.MIN, np.min), (C.ReduceOp.PROD, np.prod),
    ])
    def test_ops(self, dp8, op, npfn):
        src = jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)

        def body(row):
            row = row[0]  # this rank's full 8-vector
            t = paddle.Tensor(jnp.zeros((1,), jnp.float32))
            C.reduce_scatter(t, paddle.Tensor(row), op=op)
            return t._data

        out = _run(body, dp8, src, in_spec=P("dp", None))
        expect = npfn(np.asarray(src), axis=0)
        np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5,
                                   atol=1e-5)


class TestSendRecv:
    def test_delivers_src_value(self, dp8):
        x = jnp.arange(8.0) * 10.0

        def body(x):
            t = paddle.Tensor(x)
            C.send(t, dst=3)
            r = paddle.Tensor(jnp.zeros_like(x))
            C.recv(r, src=5)
            return r._data

        out = _run(body, dp8, x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 50.0))


class TestAllToAll:
    def test_uneven_split_raises(self, dp8):
        x = paddle.to_tensor(np.zeros(4, np.float32))
        with pytest.raises(NotImplementedError):
            C.alltoall_single(x, x, in_split_sizes=[3, 1])


class TestBroadcastInTrace:
    def test_broadcast_src(self, dp8):
        x = jnp.arange(8.0)

        def body(x):
            t = paddle.Tensor(x)
            C.broadcast(t, src=2)
            return t._data

        out = _run(body, dp8, x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 2.0))


class TestMpuRngTracker:
    def test_rng_state_context(self):
        from paddle_tpu.distributed.fleet.mpu import get_rng_state_tracker
        tr = get_rng_state_tracker()
        with tr.rng_state("model_parallel_rng"):
            a = paddle.rand([4])
        with tr.rng_state("model_parallel_rng"):
            b = paddle.rand([4])
        assert a.shape == [4] and b.shape == [4]
        # the named stream advances: consecutive draws differ
        assert not np.allclose(a.numpy(), b.numpy())


class TestRankGetterWarning:
    def test_rank_getters_warn_once_per_getter(self, dp8, monkeypatch):
        """VERDICT r1 weak item 7: reference code branching on rank would
        silently run the rank-0 path everywhere — each getter must warn on
        its first call (a benign get_rank() must not consume the warning a
        later get_stage_id() deserves), filterable by category."""
        import warnings
        from paddle_tpu.parallel import topology as topo
        from paddle_tpu.parallel.topology import (
            CommGroup, HybridCommunicateGroup, RankIsZeroWarning)
        monkeypatch.setattr(topo, "_rank_warned", set())
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            g = CommGroup("dp")
            assert g.rank == 0
            assert g.rank == 0  # second call: no second warning
            hcg = HybridCommunicateGroup()
            assert hcg.get_data_parallel_rank() == 0
            assert hcg.get_stage_id() == 0
        msgs = [x for x in w if issubclass(x.category, RankIsZeroWarning)]
        assert len(msgs) == 3, [str(m.message) for m in msgs]


class TestDistSurfaceExt:
    """Round-2 distributed surface completions: gather, P2POp/
    batch_isend_irecv, stream namespace, get_backend, parallelize,
    DataParallel wrapper."""

    def test_gather_and_backend(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist
        out = []
        dist.gather(paddle.to_tensor(np.ones(3, np.float32)), out, dst=0)
        assert len(out) >= 1 and out[0].shape == [3]
        assert dist.get_backend() == "xla"
        assert hasattr(dist.stream, "all_reduce")
        assert hasattr(dist, "launch")

    def test_batch_isend_irecv(self):
        import numpy as np
        import pytest as _pt
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist
        t = paddle.to_tensor(np.ones(2, np.float32))
        ops = [dist.P2POp(dist.isend, t, 0), dist.P2POp(dist.irecv, t, 0)]
        assert ops[0].peer == 0 and ops[0].op is dist.isend
        # eager host-driven P2P has no XLA path — the batch surfaces the
        # same documented error the underlying send/recv raise; inside
        # shard_map (the PP schedules) these lower to collectives instead
        with _pt.raises(NotImplementedError, match="shard_map"):
            dist.batch_isend_irecv(ops)

    def test_data_parallel_wrapper(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        m = paddle.DataParallel(nn.Linear(4, 2))
        x = paddle.to_tensor(np.random.randn(3, 4).astype(np.float32),
                             stop_gradient=False)
        loss = m(x).sum()
        loss.backward()
        assert m._layers.weight.grad is not None
        with m.no_sync():
            m(x)
        assert "weight" in m.state_dict()
