"""Pallas ragged paged-attention (PR 6): the kernel that walks only
each request's LIVE block chain, pinned against the XLA gather path.

Two layers:

  * kernel parity — `ragged_paged_attention` matches
    `_paged_gqa_attention` (the XLA reference) on every ragged shape
    the serving path produces: single-token decode rows, bucketed
    cached-prefix prefill rows, the fused mixed decode+prefill batch,
    and the edge cases (exactly-one-block chains, length == block_size
    boundaries, single-slot batches, fully padded batches, chains
    sharing prefix blocks with a COW-cloned tail). CPU runs the kernel
    in Pallas interpret mode — the CI parity path.
  * end-to-end parity — `ContinuousBatcher(attention_impl="pallas")`
    emits token-identical greedy output to the XLA backend across
    decode, chunked prefill, fused admission-during-decode, and
    prefix-cache COW-hit schedules, and `attention_impl="xla"` IS the
    pre-switch code path (the reference stays the fallback).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, paged
from paddle_tpu.nlp.ragged_attention import (ragged_paged_attention,
                                             resolve_attention_impl)
from paddle_tpu.quantization import kv as kvq


def _pools(seed, N, bs, KV, hd):
    rng = np.random.RandomState(seed)
    kp = jnp.asarray(rng.randn(N, bs, KV, hd), jnp.float32)
    vp = jnp.asarray(rng.randn(N, bs, KV, hd), jnp.float32)
    return rng, kp, vp


def _chains(rng, lengths, M, bs, N):
    """Distinct live block chains per row, padded table rows -> 0."""
    table = np.zeros((len(lengths), M), np.int32)
    free = list(rng.permutation(np.arange(1, N)))
    for r, L in enumerate(lengths):
        need = -(-L // bs) if L else 0
        for j in range(need):
            table[r, j] = free.pop()
    return jnp.asarray(table)


def _suffix_qpv(rng, lengths, P, M, bs):
    """Suffix-prefill style positions/valid: row r's P queries end at
    position lengths[r]-1 (rows shorter than P left-pad as invalid)."""
    R = len(lengths)
    pos = np.zeros((R, P), np.int32)
    val = np.zeros((R, P), np.bool_)
    maxpos = M * bs - 1
    for r, L in enumerate(lengths):
        for p in range(P):
            j = L - P + p
            pos[r, p] = min(max(j, 0), maxpos)
            val[r, p] = 0 <= j
    return jnp.asarray(pos), jnp.asarray(val)


def _assert_parity(q, kp, vp, table, pos, val, tol=2e-5):
    """pallas == xla on valid rows; pallas == 0 on padded rows."""
    ref = paged._paged_gqa_attention(q, kp, vp, table, pos)
    ref = np.where(np.asarray(val)[:, :, None, None], np.asarray(ref), 0.0)
    out = np.asarray(ragged_paged_attention(q, kp, vp, table, pos, val))
    np.testing.assert_allclose(out, ref, atol=tol, rtol=tol)


class TestKernelParity:
    N, bs, KV, hd, H, M = 12, 4, 2, 8, 4, 5

    def _q(self, rng, R, P):
        return jnp.asarray(rng.randn(R, P, self.H, self.hd), jnp.float32)

    def test_decode_rows(self):
        """P=1 decode rows at heterogeneous live lengths — the shape
        every steady-state decode step produces."""
        rng, kp, vp = _pools(0, self.N, self.bs, self.KV, self.hd)
        lengths = [1, 6, 17, 9]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 1, self.M, self.bs)
        _assert_parity(self._q(rng, 4, 1), kp, vp, table, pos, val)

    def test_bucketed_prefill_rows(self):
        """P=8 bucket-padded suffix rows (cached-prefix prefill): the
        invalid left-pad must not contaminate the real queries."""
        rng, kp, vp = _pools(1, self.N, self.bs, self.KV, self.hd)
        lengths = [3, 11, 19]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 8, self.M, self.bs)
        _assert_parity(self._q(rng, 3, 8), kp, vp, table, pos, val)

    def test_fused_mixed_batch(self):
        """The PR 5 fused shape: B decode rows (column 0 valid at the
        slot's position, inactive rows fully masked) stacked on top of
        bucket-width prefill rows — one kernel call serves both."""
        rng, kp, vp = _pools(2, self.N, self.bs, self.KV, self.hd)
        P = 4
        dlen, plen = [7, 13, 0], [P, 2 * P + 1]     # slot 2 inactive
        table = _chains(rng, dlen + plen, self.M, self.bs, self.N)
        dpos = np.zeros((3, P), np.int32)
        dval = np.zeros((3, P), np.bool_)
        maxpos = self.M * self.bs - 1
        for r, L in enumerate(dlen):
            dpos[r] = np.minimum(np.arange(L, L + P), maxpos)
            dval[r, 0] = L > 0
        ppos, pval = _suffix_qpv(rng, plen, P, self.M, self.bs)
        pos = jnp.concatenate([jnp.asarray(dpos), ppos], 0)
        val = jnp.concatenate([jnp.asarray(dval), pval], 0)
        _assert_parity(self._q(rng, 5, P), kp, vp, table, pos, val)

    def test_exactly_one_block(self):
        """A request whose whole live chain is ONE pool block."""
        rng, kp, vp = _pools(3, self.N, self.bs, self.KV, self.hd)
        lengths = [2, self.bs - 1]                   # both within block 0
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 2, self.M, self.bs)
        _assert_parity(self._q(rng, 2, 2), kp, vp, table, pos, val)

    def test_block_size_boundary(self):
        """length == block_size exactly: the chain walk must include
        the boundary block's last key and must NOT step into the next
        (garbage) table entry."""
        rng, kp, vp = _pools(4, self.N, self.bs, self.KV, self.hd)
        lengths = [self.bs, 2 * self.bs, self.bs + 1]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 1, self.M, self.bs)
        _assert_parity(self._q(rng, 3, 1), kp, vp, table, pos, val)

    def test_single_slot_batch(self):
        """R=1 — the one-request grid still initializes, accumulates
        and finalizes correctly."""
        rng, kp, vp = _pools(5, self.N, self.bs, self.KV, self.hd)
        lengths = [10]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 3, self.M, self.bs)
        _assert_parity(self._q(rng, 1, 3), kp, vp, table, pos, val)

    def test_all_padded_batch(self):
        """Every query invalid (empty batch of padded slots): the
        kernel emits exact zeros and touches no live chain at all."""
        rng, kp, vp = _pools(6, self.N, self.bs, self.KV, self.hd)
        R, P = 3, 2
        q = self._q(rng, R, P)
        table = jnp.zeros((R, self.M), jnp.int32)
        pos = jnp.zeros((R, P), jnp.int32)
        val = jnp.zeros((R, P), bool)
        out = np.asarray(ragged_paged_attention(q, kp, vp, table, pos, val))
        assert (out == 0.0).all()

    def test_cow_cloned_chain(self):
        """Two chains share prefix blocks; the second's tail block is a
        COW clone (identical KV content under a different block id) —
        the prefix-cache hit shape. Rows must agree with the reference
        AND with each other where their visible keys coincide."""
        rng, kp, vp = _pools(7, self.N, self.bs, self.KV, self.hd)
        L = 2 * self.bs + 2
        table = np.zeros((2, self.M), np.int32)
        table[0, :3] = [3, 7, 5]
        table[1, :3] = [3, 7, 9]                     # 9 := clone of 5
        kp = kp.at[9].set(kp[5])
        vp = vp.at[9].set(vp[5])
        pos, val = _suffix_qpv(rng, [L, L], 2, self.M, self.bs)
        q = self._q(rng, 1, 2)
        q = jnp.concatenate([q, q], 0)               # identical queries
        _assert_parity(q, kp, vp, jnp.asarray(table), pos, val)
        out = np.asarray(ragged_paged_attention(
            q, kp, vp, jnp.asarray(table), pos, val))
        np.testing.assert_allclose(out[0], out[1], atol=2e-6)

    def test_query_tiling_parity(self):
        """q_tile < P: the grid grows a query-tile dimension (VMEM
        bound for wide prefill buckets) and each tile walks only ITS
        OWN visible chain prefix — output identical to untiled."""
        rng, kp, vp = _pools(9, self.N, self.bs, self.KV, self.hd)
        lengths = [3, 11, 19]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 8, self.M, self.bs)
        q = self._q(rng, 3, 8)
        ref = paged._paged_gqa_attention(q, kp, vp, table, pos)
        ref = np.where(np.asarray(val)[:, :, None, None],
                       np.asarray(ref), 0.0)
        for tile in (2, 4):                          # 4 and 2 tiles
            out = np.asarray(ragged_paged_attention(
                q, kp, vp, table, pos, val, q_tile=tile))
            np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_query_tiling_indivisible_falls_back(self):
        """P % q_tile != 0 (exact unbucketed shapes): the largest
        divisor of P that fits becomes the tile — here Pt=1, the
        worst case (P=5 prime, q_tile=3) — same result."""
        rng, kp, vp = _pools(10, self.N, self.bs, self.KV, self.hd)
        lengths = [9, 14]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 5, self.M, self.bs)
        q = self._q(rng, 2, 5)
        out = np.asarray(ragged_paged_attention(
            q, kp, vp, table, pos, val, q_tile=3))
        ref = np.asarray(ragged_paged_attention(q, kp, vp, table, pos, val))
        np.testing.assert_allclose(out, ref, atol=2e-6)

    def test_query_dtype_roundtrip(self):
        """Output lands in q's dtype (the pool may be wider)."""
        rng, kp, vp = _pools(8, self.N, self.bs, self.KV, self.hd)
        lengths = [5]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 1, self.M, self.bs)
        q = self._q(rng, 1, 1).astype(jnp.bfloat16)
        out = ragged_paged_attention(q, kp, vp, table, pos, val)
        assert out.dtype == jnp.bfloat16


def _quantize_pools(kp, vp):
    """Per-block abs-max int8 quantization of an fp pool — the layout
    PagedKVCache's sibling scale pool stores ([N] scales per layer)."""
    ks = jnp.max(jnp.abs(kp), axis=(1, 2, 3)) / kvq.BOUND
    vs = jnp.max(jnp.abs(vp), axis=(1, 2, 3)) / kvq.BOUND
    kq = kvq.quantize(kp, ks[:, None, None, None])
    vq = kvq.quantize(vp, vs[:, None, None, None])
    return kq, vq, ks, vs


class TestKernelParityInt8:
    """int8 paged KV: the kernel's in-block-loop dequant (scales on
    scalar prefetch) pinned against the XLA path's after-the-gather
    dequant — the bit-stable reference — in interpret mode. Same math
    (quantization.kv) on both sides, so parity is the online-softmax
    tolerance, exactly like the fp rows."""

    N, bs, KV, hd, H, M = 12, 4, 2, 8, 4, 5

    def _q(self, rng, R, P):
        return jnp.asarray(rng.randn(R, P, self.H, self.hd), jnp.float32)

    def _assert_parity_q(self, q, kq, vq, ks, vs, table, pos, val,
                         tol=2e-5):
        ref = paged._paged_gqa_attention(q, kq, vq, table, pos,
                                         k_scale=ks, v_scale=vs)
        ref = np.where(np.asarray(val)[:, :, None, None],
                       np.asarray(ref), 0.0)
        out = np.asarray(ragged_paged_attention(
            q, kq, vq, table, pos, val, k_scale=ks, v_scale=vs))
        np.testing.assert_allclose(out, ref, atol=tol, rtol=tol)

    def test_decode_rows_int8(self):
        """P=1 decode rows over an int8 pool at heterogeneous live
        lengths — the quantized steady-state decode shape."""
        rng, kp, vp = _pools(20, self.N, self.bs, self.KV, self.hd)
        kq, vq, ks, vs = _quantize_pools(kp, vp)
        lengths = [1, 6, 17, 9]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 1, self.M, self.bs)
        self._assert_parity_q(self._q(rng, 4, 1), kq, vq, ks, vs,
                              table, pos, val)

    def test_bucketed_prefill_rows_int8(self):
        """Bucket-padded cached-prefix suffix rows against quantized
        prefix blocks — the warm-admission shape."""
        rng, kp, vp = _pools(21, self.N, self.bs, self.KV, self.hd)
        kq, vq, ks, vs = _quantize_pools(kp, vp)
        lengths = [3, 11, 19]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 8, self.M, self.bs)
        self._assert_parity_q(self._q(rng, 3, 8), kq, vq, ks, vs,
                              table, pos, val)

    def test_block_size_boundary_int8(self):
        """length == block_size under int8: the boundary block's last
        key dequantizes and the walk must not read the next (garbage)
        table entry's scale either."""
        rng, kp, vp = _pools(22, self.N, self.bs, self.KV, self.hd)
        kq, vq, ks, vs = _quantize_pools(kp, vp)
        lengths = [self.bs, 2 * self.bs, self.bs + 1]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        pos, val = _suffix_qpv(rng, lengths, 1, self.M, self.bs)
        self._assert_parity_q(self._q(rng, 3, 1), kq, vq, ks, vs,
                              table, pos, val)

    def test_all_padded_batch_int8_exact_zeros(self):
        """Every query invalid: the quantized kernel emits EXACT zeros
        (never-written blocks carry scale 0, and no live chain is
        touched at all)."""
        rng, kp, vp = _pools(23, self.N, self.bs, self.KV, self.hd)
        kq, vq, ks, vs = _quantize_pools(kp, vp)
        R, P = 3, 2
        q = self._q(rng, R, P)
        table = jnp.zeros((R, self.M), jnp.int32)
        pos = jnp.zeros((R, P), jnp.int32)
        val = jnp.zeros((R, P), bool)
        out = np.asarray(ragged_paged_attention(
            q, kq, vq, table, pos, val, k_scale=ks, v_scale=vs))
        assert (out == 0.0).all()

    def test_cow_cloned_chain_int8(self):
        """The prefix-cache COW shape under int8: the clone block
        copies the source's CODES AND SCALE (paged._apply_cow copies
        both pools) — identical queries over the shared prefix must
        agree across the original and the cloned chain."""
        rng, kp, vp = _pools(24, self.N, self.bs, self.KV, self.hd)
        kq, vq, ks, vs = _quantize_pools(kp, vp)
        L = 2 * self.bs + 2
        table = np.zeros((2, self.M), np.int32)
        table[0, :3] = [3, 7, 5]
        table[1, :3] = [3, 7, 9]                     # 9 := clone of 5
        kq = kq.at[9].set(kq[5])
        vq = vq.at[9].set(vq[5])
        ks = ks.at[9].set(ks[5])
        vs = vs.at[9].set(vs[5])
        pos, val = _suffix_qpv(rng, [L, L], 2, self.M, self.bs)
        q = self._q(rng, 1, 2)
        q = jnp.concatenate([q, q], 0)               # identical queries
        self._assert_parity_q(q, kq, vq, ks, vs, jnp.asarray(table),
                              pos, val)
        out = np.asarray(ragged_paged_attention(
            q, kq, vq, jnp.asarray(table), pos, val,
            k_scale=ks, v_scale=vs))
        np.testing.assert_allclose(out[0], out[1], atol=2e-6)


def _slab(rng, B, S, KV, hd):
    """An in-register draft/verify suffix slab (full precision — slab
    rows never pass through the pool's quantizer before commit)."""
    sk = jnp.asarray(rng.randn(B, S, KV, hd), jnp.float32)
    sv = jnp.asarray(rng.randn(B, S, KV, hd), jnp.float32)
    return sk, sv


class TestSuffixSlabParity:
    """The spec verify's suffix-slab operand: the Pallas kernel folds
    the in-register draft slab into the SAME online softmax as the
    pool sweep at the grid's extra chunk (`c == nchunks`), pinned in
    interpret mode against the XLA concat formulation
    (`paged._spec_gqa_attention(impl="xla")` — the bit-stable
    reference the verify path keeps). Chain triangles and packed-tree
    ancestor masks, fp and int8 pools, block-boundary straddles and
    the all-padded batch, in the TestKernelParityInt8 style."""

    N, bs, KV, hd, H, M = 12, 4, 2, 8, 4, 5

    def _q(self, rng, B, P):
        return jnp.asarray(rng.randn(B, P, self.H, self.hd),
                           jnp.float32)

    def _parity(self, q, kp, vp, table, base_len, sk, sv, vis,
                ks=None, vs=None, tol=2e-5):
        ref = np.asarray(paged._spec_gqa_attention(
            q, kp, vp, table, base_len, sk, sv, vis,
            k_scale=ks, v_scale=vs, impl="xla"))
        out = np.asarray(paged._spec_gqa_attention(
            q, kp, vp, table, base_len, sk, sv, vis,
            k_scale=ks, v_scale=vs, impl="pallas"))
        np.testing.assert_allclose(out, ref, atol=tol, rtol=tol)

    def test_chain_triangle_fp(self):
        """The chain verify shape: S = k+1 slab rows, causal-triangle
        visibility, heterogeneous committed lengths."""
        from paddle_tpu.serving.speculative import SpecConfig
        rng, kp, vp = _pools(30, self.N, self.bs, self.KV, self.hd)
        vis = jnp.asarray(SpecConfig(k=4).ancestor_mask())
        S = vis.shape[0]
        lengths = [1, 6, 17]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        sk, sv = _slab(rng, 3, S, self.KV, self.hd)
        self._parity(self._q(rng, 3, S), kp, vp, table,
                     jnp.asarray(lengths, jnp.int32), sk, sv, vis)

    def test_tree_ancestor_mask_fp(self):
        """The packed-tree verify shape: every node's query sees the
        pool plus exactly its root-to-node path (arbitrary per-row
        visibility, NOT a triangle)."""
        from paddle_tpu.serving.speculative import SpecConfig
        rng, kp, vp = _pools(31, self.N, self.bs, self.KV, self.hd)
        sc = SpecConfig(tree=[2, 2])
        vis = jnp.asarray(sc.ancestor_mask())
        S = sc.slab_rows()
        lengths = [2, 9, 14]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        sk, sv = _slab(rng, 3, S, self.KV, self.hd)
        self._parity(self._q(rng, 3, S), kp, vp, table,
                     jnp.asarray(lengths, jnp.int32), sk, sv, vis)

    def test_tree_draft_level_rows(self):
        """A draft sweep's level shape: P < S queries (one level's
        nodes) against the full slab, each seeing its own path — the
        visibility rows are a SLICE of the ancestor mask."""
        from paddle_tpu.serving.speculative import SpecConfig
        rng, kp, vp = _pools(32, self.N, self.bs, self.KV, self.hd)
        sc = SpecConfig(tree=[2, 2])
        A = jnp.asarray(sc.ancestor_mask())
        offs = sc.level_offsets()
        vis = A[offs[1]:offs[2]]                     # level-1 nodes
        S = sc.slab_rows()
        lengths = [5, 11]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        sk, sv = _slab(rng, 2, S, self.KV, self.hd)
        self._parity(self._q(rng, 2, vis.shape[0]), kp, vp, table,
                     jnp.asarray(lengths, jnp.int32), sk, sv, vis)

    def test_block_boundary_straddle(self):
        """Committed length exactly at / one past a block boundary:
        the pool sweep must include the boundary block's last key and
        the slab fold must not shift by one."""
        from paddle_tpu.serving.speculative import SpecConfig
        rng, kp, vp = _pools(33, self.N, self.bs, self.KV, self.hd)
        vis = jnp.asarray(SpecConfig(k=3).ancestor_mask())
        S = vis.shape[0]
        lengths = [self.bs, 2 * self.bs, self.bs + 1]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        sk, sv = _slab(rng, 3, S, self.KV, self.hd)
        self._parity(self._q(rng, 3, S), kp, vp, table,
                     jnp.asarray(lengths, jnp.int32), sk, sv, vis)

    def test_chain_and_tree_int8_pool(self):
        """int8 committed pool under the slab fold: pool scores
        dequantize inside the block-chunk loop (scales on scalar
        prefetch), slab rows stay fp — parity vs the XLA reference's
        after-the-gather dequant, chain AND tree visibility."""
        from paddle_tpu.serving.speculative import SpecConfig
        rng, kp, vp = _pools(34, self.N, self.bs, self.KV, self.hd)
        kq, vq, ks, vs = _quantize_pools(kp, vp)
        lengths = [3, self.bs, 13]
        table = _chains(rng, lengths, self.M, self.bs, self.N)
        for sc in (SpecConfig(k=4), SpecConfig(tree=[2, 1, 1])):
            vis = jnp.asarray(sc.ancestor_mask())
            S = sc.slab_rows()
            sk, sv = _slab(rng, 3, S, self.KV, self.hd)
            self._parity(self._q(rng, 3, S), kq, vq, table,
                         jnp.asarray(lengths, jnp.int32), sk, sv, vis,
                         ks=ks, vs=vs)

    def test_all_padded_exact_zeros(self):
        """Every query invalid: the suffix-slab grid (pool chunks PLUS
        the slab chunk) emits EXACT zeros — the slab fold must respect
        row validity exactly like the pool sweep does."""
        rng, kp, vp = _pools(35, self.N, self.bs, self.KV, self.hd)
        B, S = 2, 4
        q = self._q(rng, B, S)
        sk, sv = _slab(rng, B, S, self.KV, self.hd)
        out = np.asarray(ragged_paged_attention(
            q, kp, vp, jnp.zeros((B, self.M), jnp.int32),
            jnp.zeros((B, S), jnp.int32), jnp.zeros((B, S), bool),
            suffix_k=sk, suffix_v=sv,
            suffix_vis=jnp.ones((B, S, S), bool)))
        assert (out == 0.0).all()


class TestResolveImpl:
    def test_auto_resolves_off_tpu(self):
        """CPU CI: auto means the XLA reference (pallas off-TPU is
        interpret mode — a testing path, not a serving path)."""
        expect = "pallas" if jax.default_backend() == "tpu" else "xla"
        assert resolve_attention_impl("auto") == expect

    def test_passthrough_and_reject(self):
        assert resolve_attention_impl("pallas") == "pallas"
        assert resolve_attention_impl("xla") == "xla"
        with pytest.raises(ValueError):
            resolve_attention_impl("cuda")


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _batcher(params, cfg, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_total_len", 32)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("chunk", 2)
    return paged.ContinuousBatcher(params, cfg, **kw)


def _prompts(seed, lengths):
    rng = np.random.RandomState(seed)
    return [list(map(int, rng.randint(1, 200, n))) for n in lengths]


def _run_both(params, cfg, schedule, **kw):
    outs = []
    for impl in ("xla", "pallas"):
        cb = _batcher(params, cfg, attention_impl=impl, **kw)
        outs.append(schedule(cb))
    return outs


class TestBatcherParity:
    """pallas == xla greedy tokens through the real serving paths."""

    def test_decode_parity(self, setup):
        cfg, params = setup
        prompts = _prompts(11, (5, 9, 3))

        def schedule(cb):
            rids = [cb.submit(p) for p in prompts]
            out = cb.run()
            return [out[r] for r in rids]

        a, b = _run_both(params, cfg, schedule, prefill_buckets=(8,))
        assert a == b

    def test_fused_mid_decode_parity(self, setup):
        """Admissions landing mid-decode take the fused mixed batch —
        the kernel's hardest shape — with identical tokens."""
        cfg, params = setup
        first, late = _prompts(12, (6, 7))

        def schedule(cb):
            rids = [cb.submit(first)]
            cb.step()
            rids.append(cb.submit(late))
            out = cb.run()
            assert cb.fused_steps >= 1
            return [out[r] for r in rids]

        a, b = _run_both(params, cfg, schedule, prefill_buckets=(8,))
        assert a == b

    def test_chunked_prefill_parity(self, setup):
        """A prompt past the largest bucket streams bucket-sized chunks
        through the ragged path."""
        cfg, params = setup
        (long,) = _prompts(13, (19,))

        def schedule(cb):
            rid = cb.submit(long)
            return cb.run()[rid]

        a, b = _run_both(params, cfg, schedule, prefill_buckets=(8,))
        assert a == b

    def test_cow_prefix_hit_parity(self, setup):
        """Same prompt twice with the prefix cache on: the second
        admission COW-clones the cached tail block — chains built from
        shared + cloned blocks must decode identically."""
        cfg, params = setup
        (p,) = _prompts(14, (9,))

        def schedule(cb):
            r1 = cb.submit(p)
            cb.run()
            r2 = cb.submit(list(p))
            out = cb.run()
            stats = cb.prefix_stats()
            assert stats["hits"] >= 1
            return out[r2]

        a, b = _run_both(params, cfg, schedule, prefix_cache=True,
                         prefill_buckets=(8,))
        assert a == b

    def test_xla_is_default_off_tpu(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg)           # attention_impl="auto"
        if jax.default_backend() != "tpu":
            assert cb.attention_impl == "xla"

    def test_compile_memo_keys_on_impl(self, setup):
        """Every compiled-shape memo keys on the resolved impl, so a
        pallas batcher never aliases an xla executable."""
        cfg, params = setup
        cb = _batcher(params, cfg, attention_impl="pallas",
                      prefill_buckets=(8,))
        cb.warmup_prefill()
        keys = (list(cb._prefill_cache) + list(cb._fused_cache)
                + list(cb._chunk_cache))
        # ... and on the resolved quantization config (the trailing
        # (weight_dtype, kv_dtype) pair), so a quantized batcher never
        # aliases an fp executable either
        assert keys and all("pallas" in k and k[-2:] == ("fp", "fp")
                            for k in keys)
