"""Op unit tests: manipulation/reduction/comparison — SURVEY.md §4 style."""
import numpy as np
import pytest

import paddle_tpu as paddle
from optest import check_output, check_grad

RNG = np.random.default_rng(11)


def fdata(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


class TestReductions:
    @pytest.mark.parametrize("pop,nop", [
        (paddle.sum, np.sum), (paddle.mean, np.mean),
        (paddle.max, np.max), (paddle.min, np.min), (paddle.prod, np.prod),
    ])
    def test_full(self, pop, nop):
        check_output(pop, nop, [fdata(3, 4)])

    @pytest.mark.parametrize("axis,keepdim", [(0, False), (1, True), ([0, 1], False)])
    def test_sum_axis(self, axis, keepdim):
        check_output(paddle.sum,
                     lambda v: np.sum(v, axis=tuple(axis) if isinstance(axis, list) else axis,
                                      keepdims=keepdim),
                     [fdata(3, 4, 5)], kwargs=dict(axis=axis, keepdim=keepdim))

    def test_grad(self):
        check_grad(paddle.sum, [fdata(2, 3)])
        check_grad(paddle.mean, [fdata(2, 3)], kwargs=dict(axis=1))
        check_grad(paddle.max, [np.array([[1., 5., 2.], [7., 3., 4.]], dtype=np.float64)],
                   kwargs=dict(axis=1))

    def test_std_var(self):
        x = fdata(4, 5)
        check_output(paddle.std, lambda v: np.std(v, ddof=1), [x])
        check_output(paddle.var, lambda v: np.var(v, axis=1, ddof=1), [x],
                     kwargs=dict(axis=1))

    def test_argmax_argmin(self):
        x = fdata(3, 4)
        out = paddle.argmax(paddle.to_tensor(x), axis=1)
        np.testing.assert_array_equal(out.numpy(), np.argmax(x, axis=1))
        assert out.dtype == np.dtype("int64")
        out = paddle.argmin(paddle.to_tensor(x))
        assert out.numpy() == np.argmin(x)

    def test_all_any(self):
        x = np.array([[True, False], [True, True]])
        check_output(paddle.all, lambda v: np.all(v, axis=1), [x], kwargs=dict(axis=1))
        check_output(paddle.any, np.any, [x])


class TestManipulation:
    def test_reshape_flatten(self):
        x = fdata(2, 3, 4)
        check_output(paddle.reshape, lambda v: v.reshape(6, 4), [x],
                     kwargs=dict(shape=[6, 4]))
        check_output(paddle.reshape, lambda v: v.reshape(2, 12), [x],
                     kwargs=dict(shape=[2, -1]))
        check_output(paddle.flatten, lambda v: v.reshape(2, 12), [x],
                     kwargs=dict(start_axis=1))
        check_grad(paddle.reshape, [fdata(2, 3)], kwargs=dict(shape=[3, 2]))

    def test_transpose(self):
        x = fdata(2, 3, 4)
        check_output(paddle.transpose, lambda v: v.transpose(2, 0, 1), [x],
                     kwargs=dict(perm=[2, 0, 1]))
        check_grad(paddle.transpose, [fdata(2, 3)], kwargs=dict(perm=[1, 0]))

    def test_squeeze_unsqueeze(self):
        x = fdata(1, 3, 1, 4)
        check_output(paddle.squeeze, lambda v: v.squeeze(0), [x], kwargs=dict(axis=0))
        check_output(paddle.unsqueeze, lambda v: v[:, None], [fdata(3, 4)],
                     kwargs=dict(axis=1))

    def test_concat_stack_split(self):
        xs = [fdata(2, 3), fdata(2, 3)]
        t = [paddle.to_tensor(x) for x in xs]
        np.testing.assert_allclose(paddle.concat(t, axis=1).numpy(),
                                   np.concatenate(xs, axis=1), rtol=1e-6)
        np.testing.assert_allclose(paddle.stack(t, axis=0).numpy(),
                                   np.stack(xs), rtol=1e-6)
        parts = paddle.split(paddle.to_tensor(fdata(6, 2)), 3, axis=0)
        assert len(parts) == 3 and parts[0].shape == [2, 2]
        parts = paddle.split(paddle.to_tensor(fdata(7, 2)), [2, 5], axis=0)
        assert parts[1].shape == [5, 2]
        parts = paddle.split(paddle.to_tensor(fdata(7, 2)), [2, -1], axis=0)
        assert parts[1].shape == [5, 2]

    def test_tile_expand(self):
        x = fdata(2, 3)
        check_output(paddle.tile, lambda v: np.tile(v, (2, 1)), [x],
                     kwargs=dict(repeat_times=[2, 1]))
        e = paddle.expand(paddle.to_tensor(fdata(1, 3)), shape=[4, 3])
        assert e.shape == [4, 3]
        e = paddle.expand(paddle.to_tensor(fdata(1, 3)), shape=[4, -1])
        assert e.shape == [4, 3]

    def test_gather_scatter(self):
        x = fdata(5, 3)
        idx = np.array([0, 2, 4])
        check_output(paddle.gather, lambda v: v[idx], [x],
                     kwargs=dict(index=paddle.to_tensor(idx)))
        base = np.zeros((5, 2), np.float32)
        upd = fdata(2, 2)
        out = paddle.scatter(paddle.to_tensor(base), paddle.to_tensor(np.array([1, 3])),
                             paddle.to_tensor(upd))
        ref = base.copy(); ref[[1, 3]] = upd
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)

    def test_gather_nd(self):
        x = fdata(3, 4, 5)
        idx = np.array([[0, 1], [2, 3]])
        out = paddle.gather_nd(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), x[[0, 2], [1, 3]], rtol=1e-6)

    def test_where(self):
        c = np.array([[True, False], [False, True]])
        x, y = fdata(2, 2), fdata(2, 2)
        out = paddle.where(paddle.to_tensor(c), paddle.to_tensor(x), paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), np.where(c, x, y), rtol=1e-6)
        check_grad(lambda a, b: paddle.where(paddle.to_tensor(c), a, b), [x, y])

    def test_sort_topk(self):
        x = fdata(3, 6)
        check_output(paddle.sort, lambda v: np.sort(v, axis=1), [x], kwargs=dict(axis=1))
        out = paddle.argsort(paddle.to_tensor(x), axis=1, descending=True)
        np.testing.assert_array_equal(out.numpy(), np.argsort(-x, axis=1))
        v, i = paddle.topk(paddle.to_tensor(x), 2, axis=1)
        ref = np.sort(x, axis=1)[:, ::-1][:, :2]
        np.testing.assert_allclose(v.numpy(), ref, rtol=1e-6)

    def test_index_select_masked(self):
        x = fdata(4, 3)
        out = paddle.index_select(paddle.to_tensor(x), paddle.to_tensor(np.array([1, 3])), axis=0)
        np.testing.assert_allclose(out.numpy(), x[[1, 3]], rtol=1e-6)
        m = x > 0
        out = paddle.masked_select(paddle.to_tensor(x), paddle.to_tensor(m))
        np.testing.assert_allclose(out.numpy(), x[m], rtol=1e-6)
        out = paddle.masked_fill(paddle.to_tensor(x), paddle.to_tensor(m), 0.0)
        np.testing.assert_allclose(out.numpy(), np.where(m, 0, x), rtol=1e-6)

    def test_pad(self):
        x = fdata(2, 3)
        out = paddle.pad(paddle.to_tensor(x), [1, 1, 2, 2], value=9.0)
        ref = np.pad(x, [(1, 1), (2, 2)], constant_values=9.0)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)

    def test_flip_roll(self):
        x = fdata(3, 4)
        check_output(paddle.flip, lambda v: np.flip(v, 1), [x], kwargs=dict(axis=[1]))
        check_output(paddle.roll, lambda v: np.roll(v, 2, axis=0), [x],
                     kwargs=dict(shifts=2, axis=0))

    def test_take_along_put_along(self):
        x = fdata(3, 4)
        idx = np.argsort(x, axis=1)
        out = paddle.take_along_axis(paddle.to_tensor(x), paddle.to_tensor(idx), axis=1)
        np.testing.assert_allclose(out.numpy(), np.take_along_axis(x, idx, 1), rtol=1e-6)
        out = paddle.put_along_axis(paddle.to_tensor(x),
                                    paddle.to_tensor(np.array([[0], [1], [2]])),
                                    0.0, axis=1)
        ref = x.copy(); np.put_along_axis(ref, np.array([[0], [1], [2]]), 0.0, 1)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)

    def test_unique(self):
        x = np.array([2, 1, 3, 1, 2])
        out = paddle.unique(paddle.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3])
        v, c = paddle.unique(paddle.to_tensor(x), return_counts=True)
        np.testing.assert_array_equal(c.numpy(), [2, 2, 1])

    def test_nonzero(self):
        x = np.array([[1, 0], [0, 3]])
        out = paddle.nonzero(paddle.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(), [[0, 0], [1, 1]])


class TestComparison:
    def test_compare(self):
        x, y = fdata(3, 3), fdata(3, 3)
        t = paddle.to_tensor
        np.testing.assert_array_equal((t(x) > t(y)).numpy(), x > y)
        np.testing.assert_array_equal((t(x) == t(x)).numpy(), np.ones_like(x, bool))
        np.testing.assert_array_equal(paddle.less_equal(t(x), t(y)).numpy(), x <= y)

    def test_allclose_equal_all(self):
        x = fdata(2, 2)
        assert bool(paddle.allclose(paddle.to_tensor(x), paddle.to_tensor(x + 1e-9)))
        assert bool(paddle.equal_all(paddle.to_tensor(x), paddle.to_tensor(x)))

    def test_logical(self):
        a = np.array([True, False, True]); b = np.array([True, True, False])
        check_output(paddle.logical_and, np.logical_and, [a, b])
        check_output(paddle.logical_not, np.logical_not, [a])


class TestCreation:
    def test_basics(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2], dtype="int32").dtype == np.dtype("int32")
        assert paddle.full([2, 2], 7.0).numpy()[0, 0] == 7
        np.testing.assert_array_equal(paddle.arange(2, 8, 2).numpy(), [2, 4, 6])
        assert paddle.eye(3).numpy().trace() == 3
        x = paddle.to_tensor([1.0, 2.0])
        np.testing.assert_array_equal(paddle.zeros_like(x).numpy(), [0, 0])

    def test_tril_triu(self):
        x = fdata(4, 4)
        check_output(paddle.tril, np.tril, [x])
        check_output(paddle.triu, lambda v: np.triu(v, 1), [x], kwargs=dict(diagonal=1))

    def test_random_shapes(self):
        assert paddle.rand([3, 4]).shape == [3, 4]
        assert paddle.randn([2]).dtype == np.dtype("float32")
        r = paddle.randint(0, 10, [100])
        assert r.numpy().min() >= 0 and r.numpy().max() < 10
        p = paddle.randperm(10).numpy()
        np.testing.assert_array_equal(np.sort(p), np.arange(10))

    def test_seed_reproducible(self):
        paddle.seed(42)
        a = paddle.randn([4]).numpy()
        paddle.seed(42)
        b = paddle.randn([4]).numpy()
        np.testing.assert_array_equal(a, b)


class TestLinalg:
    def test_solve_inv(self):
        a = fdata(3, 3) + 3 * np.eye(3, dtype=np.float32)
        b = fdata(3, 2)
        check_output(paddle.linalg.solve, np.linalg.solve, [a, b], rtol=1e-4)
        check_output(paddle.linalg.inv, np.linalg.inv, [a], rtol=1e-4)

    def test_qr_svd(self):
        a = fdata(4, 3)
        q, r = paddle.linalg.qr(paddle.to_tensor(a))
        np.testing.assert_allclose((q.numpy() @ r.numpy()), a, atol=1e-5)
        u, s, v = paddle.linalg.svd(paddle.to_tensor(a))
        np.testing.assert_allclose(u.numpy() @ np.diag(s.numpy()) @ v.numpy().T, a, atol=1e-5)

    def test_det_cholesky(self):
        a = fdata(3, 3)
        spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        check_output(paddle.linalg.det, np.linalg.det, [spd], rtol=1e-4)
        l = paddle.linalg.cholesky(paddle.to_tensor(spd))
        np.testing.assert_allclose(l.numpy() @ l.numpy().T, spd, atol=1e-4)

    def test_einsum(self):
        a, b = fdata(3, 4), fdata(4, 5)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)
        check_grad(lambda x, y: paddle.einsum("ij,jk->ik", x, y), [fdata(2, 3), fdata(3, 2)])

    def test_norm(self):
        x = fdata(3, 4)
        check_output(paddle.norm, np.linalg.norm, [x], rtol=1e-5)
        check_output(paddle.norm, lambda v: np.linalg.norm(v, axis=1), [x],
                     kwargs=dict(p=2, axis=1), rtol=1e-5)
