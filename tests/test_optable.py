"""Auto-generated op tests: every optable.OpSpec row with a numpy
reference gets an OpTest-style forward check, and every grad-eligible row
a finite-difference grad check — the table IS the test list, exactly the
reference's ops.yaml -> per-op test generation loop (SURVEY.md §2.1
codegen row, §4 OpTest; VERDICT r1 item 3)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.optable import SPECS, INPLACE_FROM_TABLE
from paddle_tpu.ops._registry import REGISTRY

import optest

_FWD = sorted(n for n, s in SPECS.items() if s.ref is not None)
_GRAD = sorted(n for n, s in SPECS.items()
               if s.grad and not s.int_op and s.ref is not None)


def _inputs(spec, seed=7):
    rng = np.random.RandomState(seed)
    shapes = spec.shapes or ((3, 4),) * max(spec.n_in, 1)
    if len(shapes) < spec.n_in:
        shapes = tuple(shapes) * spec.n_in
    lo, hi = spec.domain
    if spec.int_op:
        return [rng.randint(0, 5, sh).astype(np.int64) for sh in shapes]
    return [(rng.uniform(lo, hi, sh)).astype(np.float32) for sh in shapes]


@pytest.mark.parametrize("name", _FWD)
def test_forward_matches_numpy(name):
    spec = SPECS[name]
    inputs = _inputs(spec)
    optest.check_output(REGISTRY[name], spec.ref, inputs,
                        kwargs=spec.kwargs, rtol=spec.rtol)


@pytest.mark.parametrize("name", _GRAD)
def test_grad_matches_finite_difference(name):
    spec = SPECS[name]
    inputs = _inputs(spec)
    optest.check_grad(REGISTRY[name], inputs, kwargs=spec.kwargs)


def test_table_ops_are_registered_and_attached():
    """Every table row is in REGISTRY; method rows are Tensor methods;
    inplace rows registered their `name_` twin."""
    from paddle_tpu import Tensor
    for name, spec in SPECS.items():
        assert name in REGISTRY, name
        if spec.method:
            assert hasattr(Tensor, name), name
    from paddle_tpu.ops.optable import INPLACE_NAME_OVERRIDES
    for name in INPLACE_FROM_TABLE:
        ip = INPLACE_NAME_OVERRIDES.get(name, name + "_")
        assert ip in REGISTRY, ip


def test_surface_breadth():
    """The registry op count must hold the round-3 breadth line (VERDICT
    r2 item 3: >= 800 with every surface registered)."""
    assert len(REGISTRY) >= 800, len(REGISTRY)


def test_inplace_variants_adopt():
    x = paddle.to_tensor(np.array([1.0, 4.0], np.float32))
    x.sqrt_()
    np.testing.assert_allclose(x.numpy(), [1.0, 2.0])
    y = paddle.to_tensor(np.array([True, False]))
    y.logical_not_()
    np.testing.assert_array_equal(y.numpy(), [False, True])


def test_special_value_ops():
    # i0e/i1e: exponentially-scaled Bessel identities vs i0/i1
    x = paddle.to_tensor(np.array([0.5, 1.0, 2.0], np.float32))
    np.testing.assert_allclose(
        (paddle.i0e(x) * paddle.exp(x)).numpy(), paddle.i0(x).numpy(),
        rtol=1e-5)
    np.testing.assert_allclose(
        (paddle.i1e(x) * paddle.exp(x)).numpy(), paddle.i1(x).numpy(),
        rtol=1e-5)
    # multigammaln(x, 1) == gammaln(x)
    np.testing.assert_allclose(
        paddle.multigammaln(x + 2, 1).numpy(),
        paddle.lgamma(x + 2).numpy(), rtol=1e-5)


def test_no_machinery_leaks():
    """Table builders/TABLE must not leak into paddle.* or Tensor (the
    star-import chain is __all__-gated; method=False rows stay functions)."""
    from paddle_tpu import Tensor
    assert not hasattr(paddle, "U") and not hasattr(paddle, "TABLE")
    assert not hasattr(Tensor, "lu_unpack")
    assert not hasattr(Tensor, "standard_normal")
    assert hasattr(Tensor, "cdist") and hasattr(paddle, "add_n")


def test_cdist_zero_distance_grads_finite():
    """cdist(x, x)'s zero diagonal is a non-differentiable point of the
    p-root; grads there must be 0, not NaN."""
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 4)
                         .astype(np.float32), stop_gradient=False)
    paddle.cdist(x, x).sum().backward()
    assert bool(paddle.isfinite(x.grad).all())


def test_hfftn_s_without_axes_uses_trailing_axes():
    x = paddle.to_tensor((np.random.randn(3, 4) + 0j).astype(np.complex64))
    assert paddle.fft.hfftn(x, s=[6]).shape == [3, 6]


def test_svd_lowrank_reconstructs():
    """svd_lowrank has no elementwise numpy ref (sign/basis ambiguity) —
    the checkable property is reconstruction (VERDICT r2 weak 4)."""
    rng = np.random.RandomState(0)
    x = paddle.to_tensor((rng.randn(8, 5) @ np.diag([5, 3, 1, 0.01, 0.001])
                          ).astype(np.float32))
    u, s, v = paddle.linalg.svd_lowrank(x, q=4)
    rec = (u.numpy() * s.numpy()) @ v.numpy().T
    np.testing.assert_allclose(rec, x.numpy(), atol=0.05)
