"""Portable KV-block snapshots + live migration (serving.kvtransfer).

Deterministic CPU coverage of the disaggregated prefill/decode tier:
snapshot round-trip bit-identity at the batcher level (fp AND int8-KV,
scale write-set discipline intact), fingerprint-mismatch rejection at
the import boundary, prefix-index registration visible to siblings on
the importing pool, mid-decode export under fused prefill+decode
steps, speculative-destination parity across the hop, the affinity
index re-pointing migrated chains at the destination replica, the
Router's disaggregated end-to-end path (prefill-role surrender →
snapshot migration → decode-role resume, bit-identical to a
monolithic engine with ZERO decode-replica prefill chunks), warm
failover from an exported snapshot, and the supervisor's
drain-export → respawn → resume cycle.
"""
import threading

import numpy as np
import pytest
import jax

from paddle_tpu.nlp import llama, paged
from paddle_tpu import serving
from paddle_tpu.serving import RequestState
from paddle_tpu.serving.router import Router, _AffinityIndex, _DECODE_ROLES

_RNG = np.random.RandomState(23)
PROMPTS = [list(map(int, _RNG.randint(1, 200, n))) for n in (6, 9, 5)]
MAX_NEW = 8


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _batcher(setup, **kw):
    cfg, params = setup
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_total_len", 48)
    kw.setdefault("max_new_tokens", MAX_NEW)
    kw.setdefault("chunk", 2)
    return paged.ContinuousBatcher(params, cfg, **kw)


def _export_mid_decode(cb, rid, min_tokens=2):
    """Step until `rid` holds at least `min_tokens` generated tokens
    but is still decoding, then export + surrender its slot (the
    engine's `_surrender` sequence: export, abort, release)."""
    for _ in range(64):
        if len(cb.outputs.get(rid, [])) >= min_tokens:
            break
        cb.step()
    active = {cb.slot_req[s] for s in range(cb.B) if cb.active[s]}
    assert rid in active, "request finished before the export point"
    snap = cb.export_kv(rid)
    cb.abort(rid)
    cb.release(rid)
    return snap


class TestSnapshotRoundTrip:
    def _roundtrip(self, setup, **dtypes):
        ref_cb = _batcher(setup, **dtypes)
        r_ref = ref_cb.submit(PROMPTS[0])
        ref = ref_cb.run()[r_ref]

        src = _batcher(setup, **dtypes)
        rid = src.submit(PROMPTS[0])
        snap = _export_mid_decode(src, rid)
        assert snap.prompt_len == len(PROMPTS[0])
        assert snap.tokens[snap.prompt_len:] == ref[:len(snap.tokens)
                                                    - snap.prompt_len]
        dst = _batcher(setup, **dtypes)
        return snap, dst, ref

    def test_fp_bit_identity(self, setup):
        snap, dst, ref = self._roundtrip(setup)
        rid2 = dst.import_kv(snap)
        out = dst.run()
        assert out[rid2] == ref          # resumed decode is bit-exact
        assert dst.prefill_chunk_calls == 0
        assert dst.imported_kv == 1
        # blocks drain clean after the resumed request retires
        assert dst.alloc.stats()["blocks_in_use"] == 0

    def test_int8_kv_bit_identity_and_scales(self, setup):
        snap, dst, ref = self._roundtrip(
            setup, weight_dtype="int8", kv_dtype="int8")
        assert snap.k_scale is not None and snap.v_scale is not None
        rid2 = dst.import_kv(snap)
        # scale write-set discipline BEFORE decode resumes: the
        # transferred blocks carry the source's exact scales, the
        # unwritten tail keeps the 0.0 never-written sentinel
        slot = dst.slot_req.index(rid2)
        chain = dst.slot_blocks[slot]
        nw = snap.n_blocks
        ks = np.asarray(dst.cache.k_scale)
        np.testing.assert_array_equal(ks[:, chain[:nw]],
                                      np.asarray(snap.k_scale))
        assert np.all(ks[:, chain[nw:]] == 0.0)
        out = dst.run()
        assert out[rid2] == ref          # int8 codes+scales round-trip
        assert dst.prefill_chunk_calls == 0

    def test_fingerprint_mismatch_rejected(self, setup):
        src = _batcher(setup)
        rid = src.submit(PROMPTS[0])
        snap = _export_mid_decode(src, rid)
        # wrong block size: codes would scatter misaligned
        with pytest.raises(ValueError, match="incompatible"):
            _batcher(setup, block_size=8).import_kv(snap)
        # wrong pool dtype: int8 codes are not fp values
        with pytest.raises(ValueError, match="incompatible"):
            _batcher(setup, kv_dtype="int8").import_kv(snap)

    def test_import_registers_prefix_for_siblings(self, setup):
        src = _batcher(setup, prefix_cache=True)
        rid = src.submit(PROMPTS[1])         # len 9: 2 full blocks
        snap = _export_mid_decode(src, rid)
        dst = _batcher(setup, prefix_cache=True)
        rid2 = dst.import_kv(snap)
        # registration is the IMPORT's move (pre-retire): the written
        # full blocks are already matchable on the destination index
        written = len(snap.tokens) - 1
        n_full = written // dst.bs
        assert n_full >= 1
        assert len(dst._pcache.match(snap.tokens)) == n_full
        dst.run()
        # a sibling sharing the prompt prefix admits with cached
        # tokens — prefill work it would otherwise redo
        sib = PROMPTS[1][:dst.bs] + [7, 8, 9]
        r3 = dst.submit(sib)
        out = dst.run()
        assert len(out[r3]) == MAX_NEW
        assert dst._pcache.hits >= 1
        assert dst._pcache.hit_tokens >= dst.bs

    def test_mid_decode_export_under_fused_steps(self, setup):
        dtypes = dict(fused_units=2)
        ref_cb = _batcher(setup, **dtypes)
        ra, rb = ref_cb.submit(PROMPTS[0]), ref_cb.submit(PROMPTS[2])
        refs = ref_cb.run()

        src = _batcher(setup, **dtypes)
        r0 = src.submit(PROMPTS[0])
        src.step()                       # r0 decoding
        r1 = src.submit(PROMPTS[2])      # admission lands mid-decode
        for _ in range(64):
            if src.outputs.get(r1):      # r1's prefill piggybacked
                break
            src.step()
        assert src.fused_steps >= 1      # the fused path actually ran
        assert len(src.outputs.get(r0, [])) >= 2
        snap = src.export_kv(r0)
        src.abort(r0)
        src.release(r0)
        out_src = src.run()
        assert out_src[r1] == refs[rb]   # the co-batched request is
        dst = _batcher(setup, **dtypes)  # untouched by the export
        rid2 = dst.import_kv(snap)
        assert dst.run()[rid2] == refs[ra]
        assert dst.prefill_chunk_calls == 0


class TestEngineHop:
    def test_speculative_destination_parity(self, setup):
        """An imported request on a speculative decode engine stays
        bit-identical to plain greedy: the import opts it out of the
        spec pipeline (the draft state did not travel), and spec is
        greedy-identical for native requests anyway."""
        cfg, params = setup
        ref_cb = _batcher(setup)
        r_ref = ref_cb.submit(PROMPTS[0])
        ref = ref_cb.run()[r_ref]

        src = _batcher(setup)
        rid = src.submit(PROMPTS[0])
        snap = _export_mid_decode(src, rid)
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=48,
            max_new_tokens=MAX_NEW, chunk=2, prefill_buckets=(8,),
            speculative=True, spec_k=2, start=False)
        eng.warmup()
        eng.start()
        req = eng.submit_import(snap)    # fresh pre-seeded handle
        out = req.result(timeout=300)
        eng.shutdown()
        assert out == ref
        assert eng.batcher.prefill_chunk_calls == 0
        assert eng.batcher.imported_kv == 1


class TestAffinity:
    def test_observe_repoints_migrated_chain(self, setup):
        """Unit: re-observing a chain moves every block's credit to the
        new replica — the `_place` call a snapshot import runs, so a
        migrated prefix stops steering siblings at the source."""
        idx = _AffinityIndex(4)
        toks = list(range(100, 112))     # 3 full blocks
        idx.observe(toks, 0)
        assert idx.match(toks) == {0: 12}
        idx.observe(toks, 1)             # the migration re-point
        assert idx.match(toks) == {1: 12}


class TestDisaggRouter:
    def test_end_to_end_parity_and_zero_prefill(self, setup):
        cfg, params = setup
        kw = dict(max_batch=2, block_size=4, max_total_len=48,
                  max_new_tokens=MAX_NEW, chunk=2,
                  prefill_buckets=(8,), max_queue_depth=16)
        eng = serving.ServingEngine(params, cfg, start=False, **kw)
        eng.warmup()
        eng.start()
        ref = [eng.generate(p, timeout=300) for p in PROMPTS]
        eng.shutdown()

        r = Router(params, cfg, replicas=2, disaggregated=True,
                   per_replica=[{"role": "prefill"}, {"role": "decode"}],
                   start=False, **kw)
        r.warmup()
        r.start()
        streamed = [[] for _ in PROMPTS]
        reqs = [r.submit(p, on_token=streamed[i].append)
                for i, p in enumerate(PROMPTS)]
        out = [q.result(timeout=300) for q in reqs]
        pre, dec = r.engines
        health = r.health()
        snap = r.snapshot()
        assert out == ref                    # bit-identical across hop
        # the client stream is strictly append-only across the hop:
        # every token arrived exactly once, in order
        assert streamed == out
        # MAX_NEW > 1 + chunk, so every request crosses the surrender
        # boundary and migrates exactly once
        assert health["migrations"] == len(PROMPTS)
        assert health["migration_bytes"] > 0
        assert dec.batcher.imported_kv == len(PROMPTS)
        assert dec.batcher.prefill_chunk_calls == 0
        assert pre.batcher.exported_kv == len(PROMPTS)
        assert all(e["via"] == "kv_import" and e["handoff_s"] >= 0
                   for e in snap["migration_log"])
        # prefill-role health surfaces the handoffs; the role itself
        # rides health() and load() for operators and the policy
        assert pre.health()["role"] == "prefill"
        assert dec.health()["role"] == "decode"
        # the affinity index re-pointed every migrated chain to the
        # decode replica: a decode-capable placement of a sibling
        # (what warm failover runs) now lands on replica 1
        eff = PROMPTS[0] + out[0]
        views = r._views(eff, exclude=(), roles=_DECODE_ROLES)
        assert views and views[0][1] == 1
        assert views[0][2]["affinity_tokens"] > 0
        prom = r.to_prometheus()
        assert "migrations" in prom and "migration_bytes" in prom
        r.shutdown()


class TestWarmFailover:
    def test_failover_imports_exported_kv(self, setup):
        """A replica drained for restart attaches each in-flight
        request's snapshot to the FAILED handle ("respawn_failed" when
        resume is impossible) — the router's failover predicate must
        re-place it on a survivor via `submit_import`, keeping every
        streamed token and re-prefilling nothing."""
        cfg, params = setup
        kw = dict(max_batch=2, block_size=4, max_total_len=48,
                  max_new_tokens=24, chunk=2,
                  prefill_buckets=(8,), max_queue_depth=16)
        eng = serving.ServingEngine(params, cfg, start=False, **kw)
        eng.warmup()
        eng.start()
        ref = eng.generate(PROMPTS[0], timeout=300)
        eng.shutdown()

        r = Router(params, cfg, replicas=2, start=False, **kw)
        r.warmup()
        r.start()
        got, go = threading.Event(), threading.Event()

        def on_token(_):
            got.set()
            go.wait(timeout=10.0)

        req = r.submit(PROMPTS[0], on_token=on_token)
        assert got.wait(timeout=60.0)
        victim = next(i for i, e in enumerate(r.engines)
                      if e.replica_id == req.replica_id)
        survivor = r.engines[1 - victim]
        chunks0 = survivor.batcher.prefill_chunk_calls
        go.set()
        # the supervisor's drain-and-export contract, driven by hand:
        # the victim surrenders its in-flight KV, and a respawn that
        # cannot resume fails the handle with the snapshot attached
        pairs = r.engines[victim].drain_export(timeout=10.0)
        assert len(pairs) == 1
        for s, inner in pairs:
            inner.kv_snapshot = s
            inner._finish(RequestState.FAILED, "respawn_failed")
        out = req.result(timeout=300)
        health = r.health()
        snap = r.snapshot()
        r.shutdown()
        assert out == ref                     # warm resume is bit-exact
        assert health["failovers"] == 1
        assert health["migrations"] == 1      # the warm import counted
        fo = snap["failover_log"][-1]
        assert fo["via"] == "kv_import"
        assert fo["tokens_kept"] >= 1         # streamed tokens all kept
        assert survivor.batcher.imported_kv == 1
        # zero re-prefilled tokens: the survivor never prefilled for it
        assert survivor.batcher.prefill_chunk_calls == chunks0


class TestSupervisorResume:
    def test_restart_slot_drains_exports_and_resumes(self, setup):
        """Planned rolling restart: `restart_slot` drains the serving
        engine's KV before teardown and the respawned engine adopts it
        via `submit_import` — the in-flight stream completes
        bit-identically with ZERO re-prefilled tokens (the fresh
        engine's only prefill is the readiness probe's)."""
        cfg, params = setup
        kw = dict(max_batch=2, block_size=4, max_total_len=64,
                  max_new_tokens=32, chunk=2,
                  prefill_buckets=(8,), max_queue_depth=16)
        eng = serving.ServingEngine(params, cfg, start=False, **kw)
        eng.warmup()
        eng.start()
        ref = eng.generate(PROMPTS[0], timeout=300)
        eng.shutdown()

        r = Router(params, cfg, replicas=2, auto_restart=True,
                   start=False, **kw)
        r.warmup()
        r.start()
        got, go = threading.Event(), threading.Event()

        def on_token(_):
            got.set()
            go.wait(timeout=10.0)

        req = r.submit(PROMPTS[0], on_token=on_token)
        assert got.wait(timeout=60.0)
        victim = next(i for i, e in enumerate(r.engines)
                      if e.replica_id == req.replica_id)
        old = r.engines[victim]
        go.set()
        assert r._supervisor.restart_slot(victim)
        out = req.result(timeout=300)
        # wait for the slot to finish rejoining before inspecting it
        deadline = 60.0
        while r._supervisor.states()[victim] != "SERVING" and deadline:
            threading.Event().wait(0.05)
            deadline -= 0.05
        fresh = r.engines[victim]
        health = r.health()
        r.shutdown()
        assert out == ref                     # resumed stream bit-exact
        assert fresh is not old               # the slot was respawned
        assert health["replica_restarts"] == 1
        assert fresh.batcher.imported_kv >= 1
        # the fresh engine's ONLY prefill is the readiness probe's
        # single chunk — the resumed request re-prefilled nothing
        assert fresh.batcher.prefill_chunk_calls == 1
