"""shard_map composition of the Pallas ragged kernel (PR 20): each
device runs the existing `ragged_paged_attention` kernel on its
KV-head shard of the paged pool — block tables, per-row positions and
validity replicated, int8 scales riding scalar prefetch per shard —
and GSPMD stitches the per-shard outputs on the head axis. Interpret
mode over the conftest's forced host devices, tp ∈ {1, 2, 4}, across
every ragged shape the serving path produces: decode rows, bucketed
prefill rows, block-boundary straddles, int8 KV scales and the spec
verify's suffix-slab operand.

Two claims per shape:

  * STITCH EXACTNESS — the mesh'd kernel output is BIT-identical to
    concatenating mesh-off kernel runs over each shard's contiguous
    head slice. shard_map adds zero numerics: the mesh only stitches,
    and the GQA head→kv-head mapping survives contiguous slicing
    because the grouping ratio is constant per shard.
  * REFERENCE PARITY — the mesh'd kernel matches the XLA gather
    reference at the parity suite's online-softmax tolerance, exactly
    like the mesh-off kernel does in test_ragged_attention.py.

Bitwise equality is asserted against the per-shard-slice runs, NOT
against the mesh-off full-width kernel: elementwise ops are
shape-sensitive at the last ulp in interpret mode (SIMD lane packing
over differently-sized buffers), so full-width vs sliced can drift by
~1 ulp while serving-level greedy TOKENS stay bit-identical — that
end-to-end claim is gated by tests/test_tp_serving.py and the bench
`--tp --speculative --attention-impl pallas` composition leg.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.nlp import paged
from paddle_tpu.nlp.ragged_attention import (_shard_specs,
                                             ragged_paged_attention)
from paddle_tpu.quantization import kv as kvq
from paddle_tpu.serving.speculative import SpecConfig

# H=8 / KV=4 so the head axes divide every tp under test (tp=4 needs
# KV % 4 == 0 — the same constraint MeshConfig.validate_for enforces
# on a real model config)
N, BS, KV, HD, H, M = 12, 4, 4, 8, 8, 5
TPS = (1, 2, 4)


def _mesh(tp):
    return Mesh(np.asarray(jax.devices()[:tp]), ("mp",))


def _pools(seed):
    rng = np.random.RandomState(seed)
    kp = jnp.asarray(rng.randn(N, BS, KV, HD), jnp.float32)
    vp = jnp.asarray(rng.randn(N, BS, KV, HD), jnp.float32)
    return rng, kp, vp


def _chains(rng, lengths):
    """Distinct live block chains per row, padded table entries -> 0."""
    table = np.zeros((len(lengths), M), np.int32)
    free = list(rng.permutation(np.arange(1, N)))
    for r, L in enumerate(lengths):
        for j in range(-(-L // BS) if L else 0):
            table[r, j] = free.pop()
    return jnp.asarray(table)


def _suffix_qpv(lengths, Pq):
    """Suffix-style positions/validity: row r's Pq queries end at
    position lengths[r]-1 (shorter rows left-pad as invalid)."""
    R = len(lengths)
    pos = np.zeros((R, Pq), np.int32)
    val = np.zeros((R, Pq), np.bool_)
    for r, L in enumerate(lengths):
        for p in range(Pq):
            j = L - Pq + p
            pos[r, p] = min(max(j, 0), M * BS - 1)
            val[r, p] = 0 <= j
    return jnp.asarray(pos), jnp.asarray(val)


def _q(rng, R, Pq):
    return jnp.asarray(rng.randn(R, Pq, H, HD), jnp.float32)


def _quantize(kp, vp):
    ks = jnp.max(jnp.abs(kp), axis=(1, 2, 3)) / kvq.BOUND
    vs = jnp.max(jnp.abs(vp), axis=(1, 2, 3)) / kvq.BOUND
    return (kvq.quantize(kp, ks[:, None, None, None]),
            kvq.quantize(vp, vs[:, None, None, None]), ks, vs)


def _hslice(a, s, tp):
    """Shard s's contiguous slice of a [.., .., heads, hd] operand."""
    w = a.shape[2] // tp
    return a[:, :, s * w:(s + 1) * w]


def _check(tp, q, kp, vp, table, pos, val, **kw):
    """Mesh'd kernel == concat of per-shard-slice runs (bit-exact)
    and == the XLA gather reference (parity tolerance)."""
    out = np.asarray(ragged_paged_attention(
        q, kp, vp, table, pos, val, mesh=_mesh(tp), **kw))
    shards = []
    for s in range(tp):
        skw = dict(kw)
        if "suffix_k" in kw:
            skw["suffix_k"] = _hslice(kw["suffix_k"], s, tp)
            skw["suffix_v"] = _hslice(kw["suffix_v"], s, tp)
        shards.append(np.asarray(ragged_paged_attention(
            _hslice(q, s, tp), _hslice(kp, s, tp), _hslice(vp, s, tp),
            table, pos, val, **skw)))
    np.testing.assert_array_equal(out, np.concatenate(shards, 2))
    if "suffix_k" not in kw:
        ref = paged._paged_gqa_attention(
            q, kp, vp, table, pos, k_scale=kw.get("k_scale"),
            v_scale=kw.get("v_scale"))
        ref = np.where(np.asarray(val)[:, :, None, None],
                       np.asarray(ref), 0.0)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    return out


@pytest.mark.parametrize("tp", TPS)
class TestShardMapParity:
    def test_decode_rows(self, tp):
        """P=1 decode rows at heterogeneous live lengths — the
        steady-state decode shape every mesh'd step runs."""
        rng, kp, vp = _pools(40)
        lengths = [1, 6, 17, 9]
        table = _chains(rng, lengths)
        pos, val = _suffix_qpv(lengths, 1)
        _check(tp, _q(rng, 4, 1), kp, vp, table, pos, val)

    def test_bucketed_prefill_rows(self, tp):
        """P=8 bucket-padded suffix rows: the invalid left-pad must
        stay zero on every shard independently."""
        rng, kp, vp = _pools(41)
        lengths = [3, 11, 19]
        table = _chains(rng, lengths)
        pos, val = _suffix_qpv(lengths, 8)
        _check(tp, _q(rng, 3, 8), kp, vp, table, pos, val)

    def test_block_boundary_straddle(self, tp):
        """length == block_size exactly / one past it: every shard's
        chain walk must include the boundary block's last key and not
        step into the next (garbage) table entry."""
        rng, kp, vp = _pools(42)
        lengths = [BS, 2 * BS, BS + 1]
        table = _chains(rng, lengths)
        pos, val = _suffix_qpv(lengths, 1)
        _check(tp, _q(rng, 3, 1), kp, vp, table, pos, val)

    def test_int8_kv_scales(self, tp):
        """int8 pool codes shard on the head axis while the per-block
        scales ride scalar prefetch REPLICATED — every shard
        dequantizes its slice with the same [N] scale vectors."""
        rng, kp, vp = _pools(43)
        kq, vq, ks, vs = _quantize(kp, vp)
        lengths = [3, BS, 13]
        table = _chains(rng, lengths)
        pos, val = _suffix_qpv(lengths, 1)
        _check(tp, _q(rng, 3, 1), kq, vq, table, pos, val,
               k_scale=ks, v_scale=vs)

    def test_suffix_slab_direct(self, tp):
        """The spec verify's suffix-slab operand through the kernel
        directly: the in-register slab shards on its kv-head axis
        alongside the pool, the ancestor-visibility mask replicates."""
        rng, kp, vp = _pools(44)
        sc = SpecConfig(tree=[2, 1, 1])
        vis = jnp.asarray(sc.ancestor_mask())
        S = vis.shape[0]
        lengths = [2, 9, 14]
        table = _chains(rng, lengths)
        pos = jnp.asarray([[L + i for i in range(S)] for L in lengths],
                          jnp.int32)
        val = jnp.ones((3, S), bool)
        sk = jnp.asarray(rng.randn(3, S, KV, HD), jnp.float32)
        sv = jnp.asarray(rng.randn(3, S, KV, HD), jnp.float32)
        _check(tp, _q(rng, 3, S), kp, vp, table, pos, val,
               suffix_k=sk, suffix_v=sv,
               suffix_vis=jnp.broadcast_to(vis, (3, S, S)))

    def test_suffix_slab_spec_path(self, tp):
        """The verify path itself (_spec_gqa_attention): mesh'd pallas
        == concat of per-shard pallas runs (bit) == the XLA concat
        reference (tolerance), chain triangle AND packed tree."""
        rng, kp, vp = _pools(45)
        lens = [2, 9, 14]
        base = jnp.asarray(lens, jnp.int32)
        table = _chains(rng, lens)
        for sc in (SpecConfig(k=3), SpecConfig(tree=[2, 1, 1])):
            vis = jnp.asarray(sc.ancestor_mask())
            S = vis.shape[0]
            sk = jnp.asarray(rng.randn(3, S, KV, HD), jnp.float32)
            sv = jnp.asarray(rng.randn(3, S, KV, HD), jnp.float32)
            q = _q(rng, 3, S)
            out = np.asarray(paged._spec_gqa_attention(
                q, kp, vp, table, base, sk, sv, vis,
                impl="pallas", mesh=_mesh(tp)))
            shards = [np.asarray(paged._spec_gqa_attention(
                _hslice(q, s, tp), _hslice(kp, s, tp),
                _hslice(vp, s, tp), table, base,
                _hslice(sk, s, tp), _hslice(sv, s, tp), vis,
                impl="pallas")) for s in range(tp)]
            np.testing.assert_array_equal(
                out, np.concatenate(shards, 2))
            ref = np.asarray(paged._spec_gqa_attention(
                q, kp, vp, table, base, sk, sv, vis, impl="xla"))
            np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


class TestShardSpecs:
    def test_operand_specs(self):
        """_shard_specs mirrors the kernel's operand order exactly:
        scalar-prefetch operands (table, live, scales) and positions/
        validity replicate; q, the pools and the slab shard on their
        head axis; the visibility mask replicates."""
        head = P(None, None, "mp", None)
        repl = P()
        specs, out = _shard_specs("mp", False, False)
        assert specs == (repl, repl, repl, repl, head, head, head)
        assert out == head
        specs, _ = _shard_specs("mp", True, False)
        assert specs == (repl, repl, repl, repl, repl, repl,
                         head, head, head)
        specs, _ = _shard_specs("mp", True, True)
        assert len(specs) == 12 and specs[-3:] == (head, head, repl)

    def test_indivisible_heads_rejected(self):
        """H=8/KV=4 on a 3-wide axis: the kernel refuses loudly at
        trace time instead of silently mis-slicing."""
        rng, kp, vp = _pools(46)
        table = _chains(rng, [5])
        pos, val = _suffix_qpv([5], 1)
        with pytest.raises(ValueError, match="must divide"):
            ragged_paged_attention(_q(rng, 1, 1), kp, vp, table, pos,
                                   val, mesh=_mesh(3))
