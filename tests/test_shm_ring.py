"""Native shared-memory ring buffer (io/native/shm_ring.cc) tests.

Covers: codec round-trip, single/multi-producer transport, chunking of
messages larger than a slot, stop semantics, and the DataLoader
use_shared_memory integration (multiprocess workers feeding the ring).
"""
import multiprocessing as mp

import numpy as np
import pytest

from paddle_tpu.io import shm_ring
from paddle_tpu.io.shm_ring import ShmRing, decode, encode

pytestmark = pytest.mark.skipif(
    not shm_ring.native_available(),
    reason="native shm_ring lib unavailable (no g++ or /dev/shm)")


class TestCodec:
    def round_trip(self, obj):
        buf = bytearray()
        encode(obj, buf)
        return decode(buf)

    def test_scalars_and_strings(self):
        for obj in [1, -7, 3.5, True, False, None, "héllo", b"\x00\xff"]:
            assert self.round_trip(obj) == obj

    def test_arrays(self):
        for dt in ["float32", "int64", "uint8", "bool", "float16"]:
            a = (np.arange(24).reshape(2, 3, 4) % 2).astype(dt)
            out = self.round_trip(a)
            assert out.dtype == a.dtype and out.shape == a.shape
            np.testing.assert_array_equal(out, a)

    def test_nested_tree(self):
        obj = {"x": [np.ones((4, 5), np.float32), 3],
               "y": (None, {"z": np.arange(6)}), "s": "label"}
        out = self.round_trip(obj)
        np.testing.assert_array_equal(out["x"][0], obj["x"][0])
        assert out["x"][1] == 3 and out["y"][0] is None
        np.testing.assert_array_equal(out["y"][1]["z"], obj["y"][1]["z"])
        assert out["s"] == "label"

    def test_numpy_scalar_types_preserved(self):
        # must match the queue transport: np scalars keep their exact type
        for s in [np.float32(1.5), np.float16(2.0), np.int32(7),
                  np.uint8(255), np.bool_(True)]:
            out = self.round_trip(s)
            assert type(out) is type(s) and out == s

    def test_pickle_fallback(self):
        err = ValueError("boom")
        out = self.round_trip((1, None, err))
        assert isinstance(out[2], ValueError) and out[2].args == ("boom",)

    def test_object_and_structured_dtypes(self):
        # raw transport can't carry these; codec must pickle-fallback
        a = np.empty(3, dtype=object)
        a[:] = [(1, 2), "x", None]
        out = self.round_trip(a)
        assert out.dtype == object and list(out) == [(1, 2), "x", None]
        s = np.array([(1.5, 2)], dtype=[("x", "f4"), ("y", "i8")])
        out = self.round_trip(s)
        assert out.dtype.fields is not None
        assert out["x"][0] == np.float32(1.5) and out["y"][0] == 2

    def test_array_alignment(self):
        # decode must produce aligned views regardless of header sizes
        a = np.arange(7, dtype=np.float64)
        obj = {"pad": "x" * 3, "a": a}
        out = self.round_trip(obj)
        np.testing.assert_array_equal(out["a"], a)


def _producer(name, start, count):
    ring = ShmRing.attach(name)
    for i in range(start, start + count):
        ring.send(i, {"i": i, "data": np.full((32,), i, np.int32)})
    ring.close()


class TestRing:
    def test_inprocess_round_trip(self):
        ring = ShmRing(slot_bytes=4096, n_slots=4)
        ring.send(7, [np.arange(10), "ok"])
        msg_id, obj = ring.recv(timeout_ms=2000)
        assert msg_id == 7
        np.testing.assert_array_equal(obj[0], np.arange(10))
        assert obj[1] == "ok"
        ring.close(unlink=True)

    def test_chunking_large_message(self):
        ring = ShmRing(slot_bytes=1024, n_slots=4)
        big = np.random.default_rng(0).integers(0, 255, 10_000).astype(np.uint8)
        import threading
        t = threading.Thread(target=ring.send, args=(1, big))
        t.start()
        msg_id, out = ring.recv(timeout_ms=5000)
        t.join()
        assert msg_id == 1
        np.testing.assert_array_equal(out, big)
        ring.close(unlink=True)

    def test_multiprocess_producers(self):
        ring = ShmRing(slot_bytes=8192, n_slots=8)
        ctx = mp.get_context("fork")
        procs = [ctx.Process(target=_producer, args=(ring.name, w * 100, 5))
                 for w in range(3)]
        for p in procs:
            p.start()
        got = {}
        for _ in range(15):
            msg_id, obj = ring.recv(timeout_ms=10000)
            got[msg_id] = obj
        for p in procs:
            p.join(timeout=5)
        assert set(got) == {w * 100 + i for w in range(3) for i in range(5)}
        for msg_id, obj in got.items():
            assert obj["i"] == msg_id
            np.testing.assert_array_equal(
                obj["data"], np.full((32,), msg_id, np.int32))
        ring.close(unlink=True)

    def test_recv_timeout(self):
        ring = ShmRing(slot_bytes=1024, n_slots=2)
        assert ring.recv(timeout_ms=50) is None
        ring.close(unlink=True)

    def test_stop_unblocks_producer(self):
        ring = ShmRing(slot_bytes=1024, n_slots=2)
        # fill all slots so the next acquire would block
        ring.send_bytes(0, b"x" * 100)
        ring.send_bytes(1, b"y" * 100)
        import threading
        errs = []

        def blocked():
            try:
                ring.send_bytes(2, b"z" * 100)
            except RuntimeError as e:
                errs.append(e)

        t = threading.Thread(target=blocked)
        t.start()
        import time
        time.sleep(0.1)
        ring.stop()
        t.join(timeout=5)
        assert not t.is_alive() and errs
        ring.close(unlink=True)


class TestDataLoaderShm:
    def _loader(self, **kw):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return np.full((8,), i, np.float32), i

        return DataLoader(DS(), batch_size=4, num_workers=2,
                          use_shared_memory=True, **kw)

    def test_shm_transport_in_order(self):
        loader = self._loader()
        it = iter(loader)
        assert it.ring is not None  # shm path actually active
        batches = list(it)
        assert len(batches) == 4
        for b, (xs, ys) in enumerate(batches):
            np.testing.assert_array_equal(
                np.asarray(ys), np.arange(4 * b, 4 * b + 4))
            np.testing.assert_allclose(
                np.asarray(xs)[:, 0], np.arange(4 * b, 4 * b + 4))

    def test_worker_error_via_ring(self):
        from paddle_tpu.io import DataLoader, Dataset

        class Bad(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("bad sample")
                return np.zeros(2, np.float32)

        loader = DataLoader(Bad(), batch_size=2, num_workers=2,
                            use_shared_memory=True)
        with pytest.raises(ValueError, match="bad sample"):
            list(loader)

    def test_unpicklable_worker_error_does_not_hang(self):
        from paddle_tpu.io import DataLoader, Dataset

        class Evil(Exception):
            def __reduce__(self):
                raise TypeError("cannot pickle me")

        class Bad(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                if i == 2:
                    raise Evil("boom")
                return np.zeros(2, np.float32)

        loader = DataLoader(Bad(), batch_size=2, num_workers=2,
                            use_shared_memory=True)
        with pytest.raises(RuntimeError, match="Evil"):
            list(loader)
