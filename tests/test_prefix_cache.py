"""paddle_tpu.serving.cache — refcounted prefix caching of KV blocks.

Three layers of coverage, cheapest first:

  * RefcountingBlockAllocator units — share/release refcount lifecycle,
    double-free detection, cached-LRU parking/revival, eviction order
    and callback;
  * PrefixCacheIndex units — trie match/insert/evict semantics,
    first-writer-wins dedup, orphaned-subtree eviction (no jax needed);
  * ContinuousBatcher integration — warm admissions are token-identical
    to cold ones (partial-prefix share, in-flight share, and the
    copy-on-write full-hit), eviction under pool pressure stays
    correct, and the cached-aware defer logic admits a request the
    naive block count would refuse.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, paged
from paddle_tpu.serving.cache import PrefixCacheIndex


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestRefcountingAllocator:
    def test_allocate_release_lifecycle(self):
        alloc = paged.RefcountingBlockAllocator(4)
        blocks = alloc.allocate(2)
        assert all(alloc.refcount(b) == 1 for b in blocks)
        assert alloc.free_blocks == 2
        alloc.share(blocks)                      # second holder
        assert all(alloc.refcount(b) == 2 for b in blocks)
        alloc.release(blocks)                    # first holder gone
        assert all(alloc.refcount(b) == 1 for b in blocks)
        assert alloc.free_blocks == 2            # still referenced
        alloc.release(blocks)                    # unmarked → plain free
        assert alloc.free_blocks == 4
        assert alloc.stats()["blocks_in_use"] == 0

    def test_double_release_raises(self):
        alloc = paged.RefcountingBlockAllocator(2)
        b = alloc.allocate(1)
        alloc.release(b)
        with pytest.raises(ValueError, match="double free"):
            alloc.release(b)
        with pytest.raises(ValueError, match="out of range"):
            alloc.release([9])
        with pytest.raises(ValueError, match="double free"):
            alloc.free(b)                        # free() is release()

    def test_share_requires_live_or_cached(self):
        alloc = paged.RefcountingBlockAllocator(2)
        with pytest.raises(ValueError, match="neither"):
            alloc.share([0])                     # free block: contents dead

    def test_cached_parking_and_revival(self):
        alloc = paged.RefcountingBlockAllocator(2)
        b = alloc.allocate(1)
        alloc.mark_cached(b)
        alloc.release(b)
        assert alloc.is_cached(b[0])
        assert alloc.free_blocks == 2            # cached counts as free
        assert alloc.stats()["blocks_in_use"] == 0
        assert alloc.stats()["cached_blocks"] == 1
        alloc.share(b)                           # revive: contents kept
        assert alloc.refcount(b[0]) == 1
        assert not alloc.is_cached(b[0])
        alloc.release(b)
        assert alloc.is_cached(b[0])             # still cacheable

    def test_lru_eviction_order_and_callback(self):
        evicted = []
        alloc = paged.RefcountingBlockAllocator(3, on_evict=evicted.append)
        blocks = alloc.allocate(3)
        alloc.mark_cached(blocks)
        for b in blocks:                         # park in order: LRU = first
            alloc.release([b])
        got = alloc.allocate(2)                  # must evict 2 LRU blocks
        assert evicted == blocks[:2]
        assert sorted(got) == sorted(blocks[:2])
        assert alloc.evicted_blocks == 2
        assert alloc.is_cached(blocks[2])        # newest survives

    def test_allocate_prefers_free_over_cached(self):
        alloc = paged.RefcountingBlockAllocator(3, on_evict=lambda b: None)
        b = alloc.allocate(1)
        alloc.mark_cached(b)
        alloc.release(b)
        alloc.allocate(2)                        # two truly-free blocks
        assert alloc.is_cached(b[0])             # cache untouched
        assert alloc.evicted_blocks == 0

    def test_exhaustion_counts_cached(self):
        alloc = paged.RefcountingBlockAllocator(2)
        alloc.allocate(2)
        with pytest.raises(RuntimeError, match="pool exhausted"):
            alloc.allocate(1)

    def test_release_never_half_applies(self):
        """A bad id anywhere in the list must leave EVERY refcount
        untouched — a half-applied release followed by a caller retry
        would decref the good blocks twice."""
        alloc = paged.RefcountingBlockAllocator(4)
        good = alloc.allocate(2)
        with pytest.raises(ValueError, match="out of range"):
            alloc.release([good[0], 99])
        with pytest.raises(ValueError, match="double free"):
            alloc.release([good[0], good[0]])    # dup exceeds refcount 1
        assert all(alloc.refcount(b) == 1 for b in good)
        alloc.release(good)                      # clean retry succeeds
        assert alloc.free_blocks == 4

    def test_share_never_half_applies(self):
        alloc = paged.RefcountingBlockAllocator(4)
        good = alloc.allocate(1)
        with pytest.raises(ValueError, match="neither"):
            alloc.share([good[0], 2])            # 2 is free: dead contents
        assert alloc.refcount(good[0]) == 1      # bump not applied


class TestPrefixCacheIndex:
    def test_match_insert_roundtrip(self):
        idx = PrefixCacheIndex(4)
        toks = list(range(100, 112))             # 3 full blocks
        assert idx.match(toks) == []
        assert idx.insert(toks, [7, 8, 9]) == [7, 8, 9]
        assert idx.match(toks) == [7, 8, 9]
        assert idx.match(toks[:8]) == [7, 8]     # prefix of the chain
        assert idx.match(toks[:7]) == [7]        # partial block ignored
        assert idx.match(toks[:3]) == []
        # same first block, divergent second
        other = toks[:4] + [1, 2, 3, 4]
        assert idx.match(other) == [7]

    def test_insert_first_writer_wins(self):
        idx = PrefixCacheIndex(2)
        assert idx.insert([1, 2], [0]) == [0]
        assert idx.insert([1, 2, 3, 4], [5, 6]) == [6]   # block 5 dropped
        assert idx.match([1, 2, 3, 4]) == [0, 6]         # incumbent kept

    def test_insert_rejects_partial_blocks(self):
        idx = PrefixCacheIndex(4)
        with pytest.raises(ValueError, match="full blocks"):
            idx.insert([1, 2, 3], [0])

    def test_evict_unlinks_and_orphans_descendants(self):
        idx = PrefixCacheIndex(2)
        idx.insert([1, 2, 3, 4, 5, 6], [0, 1, 2])
        idx.evict(1)                             # middle of the chain
        assert idx.match([1, 2, 3, 4, 5, 6]) == [0]      # stops at hole
        assert len(idx) == 2                     # 0 and orphaned 2 remain
        idx.evict(2)                             # orphan still evictable
        assert len(idx) == 1
        idx.evict(2)                             # idempotent
        assert idx.evicted_blocks == 2

    def test_admission_stats(self):
        idx = PrefixCacheIndex(4)
        idx.note_admission(10, 8)
        idx.note_admission(10, 0)
        s = idx.stats()
        assert (s["hits"], s["misses"]) == (1, 1)
        assert s["hit_tokens"] == 8 and s["prompt_tokens"] == 20
        assert idx.hit_rate == pytest.approx(0.4)


def _cold_run(params, cfg, prompts, max_new=6, **kw):
    cb = paged.ContinuousBatcher(
        params, cfg, max_batch=2, block_size=4, max_total_len=32,
        max_new_tokens=max_new, chunk=3, **kw)
    rids = [cb.submit(p) for p in prompts]
    out = cb.run()
    return [out[r] for r in rids], cb


class TestBatcherPrefixCache:
    """Acceptance: prefix-cached generation is token-identical to
    cold-cache generation, for partial shares, in-flight shares, and
    the COW full-hit — and the stats prove blocks were actually
    shared, not recomputed."""

    def test_shared_prefix_matches_cold(self, setup):
        cfg, params = setup
        rng = np.random.RandomState(11)
        common = list(map(int, rng.randint(1, 200, 8)))  # 2 full blocks
        prompts = [common + list(map(int, rng.randint(1, 200, n)))
                   for n in (3, 5, 2)]
        cold, _ = _cold_run(params, cfg, prompts)
        warm, cb = _cold_run(params, cfg, prompts, prefix_cache=True)
        assert warm == cold
        st = cb.prefix_stats()
        assert st["hits"] >= 2 and st["hit_tokens"] >= 16
        assert st["hit_rate"] > 0
        # drained: nothing referenced, prefix blocks parked as cached
        astats = cb.alloc.stats()
        assert astats["blocks_in_use"] == 0
        assert astats["cached_blocks"] > 0

    def test_full_hit_cow_matches_cold(self, setup):
        """A prompt that is ENTIRELY cached (length a multiple of
        block_size, served before) goes down the copy-on-write path:
        the final shared block is cloned and only the last token is
        recomputed — output must still be token-identical."""
        cfg, params = setup
        rng = np.random.RandomState(12)
        p = list(map(int, rng.randint(1, 200, 8)))       # exactly 2 blocks
        cold, _ = _cold_run(params, cfg, [p])
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=6, chunk=3, prefix_cache=True)
        r1 = cb.submit(p)
        cb.run()
        hit0 = cb.prefix_stats()["hit_tokens"]
        r2 = cb.submit(p)                                # full hit → COW
        out = cb.run()
        assert out[r1] == cold[0]
        assert out[r2] == cold[0]
        # COW caps the cached prefix at P-1 (last token recomputed)
        assert cb.prefix_stats()["hit_tokens"] - hit0 == len(p) - 1
        assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_generated_tokens_are_cached_too(self, setup):
        """Retirement registers FULL blocks of prompt+generated KV: a
        follow-up prompt equal to prompt+generated (the multi-turn
        pattern) hits past the original prompt length."""
        cfg, params = setup
        rng = np.random.RandomState(13)
        p = list(map(int, rng.randint(1, 200, 6)))
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=6, chunk=3, prefix_cache=True)
        r1 = cb.submit(p)
        out1 = cb.run()[r1]
        # turn 2: the conversation so far + a fresh user turn
        p2 = p + out1 + list(map(int, rng.randint(1, 200, 3)))
        hit0 = cb.prefix_stats()["hit_tokens"]
        r2 = cb.submit(p2)
        out2 = cb.run()[r2]
        # written KV covered prompt + all-but-last generated token →
        # (6 + 6 - 1) // 4 = 2 full blocks were registered
        assert cb.prefix_stats()["hit_tokens"] - hit0 == 8
        cold, _ = _cold_run(params, cfg, [p2])
        assert out2 == cold[0]

    def test_eviction_under_pool_pressure(self, setup):
        """A pool too small to cache every retired request evicts LRU
        cached blocks (never referenced ones) and keeps serving
        correctly."""
        cfg, params = setup
        rng = np.random.RandomState(14)
        prompts = [list(map(int, rng.randint(1, 200, 8)))
                   for _ in range(4)]
        # 3 blocks per request (8 prompt + 4 new @ bs=4); pool of 6
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=16,
            max_new_tokens=4, chunk=2, num_blocks=6, prefix_cache=True)
        rids = [cb.submit(p) for p in prompts]
        out = cb.run()
        assert cb.prefix_stats()["evictions"] > 0
        cold, _ = _cold_run(params, cfg, prompts, max_new=4)
        for r, c in zip(rids, cold):
            assert out[r] == c
        assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_cached_aware_defer_admits_on_shared_blocks(self, setup):
        """blocks_needed(tokens=...) discounts blocks pinned by an
        in-flight prefix sibling: two 11-token-prompt requests (5 blocks
        each cold) sharing 2 full blocks fit TOGETHER in an 8-block pool
        that could not hold two cold copies (2*5 > 8)."""
        cfg, params = setup
        rng = np.random.RandomState(15)
        common = list(map(int, rng.randint(1, 200, 8)))
        prompts = [common + list(map(int, rng.randint(1, 200, 3)))
                   for _ in range(2)]
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=6, chunk=3, num_blocks=8, prefix_cache=True)
        r1, r2 = [cb.submit(p) for p in prompts]
        cb.step()                  # admits both (second shares 2 blocks)
        assert cb.active == [True, True]
        assert cb.alloc.stats()["blocks_in_use"] == 8    # 5 + 3 distinct
        out = cb.run()
        cold, _ = _cold_run(params, cfg, prompts)
        assert [out[r1], out[r2]] == cold

    def test_full_hit_cow_degrades_in_exactly_full_pool(self, setup):
        """Regression: a whole-prompt hit whose COW source is cached
        transiently needs one pool unit MORE than blocks_needed()
        promises the defer check. In a pool sized exactly for one
        request that must NOT raise 'pool exhausted' — admission
        degrades to recomputing the final block and still serves
        token-identically."""
        cfg, params = setup
        rng = np.random.RandomState(17)
        p = list(map(int, rng.randint(1, 200, 8)))   # 2 full blocks
        cold, _ = _cold_run(params, cfg, [p], max_new=4)
        # 8 prompt + 4 new @ bs 4 → exactly 3 blocks, pool of 3
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=4, max_total_len=16,
            max_new_tokens=4, chunk=2, num_blocks=3, prefix_cache=True)
        r1 = cb.submit(p)
        out1 = cb.run()[r1]
        r2 = cb.submit(p)                            # full hit, no headroom
        out2 = cb.run()[r2]
        assert out1 == cold[0] and out2 == cold[0]
        assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_mixed_lengths_still_batch(self, setup):
        """Warm and cold slots co-decode in one chunk: one request with
        a cached prefix, one without, both match their cold runs."""
        cfg, params = setup
        rng = np.random.RandomState(16)
        shared = list(map(int, rng.randint(1, 200, 8)))
        fresh = list(map(int, rng.randint(1, 200, 9)))
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=6, chunk=3, prefix_cache=True)
        r0 = cb.submit(shared + [7, 8])
        cb.run()
        r1 = cb.submit(shared + [9, 10, 11])     # warm
        r2 = cb.submit(fresh)                    # cold, co-batched
        out = cb.run()
        cold, _ = _cold_run(params, cfg,
                            [shared + [7, 8], shared + [9, 10, 11], fresh])
        assert [out[r0], out[r1], out[r2]] == cold
