"""paddle.distributed.launch CLI: env wiring + elastic restart.

Reference analog: launch controller tests (SURVEY.md §2.3 launch row) — the
subprocess-on-localhost pattern from §4.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(tmp_path, script_body, extra_args=()):
    script = tmp_path / "train.py"
    script.write_text(script_body)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PADDLE_TRAINER_ID", None)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log"), *extra_args, str(script)],
        env=env, capture_output=True, text=True, timeout=120)


class TestLaunch:
    def test_env_wiring(self, tmp_path):
        r = _run_launch(tmp_path, (
            "import os\n"
            "assert os.environ['PADDLE_TRAINERS_NUM'] == '2'\n"
            "assert os.environ['PADDLE_TRAINER_ID'] == '1'\n"
            "assert os.environ['PADDLE_MASTER'] == 'h0:8090'\n"
            "assert os.environ['JAX_COORDINATOR_ADDRESS'] == 'h0:8090'\n"),
            extra_args=["--nnodes", "2", "--rank", "1",
                        "--master", "h0:8090"])
        assert r.returncode == 0, r.stderr

    def test_elastic_restart_resumes(self, tmp_path):
        marker = tmp_path / "marker"
        r = _run_launch(tmp_path, (
            f"import os, sys\n"
            f"m = {str(marker)!r}\n"
            f"if not os.path.exists(m):\n"
            f"    open(m, 'w').close(); sys.exit(1)\n"
            f"print('resumed')\n"),
            extra_args=["--max_restarts", "2"])
        assert r.returncode == 0, r.stderr
        assert "elastic restart 1/2" in r.stderr + r.stdout
        logs = list((tmp_path / "log").glob("workerlog.0.restart1"))
        assert logs and "resumed" in logs[0].read_text()

    def test_restart_budget_exhausted(self, tmp_path):
        r = _run_launch(tmp_path, "import sys; sys.exit(3)\n",
                        extra_args=["--max_restarts", "1"])
        assert r.returncode == 3
        assert "1 restarts used" in r.stderr


class TestFailureDetection:
    """VERDICT r1 item 10: exit-code/signal classification + heartbeat
    watchdog (the coordination-service-loss analog) + restart-with-resume.
    Reference: fleet/elastic's ElasticManager watch loop (SURVEY.md §5)."""

    def test_classify_exit(self):
        from paddle_tpu.distributed.launch import classify_exit
        assert classify_exit(0) == ("ok", False)
        assert classify_exit(2) == ("usage", False)
        kind, restart = classify_exit(-9)
        assert "oom" in kind and restart
        kind, restart = classify_exit(-11)
        assert "SIGSEGV" in kind and restart
        kind, restart = classify_exit(1, "...DEADLINE_EXCEEDED: heartbeat"
                                         " to coordination service lost...")
        assert kind.startswith("coord") and restart
        assert classify_exit(1) == ("error", True)

    def test_heartbeat_helper(self, tmp_path, monkeypatch):
        from paddle_tpu.distributed.launch import heartbeat
        hb = tmp_path / "hb"
        monkeypatch.delenv("PADDLE_HEARTBEAT_FILE", raising=False)
        heartbeat()  # no env set: must be a no-op, not an error
        assert not hb.exists()
        monkeypatch.setenv("PADDLE_HEARTBEAT_FILE", str(hb))
        heartbeat()
        assert hb.exists()

    def test_signal_death_classified_and_restarted(self, tmp_path):
        """Child killing itself with SIGKILL (the OOM-killer signature) is
        classified and restarted."""
        marker = tmp_path / "marker"
        r = _run_launch(tmp_path, (
            f"import os, signal\n"
            f"m = {str(marker)!r}\n"
            f"if not os.path.exists(m):\n"
            f"    open(m, 'w').close()\n"
            f"    os.kill(os.getpid(), signal.SIGKILL)\n"
            f"print('resumed after kill')\n"),
            extra_args=["--max_restarts", "1"])
        assert r.returncode == 0, r.stderr
        assert "oom-or-killed (SIGKILL)" in r.stderr

    def test_heartbeat_watchdog_kills_hung_worker_and_resumes(self, tmp_path):
        """A worker that stops beating (stuck collective / lost
        coordination service) is killed by the watchdog and restarted;
        the restart resumes from the checkpoint the first attempt wrote."""
        ckpt = tmp_path / "ckpt.txt"
        # the child beats via the env-file contract directly (importing the
        # full paddle_tpu package would outlast the short test timeout);
        # the heartbeat() helper itself is unit-tested below
        r = _run_launch(tmp_path, (
            f"import os, time\n"
            f"beat = lambda: open(os.environ['PADDLE_HEARTBEAT_FILE'],"
            f" 'w').write('x')\n"
            f"ck = {str(ckpt)!r}\n"
            f"start = int(open(ck).read()) if os.path.exists(ck) else 0\n"
            f"for step in range(start, 6):\n"
            f"    beat()\n"
            f"    open(ck, 'w').write(str(step + 1))\n"
            f"    if step == 2 and start == 0:\n"
            f"        time.sleep(3600)  # hang: no more beats\n"
            f"print('done at', int(open(ck).read()))\n"),
            extra_args=["--max_restarts", "1",
                        "--heartbeat_timeout", "3"])
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert "no heartbeat" in r.stderr
        assert "hung (heartbeat lost)" in r.stderr
        assert ckpt.read_text() == "6"
        logs = list((tmp_path / "log").glob("workerlog.0.restart1"))
        assert logs and "done at 6" in logs[0].read_text()
