"""paddle.distributed.launch CLI: env wiring + elastic restart.

Reference analog: launch controller tests (SURVEY.md §2.3 launch row) — the
subprocess-on-localhost pattern from §4.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(tmp_path, script_body, extra_args=()):
    script = tmp_path / "train.py"
    script.write_text(script_body)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PADDLE_TRAINER_ID", None)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log"), *extra_args, str(script)],
        env=env, capture_output=True, text=True, timeout=120)


class TestLaunch:
    def test_env_wiring(self, tmp_path):
        r = _run_launch(tmp_path, (
            "import os\n"
            "assert os.environ['PADDLE_TRAINERS_NUM'] == '2'\n"
            "assert os.environ['PADDLE_TRAINER_ID'] == '1'\n"
            "assert os.environ['PADDLE_MASTER'] == 'h0:8090'\n"
            "assert os.environ['JAX_COORDINATOR_ADDRESS'] == 'h0:8090'\n"),
            extra_args=["--nnodes", "2", "--rank", "1",
                        "--master", "h0:8090"])
        assert r.returncode == 0, r.stderr

    def test_elastic_restart_resumes(self, tmp_path):
        marker = tmp_path / "marker"
        r = _run_launch(tmp_path, (
            f"import os, sys\n"
            f"m = {str(marker)!r}\n"
            f"if not os.path.exists(m):\n"
            f"    open(m, 'w').close(); sys.exit(1)\n"
            f"print('resumed')\n"),
            extra_args=["--max_restarts", "2"])
        assert r.returncode == 0, r.stderr
        assert "elastic restart 1/2" in r.stderr + r.stdout
        logs = list((tmp_path / "log").glob("workerlog.0.restart1"))
        assert logs and "resumed" in logs[0].read_text()

    def test_restart_budget_exhausted(self, tmp_path):
        r = _run_launch(tmp_path, "import sys; sys.exit(3)\n",
                        extra_args=["--max_restarts", "1"])
        assert r.returncode == 3
        assert "1 restarts used" in r.stderr
