"""HLO-golden distributed tests (SURVEY.md §4 carry-over item 3).

Reference analog: test/auto_parallel/'s program-IR golden checks — their
completion/partitioner tests assert which comm ops the pass pipeline
inserted into the program without running multi-device. Ours assert on the
POST-SPMD compiled HLO text (`jit(...).lower(...).compile().as_text()` on
the 8-virtual-device CPU mesh): that GSPMD inserted the collectives each
parallelism strategy promises, and did NOT insert the ones good shardings
avoid. Counts carry slack for XLA version drift; the golden facts are
presence/absence and order-of-magnitude, not exact instruction counts.
"""
import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.topology import build_mesh
from paddle_tpu.nlp import llama, moe, train

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
               "collective-permute")


def collective_counts(txt):
    return {op: len(re.findall(r"\b" + op + r"\b", txt)) for op in COLLECTIVES}


def shard(mesh, tree_specs):
    return jax.tree.map(lambda s: NamedSharding(mesh, s), tree_specs,
                        is_leaf=lambda x: isinstance(x, P))


def compiled_text(fn, mesh, in_shardings, *args):
    return jax.jit(fn, in_shardings=in_shardings).lower(
        *args).compile().as_text()


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    toks = jnp.zeros((8, 32), jnp.int32)
    return cfg, params, toks


class TestDataParallelGolden:
    def test_dp_grads_allreduce_only(self, tiny):
        """Pure DP: grad sync is all-reduce on the dp axis and NOTHING
        else — no all-gathers (that would mean params were resharded), no
        all-to-all; and the all-reduce count stays O(param leaves), i.e.
        one per stacked-layer grad leaf, not one per op or per microbatch.
        (Reference: EagerReducer's bucketed allreduce, SURVEY.md §2.3 DP.)
        """
        cfg, params, toks = tiny
        mesh = build_mesh(dp=8)
        ps, bs = shard(mesh, llama.param_specs(cfg)), NamedSharding(
            mesh, llama.batch_spec())
        txt = compiled_text(
            jax.grad(lambda p, t: llama.loss_fn(p, t, cfg, mesh)),
            mesh, (ps, bs), params, toks)
        c = collective_counts(txt)
        n_leaves = len(jax.tree.leaves(params))
        assert c["all-gather"] == 0, c
        assert c["all-to-all"] == 0, c
        assert 1 <= c["all-reduce"] <= 2 * n_leaves + 4, (c, n_leaves)


class TestZero3Golden:
    def test_sharding_axis_gathers_params(self, tiny):
        """ZeRO-3/FSDP (the 'sharding' axis): forward+backward must gather
        the 2D-sharded params on use (all-gather) and scatter the grad
        reduction (reduce-scatter, or XLA:CPU's all-to-all lowering of it)
        — vs pure DP's zero all-gathers.
        (Reference: GroupSharded stage-3, SURVEY.md §2.3 sharding row.)"""
        cfg, params, toks = tiny
        mesh = build_mesh(sharding=8)
        ps, bs = shard(mesh, llama.param_specs(cfg)), NamedSharding(
            mesh, llama.batch_spec())
        txt = compiled_text(
            jax.grad(lambda p, t: llama.loss_fn(p, t, cfg, mesh)),
            mesh, (ps, bs), params, toks)
        c = collective_counts(txt)
        assert c["all-gather"] >= cfg.num_hidden_layers, c
        assert c["reduce-scatter"] + c["all-to-all"] > 0, c


class TestTensorParallelGolden:
    def test_tp_forward_never_gathers_full_weights(self, tiny):
        """Megatron TP: column/row-split matmuls consume their weight
        SHARDS; the compiled forward must contain no all-gather whose
        result is a full weight matrix (only activation-dim gathers are
        allowed), and must contain the row-parallel output all-reduce.
        (Reference: Column/RowParallelLinear mp_ops, SURVEY.md §2.3 TP.)"""
        cfg, params, toks = tiny
        mesh = build_mesh(mp=4, dp=2)
        ps, bs = shard(mesh, llama.param_specs(cfg)), NamedSharding(
            mesh, llama.batch_spec())
        txt = compiled_text(
            lambda p, t: llama.forward(p, t, cfg, mesh),
            mesh, (ps, bs), params, toks)
        c = collective_counts(txt)
        assert c["all-reduce"] >= 1, c

        # full (unsharded) weight shapes, e.g. "64,64" for q_proj
        weight_shapes = set()
        for leaf in jax.tree.leaves(params["layers"]):
            if leaf.ndim >= 2:
                weight_shapes.add(",".join(map(str, leaf.shape[-2:])))
        for m in re.finditer(r"\w+\[([\d,]+)\][^\n]*\ball-gather\b", txt):
            dims = m.group(1)
            for ws in weight_shapes:
                assert not dims.endswith(ws), (
                    f"all-gather materializes a full weight [{dims}]")


class TestContextParallelGolden:
    def test_ring_attention_lowers_to_collective_permute(self):
        """Ring attention's KV rotation is ppermute — the compiled body
        must contain collective-permute and NOT implement the ring as
        all-gather of the full KV. (SURVEY.md §2.3 CP row.)"""
        from paddle_tpu.kernels.ring_attention import sep_attention
        mesh = build_mesh(sep=8)
        x = jnp.zeros((2, 64, 4, 8), jnp.float32)
        sh = NamedSharding(mesh, P(None, "sep", None, None))
        txt = jax.jit(
            lambda q, k, v: sep_attention(q, k, v, mesh, impl="ring"),
            in_shardings=(sh, sh, sh)).lower(x, x, x).compile().as_text()
        c = collective_counts(txt)
        assert c["collective-permute"] >= 1, c
        assert c["all-gather"] == 0, c

    def test_ulysses_lowers_to_all_to_all(self):
        """Ulysses swaps seq<->head sharding with all_to_all — assert it
        compiles to all-to-all, not gather+reslice. (SURVEY.md §2.3 SEP.)"""
        from paddle_tpu.kernels.ring_attention import sep_attention
        mesh = build_mesh(sep=4, dp=2)
        x = jnp.zeros((2, 64, 4, 8), jnp.float32)
        sh = NamedSharding(mesh, P(None, "sep", None, None))
        txt = jax.jit(
            lambda q, k, v: sep_attention(q, k, v, mesh, impl="ulysses"),
            in_shardings=(sh, sh, sh)).lower(x, x, x).compile().as_text()
        c = collective_counts(txt)
        assert c["all-to-all"] >= 1, c

    def test_ulysses_gqa_kv_compact_on_wire(self):
        """VERDICT r2 weak 3: the GQA KV all_to_all moves the COMPACT head
        count. hkv=2, sep=4, h=8: minimal expansion is 4 heads (1/device
        post-swap), so some all-to-all result is [..., 1, hd] — full
        pre-expansion would make every swap [..., 2, hd]."""
        import re
        from paddle_tpu.kernels.ring_attention import sep_attention
        mesh = build_mesh(sep=4, dp=2)
        q = jnp.zeros((2, 64, 8, 8), jnp.float32)
        kv = jnp.zeros((2, 64, 2, 8), jnp.float32)
        shq = NamedSharding(mesh, P(None, "sep", None, None))
        txt = jax.jit(
            lambda q, k, v: sep_attention(q, k, v, mesh, impl="ulysses"),
            in_shardings=(shq, shq, shq)).lower(q, kv, kv).compile().as_text()
        # per-shard tuple entries: q/out swap as [1,16,2,8] (2 heads/dev),
        # compact KV as [1,16,1,8] (1 head/dev — half the bytes)
        kv_swaps = re.findall(r"f32\[1,16,1,8\][^\n]*all-to-all\(", txt)
        assert kv_swaps, "no compact-KV all-to-all found in HLO"


class TestPipelineGolden:
    def test_1f1b_lowers_to_collective_permute(self):
        """Both pipeline hops (activations down, cotangents up) are
        ppermute inside the 1F1B scan — the compiled fused train step must
        contain collective-permute. (SURVEY.md §3.3.)"""
        cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.zeros((8, 32), jnp.int32)
        mesh = build_mesh(pp=4, dp=2)
        ps = shard(mesh, llama.param_specs(cfg, pp=True))
        bs = NamedSharding(mesh, llama.batch_spec())
        txt = jax.jit(
            lambda p, t: llama.loss_and_grad_pp(p, t, cfg, mesh, 8),
            in_shardings=(ps, bs)).lower(params, toks).compile().as_text()
        c = collective_counts(txt)
        assert c["collective-permute"] >= 2, c


class TestExpertParallelGolden:
    def test_ep_moe_routes_with_collectives(self):
        """Experts sharded P('ep'): the dispatch/combine gathers around the
        expert einsums must compile to cross-shard collectives (the
        reference's hand-coded all_to_all over the moe_group), not a full
        replication of x or the expert weights. (SURVEY.md §2.3 EP.)"""
        mesh = build_mesh(ep=4, dp=2)
        cfg = moe.MoeConfig.tiny(num_experts=4, attn_impl="exact")
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.zeros((8, 64), jnp.int32)
        ps = shard(mesh, moe.param_specs(cfg))
        bs = NamedSharding(mesh, llama.batch_spec())
        txt = jax.jit(
            lambda p, t: moe.loss_fn(p, t, cfg, mesh),
            in_shardings=(ps, bs)).lower(params, toks).compile().as_text()
        c = collective_counts(txt)
        assert sum(c[k] for k in ("all-to-all", "all-gather",
                                  "collective-permute")) >= 1, c


class TestAsyncOverlapGolden:
    """VERDICT r4 next-7: compiled-HLO evidence that the sharded train
    step OVERLAPS collectives with compute — not merely that collectives
    exist. The module is AOT-compiled for a REAL 8-chip v5e topology
    (chipless TpuAotCompiler), so the assertion runs against the actual
    TPU scheduler: a serialized-all-gather regression (done immediately
    after start, no compute between) fails this test."""

    def _aot_topology(self):
        try:
            from jax.experimental import topologies
            return topologies.get_topology_desc(platform="tpu",
                                                topology_name="v5e:2x4")
        except Exception as e:  # no libtpu / AOT support in this env
            pytest.skip(f"TPU AOT topology unavailable: {e}")

    def test_fsdp_tp_step_overlaps_collectives(self):
        import re
        from paddle_tpu.core.flags import xla_scale_options
        topo = self._aot_topology()
        mesh = build_mesh(sharding=4, mp=2, devices=list(topo.devices))
        cfg = llama.LlamaConfig.tiny(use_flash=False)
        params = jax.eval_shape(lambda k: llama.init_params(k, cfg),
                                jax.ShapeDtypeStruct((2,), jnp.uint32))
        ps = jax.tree.map(
            lambda s, sp: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
            params, llama.param_specs(cfg),
            is_leaf=lambda x: not isinstance(x, dict))
        toks = jax.ShapeDtypeStruct(
            (8, 64), jnp.int32,
            sharding=NamedSharding(mesh, llama.batch_spec()))

        fn = jax.jit(jax.grad(lambda p, t: llama.loss_fn(p, t, cfg, mesh)))
        txt = fn.lower(ps, toks).compile(
            compiler_options=xla_scale_options()).as_text()

        lines = txt.splitlines()
        starts = [i for i, l in enumerate(lines)
                  if "async-collective-start" in l and "= " in l
                  and "get-tuple-element" not in l]
        assert starts, "no async collective starts in the scheduled module"
        # at least one start/done window with real compute inside
        overlapped = 0
        for i in starts:
            for j in range(i + 1, len(lines)):
                if "async-collective-done" in lines[j]:
                    between = lines[i + 1:j]
                    if any(re.search(r"= \S+ (fusion|convolution)\(", b)
                           for b in between):
                        overlapped += 1
                    break
        assert overlapped >= 1, (
            "async collective start/done pairs have no compute scheduled "
            "between them — latency hiding regressed")
