"""Layer tests — mirrors the reference's API/layer test style (SURVEY.md §4
'direct eager-mode asserts vs numpy')."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

RNG = np.random.default_rng(3)


def fdata(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


class TestLinearEmbedding:
    def test_linear(self):
        layer = nn.Linear(4, 3)
        x = fdata(2, 4)
        out = layer(paddle.to_tensor(x))
        ref = x @ layer.weight.numpy() + layer.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_linear_no_bias(self):
        layer = nn.Linear(4, 3, bias_attr=False)
        assert layer.bias is None
        out = layer(paddle.to_tensor(fdata(2, 4)))
        assert out.shape == [2, 3]

    def test_embedding(self):
        emb = nn.Embedding(10, 6)
        out = emb(paddle.to_tensor(np.array([[1, 2], [3, 4]])))
        assert out.shape == [2, 2, 6]
        np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1], rtol=1e-6)

    def test_embedding_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(paddle.to_tensor(np.array([0, 1])))
        np.testing.assert_allclose(out.numpy()[0], np.zeros(4), atol=1e-7)

    def test_linear_grad_flows(self):
        layer = nn.Linear(4, 2)
        out = layer(paddle.to_tensor(fdata(3, 4)))
        out.sum().backward()
        assert layer.weight.grad is not None and layer.bias.grad is not None
        assert layer.weight.grad.shape == [4, 2]


class TestConvPool:
    def test_conv2d_shape_and_ref(self):
        conv = nn.Conv2D(2, 4, 3, padding=1)
        x = fdata(1, 2, 8, 8)
        out = conv(paddle.to_tensor(x))
        assert out.shape == [1, 4, 8, 8]
        # reference check vs torch-free scipy-style direct computation on one pixel
        w = conv.weight.numpy()
        b = conv.bias.numpy()
        patch = np.pad(x[0], ((0, 0), (1, 1), (1, 1)))[:, 0:3, 0:3]
        ref00 = (w[0] * patch).sum() + b[0]
        np.testing.assert_allclose(out.numpy()[0, 0, 0, 0], ref00, rtol=1e-4)

    def test_conv2d_stride_groups(self):
        conv = nn.Conv2D(4, 8, 3, stride=2, padding=1, groups=2)
        out = conv(paddle.to_tensor(fdata(2, 4, 16, 16)))
        assert out.shape == [2, 8, 8, 8]

    def test_conv2d_transpose(self):
        deconv = nn.Conv2DTranspose(4, 2, 2, stride=2)
        out = deconv(paddle.to_tensor(fdata(1, 4, 8, 8)))
        assert out.shape == [1, 2, 16, 16]

    def test_conv1d(self):
        conv = nn.Conv1D(3, 6, 5, padding=2)
        out = conv(paddle.to_tensor(fdata(2, 3, 20)))
        assert out.shape == [2, 6, 20]

    def test_pools(self):
        x = paddle.to_tensor(fdata(1, 2, 8, 8))
        assert F.max_pool2d(x, 2).shape == [1, 2, 4, 4]
        assert F.avg_pool2d(x, 2).shape == [1, 2, 4, 4]
        assert F.adaptive_avg_pool2d(x, 1).shape == [1, 2, 1, 1]
        assert F.adaptive_avg_pool2d(x, 3).shape == [1, 2, 3, 3]
        np.testing.assert_allclose(
            F.adaptive_avg_pool2d(x, 1).numpy()[0, 0, 0, 0],
            x.numpy()[0, 0].mean(), rtol=1e-5)

    def test_max_pool_values(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        out = F.max_pool2d(paddle.to_tensor(x), 2)
        np.testing.assert_array_equal(out.numpy()[0, 0], [[5, 7], [13, 15]])

    def test_conv_grad(self):
        conv = nn.Conv2D(1, 2, 3)
        out = conv(paddle.to_tensor(fdata(1, 1, 5, 5)))
        out.sum().backward()
        assert conv.weight.grad.shape == [2, 1, 3, 3]


class TestNorms:
    def test_layernorm_ref(self):
        ln = nn.LayerNorm(8)
        x = fdata(4, 8)
        out = ln(paddle.to_tensor(x))
        mu = x.mean(-1, keepdims=True)
        sd = x.std(-1, keepdims=True, ddof=0)
        np.testing.assert_allclose(out.numpy(), (x - mu) / np.sqrt(sd ** 2 + 1e-5),
                                   rtol=1e-4, atol=1e-5)

    def test_batchnorm_train_eval(self):
        bn = nn.BatchNorm2D(3)
        x = fdata(4, 3, 5, 5) * 2 + 1
        out = bn(paddle.to_tensor(x))
        m = out.numpy().mean(axis=(0, 2, 3))
        np.testing.assert_allclose(m, np.zeros(3), atol=1e-5)
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), np.zeros(3))
        bn.eval()
        out2 = bn(paddle.to_tensor(x))
        assert out2.shape == [4, 3, 5, 5]

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        out = gn(paddle.to_tensor(fdata(2, 4, 6, 6)))
        assert out.shape == [2, 4, 6, 6]

    def test_rmsnorm(self):
        rn = nn.RMSNorm(16)
        x = fdata(2, 16)
        out = rn(paddle.to_tensor(x))
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4)


class TestActivationsDropout:
    def test_activation_layers(self):
        x = paddle.to_tensor(fdata(3, 4))
        for layer, ref in [
            (nn.ReLU(), lambda v: np.maximum(v, 0)),
            (nn.Sigmoid(), lambda v: 1 / (1 + np.exp(-v))),
            (nn.Tanh(), np.tanh),
            (nn.Hardswish(), lambda v: v * np.clip(v + 3, 0, 6) / 6),
        ]:
            np.testing.assert_allclose(layer(x).numpy(), ref(x.numpy()), rtol=1e-4, atol=1e-5)

    def test_softmax(self):
        x = fdata(2, 5)
        out = F.softmax(paddle.to_tensor(x), axis=-1)
        e = np.exp(x - x.max(-1, keepdims=True))
        np.testing.assert_allclose(out.numpy(), e / e.sum(-1, keepdims=True), rtol=1e-5)

    def test_dropout_train_eval(self):
        drop = nn.Dropout(0.5)
        x = paddle.ones([1000])
        out = drop(x)
        kept = (out.numpy() != 0).mean()
        assert 0.35 < kept < 0.65
        np.testing.assert_allclose(out.numpy()[out.numpy() != 0], 2.0, rtol=1e-6)
        drop.eval()
        np.testing.assert_array_equal(drop(x).numpy(), x.numpy())


class TestLosses:
    def test_cross_entropy(self):
        logits = fdata(4, 5)
        labels = np.array([0, 2, 1, 4])
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = fdata(4, 5)
        labels = np.array([0, -100, 1, -100])
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
        p = np.exp(logits - logits.max(-1, keepdims=True)); p /= p.sum(-1, keepdims=True)
        ref = -np.log(p[[0, 2], [0, 1]]).mean()
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-5)

    def test_mse_l1(self):
        a, b = fdata(3, 3), fdata(3, 3)
        np.testing.assert_allclose(
            float(F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()),
            ((a - b) ** 2).mean(), rtol=1e-5)
        np.testing.assert_allclose(
            float(F.l1_loss(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()),
            np.abs(a - b).mean(), rtol=1e-5)

    def test_bce_with_logits(self):
        x, y = fdata(4), (fdata(4) > 0).astype(np.float32)
        out = F.binary_cross_entropy_with_logits(paddle.to_tensor(x), paddle.to_tensor(y))
        p = 1 / (1 + np.exp(-x))
        ref = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        np.testing.assert_allclose(float(out.numpy()), ref, rtol=1e-4)


class TestTransformerRNN:
    def test_mha_self_attention(self):
        mha = nn.MultiHeadAttention(32, 4)
        out = mha(paddle.to_tensor(fdata(2, 6, 32)))
        assert out.shape == [2, 6, 32]

    def test_encoder_stack_not_tied(self):
        enc = nn.TransformerEncoder(nn.TransformerEncoderLayer(16, 2, 32), 2)
        l0 = enc.layers[0].linear1.weight.numpy()
        l1 = enc.layers[1].linear1.weight.numpy()
        assert not np.allclose(l0, l1)

    def test_transformer_full(self):
        model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                               num_decoder_layers=1, dim_feedforward=32)
        out = model(paddle.to_tensor(fdata(2, 5, 16)), paddle.to_tensor(fdata(2, 4, 16)))
        assert out.shape == [2, 4, 16]

    def test_lstm_shapes(self):
        lstm = nn.LSTM(8, 16)
        y, (h, c) = lstm(paddle.to_tensor(fdata(3, 5, 8)))
        assert y.shape == [3, 5, 16] and h.shape == [1, 3, 16] and c.shape == [1, 3, 16]

    def test_gru_cell_vs_layer(self):
        cell = nn.GRUCell(4, 8)
        out, h = cell(paddle.to_tensor(fdata(2, 4)))
        assert out.shape == [2, 8]

    def test_rnn_grad(self):
        lstm = nn.LSTM(4, 8)
        y, _ = lstm(paddle.to_tensor(fdata(2, 3, 4)))
        y.sum().backward()
        assert lstm.weight_ih_0.grad is not None


class TestLayerInfra:
    def test_state_dict_roundtrip(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = m.state_dict()
        assert "0.weight" in sd and "2.bias" in sd
        m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m2.set_state_dict(sd)
        x = paddle.to_tensor(fdata(2, 4))
        np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)

    def test_named_parameters_dedup_shared(self):
        lin = nn.Linear(3, 3)

        class Tied(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = lin
                self.b = lin

        names = [n for n, _ in Tied().named_parameters()]
        assert len(names) == 2  # weight+bias counted once

    def test_train_eval_propagates(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_apply_and_to_dtype(self):
        m = nn.Linear(2, 2)
        m.to(dtype="bfloat16")
        assert m.weight.dtype == paddle.bfloat16

    def test_hooks(self):
        m = nn.Linear(2, 2)
        calls = []
        h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
        m(paddle.to_tensor(fdata(1, 2)))
        assert calls == [1]
        h.remove()
        m(paddle.to_tensor(fdata(1, 2)))
        assert calls == [1]

    def test_buffers_in_state_dict(self):
        bn = nn.BatchNorm2D(3)
        sd = bn.state_dict()
        assert "_mean" in sd and "_variance" in sd

    def test_sublayer_iteration(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
        assert len(m.sublayers()) == 3


class TestRNNTLoss:
    """paddle.nn.RNNTLoss (VERDICT r4 missing 4 — the last nn probe miss)
    vs an independent numpy alpha-recursion reference."""

    def _ref(self, logits, labels, il, ll, blank=0):
        out = []
        for b in range(logits.shape[0]):
            lp = logits[b] - np.log(
                np.exp(logits[b]).sum(-1, keepdims=True))
            T, U = il[b], ll[b]
            alpha = np.full((T, U + 1), -np.inf)
            alpha[0, 0] = 0.0
            for u in range(1, U + 1):
                alpha[0, u] = alpha[0, u - 1] + lp[0, u - 1, labels[b, u - 1]]
            for t in range(1, T):
                alpha[t, 0] = alpha[t - 1, 0] + lp[t - 1, 0, blank]
                for u in range(1, U + 1):
                    alpha[t, u] = np.logaddexp(
                        alpha[t - 1, u] + lp[t - 1, u, blank],
                        alpha[t, u - 1] + lp[t, u - 1, labels[b, u - 1]])
            out.append(-(alpha[T - 1, U] + lp[T - 1, U, blank]))
        return np.array(out)

    def test_matches_reference_and_grads(self):
        import paddle_tpu as paddle
        rng = np.random.default_rng(0)
        B, T, U, V = 3, 7, 4, 6
        logits = rng.standard_normal((B, T, U + 1, V)).astype("float32")
        labels = rng.integers(1, V, (B, U)).astype("int32")
        il = np.array([7, 5, 6], "int32")
        ll = np.array([4, 2, 3], "int32")
        lg = paddle.to_tensor(logits, stop_gradient=False)
        loss = paddle.nn.functional.rnnt_loss(
            lg, paddle.to_tensor(labels), paddle.to_tensor(il),
            paddle.to_tensor(ll), fastemit_lambda=0.0, reduction="none")
        np.testing.assert_allclose(np.asarray(loss.numpy()),
                                   self._ref(logits, labels, il, ll),
                                   rtol=1e-4)
        crit = paddle.nn.RNNTLoss()   # default fastemit_lambda
        out = crit(lg, paddle.to_tensor(labels), paddle.to_tensor(il),
                   paddle.to_tensor(ll))
        out.backward()
        assert np.isfinite(np.asarray(lg.grad.numpy())).all()
