"""paddle_tpu.serving.slo — the serving SLO engine.

Deterministic coverage of the tentpole's control plane: dual-window
burn-rate math on a fake clock, breach→recover hysteresis (one breach
counted per excursion, the alert held through the hysteresis band),
the Router's fleet rollup (worst-of verdicts, max burn, summed
breaches), the Prometheus surface (slo_burn_rate_* gauges,
slo_breaches_total counters, native *_hist_bucket{le=...} histogram
families — including TYPE-line grouping in the router's merged
exposition), the end-to-end breach path (engine health()["slo"] →
router rollup → /health detail without flipping the 200 →
slo_breach trace events → trace_report --slo breach windows naming
the requests that rode them), and the PR 12 operator gap: the
breaker-reset surface (supervisor reset + Router.reset_breaker +
POST /admin/reset_breaker).
"""
import json
import threading
import time

import numpy as np
import pytest
import jax

from paddle_tpu.nlp import llama
from paddle_tpu import serving
from paddle_tpu.serving.metrics import MetricsRegistry
from paddle_tpu.serving.slo import (
    SloTracker, DEFAULT_OBJECTIVES, rollup, worst_verdict)

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tools"))
import trace_report as tr  # noqa: E402


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _tracker(objectives, t, **kw):
    kw.setdefault("fast_window_s", 1.0)
    kw.setdefault("slow_window_s", 10.0)
    kw.setdefault("eval_every_s", 0.0)     # recompute every evaluate()
    return SloTracker(objectives, clock=lambda: t[0], **kw)


class TestTrackerUnits:
    def test_unknown_objective_rejected(self):
        with pytest.raises(ValueError):
            SloTracker({"ttft_p99_typo": 1.0})
        with pytest.raises(ValueError):
            SloTracker({"ttft_s_p99": 0.0})
        with pytest.raises(ValueError):
            SloTracker({"ttft_s_p99": 1.0}, fast_window_s=10.0,
                       slow_window_s=5.0)

    def test_defaults_are_known(self):
        t = SloTracker()
        assert set(t.objectives) == set(DEFAULT_OBJECTIVES)
        rep = t.evaluate()
        # no samples: everything OK at burn 0
        assert rep["verdict"] == "OK"
        assert all(o["burn_rate_fast"] == 0.0
                   for o in rep["objectives"].values())

    def test_window_separation_and_burn_math(self):
        t = [100.0]
        s = _tracker({"ttft_s_p99": 0.2, "itl_ms_p99": 100.0}, t)
        s.record_ttft(0.6)              # burn 3.0 against 0.2
        s.record_itl(0.05)              # 50 ms against 100 → burn 0.5
        rep = s.evaluate(force=True)
        ttft = rep["objectives"]["ttft_s_p99"]
        assert ttft["value_fast"] == pytest.approx(0.6)
        assert ttft["burn_rate_fast"] == pytest.approx(3.0)
        itl = rep["objectives"]["itl_ms_p99"]
        assert itl["value_fast"] == pytest.approx(50.0)   # ms conversion
        assert itl["burn_rate_fast"] == pytest.approx(0.5)
        # advance past the fast window but inside the slow one: the
        # sample leaves the fast view, stays in the slow view
        t[0] = 102.0
        rep = s.evaluate(force=True)
        ttft = rep["objectives"]["ttft_s_p99"]
        assert ttft["value_fast"] is None
        assert ttft["burn_rate_fast"] == 0.0
        assert ttft["value_slow"] == pytest.approx(0.6)
        assert ttft["burn_rate_slow"] == pytest.approx(3.0)
        # past the slow window everything is pruned
        t[0] = 120.0
        rep = s.evaluate(force=True)
        assert rep["objectives"]["ttft_s_p99"]["value_slow"] is None

    def test_goodput_floor_and_error_rate(self):
        t = [0.0]
        s = _tracker({"goodput_tok_s": 100.0, "error_rate": 0.25}, t,
                     fast_window_s=2.0)
        # 50 tokens over a 1 s ACTIVE span (first in-window sample →
        # now) = 50 tok/s against a floor of 100 → burn 2.0 (floors
        # burn as target/value); the active-span denominator, not the
        # 2 s window, is what the rate divides by
        s.record_tokens(30)
        t[0] = 1.0
        s.record_tokens(20)
        s.record_request(error=False)
        s.record_request(error=False)
        s.record_request(error=False)
        s.record_request(error=True)          # 1/4 = 0.25 → burn 1.0
        rep = s.evaluate(force=True)
        good = rep["objectives"]["goodput_tok_s"]
        assert good["value_fast"] == pytest.approx(50.0)
        assert good["burn_rate_fast"] == pytest.approx(2.0)
        err = rep["objectives"]["error_rate"]
        assert err["value_fast"] == pytest.approx(0.25)
        assert err["burn_rate_fast"] == pytest.approx(1.0)
        assert err["verdict"] == "BREACH"

    def test_breach_recover_hysteresis(self):
        t = [0.0]
        s = _tracker({"ttft_s_p99": 0.1}, t)
        s.record_ttft(0.5)                    # burn 5.0
        rep = s.evaluate(force=True)
        assert rep["objectives"]["ttft_s_p99"]["verdict"] == "BREACH"
        assert rep["verdict"] == "BREACH"
        assert rep["breaches_total"] == 1
        edges = s.pop_transitions()
        assert [e["edge"] for e in edges] == ["breach"]
        assert edges[0]["objective"] == "ttft_s_p99"
        # still inside the fast window: the SAME excursion must not
        # count a second breach
        t[0] = 0.5
        rep = s.evaluate(force=True)
        assert rep["breaches_total"] == 1
        assert s.pop_transitions() == []
        # fast window clears (bad sample ages out), slow window still
        # carries it: BREACH exits through WARN, not straight to OK
        t[0] = 2.0
        s.record_ttft(0.01)
        rep = s.evaluate(force=True)
        o = rep["objectives"]["ttft_s_p99"]
        assert o["verdict"] == "WARN", o
        assert [e["edge"] for e in s.pop_transitions()] == ["recovered"]
        # slow window clears too → OK; breach count still 1
        t[0] = 15.0
        s.record_ttft(0.01)
        rep = s.evaluate(force=True)
        assert rep["objectives"]["ttft_s_p99"]["verdict"] == "OK"
        assert rep["breaches_total"] == 1

    def test_hysteresis_band_holds_the_alert(self):
        # once BREACH, a fast burn INSIDE (recover_burn, breach_burn)
        # must hold the alert instead of flapping
        t = [0.0]
        s = _tracker({"ttft_s_p99": 0.1}, t, warn_burn=0.75)
        s.record_ttft(0.5)
        assert s.evaluate(force=True)["verdict"] == "BREACH"
        t[0] = 2.0                       # bad sample out of fast window
        s.record_ttft(0.08)              # burn 0.8: in the band
        rep = s.evaluate(force=True)
        assert rep["objectives"]["ttft_s_p99"]["verdict"] == "BREACH"
        assert rep["breaches_total"] == 1        # held, not re-entered

    def test_goodput_rate_over_active_span_not_idle_window(self):
        """A window straddling pre-traffic idle (engine warmup, a
        quiet stretch before a burst) must not dilute real throughput
        into a phantom burn: the rate divides by the ACTIVE span —
        first in-window sample → now (regression: a fresh engine's
        slow-window goodput read ~0 and latched BREACH). A stall WITH
        samples still in the window decays the rate (the span keeps
        growing); a fully idle window is None/OK, not a breach."""
        t = [100.0]                           # long pre-traffic idle
        s = _tracker({"goodput_tok_s": 10.0}, t)
        s.record_tokens(10)
        t[0] = 100.5
        s.record_tokens(10)                   # 20 tok over 0.5 s span
        rep = s.evaluate(force=True)
        o = rep["objectives"]["goodput_tok_s"]
        assert o["value_fast"] == pytest.approx(40.0)
        assert o["value_slow"] == pytest.approx(40.0)
        assert o["verdict"] == "OK"
        # delivery stalls with the samples still in the slow window:
        # the active span stretches and the measured rate decays
        t[0] = 104.5
        o = s.evaluate(force=True)["objectives"]["goodput_tok_s"]
        assert o["value_slow"] == pytest.approx(20.0 / 4.5)
        assert o["burn_rate_slow"] == pytest.approx(10.0 / (20.0 / 4.5))
        # fully idle window: no evidence — None/OK, never a breach
        t[0] = 200.0
        o = s.evaluate(force=True)["objectives"]["goodput_tok_s"]
        assert o["value_fast"] is None and o["verdict"] == "OK"

    def test_evaluation_cache(self):
        t = [0.0]
        s = SloTracker({"ttft_s_p99": 0.1}, clock=lambda: t[0],
                       fast_window_s=1.0, slow_window_s=10.0,
                       eval_every_s=5.0)
        rep1 = s.evaluate()
        s.record_ttft(9.9)               # would breach if recomputed
        assert s.evaluate() is rep1      # cached within eval_every_s
        t[0] = 6.0
        assert s.evaluate() is not rep1  # cache expired
        assert s.evaluate(force=True)["breaches_total"] >= 0


class TestRollup:
    def test_worst_of_and_sums(self):
        a = {"verdict": "OK", "breaches_total": 1,
             "objectives": {"ttft_s_p99": {
                 "verdict": "OK", "burn_rate_fast": 0.2,
                 "burn_rate_slow": 0.1, "target": 1.0,
                 "kind": "ceiling"}}}
        b = {"verdict": "BREACH", "breaches_total": 2,
             "objectives": {"ttft_s_p99": {
                 "verdict": "BREACH", "burn_rate_fast": 3.0,
                 "burn_rate_slow": 1.5, "target": 1.0,
                 "kind": "ceiling"}}}
        agg = rollup([a, b, None])       # None = replica with slo off
        assert agg["verdict"] == "BREACH"
        assert agg["replicas_reporting"] == 2
        assert agg["breaches_total"] == 3
        o = agg["objectives"]["ttft_s_p99"]
        assert o["verdict"] == "BREACH"
        assert o["burn_rate_fast"] == 3.0
        assert o["burn_rate_slow"] == 1.5

    def test_empty_fleet_is_ok(self):
        agg = rollup([None, None])
        assert agg["verdict"] == "OK"
        assert agg["replicas_reporting"] == 0
        assert worst_verdict([]) == "OK"
        assert worst_verdict(["OK", "WARN"]) == "WARN"


class TestPrometheusBuckets:
    def test_histogram_bucket_counts_cumulative(self):
        m = MetricsRegistry()
        h = m.histogram("lat_s", buckets=[0.01, 0.1, 1.0])
        for v in (0.005, 0.05, 0.05, 0.5, 5.0):
            h.observe(v)
        assert h.buckets() == [(0.01, 1), (0.1, 3), (1.0, 4)]
        text = m.to_prometheus()
        assert "# TYPE paddle_tpu_lat_s summary" in text
        assert "# TYPE paddle_tpu_lat_s_hist histogram" in text
        assert 'paddle_tpu_lat_s_hist_bucket{le="0.1"} 3.0' in text
        # +Inf bucket equals the lifetime count
        assert 'paddle_tpu_lat_s_hist_bucket{le="+Inf"} 5.0' in text
        assert "paddle_tpu_lat_s_hist_count 5.0" in text
        # a bucketless histogram exports no histogram family
        m2 = MetricsRegistry()
        m2.histogram("plain").observe(1.0)
        assert "_hist" not in m2.to_prometheus()

    def test_engine_latency_histograms_carry_buckets(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=8, max_total_len=48,
            max_new_tokens=4)
        eng.generate([1, 2, 3], timeout=300)
        text = eng.metrics.to_prometheus()
        for fam in ("ttft_s", "itl_s", "queue_wait_s"):
            assert f"# TYPE paddle_tpu_{fam}_hist histogram" in text
            assert f'paddle_tpu_{fam}_hist_bucket{{le="+Inf"}}' in text
        eng.shutdown()

    def test_router_merged_hist_family_grouping(self, setup):
        """The merged exposition groups the native-histogram family's
        samples (both replicas') under exactly ONE TYPE line, with the
        replica label appended inside the existing le= braces."""
        cfg, params = setup
        r = serving.Router(params, cfg, replicas=2, max_batch=2,
                           block_size=8, max_total_len=48,
                           max_new_tokens=4)
        r.generate([1, 2, 3], timeout=300)
        lines = r.to_prometheus().splitlines()
        tl = [i for i, ln in enumerate(lines)
              if ln == "# TYPE paddle_tpu_ttft_s_hist histogram"]
        assert len(tl) == 1
        buckets = [ln for ln in lines
                   if ln.startswith("paddle_tpu_ttft_s_hist_bucket")]
        assert any(',replica="r0"}' in ln for ln in buckets)
        assert any(',replica="r1"}' in ln for ln in buckets)
        # every bucket sample sits in the contiguous block after the
        # family's one TYPE line (strict-parser grouping)
        start = tl[0]
        end = next((i for i in range(start + 1, len(lines))
                    if lines[i].startswith("# TYPE")), len(lines))
        in_block = [ln for ln in lines[start:end]
                    if ln.startswith("paddle_tpu_ttft_s_hist")]
        assert len(in_block) == len(
            [ln for ln in lines
             if ln.startswith("paddle_tpu_ttft_s_hist")])
        r.shutdown()


class TestEngineSlo:
    def test_breach_visible_in_health_prom_and_trace(self, setup,
                                                     tmp_path):
        """An impossible TTFT objective breaches on the first served
        request: health()["slo"] says BREACH, slo_breaches_total and
        the burn gauge land in the exposition, the sink carries an
        slo_breach span, and trace_report --slo shows the breach
        window WITH the request that rode it."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=8, max_total_len=48,
            max_new_tokens=4,
            slo_objectives={"ttft_s_p99": 1e-9},
            slo_opts={"eval_every_s": 0.0})
        eng.generate([1, 2, 3, 4], timeout=300)
        h = eng.health()
        assert h["slo"]["verdict"] == "BREACH"
        o = h["slo"]["objectives"]["ttft_s_p99"]
        assert o["burn_rate_fast"] > 1.0
        assert h["slo"]["breaches_total"] >= 1
        text = eng.metrics.to_prometheus()
        assert "paddle_tpu_slo_burn_rate_ttft_s_p99" in text
        bl = next(ln for ln in text.splitlines()
                  if ln.startswith("paddle_tpu_slo_breaches_total"))
        assert float(bl.split()[-1]) >= 1.0
        chrome = eng.trace.to_chrome_trace()
        breaches = [e for e in chrome["traceEvents"]
                    if e.get("name") == "slo_breach"]
        assert breaches and \
            breaches[0]["args"]["objective"] == "ttft_s_p99"
        path = tmp_path / "slo_trace.json"
        path.write_text(json.dumps(chrome))
        summary = tr.summarize(tr.load_events(str(path)))
        slo = summary["slo"]
        assert slo["breach_events"] >= 1
        assert slo["breach_windows"]
        w = slo["breach_windows"][0]
        assert w["objective"] == "ttft_s_p99"
        assert w["requests"], "no request attributed to the window"
        out = tr.render(summary, show_slo=True)
        assert "SLO breach windows" in out
        eng.shutdown()

    def test_slo_off_is_none(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=8, max_total_len=48,
            max_new_tokens=2, slo=False, start=False)
        assert eng.health()["slo"] is None
        assert "slo_burn_rate" not in eng.metrics.to_prometheus()
        eng.shutdown()


class TestRouterRollup:
    def test_worst_of_rides_health_and_metrics(self, setup):
        """One replica with an impossible objective breaches; the
        router's health rollup reports the fleet worst-of and the
        merged exposition carries per-replica burn gauges plus the
        replica="router" rollup and summed breach counter."""
        cfg, params = setup
        r = serving.Router(
            params, cfg, replicas=2, max_batch=2, block_size=8,
            max_total_len=48, max_new_tokens=4,
            slo_opts={"eval_every_s": 0.0},
            per_replica=[{"slo_objectives": {"ttft_s_p99": 1e-9}},
                         None])
        # pin placement: serve through each replica at least once
        for _ in range(4):
            r.generate([9, 8, 7], timeout=300)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            h = r.health()
            if h["slo"]["verdict"] == "BREACH":
                break
            r.generate([9, 8, 7], timeout=300)
        assert h["slo"]["verdict"] == "BREACH"
        assert h["slo"]["replicas_reporting"] == 2
        assert h["slo"]["breaches_total"] >= 1
        assert h["replicas"]["r1"]["slo"]["verdict"] == "OK"
        prom = r.to_prometheus()
        assert ('paddle_tpu_slo_burn_rate_ttft_s_p99'
                '{replica="router"}') in prom
        rows = [ln for ln in prom.splitlines()
                if ln.startswith("paddle_tpu_slo_breaches_total")]
        by_label = {ln.split("{")[1].split("}")[0]: float(ln.split()[-1])
                    for ln in rows}
        assert by_label['replica="r0"'] >= 1.0
        assert by_label['replica="router"'] >= 1.0
        r.shutdown()


class TestRollupBreachAccounting:
    def test_counter_survives_replica_respawn(self, setup):
        """The fleet breach counter accumulates per-incarnation deltas
        keyed by engine identity: a respawned replica's fresh tracker
        restarting at 0 must neither decrement the counter nor swallow
        the NEXT real breaches behind the old global sum (review
        regression: the global high-water diff lost them)."""
        cfg, params = setup
        r = serving.Router(params, cfg, replicas=1, max_batch=1,
                           block_size=8, max_total_len=48,
                           max_new_tokens=2, start=False)
        real = r.engines

        class _Inc:       # identity stand-in for an engine incarnation
            pass
        e1, e2 = _Inc(), _Inc()

        def per(total):
            return [{"replica_id": "r0",
                     "slo": {"verdict": "OK", "objectives": {},
                             "breaches_total": total}}]
        r.engines = [e1]
        r._slo_rollup(per(5))
        assert r._c_slo_breaches.value == 5
        r._slo_rollup(per(5))                 # no new breaches
        assert r._c_slo_breaches.value == 5
        r.engines = [e2]                      # respawn: counter resets
        r._slo_rollup(per(0))
        assert r._c_slo_breaches.value == 5   # never decrements
        r._slo_rollup(per(3))                 # 3 REAL new breaches
        assert r._c_slo_breaches.value == 8   # old code: stuck at 5
        r.engines = real
        r.shutdown()


class _StubRouter:
    """Just enough router surface for frontend endpoint tests: the
    operator endpoints only call reset_breaker / capture_profile /
    health."""

    def __init__(self):
        self.resets = []

    def health(self):
        return {"status": "HEALTHY", "serving_replicas": 1,
                "slo": {"verdict": "OK"}}

    def to_prometheus(self):
        return "# TYPE x gauge\nx 1.0\n"

    def reset_breaker(self, slot):
        self.resets.append(slot)
        if slot in (9, "r9"):
            raise LookupError(f"unknown replica {slot!r}")
        if slot == "nosup":
            raise RuntimeError("reset_breaker needs auto_restart=True")
        if slot in (1, "r1"):
            return {"slot": 1, "replica": "r1", "reset": True,
                    "state": "RESTARTING"}
        return {"slot": 0, "replica": "r0", "reset": False,
                "state": "SERVING"}

    def capture_profile(self, steps=8, timeout=30.0):
        return {"r0": {"sample_every": 64, "ticks": 0, "samples": 0,
                       "shapes": [],
                       "capture": {"steps_requested": steps,
                                   "steps_captured": 0,
                                   "complete": False, "steps": []}}}

    def shutdown(self, drain=True, timeout=None):
        return True


def _post(host, port, path, payload):
    import http.client
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("POST", path, json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


class TestFrontendOperatorEndpoints:
    @pytest.fixture()
    def fe(self):
        stub = _StubRouter()
        fe = serving.HttpFrontend(stub, port=0, shutdown_router=False)
        host, port = fe.start()
        yield stub, host, port
        fe.shutdown(drain=False)

    def test_reset_breaker_matrix(self, fe):
        stub, host, port = fe
        status, body = _post(host, port, "/admin/reset_breaker",
                             {"slot": 1})
        assert status == 200 and body["ok"] is True
        assert body["state"] == "RESTARTING"
        status, body = _post(host, port, "/admin/reset_breaker",
                             {"replica": "r0"})
        assert status == 409 and body["ok"] is False
        status, body = _post(host, port, "/admin/reset_breaker",
                             {"slot": 9})
        assert status == 404
        status, body = _post(host, port, "/admin/reset_breaker",
                             {"slot": "nosup"})
        assert status == 400
        status, body = _post(host, port, "/admin/reset_breaker", {})
        assert status == 400
        assert stub.resets == [1, "r0", 9, "nosup"]

    def test_profile_endpoint(self, fe):
        stub, host, port = fe
        status, body = _post(host, port, "/debug/profile",
                             {"steps": 2, "timeout_s": 0.1})
        assert status == 200
        assert body["r0"]["capture"]["steps_requested"] == 2
        status, _ = _post(host, port, "/debug/profile", {"steps": 0})
        assert status == 400
        # unbounded windows are refused: a billion-step capture would
        # fence every device call fleet-wide and pin an executor thread
        status, _ = _post(host, port, "/debug/profile",
                          {"steps": 10 ** 9})
        assert status == 400
        status, _ = _post(host, port, "/debug/profile",
                          {"steps": 2, "timeout_s": 1e9})
        assert status == 400
