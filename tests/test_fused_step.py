"""Fused prefill+decode steps (PR 5): admission chunks piggyback on the
decode chunk call instead of stalling in-flight slots.

Three layers:

  * scheduling — step() fuses exactly when pending prefill work and
    active decode coexist (fused_steps vs decode_stall_steps), the
    fusion-off flag restores the PR4 standalone path, and a mid-stream
    chunked prefill keeps its slot reserved (free_slots / max_batch
    oversubscription regression);
  * token parity — fused schedules are token-identical to the unfused
    path on mixed admission-during-decode workloads, incl. prefix-cache
    COW admissions and chunked long prompts streaming one fused chunk
    per step;
  * accounting — fused shapes are AOT-warmed with the ladder (zero
    compiles after warmup), and failure/abort paths return every
    pending block.
"""
import importlib.util
import os

import numpy as np
import pytest
import jax

from paddle_tpu.nlp import llama, paged

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "bucket_tuner", os.path.join(_REPO, "tools", "bucket_tuner.py"))
bucket_tuner = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bucket_tuner)


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _batcher(params, cfg, max_new=8, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_total_len", 32)
    kw.setdefault("chunk", 3)
    return paged.ContinuousBatcher(params, cfg, max_new_tokens=max_new,
                                   **kw)


def _prompts(seed, lengths):
    rng = np.random.RandomState(seed)
    return [list(map(int, rng.randint(1, 200, n))) for n in lengths]


def _mid_decode_schedule(cb, first, rest):
    """Admit `first`, step until it decodes, then land `rest` one step
    apart — every later admission arrives while a slot is decoding."""
    rids = [cb.submit(first)]
    cb.step()
    for p in rest:
        rids.append(cb.submit(p))
        cb.step()
    out = cb.run()
    return [out[r] for r in rids]


class TestFusedScheduling:
    def test_fuses_only_mid_decode(self, setup):
        """Admissions landing while slots decode piggyback (fused_steps)
        and never stall; the same schedule with fusion off pays one
        standalone stall per admission burst."""
        cfg, params = setup
        a, b, c = _prompts(71, (5, 7, 6))
        for fused in (True, False):
            cb = _batcher(params, cfg, max_batch=3,
                          prefill_buckets=(8,), fused_prefill=fused)
            _mid_decode_schedule(cb, a, [b, c])
            if fused:
                assert cb.fused_steps >= 2       # b and c piggybacked
                assert cb.decode_stall_steps == 0
            else:
                assert cb.fused_steps == 0       # escape hatch: PR4 path
                assert cb.decode_stall_steps >= 2
            assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_standalone_prefill_when_decode_idle(self, setup):
        """An admission with NOTHING decoding runs standalone (no one to
        stall) — neither a fused step nor a stall."""
        cfg, params = setup
        cb = _batcher(params, cfg, fused_prefill=True)
        cb.submit(_prompts(72, (6,))[0])
        cb.run()
        assert cb.fused_steps == 0
        assert cb.decode_stall_steps == 0

    def test_chunked_prefill_reserves_slot_across_steps(self, setup):
        """Oversubscription regression: a long prompt streaming one
        fused chunk per step holds its slot the whole time — free_slots
        counts it taken, admissions never exceed max_batch, and the
        batcher refuses to hand the reserved slot to later traffic."""
        cfg, params = setup
        long_p = _prompts(73, (22,))[0]      # 6 chunks on a (4,) ladder
        a, d = _prompts(74, (6, 7))
        cb = _batcher(params, cfg, max_batch=2, prefill_buckets=(4,),
                      fused_prefill=True)
        ra = cb.submit(a)
        cb.step()                            # a decoding in slot 0
        rl = cb.submit(long_p)               # multi-chunk, mid-decode
        rd = cb.submit(d)                    # must WAIT for a slot
        cb.step()                            # long prefill now mid-stream
        # slot 0 decoding + slot 1 reserved by the pending prefill + d
        # queued: nothing left for new admissions
        assert cb._pending and cb.free_slots() == 0
        seen_active = []
        while cb._pending or cb.queue:
            cb.step()
            seen_active.append(cb.active.count(True))
            assert cb.active.count(True) <= 2
        out = cb.run()
        assert max(seen_active) <= 2
        # everyone completed despite the contention
        assert all(len(out[r]) == 8 for r in (ra, rl, rd))
        assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_abort_pending_midstream_prefill_frees_blocks(self, setup):
        """Aborting a request whose chunked prefill is mid-stream (some
        chunks written, not committed) rolls back its blocks and index
        registrations — nothing else would ever free them."""
        cfg, params = setup
        cb = _batcher(params, cfg, max_batch=2, prefill_buckets=(4,),
                      prefix_cache=True, fused_prefill=True)
        ra = cb.submit(_prompts(75, (6,))[0])
        cb.step()
        rl = cb.submit(_prompts(76, (20,))[0])
        cb.step()                            # first fused chunk ran
        assert cb._pending and cb._pending[0][1] >= 1   # mid-stream
        assert cb.abort(rl) is True
        assert not cb._pending
        cb.run()
        assert cb.alloc.stats()["blocks_in_use"] == 0
        assert ra in cb.outputs and len(cb.outputs[ra]) == 8

    def test_abort_pending_requeues_poisoned_prefix_siblings(self, setup):
        """Aborting a PENDING admission must not strand a co-pending
        sibling that matched the abortee's registered prompt blocks in
        the prefix index: those blocks' KV will now never be written, so
        the sibling is rolled back and re-prepared from the queue — and
        still produces the exact tokens of a clean run (regression:
        silent garbage from a never-computed 'cached' prefix)."""
        cfg, params = setup
        w = _prompts(79, (5,))[0]
        long_p = _prompts(80, (20,))[0]      # multi-chunk pipeline head
        shared = _prompts(81, (8,))[0]       # 2 full blocks on bs=4
        pa, pb = shared + [3, 5], shared + [7, 11, 13]

        clean = _batcher(params, cfg, max_batch=4, prefill_buckets=(4,),
                         prefix_cache=True, fused_prefill=True)
        rb = clean.submit(pb)
        expect = clean.run()[rb]

        cb = _batcher(params, cfg, max_batch=4, prefill_buckets=(4,),
                      prefix_cache=True, fused_prefill=True)
        cb.submit(w)
        cb.step()                            # w decoding in slot 0
        cb.submit(long_p)                    # holds the pending head
        ra, rb = cb.submit(pa), cb.submit(pb)
        cb.step()                            # long_p mid-stream; a + b
        pending = {r.rid for r, _ in cb._pending}
        assert ra in pending and rb in pending
        assert cb.abort(ra) is True          # b's matched chain poisoned
        out = cb.run()
        assert out[rb] == expect             # token-identical to clean
        assert cb.alloc.stats()["blocks_in_use"] == 0

    def test_failed_fused_call_rolls_back_pending(self, setup,
                                                  monkeypatch):
        """A fused-call failure returns every pending record's blocks
        (the slots were never activated) — the engine's step boundary
        relies on it, exactly like the standalone path."""
        cfg, params = setup
        cb = _batcher(params, cfg, prefill_buckets=(8,),
                      fused_prefill=True)
        cb.submit(_prompts(77, (5,))[0])
        cb.step()                            # healthy admission decodes
        in_use = cb.alloc.stats()["blocks_in_use"]
        monkeypatch.setattr(
            cb, "_fused_exe",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
        cb.submit(_prompts(78, (6,))[0])
        with pytest.raises(RuntimeError, match="boom"):
            cb.step()
        # pending rolled back; the in-flight request's blocks untouched
        assert not cb._pending
        assert cb.alloc.stats()["blocks_in_use"] == in_use


class TestFusedParity:
    """Acceptance: fused steps produce bit-identical tokens to the
    unfused PR4 path on mixed admission-during-decode schedules."""

    def _both(self, params, cfg, schedule, **kw):
        outs = []
        for fused in (False, True):
            cb = _batcher(params, cfg, fused_prefill=fused, **kw)
            outs.append(schedule(cb))
            assert cb.alloc.stats()["blocks_in_use"] == 0
        assert cb.fused_steps > 0            # the fused run really fused
        return outs

    def test_mid_decode_admissions_match_unfused(self, setup):
        cfg, params = setup
        a, b, c, d = _prompts(81, (5, 9, 13, 3))
        base, fused = self._both(
            params, cfg,
            lambda cb: _mid_decode_schedule(cb, a, [b, c, d]),
            max_batch=2)
        assert fused == base

    def test_chunked_long_prompt_mid_decode_matches(self, setup):
        """A prompt past the largest bucket streams one FUSED chunk per
        step while the neighbor keeps decoding — token-identical to the
        stall-the-world unfused chunking."""
        cfg, params = setup
        a, long_p = _prompts(82, (6, 21))
        base, fused = self._both(
            params, cfg,
            lambda cb: _mid_decode_schedule(cb, a, [long_p]),
            max_batch=2, prefill_buckets=(4,))
        assert fused == base

    def test_cow_prefix_admission_mid_decode_matches(self, setup):
        """Prefix-cache interplay: a full-hit COW admission and a
        cached-prefix + long-suffix admission both land mid-decode and
        fuse; outputs match the unfused path token for token."""
        cfg, params = setup
        rng = np.random.RandomState(83)
        head = list(map(int, rng.randint(1, 200, 8)))    # 2 full blocks
        tail = list(map(int, rng.randint(1, 200, 10)))
        filler = list(map(int, rng.randint(1, 200, 5)))

        def schedule(cb):
            r0 = cb.submit(head)             # seeds the cache
            cb.run()
            r1 = cb.submit(filler)
            cb.step()                        # filler decoding
            r2 = cb.submit(head)             # full hit -> COW, mid-decode
            cb.step()
            r3 = cb.submit(head + tail)      # cached prefix + chunked tail
            out = cb.run()
            return [out[r] for r in (r0, r1, r2, r3)]

        base, fused = self._both(params, cfg, schedule, max_batch=2,
                                 prefill_buckets=(4,), prefix_cache=True)
        assert fused == base
        assert base[0] == base[2]            # COW really replayed the hit


class TestGroupGrowingAdmission:
    """Group-growing `_units` (the PR 4 follow-on): an admission burst's
    single-chunk records regroup into the EARLIEST open same-(bucket,
    cold) unit with room — interleaved buckets no longer fragment into
    singleton prefill calls — and a record never jumps over a unit
    that registered a block it depends on (matched shared-prefix chain
    or COW source), so greedy tokens are schedule-invariant."""

    @staticmethod
    def _rec(bucket, start=0, matched=(), cow_src=None, inserted=(),
             nchunks=1):
        from types import SimpleNamespace
        chunks = [(start + i * bucket, start + (i + 1) * bucket,
                   bucket) for i in range(nchunks)]
        return SimpleNamespace(chunks=chunks, matched=list(matched),
                               cow_src=cow_src,
                               inserted=list(inserted))

    @pytest.fixture(scope="class")
    def cb(self, setup):
        cfg, params = setup
        return _batcher(params, cfg, max_batch=2,
                        prefill_buckets=(8, 16))

    def test_interleaved_buckets_regroup(self, cb):
        """A-B-A-B regroups to [A,A], [B,B] when independent (the old
        consecutive rule produced four singleton units)."""
        a1 = self._rec(8, inserted=(1,))
        b1 = self._rec(16, inserted=(2,))
        a2 = self._rec(8, inserted=(3,))
        b2 = self._rec(16, inserted=(4,))
        assert cb._units([a1, b1, a2, b2]) == [[a1, a2], [b1, b2]]

    def test_unit_capacity_respected(self, cb):
        """A full unit (max_batch records) stops growing — the third
        same-key record opens a fresh unit."""
        recs = [self._rec(8, inserted=(i,)) for i in range(3)]
        assert cb._units(recs) == [[recs[0], recs[1]], [recs[2]]]

    def test_dependency_blocks_the_jump(self, cb):
        """A record whose chain references a block an INTERMEDIATE
        unit registered must not move past it — even though an
        earlier unit has room and the right key."""
        a = self._rec(8, inserted=(1,))
        b = self._rec(16, inserted=(2,))
        c = self._rec(8, cow_src=2, inserted=(3,))   # depends on b's
        assert cb._units([a, b, c]) == [[a], [b], [c]]
        # matched (non-COW) chains gate the jump identically
        d = self._rec(8, matched=(2,), inserted=(4,))
        assert cb._units([a, b, d]) == [[a], [b], [d]]
        # ... but an independent record still jumps the same gap
        e = self._rec(8, inserted=(5,))
        assert cb._units([a, b, e]) == [[a, e], [b]]

    def test_cow_never_joins_its_source_registrant(self, cb):
        """The COW clone copies the pool OUTSIDE the compiled call, so
        the source's prefill must complete in an EARLIER unit — same
        key, room available, still a new unit."""
        a = self._rec(8, inserted=(5,))
        c = self._rec(8, cow_src=5, inserted=(6,))
        assert cb._units([a, c]) == [[a], [c]]

    def test_chunked_units_stay_closed_but_jumpable(self, cb):
        """A chunked record's unit never grows; an independent later
        record jumps over it into an earlier open unit, while a
        record depending on the chunked record's blocks stays put."""
        a = self._rec(8, inserted=(1,))
        ch = self._rec(8, inserted=(2, 3), nchunks=2)
        free = self._rec(8, inserted=(4,))
        assert cb._units([a, ch, free]) == [[a, free], [ch]]
        dep = self._rec(8, matched=(3,), inserted=(5,))
        assert cb._units([a, ch, dep]) == [[a], [ch], [dep]]

    def test_tokens_schedule_invariant(self, setup):
        """The end-to-end bar: an interleaved-bucket burst landing
        mid-decode decodes token-identically whether units group-grow
        (fused), run standalone (fusion off), or arrive pre-sorted —
        the reorder changes the schedule, never the tokens."""
        cfg, params = setup
        first = _prompts(90, (4,))[0]
        prompts = _prompts(91, (5, 12, 6, 11))   # A B A B buckets

        def serve(order, fused):
            cb = _batcher(params, cfg, max_batch=4, chunk=2,
                          prefill_buckets=(8, 16),
                          fused_prefill=fused, fused_units=2)
            cb.submit(first)
            cb.step()                            # burst lands mid-decode
            rids = {i: cb.submit(prompts[i]) for i in order}
            out = cb.run()
            return [out[rids[i]] for i in range(len(prompts))]

        ref = serve([0, 1, 2, 3], fused=False)
        assert serve([0, 1, 2, 3], fused=True) == ref
        assert serve([0, 2, 1, 3], fused=True) == ref   # pre-sorted

    def test_cow_burst_schedule_invariant(self, setup):
        """Same-prompt pair (the second COW-clones the first's tail)
        split by an alien-bucket record: the clone may not jump its
        source, and tokens still match the standalone schedule."""
        cfg, params = setup
        (p, q) = _prompts(92, (6, 12))

        def serve(fused):
            cb = _batcher(params, cfg, max_batch=4, chunk=2,
                          prefill_buckets=(8, 16), prefix_cache=True,
                          fused_prefill=fused, fused_units=2)
            r = [cb.submit(list(p)), cb.submit(q),
                 cb.submit(list(p))]
            out = cb.run()
            assert cb.prefix_stats()["hits"] >= 1
            return [out[x] for x in r]

        assert serve(True) == serve(False)


class TestBucketTuner:
    """tools/bucket_tuner.py: the pad-minimizing ladder fit over the
    batcher's `prefill_suffix_hist` accounting (pure host DP — no
    model)."""

    def test_pad_cost_matches_bucket_rule(self):
        hist = {3: 2, 5: 1, 9: 4}
        # ladder (4, 16): 3->4 (x2), 5->16, 9->16 (x4)
        assert bucket_tuner.pad_cost(hist, [4, 16]) == \
            2 * 1 + 11 + 4 * 7

    def test_fit_is_optimal_and_covers_max(self):
        hist = {3: 10, 4: 10, 16: 1}
        ladder, pad = bucket_tuner.fit_ladder(hist, 2)
        # one bucket at 4 (pad 10), one at 16 — beats (3,16): pad 130
        assert ladder == [4, 16] and pad == 10
        # k >= distinct lengths: zero pad, buckets ON the lengths
        ladder, pad = bucket_tuner.fit_ladder(hist, 5)
        assert ladder == [3, 4, 16] and pad == 0
        # one bucket: everything pads to the max length
        ladder, pad = bucket_tuner.fit_ladder(hist, 1)
        assert ladder == [16] == [max(hist)]
        assert pad == bucket_tuner.pad_cost(hist, ladder)

    def test_tune_reads_bench_record(self):
        rec = {"prefill_suffix_hist": {"3": 4, "6": 2, "14": 1},
               "prefill_buckets": [8, 16]}
        r = bucket_tuner.tune(rec)          # same 2-bucket budget
        assert r["observed_ladder"] == [8, 16]
        assert len(r["recommended_ladder"]) <= 2
        assert (r["pad_tokens_recommended"]
                <= r["pad_tokens_current_ladder"])
        dense = bucket_tuner.tune(rec, max_buckets=3)
        assert dense["pad_tokens_recommended"] == 0   # one per length

    def test_batcher_records_real_chunk_lengths(self, setup):
        """The histogram feeding the tuner holds PRE-padding lengths:
        a 5-token prompt on an (8,) ladder records 5, not 8; a chunked
        prompt records each chunk."""
        cfg, params = setup
        cb = _batcher(params, cfg, prefill_buckets=(4,))
        cb.submit(_prompts(90, (3,))[0])
        cb.submit(_prompts(90, (9,))[0])    # chunks 4 + 4 + 1
        cb.run()
        assert cb.prefill_suffix_hist == {3: 1, 4: 2, 1: 1}


class TestFusedCompileAccounting:
    def test_no_compiles_after_warmup_with_fusion(self, setup):
        """warmup_prefill covers the fused (group, bucket) ladder too:
        a mixed admission-during-decode run — groups, COW, chunked long
        prompts — never compiles a new shape afterwards."""
        cfg, params = setup
        cb = _batcher(params, cfg, max_batch=2, prefill_buckets=(4, 8),
                      prefix_cache=True, fused_prefill=True)
        warmed = cb.warmup_prefill()
        # standalone ladder x groups {1,2} x {cold,cached} + fused
        # row-counts x ladder + the standalone-decode chunk. Fused
        # rows: only REACHABLE counts warm — at max_batch=2 a fused
        # step needs 1 active slot, leaving 1 for pending records, so
        # only the single-record unit shape (rows=1) can ever run
        assert warmed == 2 * 2 * 2 + 2 * 1 + 1
        c0 = cb.compile_count
        a, b, long_p = _prompts(84, (5, 7, 19))
        _mid_decode_schedule(cb, a, [b, long_p])
        cb.submit(a)                          # warm repeat (cache hit)
        cb.run()
        assert cb.fused_steps > 0
        assert cb.compile_count == c0          # NEVER recompiled

    def test_decode_only_stretch_after_fused_is_warm(self, setup):
        """The warmup bugfix: the plain decode chunk is AOT-warmed with
        the ladder, so a decode-only stretch AFTER a fused stretch (all
        of whose steps ran the fused executable) compiles nothing. The
        flatness gate is `compile_count` — `prefill_compile_count`
        never saw the chunk fn, which is exactly how the lazy compile
        used to slip through."""
        cfg, params = setup
        cb = _batcher(params, cfg, max_batch=2, prefill_buckets=(8,),
                      fused_prefill=True)
        cb.warmup_prefill()
        c0 = cb.compile_count
        assert len(cb._chunk_cache) == 1      # the chunk warmed too
        a, b = _prompts(85, (5, 7))
        # fused stretch: b lands while a decodes -> every device call so
        # far is either a standalone prefill or the FUSED executable
        cb.submit(a)
        cb.step()
        cb.submit(b)
        cb.step()
        assert cb.fused_steps >= 1
        # decode-only stretch: nothing pending, plain chunk steps
        while any(cb.active):
            cb.step()
        assert cb.compile_count == c0

    def test_multi_unit_piggyback_drains_burst(self, setup):
        """fused_units=2: one fused call carries TWO pending units — a
        chunked long prompt's current chunk AND the short admission
        behind it (same bucket; consecutive single-chunk records merge
        into one group unit, so a chunked record is what makes two
        units co-pend) — with fused_unit_count > fused_steps and tokens
        identical to the single-unit schedule."""
        cfg, params = setup
        first, b, c = _prompts(86, (5, 19, 6))

        outs = []
        for units in (1, 2):
            cb = _batcher(params, cfg, max_batch=3, prefill_buckets=(8,),
                          fused_prefill=True, fused_units=units)
            cb.warmup_prefill()
            c0 = cb.compile_count
            rids = [cb.submit(first)]
            cb.step()
            # burst of two admissions while `first` decodes
            rids += [cb.submit(b), cb.submit(c)]
            out = cb.run()
            assert cb.compile_count == c0      # multi-unit shapes warmed
            assert cb.alloc.stats()["blocks_in_use"] == 0
            if units == 2:
                assert cb.fused_unit_count > cb.fused_steps
            else:
                assert cb.fused_unit_count == cb.fused_steps
            outs.append([out[r] for r in rids])
        assert outs[0] == outs[1]

    def test_fused_units_validation(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError):
            _batcher(params, cfg, fused_units=0)
