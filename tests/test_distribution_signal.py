"""paddle.distribution / paddle.signal / paddle.geometric /
paddle.vision.ops / paddle.inference tests (SURVEY.md §2.4 inventory rows).
Density/statistics checked against scipy; stft against numpy DFT; nms/roi
against brute-force references."""
import numpy as np
import pytest
from scipy import stats as sps

import paddle_tpu as paddle
from paddle_tpu import distribution as D

RNG = np.random.default_rng(23)


def t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


class TestDistributions:
    def test_normal(self):
        d = D.Normal(t([0.0, 1.0]), t([1.0, 2.0]))
        v = np.array([0.5, -1.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(v)).numpy(),
            sps.norm.logpdf(v, [0, 1], [1, 2]), rtol=1e-5)
        np.testing.assert_allclose(
            d.entropy().numpy(), sps.norm.entropy([0, 1], [1, 2]), rtol=1e-5)
        np.testing.assert_allclose(
            d.cdf(t(v)).numpy(), sps.norm.cdf(v, [0, 1], [1, 2]), rtol=1e-5)
        s = d.sample([10000])
        assert abs(float(s.numpy()[:, 0].mean())) < 0.05

    def test_kl_normal(self):
        p = D.Normal(t(0.0), t(1.0))
        q = D.Normal(t(1.0), t(2.0))
        expected = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(D.kl_divergence(p, q).numpy(), expected,
                                   rtol=1e-5)

    @pytest.mark.parametrize("d,ref,vals", [
        (lambda: D.Beta(t(2.0), t(3.0)), lambda v: sps.beta.logpdf(v, 2, 3),
         [0.1, 0.5, 0.9]),  # in-support (0,1)
        (lambda: D.Gamma(t(2.0), t(1.5)),
         lambda v: sps.gamma.logpdf(v, 2, scale=1 / 1.5), [0.3, 1.1, 2.7]),
        (lambda: D.Exponential(t(1.5)),
         lambda v: sps.expon.logpdf(v, scale=1 / 1.5), [0.3, 1.1, 2.7]),
        (lambda: D.Laplace(t(0.5), t(1.2)),
         lambda v: sps.laplace.logpdf(v, 0.5, 1.2), [0.3, 1.1, 2.7]),
        (lambda: D.Gumbel(t(0.0), t(1.0)),
         lambda v: sps.gumbel_r.logpdf(v), [0.3, 1.1, 2.7]),
        (lambda: D.LogNormal(t(0.0), t(1.0)),
         lambda v: sps.lognorm.logpdf(v, 1.0), [0.3, 1.1, 2.7]),
        (lambda: D.StudentT(t(4.0), t(0.0), t(1.0)),
         lambda v: sps.t.logpdf(v, 4), [0.3, 1.1, 2.7]),
        (lambda: D.Poisson(t(2.5)),
         lambda v: sps.poisson.logpmf(v, 2.5), [0.0, 1.0, 4.0]),  # integers
    ])
    def test_log_prob_vs_scipy(self, d, ref, vals):
        dist = d()
        v = np.array(vals, np.float32)
        np.testing.assert_allclose(dist.log_prob(t(v)).numpy(), ref(v),
                                   rtol=1e-4, atol=1e-5)

    def test_categorical(self):
        logits = t([[0.1, 1.0, -0.5], [2.0, 0.0, 0.0]])
        d = D.Categorical(logits=logits)
        lp = d.log_prob(paddle.to_tensor(np.array([1, 0])))
        ref = np.log(np.exp(logits.numpy())
                     / np.exp(logits.numpy()).sum(-1, keepdims=True))
        np.testing.assert_allclose(lp.numpy(), [ref[0, 1], ref[1, 0]],
                                   rtol=1e-5)
        s = d.sample([500])
        assert s.numpy().shape == (500, 2)
        e = d.entropy().numpy()
        np.testing.assert_allclose(e, [-(np.exp(ref[i]) * ref[i]).sum()
                                       for i in range(2)], rtol=1e-5)

    def test_dirichlet_multinomial(self):
        d = D.Dirichlet(t([2.0, 3.0, 4.0]))
        x = np.array([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(d.log_prob(t(x)).numpy(),
                                   sps.dirichlet.logpdf(x, [2, 3, 4]),
                                   rtol=1e-5)
        m = D.Multinomial(5, t([0.2, 0.3, 0.5]))
        counts = np.array([1.0, 2.0, 2.0], np.float32)
        np.testing.assert_allclose(
            m.log_prob(t(counts)).numpy(),
            sps.multinomial.logpmf(counts, 5, [0.2, 0.3, 0.5]), rtol=1e-5)
        s = m.sample()
        assert float(s.numpy().sum()) == 5.0

    def test_bernoulli_uniform_geometric_kl(self):
        b1, b2 = D.Bernoulli(t(0.3)), D.Bernoulli(t(0.6))
        ref = 0.3 * np.log(0.3 / 0.6) + 0.7 * np.log(0.7 / 0.4)
        np.testing.assert_allclose(D.kl_divergence(b1, b2).numpy(), ref,
                                   rtol=1e-5)
        u1 = D.Uniform(t(0.0), t(1.0))
        u2 = D.Uniform(t(-1.0), t(2.0))
        np.testing.assert_allclose(D.kl_divergence(u1, u2).numpy(),
                                   np.log(3.0), rtol=1e-5)
        assert np.isinf(D.kl_divergence(u2, u1).numpy())
        g = D.Geometric(t(0.25))
        np.testing.assert_allclose(g.mean.numpy(), 3.0, rtol=1e-5)

    def test_independent_and_transformed(self):
        base = D.Normal(t(np.zeros((3, 4))), t(np.ones((3, 4))))
        ind = D.Independent(base, 1)
        assert ind.batch_shape == [3] and ind.event_shape == [4]
        v = RNG.standard_normal((3, 4)).astype(np.float32)
        np.testing.assert_allclose(ind.log_prob(t(v)).numpy(),
                                   base.log_prob(t(v)).numpy().sum(-1),
                                   rtol=1e-5)
        # exp(Normal) == LogNormal
        td = D.TransformedDistribution(D.Normal(t(0.0), t(1.0)),
                                       [D.ExpTransform()])
        x = np.array([0.5, 1.5], np.float32)
        np.testing.assert_allclose(td.log_prob(t(x)).numpy(),
                                   sps.lognorm.logpdf(x, 1.0), rtol=1e-5)

    def test_rsample_gradient(self):
        loc = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
        # reparameterized sample: d(sample)/d(loc) == 1
        d = D.Normal(loc, t(1.0))
        s = d.rsample([8])
        s.sum().backward()
        np.testing.assert_allclose(loc.grad.numpy(), 8.0, rtol=1e-5)


class TestSignal:
    def test_stft_matches_naive_dft(self):
        x = RNG.standard_normal(512).astype(np.float32)
        n_fft, hop = 64, 16
        out = paddle.signal.stft(t(x[None]), n_fft, hop_length=hop,
                                 center=False).numpy()[0]
        # naive reference
        frames = np.stack([x[i * hop:i * hop + n_fft]
                           for i in range(1 + (512 - n_fft) // hop)])
        ref = np.fft.rfft(frames, axis=-1).T
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)

    def test_stft_istft_round_trip(self):
        x = RNG.standard_normal((2, 1024)).astype(np.float32)
        win = paddle.to_tensor(np.hanning(128).astype(np.float32))
        spec = paddle.signal.stft(t(x), 128, hop_length=32, window=win)
        back = paddle.signal.istft(spec, 128, hop_length=32, window=win,
                                   length=1024)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-4)


class TestGeometric:
    def test_segment_ops(self):
        data = t([[1.0, 2], [3, 4], [5, 6], [7, 8]])
        ids = paddle.to_tensor(np.array([0, 0, 1, 1]))
        np.testing.assert_allclose(
            paddle.geometric.segment_sum(data, ids).numpy(),
            [[4, 6], [12, 14]])
        np.testing.assert_allclose(
            paddle.geometric.segment_mean(data, ids).numpy(),
            [[2, 3], [6, 7]])
        np.testing.assert_allclose(
            paddle.geometric.segment_max(data, ids).numpy(),
            [[3, 4], [7, 8]])
        np.testing.assert_allclose(
            paddle.geometric.segment_min(data, ids).numpy(),
            [[1, 2], [5, 6]])

    def test_send_u_recv(self):
        x = t([[1.0], [2.0], [3.0]])
        src = paddle.to_tensor(np.array([0, 1, 2, 0]))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0]))
        out = paddle.geometric.send_u_recv(x, src, dst, "sum").numpy()
        np.testing.assert_allclose(out, [[1.0], [4.0], [2.0]])
        out_max = paddle.geometric.send_u_recv(x, src, dst, "max").numpy()
        np.testing.assert_allclose(out_max, [[1.0], [3.0], [2.0]])

    def test_send_ue_recv(self):
        x = t([[1.0], [2.0]])
        e = t([[10.0], [20.0]])
        src = paddle.to_tensor(np.array([0, 1]))
        dst = paddle.to_tensor(np.array([1, 0]))
        out = paddle.geometric.send_ue_recv(x, e, src, dst, "add",
                                            "sum").numpy()
        np.testing.assert_allclose(out, [[22.0], [11.0]])


class TestVisionOps:
    def test_box_iou_area(self):
        a = t([[0, 0, 2, 2], [1, 1, 3, 3]])
        np.testing.assert_allclose(paddle.vision.ops.box_area(a).numpy(),
                                   [4.0, 4.0])
        iou = paddle.vision.ops.box_iou(a, a).numpy()
        np.testing.assert_allclose(np.diag(iou), [1.0, 1.0], rtol=1e-5)
        np.testing.assert_allclose(iou[0, 1], 1.0 / 7.0, rtol=1e-5)

    def test_nms(self):
        boxes = t([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]])
        scores = t([0.9, 0.8, 0.7])
        keep = paddle.vision.ops.nms(boxes, 0.5, scores).numpy()
        np.testing.assert_array_equal(keep, [0, 2])

    def test_nms_categories(self):
        boxes = t([[0, 0, 10, 10], [1, 1, 11, 11]])
        scores = t([0.9, 0.8])
        cats = paddle.to_tensor(np.array([0, 1]))
        keep = paddle.vision.ops.nms(boxes, 0.5, scores, category_idxs=cats,
                                     categories=[0, 1]).numpy()
        assert set(keep) == {0, 1}  # different classes never suppress

    def test_roi_align_identity(self):
        x = paddle.to_tensor(
            np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        boxes = t([[0.0, 0.0, 4.0, 4.0]])
        out = paddle.vision.ops.roi_align(
            x, boxes, paddle.to_tensor(np.array([1])), output_size=2,
            spatial_scale=1.0, sampling_ratio=2, aligned=False).numpy()
        assert out.shape == (1, 1, 2, 2)

        # exact bilinear reference at the sample points (sr=2 default)
        def bil(v, y, xx):
            y0, x0 = int(np.floor(y)), int(np.floor(xx))
            y1, x1 = min(y0 + 1, 3), min(x0 + 1, 3)
            wy, wx = y - y0, xx - x0
            return (v[y0, x0] * (1 - wy) * (1 - wx)
                    + v[y0, x1] * (1 - wy) * wx
                    + v[y1, x0] * wy * (1 - wx) + v[y1, x1] * wy * wx)

        pts = [0.5, 1.5, 2.5, 3.5]
        v = x.numpy()[0, 0]
        ref = np.array([[np.mean([bil(v, pts[2 * i + a], pts[2 * j + b])
                                  for a in range(2) for b in range(2)])
                         for j in range(2)] for i in range(2)])
        np.testing.assert_allclose(out[0, 0], ref, rtol=1e-5)


class TestInference:
    def test_predictor_round_trip(self, tmp_path):
        import os
        layer = paddle.nn.Linear(4, 2)
        paddle.enable_static()
        from paddle_tpu import static
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4], "float32")
            y = layer(x)
        prefix = os.path.join(str(tmp_path), "m")
        static.save_inference_model(prefix, [x], [y], static.Executor(),
                                    program=main)
        paddle.disable_static()

        config = paddle.inference.Config(prefix + ".pdmodel")
        pred = paddle.inference.create_predictor(config)
        assert pred.get_input_names() == ["x"]
        xs = RNG.standard_normal((3, 4)).astype(np.float32)
        h = pred.get_input_handle("x")
        h.copy_from_cpu(xs)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(out, layer(t(xs)).numpy(), rtol=1e-5,
                                   atol=1e-6)


class TestDistributionGrads:
    def test_kl_param_gradients_flow(self):
        """VAE-style: KL(N(mu,exp(logsig)) || N(0,1)) must be trainable."""
        mu = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        logsig = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.2,
                                   parameters=[mu, logsig])
        first = last = None
        for _ in range(50):
            q = D.Normal(mu, logsig.exp())
            kl = D.kl_divergence(q, D.Normal(t(0.0), t(1.0)))
            kl.backward()
            opt.step()
            opt.clear_grad()
            v = float(kl.numpy())
            first = first or v
            last = v
        assert first > 1.5 and last < 0.05, (first, last)

    def test_categorical_policy_gradient(self):
        logits = paddle.to_tensor(np.zeros(3, np.float32),
                                  stop_gradient=False)
        d = D.Categorical(logits=logits)
        lp = d.log_prob(paddle.to_tensor(np.array(1)))
        lp.backward()
        g = logits.grad.numpy()
        # d log_softmax[1] / d logits = onehot(1) - softmax
        np.testing.assert_allclose(g, [-1 / 3, 2 / 3, -1 / 3], rtol=1e-5)

    def test_normal_rsample_pathwise(self):
        loc = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
        d = D.Normal(loc, t(1.0))
        s = d.rsample([8])
        s.sum().backward()
        np.testing.assert_allclose(loc.grad.numpy(), 8.0, rtol=1e-5)


class TestReviewRegressions:
    def test_send_ue_recv_empty_segment_max(self):
        x = t([[1.0], [2.0]])
        e = t([[10.0], [20.0]])
        src = paddle.to_tensor(np.array([0, 1]))
        dst = paddle.to_tensor(np.array([0, 2]))
        out = paddle.geometric.send_ue_recv(x, e, src, dst, "add", "max",
                                            out_size=3).numpy()
        np.testing.assert_allclose(out, [[11.0], [0.0], [22.0]])  # no -inf

    def test_istft_complex_round_trip(self):
        xr = RNG.standard_normal((1, 512)).astype(np.float32)
        xi = RNG.standard_normal((1, 512)).astype(np.float32)
        xc = paddle.to_tensor(xr + 1j * xi)
        win = paddle.to_tensor(np.hanning(64).astype(np.float32))
        spec = paddle.signal.stft(xc, 64, hop_length=16, window=win,
                                  onesided=False)
        back = paddle.signal.istft(spec, 64, hop_length=16, window=win,
                                   onesided=False, return_complex=True,
                                   length=512)
        np.testing.assert_allclose(back.numpy(), xr + 1j * xi, rtol=1e-3,
                                   atol=1e-4)

    def test_categorical_props_are_tensors(self):
        d = D.Categorical(logits=t([0.0, 1.0, 2.0]))
        assert hasattr(d.probs, "numpy") and hasattr(d.logits, "numpy")
        np.testing.assert_allclose(d.probs.numpy().sum(), 1.0, rtol=1e-6)


class TestIncubateFused:
    def test_fused_rms_norm(self):
        import paddle_tpu.incubate as incubate
        x = t(RNG.standard_normal((2, 8, 64)))
        w = t(np.ones(64))
        out = incubate.nn.functional.fused_rms_norm(x, w)
        ref = x.numpy() / np.sqrt(
            (x.numpy() ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_fused_rope_and_varlen_attention(self):
        import paddle_tpu.incubate as incubate
        q = t(RNG.standard_normal((2, 16, 4, 32)))
        k = t(RNG.standard_normal((2, 16, 4, 32)))
        oq, ok, _ = incubate.nn.functional.fused_rotary_position_embedding(
            q, k)
        assert oq.shape == [2, 16, 4, 32] and ok.shape == [2, 16, 4, 32]
        # norm-preserving rotation
        np.testing.assert_allclose(
            np.linalg.norm(oq.numpy(), axis=-1),
            np.linalg.norm(q.numpy(), axis=-1), rtol=1e-4)
        qb = t(RNG.standard_normal((1, 4, 16, 32)))  # B,H,S,D layout
        out = incubate.nn.functional.\
            variable_length_memory_efficient_attention(qb, qb, qb,
                                                       causal=True)
        assert out.shape == [1, 4, 16, 32]

    def test_onnx_stub(self):
        with pytest.raises(NotImplementedError):
            paddle.onnx.export(None, "x")


class TestIncubateRegressions:
    def test_rope_long_cached_table(self):
        """Tables longer than seq must be row-sliced, not reshaped."""
        import jax.numpy as jnp
        import paddle_tpu.incubate as incubate
        from paddle_tpu.kernels.rope import rope_freqs, apply_rope_half
        q = t(RNG.standard_normal((1, 16, 2, 32)))
        cos, sin = rope_freqs(32, 64)  # max_pos=64 > seq=16
        oq, _, _ = incubate.nn.functional.fused_rotary_position_embedding(
            q, cos=paddle.to_tensor(np.asarray(cos)),
            sin=paddle.to_tensor(np.asarray(sin)))
        ref, _ = apply_rope_half(jnp.asarray(q.numpy()),
                                 jnp.asarray(q.numpy()), cos, sin)
        np.testing.assert_allclose(oq.numpy(), np.asarray(ref), rtol=1e-5)

    def test_rope_position_ids(self):
        import paddle_tpu.incubate as incubate
        q = t(RNG.standard_normal((1, 4, 2, 16)))
        base, _, _ = incubate.nn.functional.fused_rotary_position_embedding(q)
        shifted, _, _ = incubate.nn.functional.\
            fused_rotary_position_embedding(
                q, position_ids=paddle.to_tensor(np.array([[8, 9, 10, 11]])))
        assert not np.allclose(base.numpy(), shifted.numpy())

    def test_varlen_attention_masks_padding(self):
        import paddle_tpu.incubate as incubate
        q = t(RNG.standard_normal((1, 2, 8, 16)))  # B,H,S,D
        k = t(RNG.standard_normal((1, 2, 8, 16)))
        v = t(RNG.standard_normal((1, 2, 8, 16)))
        full = incubate.nn.functional.\
            variable_length_memory_efficient_attention(q, k, v)
        masked = incubate.nn.functional.\
            variable_length_memory_efficient_attention(
                q, k, v, seq_lens=paddle.to_tensor(np.array([4])))
        assert not np.allclose(full.numpy(), masked.numpy())
        # masked result must equal attention over the first 4 keys only
        ref = incubate.nn.functional.\
            variable_length_memory_efficient_attention(
                q, k[:, :, :4], v[:, :, :4])
        np.testing.assert_allclose(masked.numpy(), ref.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_fused_layer_norm_delegates(self):
        import paddle_tpu.incubate as incubate
        x = t(RNG.standard_normal((2, 8)))
        w, b = t(np.ones(8)), t(np.zeros(8))
        out = incubate.nn.functional.fused_layer_norm(x, w, b)
        ref = paddle.nn.functional.layer_norm(x, 8, weight=w, bias=b)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)
        with pytest.raises(NotImplementedError):
            incubate.nn.functional.fused_layer_norm(x, w, b,
                                                    begin_norm_axis=0)

    def test_istft_rejects_onesided_complex(self):
        spec = paddle.to_tensor(np.zeros((1, 33, 4), np.complex64))
        with pytest.raises(ValueError):
            paddle.signal.istft(spec, 64, return_complex=True)


class TestRound3Distributions:
    """The remaining paddle.distribution surface (round 3): closed-form
    log_prob/moment checks like the reference's distribution tests."""

    def test_multivariate_normal(self):
        import math
        D = paddle.distribution
        mvn = D.MultivariateNormal(
            paddle.to_tensor(np.zeros(3, np.float32)),
            covariance_matrix=paddle.to_tensor(
                np.eye(3, dtype=np.float32) * 2))
        lp = float(mvn.log_prob(
            paddle.to_tensor(np.zeros(3, np.float32))).numpy())
        expect = -1.5 * math.log(2 * math.pi) - 1.5 * math.log(2.0)
        assert abs(lp - expect) < 1e-5
        ent = float(mvn.entropy().numpy())
        assert abs(ent - (1.5 * (1 + math.log(2 * math.pi))
                          + 1.5 * math.log(2.0))) < 1e-5
        s = mvn.sample((500,))
        assert np.allclose(np.var(s.numpy(), 0), 2.0, atol=0.6)

    def test_binomial_and_cauchy(self):
        import math
        D = paddle.distribution
        b = D.Binomial(paddle.to_tensor(10.0), paddle.to_tensor(0.5))
        assert float(b.mean.numpy()) == 5.0
        assert abs(float(b.log_prob(paddle.to_tensor(5.0)).numpy())
                   - math.log(math.comb(10, 5) * 0.5 ** 10)) < 1e-5
        c = D.Cauchy(paddle.to_tensor(0.0), paddle.to_tensor(1.0))
        assert abs(float(c.log_prob(paddle.to_tensor(0.0)).numpy())
                   + math.log(math.pi)) < 1e-5
        assert abs(float(c.cdf(paddle.to_tensor(0.0)).numpy()) - 0.5) < 1e-6

    def test_chisq_continuous_bernoulli_lkj(self):
        D = paddle.distribution
        chi = D.ChiSquared(paddle.to_tensor(4.0))
        assert abs(float(np.mean(chi.sample((3000,)).numpy())) - 4.0) < 0.5
        cb = D.ContinuousBernoulli(paddle.to_tensor(0.3))
        # density integrates to ~1 over a grid
        xs = np.linspace(1e-4, 1 - 1e-4, 2001, dtype=np.float32)
        pdf = np.exp(cb.log_prob(paddle.to_tensor(xs)).numpy())
        assert abs(np.trapezoid(pdf, xs) - 1.0) < 1e-2
        lkj = D.LKJCholesky(4, 1.5)
        L = lkj.sample()
        corr = L.numpy() @ L.numpy().T
        assert np.allclose(np.diag(corr), 1.0, atol=1e-5)
        assert np.isfinite(float(lkj.log_prob(
            paddle.to_tensor(L.numpy())).numpy()))

    def test_transform_long_tail(self):
        import math
        D = paddle.distribution
        sb = D.StickBreakingTransform()
        x = paddle.to_tensor(np.array([0.2, -0.3, 0.5], np.float32))
        y = sb.forward(x)
        assert abs(float(y.numpy().sum()) - 1.0) < 1e-5
        np.testing.assert_allclose(sb.inverse(y).numpy(), x.numpy(),
                                   atol=1e-5)
        ch = D.ChainTransform([
            D.AffineTransform(paddle.to_tensor(1.0), paddle.to_tensor(2.0)),
            D.ExpTransform()])
        assert abs(float(ch.forward(paddle.to_tensor(0.0)).numpy())
                   - math.e) < 1e-5
        pw = D.PowerTransform(paddle.to_tensor(2.0))
        np.testing.assert_allclose(
            pw.inverse(pw.forward(paddle.to_tensor(3.0))).numpy(), 3.0,
            rtol=1e-6)
        sm = D.SoftmaxTransform()
        v = sm.forward(paddle.to_tensor(np.array([1., 2., 3.], np.float32)))
        assert abs(float(v.numpy().sum()) - 1.0) < 1e-6
        rs = D.ReshapeTransform((4,), (2, 2))
        out = rs.forward(paddle.to_tensor(np.zeros((3, 4), np.float32)))
        assert out.shape == [3, 2, 2]
