"""Semi-auto parallel API tests (ProcessMesh/shard_tensor/reshard/shard_layer)
on the 8-virtual-device CPU platform (conftest).

Mirrors the reference's test/auto_parallel/ approach (SURVEY.md §4): assert on
sharding metadata and on resharded numerics without real multi-host.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import ProcessMesh, Replicate, Shard, Partial


@pytest.fixture
def mesh2x4():
    return ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])


class TestProcessMesh:
    def test_basic(self, mesh2x4):
        assert mesh2x4.shape == [2, 4]
        assert mesh2x4.ndim == 2
        assert mesh2x4.dim_names == ["x", "y"]
        assert mesh2x4.process_ids == list(range(8))
        assert mesh2x4.size == 8
        assert mesh2x4.get_dim_size("y") == 4

    def test_equality_and_pickle(self, mesh2x4):
        import pickle
        other = pickle.loads(pickle.dumps(mesh2x4))
        assert other == mesh2x4
        assert hash(other) == hash(mesh2x4)

    def test_submesh(self, mesh2x4):
        sub = mesh2x4.get_mesh_with_dim("x", 0)
        assert sub.shape == [4]
        assert sub.process_ids == [0, 1, 2, 3]

    def test_jax_mesh(self, mesh2x4):
        m = mesh2x4.jax_mesh()
        assert m.axis_names == ("x", "y")
        assert m.devices.shape == (2, 4)


class TestShardTensor:
    def test_shard_dim0(self, mesh2x4):
        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        d = dist.shard_tensor(x, mesh2x4, [Shard(0), Replicate()])
        sh = d._data.sharding
        assert isinstance(sh, NamedSharding)
        assert sh.spec == P("x")
        # each x-shard holds 4 rows, replicated over y
        shard_shapes = {s.data.shape for s in d._data.addressable_shards}
        assert shard_shapes == {(4, 8)}
        np.testing.assert_array_equal(np.asarray(d._data), x)

    def test_shard_both_dims(self, mesh2x4):
        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        d = dist.shard_tensor(x, mesh2x4, [Shard(0), Shard(1)])
        assert d._data.sharding.spec == P("x", "y")
        assert {s.data.shape for s in d._data.addressable_shards} == {(4, 2)}

    def test_default_replicate_and_partial_resolution(self, mesh2x4):
        x = np.ones((4, 4), np.float32)
        d = dist.shard_tensor(x, mesh2x4)
        assert all(p.is_replicate() for p in d.placements)
        d2 = dist.shard_tensor(x, mesh2x4, [Partial(), Shard(1)])
        assert d2.placements[0].is_replicate()
        assert d2.placements[1] == Shard(1)

    def test_negative_dim_and_errors(self, mesh2x4):
        x = np.ones((4, 8), np.float32)
        d = dist.shard_tensor(x, mesh2x4, [Replicate(), Shard(-1)])
        assert d._data.sharding.spec == P(None, "y")
        with pytest.raises(ValueError):
            dist.shard_tensor(x, mesh2x4, [Shard(5)])
        with pytest.raises(ValueError):
            dist.shard_tensor(x, mesh2x4, [Shard(0)] * 3)

    def test_dtensor_from_fn(self, mesh2x4):
        d = dist.dtensor_from_fn(paddle.ones, mesh2x4, [Shard(0)], [8, 4])
        assert d._data.sharding.spec == P("x")
        np.testing.assert_array_equal(np.asarray(d._data), np.ones((8, 4)))


class TestReshard:
    def test_round_trip(self, mesh2x4):
        x = np.random.default_rng(0).standard_normal((8, 8)).astype(np.float32)
        d = dist.shard_tensor(x, mesh2x4, [Shard(0), Replicate()])
        d2 = dist.reshard(d, mesh2x4, [Replicate(), Shard(1)])
        assert d2._data.sharding.spec == P(None, "y")
        np.testing.assert_array_equal(np.asarray(d2._data), x)
        d3 = dist.unshard_dtensor(d2)
        assert all(p.is_replicate() for p in d3.placements)
        np.testing.assert_array_equal(np.asarray(d3._data), x)

    def test_unshard_op_output_without_metadata(self, mesh2x4):
        """Op outputs carry only the jax NamedSharding — unshard must still
        gather them (review regression)."""
        x = np.random.default_rng(2).standard_normal((8, 8)).astype(np.float32)
        d = dist.shard_tensor(x, mesh2x4, [Shard(0)])
        y = jax.jit(lambda a: a * 2.0)(d._data)
        out = dist.unshard_dtensor(paddle.to_tensor(y))
        assert all(p.is_replicate() for p in out.placements)
        np.testing.assert_allclose(np.asarray(out._data), x * 2.0, rtol=1e-6)

    def test_sharded_compute(self, mesh2x4):
        """Sharded operands: XLA propagates shardings through jit compute."""
        rng = np.random.default_rng(1)
        a = rng.standard_normal((8, 16)).astype(np.float32)
        b = rng.standard_normal((16, 8)).astype(np.float32)
        da = dist.shard_tensor(a, mesh2x4, [Shard(0), Replicate()])
        db = dist.shard_tensor(b, mesh2x4, [Replicate(), Shard(1)])
        out = jax.jit(jnp.matmul)(da._data, db._data)
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=2e-5)


class TestDifferentiableReshard:
    def test_grad_flows_through_reshard(self, mesh2x4):
        """reshard inside a forward pass must not detach the graph
        (review regression)."""
        w = paddle.to_tensor(np.ones((8, 8), np.float32),
                             stop_gradient=False)
        y = dist.reshard(w * 2.0, mesh2x4, [Shard(0)])
        loss = y.sum()
        loss.backward()
        assert w.grad is not None
        np.testing.assert_allclose(w.grad.numpy(),
                                   np.full((8, 8), 2.0), rtol=1e-6)

    def test_shard_tensor_stop_gradient_override(self, mesh2x4):
        t = paddle.to_tensor(np.ones((4, 4), np.float32))  # stop_grad True
        d = dist.shard_tensor(t, mesh2x4, stop_gradient=False)
        assert not d.stop_gradient
        d2 = dist.shard_tensor(t, mesh2x4)  # inherit
        assert d2.stop_gradient


class TestShardLayerOptimizer:
    def test_shard_layer_default(self, mesh2x4):
        layer = paddle.nn.Linear(8, 8)
        dist.shard_layer(layer, mesh2x4)
        for _, p in layer.named_parameters():
            assert isinstance(p._data.sharding, NamedSharding)
            assert p.process_mesh == mesh2x4

    def test_shard_layer_custom_fn(self, mesh2x4):
        layer = paddle.nn.Linear(8, 8)

        def megatron_col(name, sub, mesh):
            if hasattr(sub, "weight") and sub.weight is not None:
                s = dist.shard_tensor(sub.weight, mesh,
                                      [Replicate(), Shard(1)])
                sub.weight._rebind(s._data)
                sub.weight.placements = s.placements

        dist.shard_layer(layer, mesh2x4, megatron_col)
        assert layer.weight._data.sharding.spec == P(None, "y")

    def test_shard_optimizer_replaces_state(self, mesh2x4):
        layer = paddle.nn.Linear(8, 8)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=layer.parameters())
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        loss = layer(x).mean()
        loss.backward()
        opt.step()
        dist.shard_layer(layer, mesh2x4)
        dist.shard_optimizer(opt)
        st = opt._state[id(layer.weight)]
        for k, v in st.items():
            if getattr(v, "shape", None) == layer.weight._data.shape:
                assert v.sharding == layer.weight._data.sharding


class TestAutoEngine:
    """auto.Engine facade (SURVEY.md §3.4's semi-auto entry point — the
    reference's completion/partitioner/reshard pipeline is GSPMD here, so
    Engine is the trainer loop over placed tensors)."""

    def _engine(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet import auto
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        return auto.Engine(model, nn.CrossEntropyLoss(), opt,
                           strategy=auto.Strategy())

    def _data(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.io import TensorDataset
        rng = np.random.RandomState(0)
        return TensorDataset([
            paddle.to_tensor(rng.randn(32, 8).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 4, (32,)))])

    def test_fit_evaluate_predict_save_load(self, tmp_path):
        import os
        engine = self._engine()
        ds = self._data()
        hist = engine.fit(ds, epochs=2, batch_size=8, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]
        ev = engine.evaluate(ds, batch_size=8, verbose=0)
        assert ev["loss"] is not None
        preds = engine.predict(ds, batch_size=8)
        assert len(preds) == 4 and preds[0].shape == [8, 4]
        engine.save(os.path.join(str(tmp_path), "ckpt"))
        engine2 = self._engine()
        engine2.load(os.path.join(str(tmp_path), "ckpt"))
        import numpy as np
        np.testing.assert_allclose(
            engine.model[0].weight.numpy(),
            engine2.model[0].weight.numpy())

    def test_strategy_knobs(self):
        from paddle_tpu.distributed.fleet import auto
        s = auto.Strategy()
        s.amp.enable = True
        s.recompute.enable = True
        assert s.amp.dtype == "bfloat16" and s.sharding.stage == 1

    def test_metrics_through_engine(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet import auto
        engine = self._engine()
        engine.metrics = [paddle.metric.Accuracy(topk=(1, 2))]
        ev = engine.evaluate(self._data(), batch_size=8, verbose=0)
        assert "acc_top1" in ev and "acc_top2" in ev
        assert 0.0 <= ev["acc_top1"] <= ev["acc_top2"] <= 1.0


class TestCompiledEngine:
    """VERDICT r2 weak 1: the Engine must COMPILE its Strategy — mesh +
    specs for sharding stages, jax.checkpoint for recompute, one jitted
    sharded train step for fit (no per-step host sync)."""

    def _setup(self, stage):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet import auto
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                              nn.Linear(64, 4))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        s = auto.Strategy()
        s.sharding.enable = True
        s.sharding.stage = stage
        engine = auto.Engine(model, nn.CrossEntropyLoss(), opt, strategy=s)
        rng = np.random.RandomState(0)
        X = rng.randn(64, 16).astype("float32")
        Y = np.abs(X[:, :4]).argmax(axis=1).astype("int64")
        data = [(X[i:i + 16], Y[i:i + 16]) for i in range(0, 64, 16)]
        return engine, data

    def test_stage3_fit_shards_params_and_trains(self):
        from jax.sharding import PartitionSpec as P
        engine, data = self._setup(stage=3)
        hist = engine.fit(data, epochs=3, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]
        # strategy -> mesh with the full sharding axis
        assert engine._mesh is not None
        assert engine._mesh.shape["sharding"] == 8
        # param shardings match the strategy: every 2D+ param carries the
        # 'sharding' axis (ZeRO-3/FSDP), and the LIVE post-fit params are
        # actually placed with those shardings
        entries = engine.model.state_dict()
        for name, sh in engine._param_shardings.items():
            if entries[name]._data.ndim >= 2:
                axes = [a for e in sh.spec if e
                        for a in ((e,) if isinstance(e, str) else e)]
                assert "sharding" in axes, (name, sh.spec)
            live = entries[name]._data
            assert live.sharding.is_equivalent_to(sh, live.ndim), name

    def test_stage1_keeps_params_replicated_shards_opt(self):
        import jax
        engine, data = self._setup(stage=1)
        engine.fit(data, epochs=1, verbose=0)
        from jax.sharding import PartitionSpec as P
        for name, sh in engine._param_shardings.items():
            assert sh.spec == P(), (name, sh.spec)
        # optimizer moments got the FSDP axis
        opt = engine.optimizer
        entries = engine.model.state_dict()
        w = entries["0.weight"]
        m = opt._state[id(w)]["moment1"]
        specs = str(m.sharding)
        assert "sharding" in specs, specs

    def test_recompute_wraps_children(self):
        import paddle_tpu.nn as nn
        engine, data = self._setup(stage=1)
        engine.strategy.recompute.enable = True
        fwd_before = [sub.forward for _, sub in
                      engine.model.named_children()]
        engine.fit(data, epochs=1, verbose=0)
        fwd_after = [sub.forward for _, sub in
                     engine.model.named_children()]
        assert all(a is not b for a, b in zip(fwd_before, fwd_after))
