"""paddle.audio tests — mel scale/fbank/DCT vs known values; feature layers
shape + consistency with paddle.signal.stft (SURVEY.md §2.4 domain rows)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.audio import functional as AF

RNG = np.random.default_rng(41)


class TestFunctional:
    def test_mel_round_trip(self):
        for htk in (False, True):
            f = np.array([100.0, 440.0, 4000.0], np.float32)
            m = AF.hz_to_mel(paddle.to_tensor(f), htk=htk)
            back = AF.mel_to_hz(m, htk=htk)
            np.testing.assert_allclose(back.numpy(), f, rtol=1e-4)

    def test_hz_to_mel_htk_scalar(self):
        # classic anchor: 1000 Hz ~ 1000 mel (HTK)
        assert abs(AF.hz_to_mel(1000.0, htk=True) - 999.99) < 0.1

    def test_fbank_matrix(self):
        fb = AF.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        assert (fb.sum(axis=1) > 0).all()  # every filter non-empty

    def test_dct_orthonormal(self):
        d = AF.create_dct(13, 40).numpy()
        assert d.shape == (40, 13)
        gram = d.T @ d
        np.testing.assert_allclose(gram, np.eye(13), atol=1e-5)

    def test_get_window(self):
        w = AF.get_window("hann", 64).numpy()
        assert w.shape == (64,)
        np.testing.assert_allclose(w, np.hanning(65)[:-1], rtol=1e-6)
        with pytest.raises(ValueError):
            AF.get_window("nope", 8)
        tk = AF.get_window(("tukey", 0.5), 64)  # scipy zoo fallback
        assert tk.shape == [64]

    def test_power_to_db(self):
        x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = AF.power_to_db(x, top_db=None).numpy()
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-5)


class TestFeatures:
    def test_spectrogram_matches_stft(self):
        x = paddle.to_tensor(RNG.standard_normal((2, 2048))
                             .astype(np.float32))
        layer = paddle.audio.Spectrogram(n_fft=256, hop_length=128,
                                         power=2.0)
        out = layer(x)
        spec = paddle.signal.stft(x, 256, 128, window=layer.window)
        np.testing.assert_allclose(out.numpy(),
                                   np.abs(spec.numpy()) ** 2, rtol=1e-4,
                                   atol=1e-5)
        # reference default: magnitude (power=1) spectrum
        mag = paddle.audio.Spectrogram(n_fft=256, hop_length=128)(x)
        np.testing.assert_allclose(mag.numpy(), np.abs(spec.numpy()),
                                   rtol=1e-4, atol=1e-5)

    def test_mel_and_mfcc_shapes(self):
        x = paddle.to_tensor(RNG.standard_normal((1, 16000))
                             .astype(np.float32))
        mel = paddle.audio.MelSpectrogram(sr=16000, n_fft=512,
                                          hop_length=256, n_mels=40)(x)
        assert mel.shape[1] == 40
        logmel = paddle.audio.LogMelSpectrogram(
            sr=16000, n_fft=512, hop_length=256, n_mels=40)(x)
        assert logmel.shape == mel.shape
        assert float(logmel.max().numpy()) <= 10 * np.log10(
            float(mel.max().numpy())) + 1e-3
        mfcc = paddle.audio.MFCC(sr=16000, n_mfcc=13, n_fft=512,
                                 hop_length=256, n_mels=40)(x)
        assert mfcc.shape[1] == 13


class TestWorkerInfo:
    def test_main_process_none(self):
        from paddle_tpu.io import get_worker_info
        assert get_worker_info() is None

    def test_worker_sees_info(self):
        from paddle_tpu.io import DataLoader, Dataset, get_worker_info

        class DS(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                info = get_worker_info()
                assert info is not None and info.num_workers == 2
                return np.float32(info.id)

        loader = DataLoader(DS(), batch_size=2, num_workers=2)
        ids = set()
        for batch in loader:
            ids.update(batch.numpy().tolist())
        assert ids <= {0.0, 1.0}
