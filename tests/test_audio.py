"""paddle.audio tests — mel scale/fbank/DCT vs known values; feature layers
shape + consistency with paddle.signal.stft (SURVEY.md §2.4 domain rows)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.audio import functional as AF

RNG = np.random.default_rng(41)


class TestFunctional:
    def test_mel_round_trip(self):
        for htk in (False, True):
            f = np.array([100.0, 440.0, 4000.0], np.float32)
            m = AF.hz_to_mel(paddle.to_tensor(f), htk=htk)
            back = AF.mel_to_hz(m, htk=htk)
            np.testing.assert_allclose(back.numpy(), f, rtol=1e-4)

    def test_hz_to_mel_htk_scalar(self):
        # classic anchor: 1000 Hz ~ 1000 mel (HTK)
        assert abs(AF.hz_to_mel(1000.0, htk=True) - 999.99) < 0.1

    def test_fbank_matrix(self):
        fb = AF.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        assert (fb.sum(axis=1) > 0).all()  # every filter non-empty

    def test_dct_orthonormal(self):
        d = AF.create_dct(13, 40).numpy()
        assert d.shape == (40, 13)
        gram = d.T @ d
        np.testing.assert_allclose(gram, np.eye(13), atol=1e-5)

    def test_get_window(self):
        w = AF.get_window("hann", 64).numpy()
        assert w.shape == (64,)
        np.testing.assert_allclose(w, np.hanning(65)[:-1], rtol=1e-6)
        with pytest.raises(ValueError):
            AF.get_window("nope", 8)
        tk = AF.get_window(("tukey", 0.5), 64)  # scipy zoo fallback
        assert tk.shape == [64]

    def test_power_to_db(self):
        x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = AF.power_to_db(x, top_db=None).numpy()
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-5)


class TestFeatures:
    def test_spectrogram_matches_stft(self):
        x = paddle.to_tensor(RNG.standard_normal((2, 2048))
                             .astype(np.float32))
        layer = paddle.audio.Spectrogram(n_fft=256, hop_length=128,
                                         power=2.0)
        out = layer(x)
        spec = paddle.signal.stft(x, 256, 128, window=layer.window)
        np.testing.assert_allclose(out.numpy(),
                                   np.abs(spec.numpy()) ** 2, rtol=1e-4,
                                   atol=1e-5)
        # reference default: magnitude (power=1) spectrum
        mag = paddle.audio.Spectrogram(n_fft=256, hop_length=128)(x)
        np.testing.assert_allclose(mag.numpy(), np.abs(spec.numpy()),
                                   rtol=1e-4, atol=1e-5)

    def test_mel_and_mfcc_shapes(self):
        x = paddle.to_tensor(RNG.standard_normal((1, 16000))
                             .astype(np.float32))
        mel = paddle.audio.MelSpectrogram(sr=16000, n_fft=512,
                                          hop_length=256, n_mels=40)(x)
        assert mel.shape[1] == 40
        logmel = paddle.audio.LogMelSpectrogram(
            sr=16000, n_fft=512, hop_length=256, n_mels=40)(x)
        assert logmel.shape == mel.shape
        assert float(logmel.max().numpy()) <= 10 * np.log10(
            float(mel.max().numpy())) + 1e-3
        mfcc = paddle.audio.MFCC(sr=16000, n_mfcc=13, n_fft=512,
                                 hop_length=256, n_mels=40)(x)
        assert mfcc.shape[1] == 13


class TestWorkerInfo:
    def test_main_process_none(self):
        from paddle_tpu.io import get_worker_info
        assert get_worker_info() is None

    def test_worker_sees_info(self):
        from paddle_tpu.io import DataLoader, Dataset, get_worker_info

        class DS(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                info = get_worker_info()
                assert info is not None and info.num_workers == 2
                return np.float32(info.id)

        loader = DataLoader(DS(), batch_size=2, num_workers=2)
        ids = set()
        for batch in loader:
            ids.update(batch.numpy().tolist())
        assert ids <= {0.0, 1.0}


class TestAudioBackends:
    def test_save_load_info_roundtrip(self, tmp_path):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import audio
        assert audio.backends.list_available_backends() == ["wave"]
        assert audio.backends.get_current_audio_backend() == "wave"
        sr = 16000
        t = np.linspace(0, 1, sr, endpoint=False)
        wav = np.stack([0.5 * np.sin(2 * np.pi * 440 * t),
                        0.25 * np.sin(2 * np.pi * 220 * t)]).astype("float32")
        f = str(tmp_path / "tone.wav")
        audio.save(f, paddle.to_tensor(wav), sr)
        meta = audio.info(f)
        assert (meta.sample_rate, meta.num_channels,
                meta.bits_per_sample) == (sr, 2, 16)
        back, sr2 = audio.load(f)
        assert sr2 == sr
        np.testing.assert_allclose(back.numpy(), wav, atol=2e-4)
        # offset/num_frames windowing
        part, _ = audio.load(f, frame_offset=100, num_frames=50)
        np.testing.assert_allclose(part.numpy(), wav[:, 100:150], atol=2e-4)

    def test_set_backend_rejects_unknown(self):
        import pytest
        from paddle_tpu import audio
        with pytest.raises(NotImplementedError):
            audio.backends.set_backend("soundfile")


def _tone_wav_bytes(freq, sr=4000, n=2000):
    import io
    import wave

    import numpy as np
    t = np.arange(n) / sr
    pcm = (0.4 * np.sin(2 * np.pi * freq * t) * 32767).astype("<i2")
    buf = io.BytesIO()
    with wave.open(buf, "wb") as f:
        f.setnchannels(1)
        f.setsampwidth(2)
        f.setframerate(sr)
        f.writeframes(pcm.tobytes())
    return buf.getvalue()


class TestAudioDatasets:
    def _tess_zip(self, tmp_path):
        import zipfile
        p = str(tmp_path / "tess.zip")
        emotions = ["angry", "happy", "sad", "neutral", "fear"]
        with zipfile.ZipFile(p, "w") as zf:
            for i in range(10):
                emo = emotions[i % len(emotions)]
                zf.writestr(f"TESS/OAF_word{i}_{emo}.wav",
                            _tone_wav_bytes(200 + 40 * i))
        return p

    def test_tess_split_and_labels(self, tmp_path):
        from paddle_tpu.audio.datasets import TESS
        p = self._tess_zip(tmp_path)
        train = TESS(mode="train", n_folds=5, split=1, data_file=p)
        dev = TESS(mode="dev", n_folds=5, split=1, data_file=p)
        assert len(train) + len(dev) == 10 and len(dev) == 2
        wav, label = train[0]
        assert wav.ndim == 1 and wav.size == 2000
        assert 0 <= int(label) < len(TESS.label_list)

    def test_tess_feature_mode(self, tmp_path):
        from paddle_tpu.audio.datasets import TESS
        p = self._tess_zip(tmp_path)
        ds = TESS(mode="dev", split=1, data_file=p,
                  feat_type="melspectrogram", sr=4000, n_fft=256,
                  hop_length=128, n_mels=16)
        feat, _ = ds[0]
        assert feat.shape[0] == 16

    def test_esc50_meta_folds(self, tmp_path):
        import zipfile
        from paddle_tpu.audio.datasets import ESC50
        p = str(tmp_path / "esc50.zip")
        rows = ["filename,fold,target,category"]
        with zipfile.ZipFile(p, "w") as zf:
            for i in range(8):
                name = f"{i}.wav"
                fold = i % 4 + 1
                rows.append(f"{name},{fold},{i % 3},cat{i % 3}")
                zf.writestr(f"ESC-50/audio/{name}",
                            _tone_wav_bytes(150 + 30 * i))
            zf.writestr("ESC-50/meta/esc50.csv", "\n".join(rows))
        train = ESC50(mode="train", split=2, data_file=p)
        dev = ESC50(mode="dev", split=2, data_file=p)
        assert len(train) == 6 and len(dev) == 2
        wav, label = dev[0]
        assert wav.ndim == 1 and 0 <= int(label) < 3
