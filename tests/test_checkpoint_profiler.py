"""Distributed checkpoint (orbax, reshard-on-load, async) + profiler facade.

Reference analog: paddle.distributed.checkpoint save/load tests and
paddle.profiler API tests (SURVEY.md §5 checkpoint/tracing rows).
"""
import glob
import os
import tempfile

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed.checkpoint as dck
from paddle_tpu import profiler as prof
from paddle_tpu.parallel.topology import build_mesh
from paddle_tpu.nlp import llama, train


class TestDistributedCheckpoint:
    def test_save_load_roundtrip_plain(self, tmp_path):
        sd = {"w": paddle.to_tensor(np.arange(12.0, dtype="float32")
                                    .reshape(3, 4)),
              "step": 3}
        d = str(tmp_path / "ck")
        dck.save_state_dict(sd, d)
        target = {"w": paddle.zeros([3, 4]), "step": 0}
        out = dck.load_state_dict(target, d)
        np.testing.assert_array_equal(out["w"].numpy(), sd["w"].numpy())
        assert int(out["step"]) == 3
        # in-place mutation parity: the passed dict's tensors were updated
        np.testing.assert_array_equal(target["w"].numpy(), sd["w"].numpy())

    def test_reshard_on_load(self, tmp_path):
        mesh_a = build_mesh(dp=2, mp=4)
        cfg = llama.LlamaConfig.tiny()
        tx = train.make_optimizer(1e-3)
        state = train.init_state(jax.random.key(0), cfg, tx, mesh_a)
        d = str(tmp_path / "ck")
        dck.save_state_dict({"params": state.params}, d)

        mesh_b = build_mesh(dp=1, sharding=4, mp=2)
        specs = llama.param_specs(cfg)
        target = jax.tree.map(
            lambda spec, v: jax.device_put(
                jnp.zeros(v.shape, v.dtype), NamedSharding(mesh_b, spec)),
            specs, state.params, is_leaf=lambda x: isinstance(x, P))
        restored = dck.load_state_dict({"params": target}, d)
        errs = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(
                a.astype(jnp.float32) - b.astype(jnp.float32)))),
            restored["params"], state.params)
        assert max(jax.tree.leaves(errs)) == 0.0
        q = restored["params"]["layers"]["q_proj"]
        assert q.sharding.spec == P(None, "sharding", "mp")

    def test_async_save(self, tmp_path):
        d = str(tmp_path / "ck")
        sd = {"w": paddle.to_tensor(np.ones((4, 4), np.float32))}
        dck.save_state_dict(sd, d, async_save=True)
        dck.wait_async_save()
        out = dck.load_state_dict({"w": paddle.zeros([4, 4])}, d)
        np.testing.assert_array_equal(out["w"].numpy(), np.ones((4, 4)))


class TestProfiler:
    def test_scheduler_states(self):
        sched = prof.make_scheduler(closed=1, ready=1, record=2, repeat=1)
        names = [sched(i).name for i in range(6)]
        assert names == ["CLOSED", "READY", "RECORD", "RECORD_AND_RETURN",
                         "CLOSED", "CLOSED"]

    def test_skip_first(self):
        sched = prof.make_scheduler(closed=0, ready=0, record=1,
                                    skip_first=2)
        assert sched(0).name == "CLOSED" and sched(1).name == "CLOSED"
        assert sched(2).name == "RECORD_AND_RETURN"

    def test_profiler_writes_trace(self, tmp_path):
        d = str(tmp_path / "prof")
        cb = prof.export_chrome_tracing(d)
        with prof.Profiler(targets=[prof.ProfilerTarget.CPU],
                           scheduler=(1, 3), on_trace_ready=cb) as p:
            for _ in range(4):
                with prof.RecordEvent("compute"):
                    x = paddle.to_tensor(
                        np.random.randn(16, 16).astype("float32"))
                    (x @ x).sum()
                p.step()
        assert glob.glob(d + "/**/*", recursive=True)

    def test_record_event_standalone(self):
        ev = prof.RecordEvent("span")
        ev.begin()
        ev.end()

    def test_timer_only_mode(self):
        with prof.Profiler(timer_only=True) as p:
            p.step()


class TestDeviceMemoryStats:
    """paddle.device.cuda.* memory introspection (SURVEY.md §5 metrics
    row — reference: paddle.device.cuda.memory_allocated family)."""

    def test_api_surface_and_types(self):
        import paddle_tpu as paddle
        d = paddle.device
        for fn in (d.memory_allocated, d.max_memory_allocated,
                   d.memory_reserved, d.max_memory_reserved):
            v = fn()
            assert isinstance(v, int) and v >= 0
        d.empty_cache()
        d.synchronize()
        props = d.get_device_properties()
        assert props.name
        # cuda namespace aliases (recipes call cuda.* regardless of backend)
        assert d.cuda.memory_allocated() == d.memory_allocated()
        assert d.cuda.device_count() >= 1


class TestMemoryModel:
    """utils.memory_model.hbm_plan — the v5p-64 north-star projection
    (VERDICT r2 missing 7) walks the real param_specs tables."""

    def test_sharded_total_shrinks_with_mesh(self):
        from paddle_tpu.nlp import llama
        from paddle_tpu.utils.memory_model import hbm_plan
        cfg = llama.LlamaConfig.tiny()
        one = hbm_plan(cfg, dict(), batch=8, seq=64)
        many = hbm_plan(cfg, dict(sharding=4, mp=2), batch=8, seq=64)
        assert many["params"] < one["params"] / 4
        assert many["total"] < one["total"]
        assert many["n_chips"] == 8

    def test_8b_fits_v5p(self):
        from paddle_tpu.nlp import llama
        from paddle_tpu.utils.memory_model import hbm_plan
        cfg = llama.LlamaConfig.llama3_8b()
        plan = hbm_plan(cfg, dict(dp=2, sharding=8, mp=4),
                        batch=32, seq=8192)
        # the README table: ~10.7 GiB/chip, far under v5p's 95 GiB
        assert 8 < plan["total_gib"] < 20, plan["total_gib"]
        # params 8B f32 over the 32-way (sharding x mp) 2D shard
        assert 0.5 < plan["params"] / 2**30 < 1.5
