"""Tensor-parallel serving (serving.tp) — mesh-sharded paged decode.

Deterministic CPU coverage over the conftest's 8 forced host devices:
MeshConfig validation/key units, sharding-spec derivation for the
llama/paged state (column/row weight splits, head-axis KV pool,
replicated scales), memo-key mesh-element placement (the KEY001
convention: `.key()` rides the tail of every compiled-shape cache key;
mesh-off keys stay byte-identical to the unsharded batcher), greedy
bit-identity of a TP=2 engine vs single-device across cold +
prefix-cache-warm serves with ZERO post-warmup recompiles, the
TP=4 × int8-KV × speculative composition at the batcher level,
export/import round-trips across mesh shapes (snapshots are
host-gathered, mesh-agnostic), and a Router fronting one sharded
replica.
"""
import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

from paddle_tpu.nlp import llama, paged
from paddle_tpu import serving
from paddle_tpu.serving.router import Router
from paddle_tpu.serving.tp import (
    MeshConfig, param_pspecs, build_shardings, shard_info)

_RNG = np.random.RandomState(7)
PROMPTS = [list(map(int, _RNG.randint(1, 200, n))) for n in (5, 9, 6)]
MAX_NEW = 6


@pytest.fixture(scope="module")
def setup():
    # kv_heads=4 so the pool's head axis splits at TP=4 (tiny()'s
    # default 2 kv heads would fail MeshConfig.validate_for)
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2,
                                 num_key_value_heads=4)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _batcher(setup, **kw):
    cfg, params = setup
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_total_len", 48)
    kw.setdefault("max_new_tokens", MAX_NEW)
    kw.setdefault("chunk", 3)
    kw.setdefault("max_prefill_bucket", 16)
    return paged.ContinuousBatcher(params, cfg, **kw)


E_KW = dict(max_batch=2, block_size=4, max_total_len=48,
            max_new_tokens=MAX_NEW, chunk=3, max_prefill_bucket=16,
            prefix_cache=True)


@pytest.fixture(scope="module")
def baselines(setup):
    """Single-device reference tokens, same engine geometry the TP
    engines use (greedy — device-layout-invariant is the claim)."""
    cfg, params = setup
    eng = serving.ServingEngine(params, cfg, **E_KW)
    out = [eng.generate(p, timeout=300) for p in PROMPTS]
    eng.shutdown()
    return out


class TestMeshConfig:
    def test_key_contents(self):
        assert MeshConfig(tp=2).key() == ("tp", 2, "mp", None)
        assert MeshConfig(tp=4, axis="tpax", devices=(3, 2, 1, 0)).key() \
            == ("tp", 4, "tpax", (3, 2, 1, 0))

    def test_validation(self, setup):
        cfg, _ = setup
        with pytest.raises(ValueError, match="does not divide"):
            MeshConfig(tp=3).validate_for(cfg)
        MeshConfig(tp=4).validate_for(cfg)     # 4 | heads/kv/ffn/vocab
        with pytest.raises(ValueError, match="tp=2"):
            MeshConfig(tp=2, devices=(0,))
        with pytest.raises(ValueError, match="tp degree"):
            MeshConfig(tp=0)

    def test_build_against_device_set(self):
        n = len(jax.devices())
        m = MeshConfig(tp=2, devices=(1, 0)).build()
        assert list(m.devices) == [jax.devices()[1], jax.devices()[0]]
        with pytest.raises(ValueError,
                           match="xla_force_host_platform_device_count"):
            MeshConfig(tp=n + 1).build()
        with pytest.raises(ValueError, match="out of range"):
            MeshConfig(tp=2, devices=(0, n + 7)).build()

    def test_describe(self):
        assert MeshConfig(tp=2).describe() == {
            "tp": 2, "axis": "mp", "devices": [0, 1]}


class TestSpecDerivation:
    def test_weight_specs(self, setup):
        """EVERY projection is output-split — serving never shards a
        contracted dim (Megatron's o/down row split would psum in a
        different bf16 summation order than the unsharded dot, and
        the ulp drift flips near-tie argmaxes — the bit-identity
        invariant forbids it)."""
        cfg, params = setup
        specs = param_pspecs(cfg, params)
        lay = specs["layers"]
        for col in ("q_proj", "k_proj", "v_proj", "gate_proj",
                    "up_proj", "o_proj", "down_proj"):
            assert lay[col] == P(None, None, "mp")
        assert specs["lm_head"] == P(None, "mp")
        assert "mp" not in tuple(specs["embed_tokens"])
        assert "mp" not in tuple(lay["input_layernorm"])

    def test_quantized_scale_specs(self, setup):
        """int8 weight scales follow their weight's split with the
        contracted (size-1) dim replicated — output-split weights
        carry output-split scales."""
        cb = _batcher(setup, weight_dtype="int8")
        specs = param_pspecs(setup[0], cb.params)
        lay = specs["layers"]
        for name in ("q_proj:scale", "o_proj:scale",
                     "down_proj:scale"):
            assert lay[name] == P(None, None, "mp")

    def test_build_shardings(self, setup):
        cfg, params = setup
        mesh, sp, pool, repl = build_shardings(
            MeshConfig(tp=2), cfg, params)
        assert pool.spec == P(None, None, None, "mp", None)
        assert repl.spec == P()
        assert sp["layers"]["o_proj"].spec == P(None, None, "mp")

    def test_shard_info_per_device_bytes(self, setup):
        cb = _batcher(setup, mesh=MeshConfig(tp=2))
        info = shard_info(MeshConfig(tp=2), cb)
        # the mesh stamp carries the resolved fast-path attribution
        # (PR 20): which attention impl runs on the mesh, and which
        # spec backend (None — this batcher isn't speculative)
        assert info["mesh"] == {"tp": 2, "axis": "mp",
                                "devices": [0, 1],
                                "attention_impl": "xla",
                                "spec_backend": None}
        assert info["kv_pool_bytes_per_device"] \
            == cb.kv_pool_bytes() // 2
        assert info["weight_bytes_per_device"] < cb.weight_bytes()


class TestMemoKeys:
    def test_mesh_off_keys_unchanged(self, setup):
        """A mesh-less batcher's memo keys carry NO mesh element —
        byte-identical to the pre-TP key shape (`_mkey` is ())."""
        cb = _batcher(setup)
        assert cb._mkey == ()
        rid = cb.submit(PROMPTS[0])
        cb.run()
        for cache in (cb._prefill_cache, cb._chunk_cache,
                      cb._fused_cache):
            for k in cache:
                assert "tp" not in k

    def test_mesh_key_rides_every_cache(self, setup):
        """Every compiled-shape cache key of a mesh batcher ends with
        MeshConfig.key() — two batchers differing only in mesh layout
        can never collide on an executable."""
        cb = _batcher(setup, mesh=MeshConfig(tp=2))
        mk = cb._mesh_cfg.key()
        assert cb._mkey == mk
        for p in PROMPTS[:2]:
            cb.submit(p)
        cb.run()
        assert cb._prefill_cache and cb._chunk_cache
        for cache in (cb._prefill_cache, cb._chunk_cache,
                      cb._fused_cache, cb._spec_cache):
            for k in cache:
                assert k[-len(mk):] == mk


class TestTPServing:
    def test_tp2_engine_bit_identity_zero_recompiles(self, setup,
                                                     baselines):
        """The tentpole invariant: a TP=2 engine serves greedy output
        bit-identical to single-device — cold AND prefix-cache-warm —
        with zero recompiles after the AOT warmup ladder, and stamps
        its mesh shape into snapshot()/health()/to_prometheus()."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, mesh=MeshConfig(tp=2),
                                    start=False, **E_KW)
        eng.warmup()
        eng.start()
        warm = eng.batcher.compile_count
        cold = [eng.generate(p, timeout=300) for p in PROMPTS]
        rewarm = [eng.generate(p, timeout=300) for p in PROMPTS]
        assert cold == baselines       # cold serves
        assert rewarm == baselines     # prefix-cache-warm serves
        assert eng.batcher.compile_count == warm   # 0 recompiles
        snap = eng.snapshot()
        assert snap["tp"]["mesh"]["tp"] == 2
        assert snap["tp"]["kv_pool_bytes_per_device"] \
            == eng.batcher.kv_pool_bytes() // 2
        assert eng.health()["mesh"]["tp"] == 2
        assert "mesh_devices 2" in eng.metrics.to_prometheus()
        eng.shutdown()

    def test_tp4_int8kv_speculative_composition(self, setup):
        """TP composes with the quantized-KV and speculative paths:
        TP=4 × int8-KV × speculative decode matches the identical
        single-device batcher token-for-token."""
        ref = _batcher(setup, kv_dtype="int8", speculative=True)
        ref_rids = [ref.submit(p) for p in PROMPTS[:2]]
        want = ref.run()
        cb = _batcher(setup, kv_dtype="int8", speculative=True,
                      mesh=MeshConfig(tp=4))
        rids = [cb.submit(p) for p in PROMPTS[:2]]
        got = cb.run()
        assert [got[r] for r in rids] == [want[r] for r in ref_rids]
        assert cb._spec_cache           # the spec path actually ran

    def test_mesh_off_stamp(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, start=False, **E_KW)
        assert eng.snapshot()["tp"]["mesh"] is None
        assert eng.health()["mesh"] is None
        eng.shutdown()

    def test_pallas_spec_mesh_composition(self, setup):
        """PR 18's mutual exclusion is gone: attention_impl="pallas"
        under a mesh shard_maps the ragged kernel over the KV-head
        axis (interpret mode on CPU — tests/test_ragged_shard_map.py
        is the kernel-level parity suite). TP=2 × pallas × tree
        speculation serves greedy tokens identical to the mesh-off XLA
        plain batcher, re-serves with ZERO new compiles, and stamps
        the pallas backend into spec_stats()."""
        ref = _batcher(setup)
        ref_rids = [ref.submit(p) for p in PROMPTS[:2]]
        want = ref.run()
        cb = _batcher(setup, attention_impl="pallas", speculative=True,
                      spec_tree=(2, 1), spec_attention_impl="pallas",
                      mesh=MeshConfig(tp=2))
        rids = [cb.submit(p) for p in PROMPTS[:2]]
        got = cb.run()
        assert [got[r] for r in rids] == [want[r] for r in ref_rids]
        warm = cb.compile_count
        rids2 = [cb.submit(list(p)) for p in PROMPTS[:2]]
        got2 = cb.run()
        assert [got2[r] for r in rids2] == [want[r] for r in ref_rids]
        assert cb.compile_count == warm     # warm re-serve: 0 compiles
        st = cb.spec_stats()
        assert st["enabled"] and st["backend"] == "pallas"


def _export_mid_decode(cb, rid, min_tokens=2):
    for _ in range(64):
        if len(cb.outputs.get(rid, [])) >= min_tokens:
            break
        cb.step()
    snap = cb.export_kv(rid)
    cb.abort(rid)
    cb.release(rid)
    return snap


class TestShardedKVTransfer:
    def test_export_import_across_mesh_shapes(self, setup):
        """Snapshots are host-gathered FULL arrays (mesh-agnostic):
        export from a TP=2 pool, resume bit-identically on a
        single-device pool AND on a TP=4 pool — zero re-prefill."""
        ref_cb = _batcher(setup)
        r_ref = ref_cb.submit(PROMPTS[0])
        ref = ref_cb.run()[r_ref]

        src = _batcher(setup, mesh=MeshConfig(tp=2))
        rid = src.submit(PROMPTS[0])
        snap = _export_mid_decode(src, rid)
        # gathered, not a shard: full kv-head width on the host
        assert snap.k.shape[3] == setup[0].num_key_value_heads
        for dst in (_batcher(setup),
                    _batcher(setup, mesh=MeshConfig(tp=4))):
            rid2 = dst.import_kv(snap)
            assert dst.run()[rid2] == ref
            assert dst.prefill_chunk_calls == 0


class TestRouterShardedReplica:
    def test_router_fronts_sharded_engine(self, setup, baselines):
        cfg, params = setup
        r = Router(params, cfg, replicas=1,
                   per_replica=[{"mesh": MeshConfig(tp=2)}],
                   start=False, **E_KW)
        r.warmup()
        r.start()
        assert r.generate(PROMPTS[0], timeout=300) == baselines[0]
        assert r.health()["replicas"]["r0"]["mesh"]["tp"] == 2
        r.shutdown()
