"""ERNIE encoder + DiT model-family tests (BASELINE configs 1 and 3):
forward shapes, loss gradients, and the sharded path on the 8-dev CPU mesh
(SURVEY.md §4 auto-parallel test style)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.nlp import ernie
from paddle_tpu.mix import dit

RNG = np.random.default_rng(5)


class TestErnie:
    cfg = ernie.ErnieConfig.tiny()

    def _inputs(self, b=2, s=16):
        ids = jnp.asarray(RNG.integers(0, self.cfg.vocab_size, (b, s)))
        types = jnp.zeros_like(ids)
        mask = jnp.ones((b, s), bool)
        return ids, types, mask

    def test_forward_shapes(self):
        params = ernie.init_params(jax.random.key(0), self.cfg)
        ids, types, mask = self._inputs()
        seq, pooled = ernie.forward(params, ids, types, mask, self.cfg)
        assert seq.shape == (2, 16, self.cfg.hidden_size)
        assert pooled.shape == (2, self.cfg.hidden_size)
        logits = ernie.cls_logits(params, pooled, self.cfg)
        assert logits.shape == (2, self.cfg.num_labels)
        mlm = ernie.mlm_logits(params, seq, self.cfg)
        assert mlm.shape == (2, 16, self.cfg.vocab_size)

    def test_attention_mask_effect(self):
        params = ernie.init_params(jax.random.key(0), self.cfg)
        ids, types, _ = self._inputs()
        full = jnp.ones((2, 16), bool)
        half = full.at[:, 8:].set(False)
        s1, _ = ernie.forward(params, ids, types, full, self.cfg)
        s2, _ = ernie.forward(params, ids, types, half, self.cfg)
        # masking the tail must change the visible-prefix representations
        assert not np.allclose(np.asarray(s1[:, :8]), np.asarray(s2[:, :8]))

    def test_finetune_loss_decreases(self):
        cfg = self.cfg
        params = ernie.init_params(jax.random.key(1), cfg)
        ids, types, mask = self._inputs(8, 12)
        labels = jnp.asarray(RNG.integers(0, cfg.num_labels, (8,)))
        step = jax.jit(jax.value_and_grad(
            lambda p: ernie.finetune_loss(p, ids, labels, cfg, types, mask)))
        loss0, grads = step(params)
        lr = 5e-2
        for _ in range(8):
            loss, grads = step(params)
            params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        loss1, _ = step(params)
        assert float(loss1) < float(loss0)

    def test_mlm_loss_grad_finite(self):
        cfg = self.cfg
        params = ernie.init_params(jax.random.key(2), cfg)
        ids, types, mask = self._inputs(2, 10)
        labels = jnp.where(jnp.asarray(RNG.random((2, 10)) < 0.2),
                           ids, -100)
        loss, grads = jax.value_and_grad(ernie.mlm_loss)(
            params, ids, labels, cfg, types, mask)
        assert np.isfinite(float(loss))
        flat, _ = jax.tree_util.tree_flatten(grads)
        assert all(np.isfinite(np.asarray(g)).all() for g in flat)

    def test_sharded_finetune_step(self):
        """DP+FSDP finetune on a 2x2x2 (dp, sharding, mp) mesh — the
        BASELINE config-1 shape."""
        cfg = ernie.ErnieConfig.tiny(hidden_size=64, num_hidden_layers=2)
        mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                    ("dp", "sharding", "mp"))
        params = ernie.init_params(jax.random.key(0), cfg)
        specs = ernie.param_specs(cfg)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        ids = jax.device_put(
            jnp.asarray(RNG.integers(0, cfg.vocab_size, (8, 16))),
            NamedSharding(mesh, ernie.batch_spec()))
        labels = jax.device_put(
            jnp.asarray(RNG.integers(0, cfg.num_labels, (8,))),
            NamedSharding(mesh, P(("dp", "sharding"))))

        @jax.jit
        def step(p, i, l):
            return jax.value_and_grad(
                lambda q: ernie.finetune_loss(q, i, l, cfg))(p)

        loss, grads = step(params, ids, labels)
        assert np.isfinite(float(loss))
        # grads keep the param shardings (GSPMD propagated)
        assert grads["layers"]["q_w"].sharding.spec == \
            specs["layers"]["q_w"]


class TestDiT:
    cfg = dit.DiTConfig.tiny()

    def test_forward_shape(self):
        params = dit.init_params(jax.random.key(0), self.cfg)
        x = jnp.asarray(RNG.standard_normal((2, 4, 8, 8)), jnp.float32)
        t = jnp.asarray([10, 500])
        y = jnp.asarray([1, 3])
        out = dit.forward(params, x, t, y, self.cfg)
        assert out.shape == (2, self.cfg.out_channels, 8, 8)
        assert np.isfinite(np.asarray(out, np.float32)).all()

    def test_patchify_round_trip(self):
        cfg = dit.DiTConfig.tiny(learn_sigma=False)
        x = jnp.asarray(RNG.standard_normal((2, 4, 8, 8)), jnp.float32)
        p = dit.patchify(x, cfg)
        assert p.shape == (2, cfg.n_patches, 16)
        back = dit.unpatchify(p, cfg)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x))

    def test_adaln_zero_identity_at_init(self):
        """Zero-init AdaLN gates → blocks are identity; the final layer is
        zero-init → output is exactly zero at init (DiT recipe)."""
        params = dit.init_params(jax.random.key(0), self.cfg)
        x = jnp.asarray(RNG.standard_normal((1, 4, 8, 8)), jnp.float32)
        out = dit.forward(params, x, jnp.asarray([0]), jnp.asarray([0]),
                          self.cfg)
        np.testing.assert_allclose(np.asarray(out, np.float32), 0.0)

    def test_diffusion_loss_trains(self):
        cfg = self.cfg
        params = dit.init_params(jax.random.key(1), cfg)
        x0 = jnp.asarray(RNG.standard_normal((8, 4, 8, 8)), jnp.float32)
        y = jnp.asarray(RNG.integers(0, cfg.num_classes, (8,)))
        step = jax.jit(jax.value_and_grad(
            lambda p, k: dit.diffusion_loss(p, k, x0, y, cfg)))
        key = jax.random.key(0)
        loss0, _ = step(params, key)
        for i in range(10):
            loss, grads = step(params, jax.random.fold_in(key, i))
            params = jax.tree.map(lambda p, g: p - 1e-2 * g, params, grads)
        lossN, _ = step(params, key)
        assert float(lossN) < float(loss0)

    def test_sharded_step(self):
        cfg = dit.DiTConfig.tiny()
        mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                    ("dp", "sharding", "mp"))
        params = dit.init_params(jax.random.key(0), cfg)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, dit.param_specs(cfg),
            is_leaf=lambda x: isinstance(x, P))
        x0 = jax.device_put(
            jnp.asarray(RNG.standard_normal((8, 4, 8, 8)), jnp.float32),
            NamedSharding(mesh, dit.batch_spec()))
        y = jax.device_put(jnp.asarray(RNG.integers(0, 10, (8,))),
                           NamedSharding(mesh, P(("dp", "sharding"))))

        @jax.jit
        def step(p, k):
            return jax.value_and_grad(
                lambda q: dit.diffusion_loss(q, k, x0, y, cfg))(p)

        loss, grads = step(params, jax.random.key(1))
        assert np.isfinite(float(loss))
