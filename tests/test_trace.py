"""Serving observability: per-request trace timelines
(paddle_tpu.serving.trace), the step flight recorder, and Prometheus
export.

Coverage per the PR's acceptance criteria: every terminal request
state (FINISHED / CANCELLED / TIMED_OUT / FAILED) yields a complete,
ordered timeline; fused prefill chunks are attributed to the RIGHT
request (with bucket / pad / cached-token annotations); an injected
step failure dumps the flight recorder — naming the failing step's
mode and unit composition — and the dump round-trips through
json.loads; the Chrome-trace export is schema-valid with monotonic
timestamps; Histogram.summary() separates windowed from lifetime
stats once the ring wraps; MetricsRegistry.to_prometheus() renders
the text exposition format.
"""
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest
import jax

from paddle_tpu.nlp import llama, paged
from paddle_tpu import serving
from paddle_tpu.serving import (FlightRecorder, MetricsRegistry,
                                RequestState, TraceSink)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


_RNG = np.random.RandomState(7)
PROMPT = list(map(int, _RNG.randint(1, 200, 5)))
PROMPT2 = list(map(int, _RNG.randint(1, 200, 7)))


def _kinds(tl):
    return [e["kind"] for e in tl["events"]]


def _assert_ordered(tl, *subsequence):
    """Each kind's FIRST occurrence appears in the given order, and
    timestamps never go backwards."""
    ks = _kinds(tl)
    idx = []
    for kind in subsequence:
        assert kind in ks, f"{kind} missing from timeline {ks}"
        idx.append(ks.index(kind))
    assert idx == sorted(idx), f"{subsequence} out of order in {ks}"
    ts = [e["t"] for e in tl["events"]]
    assert ts == sorted(ts), "timeline timestamps are not monotonic"


# ---- metrics: windowed histogram + prometheus --------------------------
class TestMetricsObservability:
    def test_histogram_window_wrap_regression(self):
        """Once the ring wraps past cap, lifetime min/max/mean must NOT
        leak into the windowed view the percentiles rank — the window
        gets its own explicit keys (the satellite bugfix)."""
        m = MetricsRegistry()
        h = m.histogram("lat", cap=4)
        for v in range(1, 11):          # 1..10; ring keeps 7, 8, 9, 10
            h.observe(float(v))
        s = h.summary()
        assert s["count"] == 10
        assert s["min"] == 1.0 and s["max"] == 10.0      # lifetime
        assert s["mean"] == pytest.approx(5.5)
        assert s["window_count"] == 4
        assert s["window_min"] == 7.0 and s["window_max"] == 10.0
        # percentiles rank ONLY the window — p50 can't be the lifetime
        # median once early observations fell off the ring
        assert s["p50"] >= 7.0
        assert s["p99"] == 10.0

    def test_histogram_window_matches_lifetime_before_wrap(self):
        m = MetricsRegistry()
        h = m.histogram("lat2", cap=8)
        for v in (3.0, 1.0, 2.0):
            h.observe(v)
        s = h.summary()
        assert s["window_count"] == s["count"] == 3
        assert s["window_min"] == s["min"] == 1.0
        assert s["window_max"] == s["max"] == 3.0

    def test_to_prometheus_text_format(self):
        m = MetricsRegistry()
        m.counter("requests_done").inc(3)
        m.gauge("queue_depth").set(2.0)
        h = m.histogram("serving.step_s")
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        text = m.to_prometheus()
        lines = text.strip().splitlines()
        # the TYPE family must name the _total sample exactly, or the
        # scraper types every counter "unknown"
        assert "# TYPE paddle_tpu_requests_done_total counter" in lines
        assert "paddle_tpu_requests_done_total 3.0" in lines
        assert "# TYPE paddle_tpu_queue_depth gauge" in lines
        assert "paddle_tpu_queue_depth 2.0" in lines
        # dotted names sanitize to the prometheus charset
        assert "# TYPE paddle_tpu_serving_step_s summary" in lines
        assert any(l.startswith('paddle_tpu_serving_step_s{quantile="0.5"}')
                   for l in lines)
        assert "paddle_tpu_serving_step_s_count 3.0" in lines
        # every sample line is "name{labels} value" — two fields
        for l in lines:
            if not l.startswith("#"):
                assert len(l.split()) == 2, l

    def test_empty_histogram_renders(self):
        m = MetricsRegistry()
        m.histogram("never_observed")
        text = m.to_prometheus()
        assert "paddle_tpu_never_observed_count 0.0" in text


# ---- trace sink units --------------------------------------------------
class TestTraceSink:
    def test_start_emit_finish_roundtrip(self):
        s = TraceSink()
        tid = s.start()
        s.emit(tid, "enqueued", prompt_len=4)
        s.alias(17, tid)
        s.emit(17, "prepared", slot=1)          # resolves via alias
        assert s.timeline(17)["trace_id"] == tid
        s.finish(tid, "finished", reason="length")
        tl = s.timeline(tid)
        assert tl["done"] is True
        assert _kinds(tl) == ["enqueued", "prepared", "finished"]
        assert tl["slot"] == 1                  # slot attr tracked
        assert s.timeline(17) is None           # alias released on finish
        # finish is idempotent
        s.finish(tid, "finished")
        assert len(_kinds(s.timeline(tid))) == 3

    def test_unaliased_rid_autocreates_timeline(self):
        """A standalone batcher traces without an engine: rid refs
        auto-open rid<n> timelines."""
        s = TraceSink()
        s.emit(5, "prepared", slot=0)
        tl = s.timeline(5)
        assert tl["trace_id"] == "rid5"
        assert _kinds(tl) == ["prepared"]

    def test_event_bound_drops_but_terminal_lands(self):
        s = TraceSink(max_events=3)
        tid = s.start()
        for i in range(10):
            s.emit(tid, "decode_emit", n=1)
        s.finish(tid, "finished")
        tl = s.timeline(tid)
        assert len(tl["events"]) == 4           # 3 kept + forced terminal
        assert tl["events"][-1]["kind"] == "finished"
        assert s.dropped_events == 7

    def test_done_ring_bounded(self):
        s = TraceSink(max_requests=2)
        tids = []
        for _ in range(5):
            tid = s.start()
            s.finish(tid, "finished")
            tids.append(tid)
        assert len(s.timelines()) == 2
        assert s.timeline(tids[0]) is None      # oldest evicted
        assert s.timeline(tids[-1]) is not None

    def test_emit_after_finish_is_dropped(self):
        s = TraceSink()
        tid = s.start()
        s.finish(tid, "cancelled")
        s.emit(tid, "decode_emit", n=1)
        assert _kinds(s.timeline(tid)) == ["cancelled"]
        assert s.dropped_events == 1            # lost, but never silently

    def test_chrome_trace_schema(self):
        s = TraceSink()
        tid = s.start()
        s.emit(tid, "enqueued", prompt_len=4)
        s.emit(tid, "prefill_chunk", dur=0.01, slot=1, bucket=8, pad=3)
        s.span("engine.step", dur=0.005, tokens=2)
        s.finish(tid, "finished")
        ct = s.to_chrome_trace()
        assert set(ct) == {"traceEvents", "displayTimeUnit"}
        evs = ct["traceEvents"]
        json.loads(json.dumps(ct))              # JSON-serializable
        meta = [e for e in evs if e["ph"] == "M"]
        body = [e for e in evs if e["ph"] != "M"]
        assert {m["name"] for m in meta} >= {"process_name", "thread_name"}
        for e in body:
            assert e["ph"] in ("X", "i")
            assert isinstance(e["ts"], float) and e["ts"] >= 0.0
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
            if e["ph"] == "X":
                assert e["dur"] >= 0.0
        # monotonic timestamps (the Perfetto-validity acceptance bar)
        ts = [e["ts"] for e in body]
        assert ts == sorted(ts)
        # pid = engine, tid = slot for slot-anchored events
        chunk = next(e for e in body if e["name"] == "prefill_chunk")
        assert chunk["tid"] == 1 and chunk["args"]["bucket"] == 8
        step = next(e for e in body if e["name"] == "engine.step")
        assert step["ph"] == "X"

    def test_chrome_span_renders_at_start_not_emission(self):
        """A dur-carrying event is emitted AFTER the measured call, so
        its chrome ts must be (emission - dur) — rendering at emission
        time would shift every chunk span right by its own duration,
        outside the engine.step span that contained it."""
        t = {"v": 100.0}

        def clock():
            return t["v"]

        s = TraceSink(clock=clock)              # origin = 100.0
        t["v"] = 105.0
        tid = s.start()
        s.emit(tid, "prefill_chunk", dur=2.0)   # ran [103, 105]
        s.finish(tid, "finished")
        body = [e for e in s.to_chrome_trace()["traceEvents"]
                if e["ph"] != "M"]
        chunk = next(e for e in body if e["name"] == "prefill_chunk")
        assert chunk["ts"] == pytest.approx(3.0 * 1e6)   # 103 - origin
        assert chunk["dur"] == pytest.approx(2.0 * 1e6)

    def test_live_timelines_bounded_without_finish(self):
        """A producer that never finishes (standalone batcher rid
        timelines) must not grow the live set unboundedly: the oldest
        displaces onto the completed ring, aliases dropped."""
        s = TraceSink(max_requests=2)
        for rid in range(5):
            s.emit(rid, "prepared", slot=0)
        assert len(s._live) <= 2
        assert len(s.timelines()) <= 4          # live + done ring
        assert s.displaced_live == 3            # loss is accounted
        # a late emit for a displaced-but-retained rid neither
        # resurrects nor splits its timeline — it drops, visibly
        # (rid2 still sits on the done ring; rid0 fell off entirely)
        s.emit(2, "retired", slot=0)
        assert s.timeline(2)["trace_id"] == "rid2"   # the displaced one
        assert _kinds(s.timeline(2)) == ["prepared"]
        assert s.dropped_events == 1
        s.alias(99, s.start())
        for _ in range(3):
            s.start()
        assert 99 not in s._alias               # displaced with its tl

    def test_flight_recorder_ring(self):
        fr = FlightRecorder(cap=3)
        for i in range(7):
            fr.record("decode", free_slots=i)
        recs = fr.records()
        assert len(recs) == len(fr) == 3
        assert [r["seq"] for r in recs] == [4, 5, 6]
        assert all(r["mode"] == "decode" for r in recs)
        json.loads(json.dumps(recs))

    def test_sync_rule_covers_trace_emission(self):
        """The SYNC001 hot-path set extends to the trace emission
        helpers — a device sync hiding in an event attr would tax
        every step. Since the call-graph closure replaced the hand
        list, coverage is asserted on the DERIVED set of the real
        tree (the sink's emit is reached through the batcher's typed
        `_trace` attr, not a hand entry)."""
        import os
        from paddle_tpu.analysis.core import load_project
        from paddle_tpu.analysis.rules.sync import derive_hot_paths
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # the decode hot path's roots all live in these three subtrees
        # — loading just them keeps this assertion cheap in tier-1
        project, errs = load_project(
            [os.path.join(repo, "paddle_tpu", d)
             for d in ("nlp", "serving", "quantization")], repo)
        assert errs == []
        hot, _dead = derive_hot_paths(project)
        names = {(ctx.relpath, node.name) for ctx, node, _ in hot.values()}
        assert ("paddle_tpu/serving/trace.py", "emit") in names
        assert ("paddle_tpu/nlp/paged.py", "_trace_emit") in names


# ---- batcher-level: chunk attribution + flight records -----------------
class TestBatcherTracing:
    def test_fused_chunks_attributed_to_right_request(self, setup):
        """A long prompt admitted mid-decode streams its chunks FUSED;
        every chunk event lands on that request's timeline (contiguous
        spans covering exactly its suffix), never the decoding
        neighbor's."""
        cfg, params = setup
        sink = TraceSink()
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=8, chunk=2, max_prefill_bucket=8, trace=sink)
        r1 = cb.submit(PROMPT)
        cb.step()                                # r1 prefills + decodes
        long_prompt = list(range(1, 21))         # 20 toks -> 3 chunks @ 8
        r2 = cb.submit(long_prompt)
        while cb.queue or cb._pending or any(cb.active):
            cb.step()

        tl2 = sink.timeline(r2)
        chunks = [e["attrs"] for e in tl2["events"]
                  if e["kind"] == "prefill_chunk"]
        assert [c["fused"] for c in chunks] == [True, True, True]
        assert [(c["start"], c["end"]) for c in chunks] == \
            [(0, 8), (8, 16), (16, 20)]
        assert chunks[-1]["pad"] == 4            # 20 pads to 3 x bucket 8
        assert all(c["bucket"] == 8 for c in chunks)
        # the decoding neighbor's prefill was standalone, not fused
        tl1 = sink.timeline(r1)
        assert [e["attrs"]["fused"] for e in tl1["events"]
                if e["kind"] == "prefill_chunk"] == [False]
        # ... and the fused flight record names exactly r2's unit
        fused = [r for r in cb.flight.records() if r["mode"] == "fused"]
        assert len(fused) == 3                   # one per streamed chunk
        assert all(r["units"] == [[r2]] for r in fused)
        assert all(r["bucket"] == 8 for r in fused)

    def test_flight_records_have_tick_state(self, setup):
        cfg, params = setup
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2)
        cb.submit(PROMPT)
        cb.run()
        recs = cb.flight.records()
        assert recs, "step ticks must record"
        assert {r["mode"] for r in recs} <= {"prefill", "decode", "fused"}
        for r in recs:
            for key in ("seq", "t", "free_slots", "free_blocks",
                        "active_slots", "queue_depth", "pending",
                        "compile_hit"):
                assert key in r, f"{key} missing from {r}"
        # the first prefill/decode of a cold batcher are compile misses
        assert recs[0]["compile_hit"] is False
        # steady-state decode hits the memo
        assert recs[-1]["mode"] == "decode" and recs[-1]["compile_hit"]

    def test_trace_off_is_default_and_silent(self, setup):
        cfg, params = setup
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=4, max_total_len=16,
            max_new_tokens=2, chunk=2)
        cb.submit(PROMPT)
        out = cb.run()
        assert len(out[0]) == 2                  # serves fine untraced
        assert cb._trace is None

    def test_batcher_trace_bool_mirrors_engine_api(self, setup):
        """trace=True on the batcher builds a default sink (the engine's
        bool API, mirrored) instead of crashing mid-step; a non-sink
        value is rejected at construction, not as a device failure."""
        cfg, params = setup
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=4, max_total_len=16,
            max_new_tokens=2, chunk=2, trace=True)
        rid = cb.submit(PROMPT)
        cb.run()
        assert _kinds(cb._trace.timeline(rid))[0] == "prepared"
        assert paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=4, max_total_len=16,
            max_new_tokens=2, chunk=2, trace=False)._trace is None
        with pytest.raises(TypeError):
            paged.ContinuousBatcher(
                params, cfg, max_batch=1, block_size=4, max_total_len=16,
                max_new_tokens=2, chunk=2, trace=42)


# ---- engine-level: terminal timelines ----------------------------------
class TestEngineTimelines:
    def test_finished_timeline_complete_and_ordered(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2, start=False)
        # the engine sizes the sink's live bound above everything it
        # can hold open at once, so a deep queued burst can never
        # displace a running request's timeline
        assert eng.trace._max_live > eng.queue.max_depth + 2
        r1 = eng.submit(PROMPT)
        r2 = eng.submit(PROMPT2)
        eng.start()
        eng.shutdown(drain=True, timeout=300)
        assert r1.result() and r2.result()
        for req in (r1, r2):
            tl = eng.trace.timeline(req.trace_id)
            assert tl is not None and tl["done"]
            _assert_ordered(tl, "enqueued", "admitted", "prepared",
                            "prefill_chunk", "first_token",
                            "decode_emit", "retired", "finished")
            assert _kinds(tl)[-1] == "finished"
            ev = tl["events"]
            enq = next(e for e in ev if e["kind"] == "enqueued")
            assert enq["attrs"]["prompt_len"] == len(req.prompt)
            fin = ev[-1]
            assert fin["attrs"]["reason"] == "length"

    def test_cancelled_and_timed_out_timelines(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2, start=False)
        r_cancel = eng.submit(PROMPT)
        r_cancel.cancel()
        r_timeout = eng.submit(PROMPT2, timeout_s=0.0)
        eng.start()
        eng.shutdown(drain=True, timeout=300)
        assert r_cancel.state is RequestState.CANCELLED
        assert r_timeout.state is RequestState.TIMED_OUT
        tl_c = eng.trace.timeline(r_cancel.trace_id)
        _assert_ordered(tl_c, "enqueued", "cancelled")
        assert _kinds(tl_c)[-1] == "cancelled"
        tl_t = eng.trace.timeline(r_timeout.trace_id)
        _assert_ordered(tl_t, "enqueued", "timed_out")
        assert _kinds(tl_t)[-1] == "timed_out"

    def test_failed_timeline_on_token_boundary(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2, start=False)

        def boom(tok):
            raise RuntimeError("consumer exploded")

        r_bad = eng.submit(PROMPT, on_token=boom)
        r_ok = eng.submit(PROMPT2)
        eng.start()
        eng.shutdown(drain=True, timeout=300)
        assert r_bad.state is RequestState.FAILED
        assert r_ok.state is RequestState.FINISHED
        tl = eng.trace.timeline(r_bad.trace_id)
        _assert_ordered(tl, "enqueued", "admitted", "prepared",
                        "prefill_chunk", "first_token", "decode_emit",
                        "failed")
        assert _kinds(tl)[-1] == "failed"
        assert "consumer exploded" in tl["events"][-1]["attrs"]["error"]
        # the delivered-before-failure tokens stay on the timeline, so
        # it agrees with the ttft histogram and req.tokens
        emit = next(e for e in tl["events"] if e["kind"] == "decode_emit")
        assert emit["attrs"]["n"] == len(r_bad.tokens) >= 1

    def test_cached_prefix_skip_visible(self, setup):
        """The acceptance bar's shared-prefix story: a repeat prompt's
        timeline shows the prefix cache skipping cached tokens."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2)
        warm = PROMPT + PROMPT2                  # 12 toks = 3 full blocks
        r1 = eng.submit(warm)
        r1.result(timeout=300)
        r2 = eng.submit(warm)
        r2.result(timeout=300)
        eng.shutdown()
        tl1 = eng.trace.timeline(r1.trace_id)
        tl2 = eng.trace.timeline(r2.trace_id)
        prep1 = next(e for e in tl1["events"] if e["kind"] == "prepared")
        prep2 = next(e for e in tl2["events"] if e["kind"] == "prepared")
        assert prep1["attrs"]["cached_tokens"] == 0
        assert prep2["attrs"]["cached_tokens"] > 0
        chunk2 = next(e for e in tl2["events"]
                      if e["kind"] == "prefill_chunk")
        assert chunk2["attrs"]["cached_tokens"] == \
            prep2["attrs"]["cached_tokens"]
        assert chunk2["attrs"]["cold"] is False  # suffix-only prefill

    def test_trace_disabled_engine(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=16,
            max_new_tokens=2, chunk=2, trace=False)
        assert eng.trace is None
        assert eng.generate(PROMPT, timeout=300)
        # the flight recorder stays on even with timelines off
        dump = eng.dump_flight_recorder()
        assert dump["records"]
        eng.shutdown()


# ---- flight recorder dumps --------------------------------------------
class TestFlightRecorderDump:
    def test_injected_decode_fault_dumps_and_roundtrips(self, setup,
                                                        tmp_path):
        """A device-step failure mid-decode leaves a JSON dump naming
        the failing step's mode, with allocator/queue state attached —
        and the engine keeps serving afterwards."""
        cfg, params = setup
        dump_path = tmp_path / "flight.json"
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2, flight_dump_path=str(dump_path))
        assert eng.generate(PROMPT, timeout=300)     # healthy first

        real = eng.batcher._chunk_exe

        def faulty():
            raise RuntimeError("injected device fault")

        eng.batcher._chunk_exe = faulty
        r = eng.submit(PROMPT2)
        with pytest.raises(serving.RequestFailed):
            r.result(timeout=300)
        # the dump round-trips through json.loads and names the step
        dump = json.loads(eng.last_flight_dump_json)
        assert "injected device fault" in dump["error"]
        assert dump["failing_record"]["mode"] == "decode"
        assert dump["records"][-1] == dump["failing_record"]
        assert dump["allocator"]["capacity_blocks"] > 0
        assert isinstance(dump["running_rids"], list)
        # ... and hit the configured path too
        on_disk = json.loads(dump_path.read_text())
        assert on_disk["failing_record"]["mode"] == "decode"
        # engine survives: heal the batcher and serve again
        eng.batcher._chunk_exe = real
        assert eng.generate(PROMPT, timeout=300)
        eng.shutdown()

    def test_injected_fused_fault_names_unit_composition(self, setup):
        """The acceptance bar: a fault in the FUSED step's device call
        dumps a record naming mode='fused' and the unit composition
        (which pending rids rode the failing call)."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=64,
            max_new_tokens=24, chunk=2, start=False)

        def faulty(Gp, Pb):
            raise RuntimeError("injected fused fault")

        eng.batcher._fused_exe = faulty
        got_first = threading.Event()
        r1 = eng.submit(PROMPT, on_token=lambda t: got_first.set())
        eng.start()
        assert got_first.wait(timeout=300)       # r1 is mid-decode
        r2 = eng.submit(PROMPT2)                 # lands while r1 decodes
        with pytest.raises(serving.RequestFailed):
            r2.result(timeout=300)
        dump = json.loads(eng.last_flight_dump_json)
        assert dump["failing_record"]["mode"] == "fused"
        assert [r2.request_id] in dump["failing_record"]["units"]
        assert "injected fused fault" in dump["error"]
        eng.shutdown()

    def test_on_demand_dump(self, setup, tmp_path):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=16,
            max_new_tokens=2, chunk=2)
        eng.generate(PROMPT, timeout=300)
        path = tmp_path / "dump.json"
        dump = eng.dump_flight_recorder(str(path))
        assert dump["error"] is None
        assert json.loads(path.read_text())["records"] == dump["records"]
        eng.shutdown()


# ---- artifact tooling --------------------------------------------------
class TestTraceArtifacts:
    @pytest.fixture(scope="class")
    def trace_file(self, setup, tmp_path_factory):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2)
        for p in (PROMPT, PROMPT2, PROMPT):
            eng.generate(p, timeout=300)
        path = tmp_path_factory.mktemp("trace") / "trace.json"
        with open(path, "w") as f:
            json.dump(eng.trace.to_chrome_trace(), f)
        eng.shutdown()
        return path

    def test_trace_report_cli(self, trace_file):
        out = subprocess.run(
            [sys.executable, str(REPO / "tools" / "trace_report.py"),
             str(trace_file), "--json"],
            capture_output=True, text=True, check=True)
        summary = json.loads(out.stdout)
        t = summary["total"]
        assert t["requests"] == 3
        assert t["terminals"] == {"finished": 3}
        assert t["prefill_chunks"] >= 3
        assert 0.0 <= t["pad_waste"] < 1.0
        assert t["cache_hit_rate"] > 0.0         # repeat PROMPT hit
        assert t["engine_steps"] > 0
        for row in summary["requests"]:
            assert row["terminal"] == "finished"
            assert row["ttft_ms"] is not None
            assert row["total_ms"] >= row["ttft_ms"] >= 0.0
        # human rendering exercises the same summary
        txt = subprocess.run(
            [sys.executable, str(REPO / "tools" / "trace_report.py"),
             str(trace_file)], capture_output=True, text=True, check=True)
        assert "serving trace summary" in txt.stdout

    def test_load_profiler_result_reads_serving_trace(self, trace_file,
                                                      tmp_path):
        from paddle_tpu import profiler
        data = profiler.load_profiler_result(str(trace_file))
        assert "traceEvents" in data
        other = tmp_path / "not_a_trace.json"
        other.write_text("[1, 2, 3]")
        with pytest.raises(NotImplementedError):
            profiler.load_profiler_result(str(other))
        # a typo'd path stays a file error, not a format error
        with pytest.raises(OSError):
            profiler.load_profiler_result(str(tmp_path / "missing.json"))

    def test_trace_report_handles_live_requests(self, tmp_path):
        """An artifact exported mid-run (requests without a terminal
        event yet) summarizes as 'live' instead of crashing."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_report", REPO / "tools" / "trace_report.py")
        tr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tr)
        path = tmp_path / "mid_run.json"
        path.write_text(json.dumps({"traceEvents": [
            {"name": "enqueued", "ph": "i", "pid": 1, "tid": 9998,
             "ts": 1.0, "args": {"trace_id": "t0", "prompt_len": 4}},
            {"name": "enqueued", "ph": "i", "pid": 1, "tid": 9998,
             "ts": 2.0, "args": {"trace_id": "t1", "prompt_len": 4}},
            {"name": "finished", "ph": "i", "pid": 1, "tid": 0,
             "ts": 9.0, "args": {"trace_id": "t1"}},
        ]}))
        summary = tr.summarize(tr.load_events(str(path)))
        assert summary["total"]["terminals"] == {"finished": 1,
                                                "live": 1}


# ---- sampled device-time profiler (PR 13) ------------------------------
class TestStepProfiler:
    def test_sampling_cadence_honored(self, setup):
        """profile_sample_every=N fences exactly every Nth device-call
        tick — the profiler's tick count matches the flight recorder's
        and samples == ticks // N."""
        cfg, params = setup
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=8, chunk=2, profile_sample_every=3)
        cb.submit(PROMPT)
        cb.submit(PROMPT2)
        cb.run()
        rep = cb.profiler.report()
        assert rep["ticks"] == cb.flight.seq    # one gate per tick
        assert rep["ticks"] >= 4
        assert rep["samples"] == rep["ticks"] // 3
        # 0 disables: no fences, no samples
        cb2 = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=8, chunk=2, profile_sample_every=0)
        cb2.submit(PROMPT)
        cb2.run()
        assert cb2.profiler.report()["samples"] == 0

    def test_zero_recompiles_with_sampling_on(self, setup):
        """Fencing every single step must not touch the compiled-shape
        memo: compile_count stays at its warmup value."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=6, chunk=2, max_prefill_bucket=8,
            profile_sample_every=1, start=False)
        eng.warmup()
        eng.start()
        warm = eng.batcher.compile_count
        for p in (PROMPT, PROMPT2, list(range(1, 21))):
            eng.generate(p, timeout=300)
        assert eng.batcher.compile_count == warm
        assert eng.batcher.profiler.report()["samples"] >= 3
        eng.shutdown()

    def test_per_shape_keys_carry_mode_bucket_impl_qkey(self, setup):
        """The per-shape histograms key on (mode, bucket, units, impl,
        weight_dtype, kv_dtype) — decode keys carry the chunk length,
        prefill keys the ladder bucket, and the resolved impl/qkey ride
        every row."""
        cfg, params = setup
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=8, chunk=2, max_prefill_bucket=8,
            profile_sample_every=1)
        cb.submit(PROMPT)
        cb.step()                      # r1 decodes
        cb.submit(list(range(1, 21)))  # chunks fused onto the decode
        cb.run()
        rep = cb.profiler.report()
        by_mode = {}
        for row in rep["shapes"]:
            by_mode.setdefault(row["mode"], []).append(row)
            assert row["impl"] == cb.attention_impl
            assert row["weight_dtype"] == "fp"
            assert row["kv_dtype"] == "fp"
            assert row["count"] >= 1
            assert row["device_sum_s"] >= row["host_sum_s"] >= 0.0
            assert row["device_p99_s"] >= row["device_p50_s"] >= 0.0
        assert "decode" in by_mode and "prefill" in by_mode
        assert "fused" in by_mode       # the long prompt fused its chunks
        assert all(r["bucket"] == 2 for r in by_mode["decode"])
        assert all(r["bucket"] in cb.prefill_buckets
                   for r in by_mode["prefill"] + by_mode["fused"])
        assert all(r["units"] >= 1 for r in by_mode["fused"])

    def test_capture_window_lands_device_wall_in_timelines(
            self, setup, tmp_path):
        """engine.capture_profile(steps=K) fences K ticks: the report
        comes back complete, prefill_chunk events carry device_dur next
        to their host dur, device.* spans land on the device lane of
        to_chrome_trace(), and trace_report shows the device columns."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=6, chunk=2, max_prefill_bucket=8,
            profile_sample_every=0, start=False)
        eng.warmup()
        eng.start()
        done = threading.Event()

        def traffic():
            for p in (PROMPT, PROMPT2, PROMPT):
                eng.generate(p, timeout=300)
            done.set()

        t = threading.Thread(target=traffic)
        # arm BEFORE traffic so the first prefill ticks are inside the
        # window (sampling is off — only the capture fences)
        eng.batcher.profiler.arm_capture(6)
        t.start()
        while eng.batcher.profiler.capture_active() \
                and not done.wait(0.01):
            pass
        t.join(300)
        report = eng.batcher.profiler.report()
        assert report["capture"]["complete"], report["capture"]
        assert report["capture"]["steps_captured"] == 6
        step0 = report["capture"]["steps"][0]
        assert {"mode", "device_s", "host_s", "rids"} <= set(step0)
        chrome = eng.trace.to_chrome_trace()
        dev = [e for e in chrome["traceEvents"]
               if str(e.get("name", "")).startswith("device.")]
        assert dev, "no device spans in the chrome trace"
        dev_tids = {e["tid"] for e in dev}
        assert len(dev_tids) == 1
        lane_names = {m["tid"]: m["args"]["name"]
                      for m in chrome["traceEvents"]
                      if m.get("ph") == "M"
                      and m.get("name") == "thread_name"}
        assert lane_names[dev_tids.pop()] == "device steps"
        chunks = [e for e in chrome["traceEvents"]
                  if e.get("name") == "prefill_chunk"
                  and "device_dur" in e.get("args", {})]
        assert chunks, "no prefill chunk carried device_dur"
        for c in chunks:
            # a real measured device wall, distinguishable from (and
            # carried next to) the host-wall span the event renders
            assert c["args"]["device_dur"] > 0.0
            assert c["dur"] > 0.0
        path = tmp_path / "capture_trace.json"
        path.write_text(json.dumps(chrome))
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_report", REPO / "tools" / "trace_report.py")
        tr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tr)
        summary = tr.summarize(tr.load_events(str(path)))
        assert summary["total"]["device_steps"] >= 1
        assert summary["total"]["device_step_ms_total"] > 0.0
        assert any(r["device_ms"] for r in summary["requests"])
        txt = tr.render(summary)
        assert "device_ms" in txt and "device steps:" in txt
        eng.shutdown()

    def test_capture_timeout_on_idle_engine_disarms(self, setup):
        """A capture armed on an idle engine times out bounded,
        reports complete=False, AND disarms the window — a leftover
        armed capture must not silently fence every future tick once
        traffic resumes (review regression)."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=2, profile_sample_every=0, start=False)
        rep = eng.capture_profile(steps=2, timeout=0.2)
        assert rep["capture"]["complete"] is False
        assert rep["capture"]["steps_captured"] == 0
        assert eng.batcher.profiler.capture_active() is False
        # traffic after the timed-out capture pays zero fences
        # (sampling is off on this engine: any sample = a leak)
        eng.start()
        eng.generate(PROMPT, timeout=300)
        assert eng.batcher.profiler.report()["samples"] == 0
        eng.shutdown()
