"""OpTest harness: numpy-reference forward checks + finite-difference grad
checks.

Modeled on the reference's op-test backbone (SURVEY.md §4: OpTest in
test/legacy_test/op_test.py builds a one-op program, checks fwd against a
numpy reference and grads against numeric finite differences, with a
tolerance ladder rtol 1e-5 fp32 / 1e-3 fp16 / 1e-2 bf16). Re-designed for
eager+tape: we call the public op, compare with a numpy fn, and check
`backward()` grads against central differences on the numpy fn.
"""
from __future__ import annotations

from typing import Callable, Dict, Sequence

import numpy as np

import paddle_tpu as paddle

RTOL = {np.dtype("float64"): 1e-7, np.dtype("float32"): 1e-5,
        np.dtype("float16"): 1e-3}
DEFAULT_RTOL = 1e-2  # bf16 and below


def rtol_for(dtype) -> float:
    return RTOL.get(np.dtype(dtype), DEFAULT_RTOL)


def check_output(op: Callable, np_ref: Callable, inputs: Sequence[np.ndarray],
                 kwargs: Dict = None, rtol=None, atol=0.0):
    """Run `op` on Tensors built from `inputs`, compare against np_ref(*inputs)."""
    kwargs = kwargs or {}
    tin = [paddle.to_tensor(x) for x in inputs]
    out = op(*tin, **kwargs)
    ref = np_ref(*inputs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    refs = ref if isinstance(ref, (tuple, list)) else [ref]
    assert len(outs) == len(refs), f"{len(outs)} outputs vs {len(refs)} refs"
    for o, r in zip(outs, refs):
        o = o.numpy()
        r = np.asarray(r)
        tol = rtol if rtol is not None else rtol_for(o.dtype)
        np.testing.assert_allclose(
            o.astype(np.float64) if o.dtype.kind == "f" else o,
            r.astype(np.float64) if np.asarray(r).dtype.kind == "f" else r,
            rtol=tol, atol=atol or tol)
    return outs


def check_grad(op: Callable, inputs: Sequence[np.ndarray], kwargs: Dict = None,
               eps=1e-4, rtol=1e-3, atol=1e-3, grad_index=None,
               reduce_to_scalar=True):
    """Compare tape gradients with central finite differences.

    The op's (possibly multi-) output is reduced to a scalar via sum() so a
    single backward pass yields all grads — same trick as the reference's
    numeric check (SURVEY.md §4 check_grad).
    """
    kwargs = kwargs or {}
    inputs = [np.asarray(x, dtype=np.float64) for x in inputs]
    check_idx = range(len(inputs)) if grad_index is None else [grad_index]

    def scalar(np_inputs):
        tin = [paddle.to_tensor(x) for x in np_inputs]
        out = op(*tin, **kwargs)
        outs = out if isinstance(out, (tuple, list)) else [out]
        total = None
        for o in outs:
            if o.dtype.kind != "f":
                continue
            s = o.sum()
            total = s if total is None else total + s
        return total

    # analytic grads via tape
    tin = [paddle.to_tensor(x, stop_gradient=False) for x in inputs]
    out = op(*tin, **kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    total = None
    for o in outs:
        if o.dtype.kind != "f":
            continue
        s = o.sum()
        total = s if total is None else total + s
    total.backward()

    for i in check_idx:
        analytic = tin[i].grad
        assert analytic is not None, f"no grad for input {i}"
        analytic = analytic.numpy().astype(np.float64)
        numeric = np.zeros_like(inputs[i], dtype=np.float64)
        flat = inputs[i].reshape(-1)
        nflat = numeric.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(scalar(inputs).numpy())
            flat[j] = orig - eps
            fm = float(scalar(inputs).numpy())
            flat[j] = orig
            nflat[j] = (fp - fm) / (2 * eps)
        np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                                   err_msg=f"grad mismatch for input {i}")
