"""Quantized serving end-to-end: w8 weights + int8 paged KV through
the whole stack (ROADMAP direction 4).

The PR's acceptance matrix:

  * kv math units — quantize/dequantize/rescale invariants from
    quantization.kv (exact identity on an unchanged scale, exact zeros
    for never-written blocks, byte accounting matching device nbytes);
  * batcher — warm==cold token parity under every (weight_dtype,
    kv_dtype) combination (cached-prefix reads reproduce the cold
    prefill exactly, COW full-hit included), zero post-warmup
    recompiles with memo keys carrying the quantized config, block
    COUNT accounting invariant across kv_dtype (cached-aware deferral
    admits identically), and quantized-vs-fp greedy divergence within
    the documented bound;
  * engine — snapshot()/prometheus expose the resolved quantization
    config and the byte gauges; quarantine/probe parity under
    weight_dtype="int8" (a poisoned fused batch convicts the culprit
    alone, innocents BIT-identical to the fault-free quantized run,
    probes reuse the warmed quantized executables — 0 recompiles).
"""
import threading

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama
from paddle_tpu.nlp.paged import ContinuousBatcher
from paddle_tpu.quantization import kv as kvq
from paddle_tpu import serving
from paddle_tpu.serving import RequestState
from paddle_tpu.serving.faults import FaultInjector


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


_RNG = np.random.RandomState(7)
PROMPTS = [list(map(int, _RNG.randint(1, 200, L)))
           for L in (5, 11, 8, 19)]


def _batcher(params, cfg, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_total_len", 64)
    kw.setdefault("max_new_tokens", 8)
    kw.setdefault("prefill_buckets", (8,))
    kw.setdefault("prefix_cache", True)
    return ContinuousBatcher(params, cfg, **kw)


def _serve_round(cb, prompts):
    rids = [cb.submit(p) for p in prompts]
    out = cb.run()
    return [out[r] for r in rids]


# ---- quantization.kv math units ----------------------------------------
class TestKvMath:
    def test_resolve_kv_dtype(self):
        assert kvq.resolve_kv_dtype(None) == "fp"
        assert kvq.resolve_kv_dtype("fp") == "fp"
        assert kvq.resolve_kv_dtype("int8") == "int8"
        with pytest.raises(ValueError):
            kvq.resolve_kv_dtype("int4")

    def test_quant_dequant_roundtrip_error_bound(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 8), jnp.float32)
        scale = jnp.max(jnp.abs(x)) / kvq.BOUND
        err = np.abs(np.asarray(kvq.dequantize(kvq.quantize(x, scale),
                                               scale) - x))
        # symmetric rounding: at most half a quantization step
        assert err.max() <= float(scale) / 2 + 1e-7

    def test_zero_scale_dequantizes_to_exact_zeros(self):
        codes = jnp.zeros((3, 4), jnp.int8)
        assert (np.asarray(kvq.dequantize(codes, 0.0)) == 0.0).all()

    def test_rescale_identity_when_scale_unchanged(self):
        codes = jnp.arange(-127, 128, dtype=jnp.int8)
        s = jnp.float32(0.37)
        out = kvq.rescale_codes(codes, s, s)
        assert (np.asarray(out) == np.asarray(codes)).all()

    def test_rescale_growth_halves_codes(self):
        codes = jnp.asarray([100, -50, 3], jnp.int8)
        out = kvq.rescale_codes(codes, jnp.float32(1.0), jnp.float32(2.0))
        assert list(np.asarray(out)) == [50, -25, 2]

    def test_block_bytes_includes_scale_overhead(self):
        fp = kvq.kv_block_bytes(2, 4, 2, 16, "fp", fp_itemsize=2)
        q = kvq.kv_block_bytes(2, 4, 2, 16, "int8")
        assert fp == 2 * 4 * 2 * 16 * 2 * 2
        assert q == 2 * 4 * 2 * 16 * 2 + 2 * 2 * 4
        assert q / fp < 0.55


# ---- batcher: parity, accounting, memo keys ----------------------------
QUANT_CONFIGS = [
    {"weight_dtype": "int8"},
    {"kv_dtype": "int8"},
    {"weight_dtype": "int8", "kv_dtype": "int8"},
]


class TestQuantizedBatcher:
    @pytest.mark.parametrize("qkw", QUANT_CONFIGS)
    def test_warm_equals_cold_with_zero_recompiles(self, setup, qkw):
        """The headline batcher gate: a second round of the SAME
        prompts (cached-prefix warm, COW full-hits included) emits
        token-identical output to the cold round, with every shape —
        probe, prefill, fused, chunk — on the warmed quantized
        ladder."""
        cfg, params = setup
        cb = _batcher(params, cfg, **qkw)
        cb.warmup_prefill()
        n0 = cb.compile_count
        cold = _serve_round(cb, PROMPTS)
        hits0 = cb.prefix_stats()["hit_tokens"]
        warm = _serve_round(cb, PROMPTS)
        assert warm == cold, "cached-prefix reads diverged from the " \
            "cold prefill under quantization"
        assert cb.prefix_stats()["hit_tokens"] > hits0, \
            "warm round never hit the cache — the parity was vacuous"
        assert cb.compile_count - n0 == 0

    def test_cow_full_hit_under_int8(self, setup):
        """A block-aligned full-prompt hit takes the COW path: the
        clone must copy the source block's CODES AND SCALES, so the
        re-served prompt decodes token-identically."""
        cfg, params = setup
        cb = _batcher(params, cfg, kv_dtype="int8")
        prompt = PROMPTS[0][:4] * 2          # 8 tokens = 2 full blocks
        cold = _serve_round(cb, [prompt])
        warm = _serve_round(cb, [prompt])    # full-prompt hit → COW
        assert warm == cold
        assert cb.prefix_stats()["hit_tokens"] > 0

    def test_quantized_vs_fp_divergence_bound(self, setup):
        """Greedy outputs under quantization track the fp run within
        the documented bound (bench_serving.QUANT_MATCH_FLOOR): the
        matched-prefix fraction across the workload stays above the
        floor for every quantized configuration."""
        from bench_serving import QUANT_MATCH_FLOOR, _prefix_match
        cfg, params = setup
        base = _serve_round(_batcher(params, cfg), PROMPTS)
        for qkw in QUANT_CONFIGS:
            got = _serve_round(_batcher(params, cfg, **qkw), PROMPTS)
            m = _prefix_match(base, got)
            assert m >= QUANT_MATCH_FLOOR, \
                f"{qkw}: match {m:.3f} below the documented floor"

    def test_memo_keys_carry_quant_config(self, setup):
        cfg, params = setup
        cb = _batcher(params, cfg, weight_dtype="int8", kv_dtype="int8")
        cb.warmup_prefill()
        keys = (list(cb._prefill_cache) + list(cb._fused_cache)
                + list(cb._chunk_cache))
        assert keys and all(k[-2:] == ("int8", "int8") for k in keys)

    def test_w8_params_quantized_and_idempotent(self, setup):
        """weight_dtype="int8" routes params through
        quantize_for_serving (codes + per-channel scales) and accepts
        an already-quantized tree unchanged."""
        cfg, params = setup
        cb = _batcher(params, cfg, weight_dtype="int8")
        assert cb.params["layers"]["q_proj"].dtype == jnp.int8
        assert "q_proj:scale" in cb.params["layers"]
        cb2 = _batcher(cb.params, cfg, weight_dtype="int8")
        assert cb2.params["layers"]["q_proj"] is \
            cb.params["layers"]["q_proj"]
        with pytest.raises(ValueError):
            _batcher(params, cfg, weight_dtype="int4")

    def test_block_count_accounting_invariant_across_kv_dtype(self, setup):
        """The admission/deferral fix's proof: block COUNTS (and so
        cached-aware defer decisions) are identical under fp and int8 —
        the scale pool rides the same block ids. Only BYTES change."""
        cfg, params = setup
        fp = _batcher(params, cfg)
        q8 = _batcher(params, cfg, kv_dtype="int8")
        for p in PROMPTS:
            assert fp.blocks_needed(len(p), tokens=p) == \
                q8.blocks_needed(len(p), tokens=p)
        assert fp.alloc.num_blocks == q8.alloc.num_blocks
        assert q8.kv_block_bytes() < fp.kv_block_bytes()

    def test_byte_accounting_matches_device_nbytes(self, setup):
        """kv_pool_bytes (quantization.kv.kv_block_bytes x capacity)
        equals the actual device arrays' nbytes, scales included — the
        single-source math and the real pool cannot drift."""
        cfg, params = setup
        for qkw in ({}, {"kv_dtype": "int8"}):
            cb = _batcher(params, cfg, **qkw)
            c = cb.cache
            nbytes = c.k.nbytes + c.v.nbytes
            if c.k_scale is not None:
                nbytes += c.k_scale.nbytes + c.v_scale.nbytes
            assert cb.kv_pool_bytes() == nbytes
        ratio = (_batcher(params, cfg, kv_dtype="int8").kv_bytes_per_token()
                 / _batcher(params, cfg).kv_bytes_per_token())
        assert ratio <= 0.55

    def test_reused_blocks_reset_stale_scales(self, setup):
        """free() is host-side bookkeeping, so a recycled block keeps
        its previous tenant's scale — admission must reset fresh
        blocks to the never-written sentinel or this request's KV
        quantizes coarser than a fresh pool's would. Poisoning every
        scale as if a huge-range tenant had used the pool must not
        change a single output token."""
        cfg, params = setup
        cb = _batcher(params, cfg, kv_dtype="int8", prefix_cache=False)
        base = _serve_round(cb, [PROMPTS[1]])
        cb2 = _batcher(params, cfg, kv_dtype="int8", prefix_cache=False)
        cb2.cache = cb2.cache._replace(
            k_scale=cb2.cache.k_scale + 100.0,
            v_scale=cb2.cache.v_scale + 100.0)
        assert _serve_round(cb2, [PROMPTS[1]]) == base

    def test_abort_and_rollback_clean_under_int8(self, setup):
        """The rollback/abort machinery is dtype-agnostic: aborting a
        mid-decode quantized request returns every block."""
        cfg, params = setup
        cb = _batcher(params, cfg, kv_dtype="int8", chunk=2)
        rid = cb.submit(PROMPTS[3])
        cb.step()
        assert any(cb.active)
        assert cb.abort(rid)
        assert cb.alloc.stats()["blocks_in_use"] == 0


# ---- engine: config surface + quarantine parity under w8 ---------------
class TestQuantizedEngine:
    def _engine(self, setup, inj=None, **kw):
        cfg, params = setup
        return serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=64,
            max_new_tokens=16, chunk=2, prefill_buckets=(8,),
            start=False, fault_injector=inj, **kw)

    def test_snapshot_and_prometheus_expose_quant_config(self, setup):
        eng = self._engine(setup, weight_dtype="int8", kv_dtype="int8")
        snap = eng.snapshot()
        q = snap["quantization"]
        assert q["weight_dtype"] == "int8" and q["kv_dtype"] == "int8"
        assert q["kv_pool_bytes"] == eng.batcher.kv_pool_bytes()
        assert q["weight_bytes"] == eng.batcher.weight_bytes()
        assert q["kv_bytes_per_token"] == eng.batcher.kv_bytes_per_token()
        prom = eng.metrics.to_prometheus()
        assert f"paddle_tpu_kv_pool_bytes {float(q['kv_pool_bytes'])!r}" \
            in prom
        assert "paddle_tpu_weight_bytes" in prom
        assert "paddle_tpu_kv_cached_bytes" in prom
        eng.shutdown()

    def test_w8_pool_smaller_and_weights_smaller(self, setup):
        fp = self._engine(setup)
        q = self._engine(setup, weight_dtype="int8", kv_dtype="int8")
        sfp, sq = fp.snapshot()["quantization"], \
            q.snapshot()["quantization"]
        assert sq["weight_bytes"] < sfp["weight_bytes"]
        assert sq["kv_pool_bytes"] < sfp["kv_pool_bytes"] * 0.55
        fp.shutdown()
        q.shutdown()

    def test_kv_cached_bytes_gauge_tracks_retirement(self, setup):
        """Retired requests park their blocks on the cached LRU — the
        kv_cached_bytes gauge must price exactly those blocks."""
        eng = self._engine(setup, kv_dtype="int8").start()
        eng.generate(PROMPTS[0], timeout=300)
        eng.shutdown()
        cached = eng.batcher.alloc.stats()["cached_blocks"]
        assert cached > 0
        g = eng.metrics.gauge("kv_cached_bytes").value
        assert g == cached * eng.batcher.kv_block_bytes()

    def test_prepared_event_carries_quant_config(self, setup):
        eng = self._engine(setup, kv_dtype="int8").start()
        r = eng.submit(PROMPTS[0])
        r.result(timeout=300)
        tl = eng.trace.timeline(r.trace_id)
        prep = next(e for e in tl["events"] if e["kind"] == "prepared")
        assert prep["attrs"]["kv_dtype"] == "int8"
        assert prep["attrs"]["weight_dtype"] == "fp"
        assert prep["attrs"]["kv_block_bytes"] == \
            eng.batcher.kv_block_bytes()
        eng.shutdown()

    def _serve_all(self, eng, prompts, budgets, culprit_idx=None,
                   inj=None):
        """test_fault_tolerance's harness under quantization: warmed
        lifecycle, optional first-streamed-token poison on the
        culprit. Returns (requests, post-warmup recompiles)."""
        eng.warmup()
        eng.start()
        eng.generate(prompts[0], timeout=300)
        warm = eng.batcher.compile_count
        armed = threading.Event()

        def arm(tok):
            if not armed.is_set():
                armed.set()
                inj.fail_on_rid(culprit_req.request_id)

        culprit_req = None if culprit_idx is None else \
            serving.GenerationRequest(prompts[culprit_idx],
                                      max_new_tokens=budgets[culprit_idx],
                                      on_token=arm)
        reqs = []
        for i, (p, mn) in enumerate(zip(prompts, budgets)):
            reqs.append(eng.submit(culprit_req) if i == culprit_idx
                        else eng.submit(p, max_new_tokens=mn))
        assert eng.drain(timeout=300)
        return reqs, eng.batcher.compile_count - warm

    def test_quarantine_convicts_culprit_under_w8(self, setup):
        """PR 8's headline gate re-run under weight_dtype="int8" +
        kv_dtype="int8": probe_decode_slot/probe_queued must reuse the
        warmed QUANTIZED executables — the poisoned fused batch
        convicts the culprit alone, innocents finish BIT-identical to
        the fault-free quantized run, zero post-warmup recompiles,
        clean pool."""
        budgets = [8, 5, 7, 6]
        qkw = {"weight_dtype": "int8", "kv_dtype": "int8"}
        eng0 = self._engine(setup, **qkw)
        base, _ = self._serve_all(eng0, PROMPTS, budgets)
        base_toks = [r.result(timeout=5) for r in base]
        eng0.shutdown()

        inj = FaultInjector(seed=0)
        eng = self._engine(setup, inj, **qkw)
        reqs, recompiles = self._serve_all(eng, PROMPTS, budgets,
                                           culprit_idx=1, inj=inj)
        culprit = reqs[1]
        assert [r.state for r in reqs].count(RequestState.FAILED) == 1
        assert culprit.state is RequestState.FAILED
        assert culprit.tokens
        assert culprit.tokens == base_toks[1][:len(culprit.tokens)]
        for i in (0, 2, 3):
            assert reqs[i].state is RequestState.FINISHED
            assert reqs[i].result(timeout=5) == base_toks[i], \
                f"innocent {i} lost token parity under quantization"
        assert recompiles == 0, \
            "quarantine probes left the warmed quantized ladder"
        assert eng.batcher.alloc.stats()["blocks_in_use"] == 0
        assert eng.health()["quarantines"] >= 1
        eng.shutdown()


# ---- tools: tuner pad-bytes + trace_report bytes columns ---------------
class TestQuantizedTools:
    def test_bucket_tuner_prices_pad_in_kv_bytes(self):
        import importlib
        tuner = importlib.import_module("tools.bucket_tuner")
        bench = {"prefill_suffix_hist": {"3": 2, "7": 1},
                 "prefill_buckets": [8], "kv_dtype": "int8",
                 "kv_bytes_per_token": 130.0}
        out = tuner.tune(bench, max_buckets=1)
        # ladder (7,): pads 2x(7-3)=8 tokens; observed (8,): 11 tokens
        assert out["pad_tokens_current_ladder"] == 11
        assert out["pad_kv_bytes_current_ladder"] == int(11 * 130.0)
        assert out["pad_kv_bytes_recommended"] == \
            int(out["pad_tokens_recommended"] * 130.0)
        assert out["kv_dtype"] == "int8"

    def test_trace_report_bytes_columns(self, setup, tmp_path):
        import importlib
        import json
        rep = importlib.import_module("tools.trace_report")
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=64,
            max_new_tokens=4, chunk=2, prefill_buckets=(8,),
            kv_dtype="int8")
        eng.generate(PROMPTS[0], timeout=300)
        eng.shutdown()
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(eng.trace.to_chrome_trace()))
        summary = rep.summarize(rep.load_events(str(path)))
        assert summary["total"]["kv_dtype"] == "int8"
        assert summary["total"]["kv_bytes_total"] > 0
        row = summary["requests"][0]
        assert row["kv_bytes"] > 0
        assert "kv_bytes" in rep.render(summary)
