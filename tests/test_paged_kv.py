"""Paged KV-cache serving (VERDICT r4 missing 2): block-table cache over
one shared pool, ragged batch admission, decode parity vs the dense path,
and allocator-level pool-reuse evidence.

Reference analog: upstream fused block_multihead_attention + PaddleNLP
serving's block manager (upstream-canonical, unverified — SURVEY.md §0).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama, generation, paged


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestPagedGenerate:
    def test_equal_lengths_match_dense_greedy(self, setup):
        cfg, params = setup
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(1, 200, (3, 12)), jnp.int32)
        dense = generation.generate(params, prompt, cfg, max_new_tokens=6,
                                    greedy=True)
        out, alloc, _ = paged.paged_generate(
            params, prompt, np.full((3,), 12), cfg, max_new_tokens=6,
            block_size=4)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(dense))

    def test_mixed_lengths_match_per_request_dense(self, setup):
        """Requests of DIFFERENT lengths decode in ONE paged batch and
        match each request's individual dense run — the dense batch path
        cannot admit this shape without re-padding."""
        cfg, params = setup
        rng = np.random.RandomState(1)
        lens = [5, 9, 12]
        pmax = max(lens)
        rows = np.zeros((3, pmax), np.int64)
        for i, L in enumerate(lens):
            rows[i, :L] = rng.randint(1, 200, L)
        out, alloc, _ = paged.paged_generate(
            params, jnp.asarray(rows, jnp.int32), np.asarray(lens), cfg,
            max_new_tokens=5, block_size=4)
        for i, L in enumerate(lens):
            single = generation.generate(
                params, jnp.asarray(rows[None, i, :L], jnp.int32), cfg,
                max_new_tokens=5, greedy=True)
            np.testing.assert_array_equal(np.asarray(out[i]),
                                          np.asarray(single[0]),
                                          err_msg=f"request {i} (len {L})")

    def test_pool_reuse_and_memory_analysis(self, setup):
        """Completed requests' blocks are reused by later admissions; the
        pool's high-water mark tracks the SUM of ragged lengths, not
        B x T_max (the dense cache's footprint)."""
        cfg, params = setup
        block_size = 4
        max_new = 4
        lens = np.asarray([3, 7])
        pmax, B = 7, 2
        rows = np.zeros((B, pmax), np.int64)
        rng = np.random.RandomState(2)
        for i, L in enumerate(lens):
            rows[i, :L] = rng.randint(1, 200, L)
        # pool sized for exactly one ragged batch
        per_req = -(-(lens.max() + max_new) // block_size)
        alloc = paged.BlockAllocator(B * per_req)
        out1, alloc, owned1 = paged.paged_generate(
            params, jnp.asarray(rows, jnp.int32), lens, cfg,
            max_new_tokens=max_new, block_size=block_size, allocator=alloc)
        assert alloc.stats()["blocks_in_use"] == B * per_req
        with pytest.raises(RuntimeError):   # pool full while batch 1 holds it
            paged.build_table(alloc, lens, int(lens.max()) + max_new,
                              block_size)
        for blocks in owned1:               # batch 1 completes
            alloc.free(blocks)
        out2, alloc, owned2 = paged.paged_generate(
            params, jnp.asarray(rows, jnp.int32), lens, cfg,
            max_new_tokens=max_new, block_size=block_size, allocator=alloc)
        stats = alloc.stats()
        assert stats["reused_blocks"] >= B * per_req    # real pool reuse
        assert stats["high_water_blocks"] == B * per_req
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))

    def test_predictor_enable_paged_kv(self, setup, tmp_path):
        from paddle_tpu import inference
        from paddle_tpu.inference.llm import save_llm
        cfg, params = setup
        prefix = str(tmp_path / "m")
        save_llm(prefix, params, cfg)
        config = inference.Config(prefix)
        config.enable_llm_generation(max_new_tokens=4, pad_token_id=0)
        config.enable_paged_kv(block_size=4)
        pred = inference.create_predictor(config)
        rows = np.zeros((2, 8), np.int64)
        rows[0, :8] = np.arange(1, 9)
        rows[1, :5] = np.arange(1, 6)
        out = pred.run([rows])[0]
        assert out.shape == (2, 4)
        assert pred._paged_stats["high_water_blocks"] > 0
        # the allocator persists across run() calls: the second request
        # batch reuses the blocks the first released
        out2 = pred.run([rows])[0]
        np.testing.assert_array_equal(out2, out)
        assert pred._paged_stats["reused_blocks"] > 0


class TestBlockAllocatorFree:
    """Regression: free() used to silently accept duplicate and
    out-of-range block ids — a double free splices a block into the
    free list twice, and two later requests then share (and corrupt)
    one KV block."""

    def test_double_free_raises(self):
        alloc = paged.BlockAllocator(4)
        blocks = alloc.allocate(2)
        alloc.free(blocks)
        with pytest.raises(ValueError, match="already free"):
            alloc.free(blocks)                  # freed twice
        assert alloc.free_blocks == 4           # first free stuck

    def test_duplicate_within_one_call_raises(self):
        alloc = paged.BlockAllocator(4)
        b = alloc.allocate(1)
        with pytest.raises(ValueError, match="already free"):
            alloc.free([b[0], b[0]])
        # the failed call must not have half-applied
        assert alloc.free_blocks == 3
        alloc.free(b)
        assert alloc.free_blocks == 4

    def test_out_of_range_raises(self):
        alloc = paged.BlockAllocator(4)
        with pytest.raises(ValueError, match="out of range"):
            alloc.free([4])
        with pytest.raises(ValueError, match="out of range"):
            alloc.free([-1])

    def test_free_list_never_grows_past_capacity(self):
        alloc = paged.BlockAllocator(2)
        blocks = alloc.allocate(2)
        alloc.free(blocks)
        with pytest.raises(ValueError):
            alloc.free([0])
        assert alloc.free_blocks == alloc.num_blocks


class TestContinuousBatching:
    """Continuous batching over the block pool: more requests than batch
    slots, admission into freed slots mid-stream, outputs matching each
    request's individual dense greedy run."""

    def test_three_requests_two_slots(self, setup):
        cfg, params = setup
        rng = np.random.RandomState(7)
        prompts = [list(rng.randint(1, 200, L)) for L in (5, 9, 7)]
        max_new = 6
        # pool sized so the third request can only be admitted by
        # reusing blocks the first two released
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=32,
            max_new_tokens=max_new, chunk=3, num_blocks=8)
        rids = [cb.submit(p) for p in prompts]
        out = cb.run()
        assert cb.alloc.stats()["reused_blocks"] > 0  # slot recycled
        for rid, p in zip(rids, prompts):
            dense = generation.generate(
                params, jnp.asarray([p], jnp.int32), cfg,
                max_new_tokens=max_new, greedy=True)
            np.testing.assert_array_equal(
                np.asarray(out[rid]), np.asarray(dense[0]),
                err_msg=f"request {rid}")

    def test_eos_frees_slot_early(self, setup):
        cfg, params = setup
        rng = np.random.RandomState(8)
        p = list(rng.randint(1, 200, 6))
        # discover this prompt's first generated token, then use it as eos
        probe = generation.generate(params, jnp.asarray([p], jnp.int32),
                                    cfg, max_new_tokens=2, greedy=True)
        eos = int(probe[0, 0])
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=8, eos_token_id=eos, chunk=4)
        r1 = cb.submit(p)
        r2 = cb.submit(list(rng.randint(1, 200, 4)))
        out = cb.run()
        assert out[r1] == [eos]          # stopped at eos immediately
        assert len(out[r2]) >= 1         # second request got the slot

    def test_chunk_overrun_does_not_corrupt_neighbor(self, setup):
        """A fixed-size chunk much larger than a request's budget must
        deactivate the slot ON DEVICE — continuing to write would spill
        through the table row's padding into block 0 (another request's
        cache). Regression: the first-admitted request's output must
        still match its dense run while sharing the pool."""
        cfg, params = setup
        rng = np.random.RandomState(9)
        p0 = list(rng.randint(1, 200, 4))   # owns block 0
        p1 = list(rng.randint(1, 200, 4))
        max_new = 2
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=16,
            max_new_tokens=max_new, chunk=8)   # chunk >> budget
        r0, r1 = cb.submit(p0), cb.submit(p1)
        out = cb.run()
        for rid, p in ((r0, p0), (r1, p1)):
            dense = generation.generate(
                params, jnp.asarray([p], jnp.int32), cfg,
                max_new_tokens=max_new, greedy=True)
            np.testing.assert_array_equal(np.asarray(out[rid]),
                                          np.asarray(dense[0]))

    def test_admission_defers_when_pool_short(self, setup):
        """A free batch slot without enough free blocks DEFERS admission
        until a request retires (instead of aborting the run)."""
        cfg, params = setup
        rng = np.random.RandomState(10)
        p = [list(rng.randint(1, 200, 4)) for _ in range(2)]
        # 3 blocks per request; pool of 4: second must wait for the first
        cb = paged.ContinuousBatcher(
            params, cfg, max_batch=2, block_size=4, max_total_len=16,
            max_new_tokens=4, chunk=2, num_blocks=4)
        rids = [cb.submit(x) for x in p]
        out = cb.run()
        for rid, pr in zip(rids, p):
            dense = generation.generate(
                params, jnp.asarray([pr], jnp.int32), cfg,
                max_new_tokens=4, greedy=True)
            np.testing.assert_array_equal(np.asarray(out[rid]),
                                          np.asarray(dense[0]))
        # a single over-sized request still fails loudly
        big = paged.ContinuousBatcher(
            params, cfg, max_batch=1, block_size=4, max_total_len=64,
            max_new_tokens=40, chunk=2, num_blocks=2)
        big.submit(list(rng.randint(1, 200, 8)))
        with pytest.raises(RuntimeError):
            big.run()
