"""paddle.autograd.{jacobian,hessian,vjp,jvp}, paddle.summary/flops, and
dist.shard_dataloader tests (SURVEY.md §2.4 autograd + hapi rows)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import autograd

RNG = np.random.default_rng(31)


def t(a, sg=True):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=sg)


class TestFunctionalTransforms:
    def test_jacobian(self):
        x = t([1.0, 2.0, 3.0])
        J = autograd.jacobian(lambda a: a * a, x)
        np.testing.assert_allclose(J.numpy(), np.diag([2.0, 4.0, 6.0]),
                                   rtol=1e-6)

    def test_jacobian_multi_input(self):
        x, y = t([1.0, 2.0]), t([3.0, 4.0])
        Jx, Jy = autograd.jacobian(lambda a, b: a * b, [x, y])
        np.testing.assert_allclose(Jx.numpy(), np.diag([3.0, 4.0]))
        np.testing.assert_allclose(Jy.numpy(), np.diag([1.0, 2.0]))

    def test_jacobian_batched(self):
        xb = t(RNG.standard_normal((4, 3)))
        Jb = autograd.jacobian(lambda a: (a ** 2).sum(), xb, batch_axis=0)
        np.testing.assert_allclose(Jb.numpy(), 2 * xb.numpy(), rtol=1e-5)

    def test_hessian(self):
        x = t([1.0, 2.0])
        H = autograd.hessian(lambda a: (a ** 3).sum(), x)
        np.testing.assert_allclose(H.numpy(), np.diag([6.0, 12.0]),
                                   rtol=1e-6)

    def test_vjp_jvp(self):
        x = t([1.0, 2.0])
        out, g = autograd.vjp(lambda a: a * a, x, v=t([1.0, 1.0]))
        np.testing.assert_allclose(out.numpy(), [1.0, 4.0])
        np.testing.assert_allclose(g.numpy(), [2.0, 4.0])
        out2, tg = autograd.jvp(lambda a: a * a, x, v=t([1.0, 0.0]))
        np.testing.assert_allclose(tg.numpy(), [2.0, 0.0])

    def test_lazy_wrappers(self):
        x = t([1.0, 2.0])
        J = autograd.Jacobian(lambda a: a * 3.0, x)
        np.testing.assert_allclose(np.asarray(J[0, 0]._data), 3.0)
        assert J.shape == [2, 2]


class TestSummaryFlops:
    def _model(self):
        return paddle.nn.Sequential(
            paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
            paddle.nn.Linear(32, 10))

    def test_summary_counts(self, capsys):
        info = paddle.summary(self._model(), (1, 16))
        out = capsys.readouterr().out
        assert "Linear" in out and "Total params" in out
        assert info["total_params"] == 16 * 32 + 32 + 32 * 10 + 10
        assert info["trainable_params"] == info["total_params"]

    def test_flops_positive(self):
        n = paddle.flops(self._model(), (1, 16))
        # ≥ 2 * params-in-matmuls MACs
        assert n >= 2 * (16 * 32 + 32 * 10)


class TestShardDataloader:
    def test_batches_sharded(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return np.full((4,), i, np.float32), np.int64(i % 2)

        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        loader = dist.shard_dataloader(
            DataLoader(DS(), batch_size=8), mesh, shard_dims="dp")
        assert len(loader) == 2
        for x, y in loader:
            assert x._data.sharding.spec[0] == "dp"
            assert np.asarray(x._data).shape == (8, 4)


class TestReviewRegressions:
    def test_multi_input_lazy_jacobian(self):
        x, y = t([1.0, 2.0]), t([3.0, 4.0])
        J = autograd.Jacobian(lambda a, b: a * b, [x, y])
        assert len(J.shape) == 2  # per-input block shapes
        np.testing.assert_allclose(np.asarray(J[0]._data),
                                   np.diag([3.0, 4.0]))
        with pytest.raises(TypeError):
            J[0, 0]

    def test_vjp_list_cotangent_for_tuple_output(self):
        x = t([1.0, 2.0])
        out, g = autograd.vjp(lambda a: (a * a, a + 1.0), x,
                              v=[t([1.0, 1.0]), t([1.0, 1.0])])
        np.testing.assert_allclose(g.numpy(), [3.0, 5.0])  # 2x+1

    def test_shard_dataloader_multi_mesh_rejected(self):
        import paddle_tpu.distributed as dist
        m = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        with pytest.raises(NotImplementedError):
            dist.shard_dataloader([], [m, m])

    def test_shard_dataloader_input_keys(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return {"images": np.zeros((4,), np.float32),
                        "meta": np.float32(i)}

        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        loader = dist.shard_dataloader(DataLoader(DS(), batch_size=8), mesh,
                                       shard_dims="dp",
                                       input_keys=["images"])
        batch = next(iter(loader))
        assert batch["images"]._data.sharding.spec[0] == "dp"
        assert getattr(batch["meta"], "placements", None) is None

    def test_summary_without_inputs_raises(self):
        with pytest.raises(ValueError, match="input_size"):
            paddle.summary(paddle.nn.Linear(2, 2))

    def test_hessian_rejects_vector_output(self):
        with pytest.raises(ValueError, match="scalar"):
            autograd.hessian(lambda a: a * a, t([1.0, 2.0]))

    def test_shard_dataloader_bad_dim_and_nested_keys(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.io import DataLoader, Dataset
        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        with pytest.raises(ValueError, match="shard_dims"):
            dist.shard_dataloader([], mesh, shard_dims="dpp")

        class DS(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return {"images": np.zeros((4,), np.float32),
                        "meta": [np.float32(i), np.float32(i * 2)]}

        loader = dist.shard_dataloader(DataLoader(DS(), batch_size=8), mesh,
                                       shard_dims="dp",
                                       input_keys=["images"])
        batch = next(iter(loader))
        for m in batch["meta"]:  # nested under an excluded key: unsharded
            assert getattr(m, "placements", None) is None

    def test_flops_counts_aux_outputs(self):
        class TwoHead(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = paddle.nn.Linear(16, 64)
                self.b = paddle.nn.Linear(16, 64)

            def forward(self, x):
                return self.a(x), self.b(x)

        class OneHead(TwoHead):
            def forward(self, x):
                return self.a(x)

        two = paddle.flops(TwoHead(), (1, 16))
        one = paddle.flops(OneHead(), (1, 16))
        assert two > one  # aux head not DCE'd

    def test_shard_dims_int_and_nested_included_dict(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.io import DataLoader, Dataset
        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])

        class DS(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return {"images": {"rgb": np.zeros((4,), np.float32)},
                        "meta": np.float32(i)}

        loader = dist.shard_dataloader(DataLoader(DS(), batch_size=8), mesh,
                                       shard_dims=0,  # int index form
                                       input_keys=["images"])
        batch = next(iter(loader))
        # nested under an INCLUDED key: sharded
        assert batch["images"]["rgb"]._data.sharding.spec[0] == "dp"
        assert getattr(batch["meta"], "placements", None) is None


class TestUtilsInitializer:
    def test_unique_name(self):
        from paddle_tpu.utils import unique_name
        with unique_name.guard():
            a = unique_name.generate("fc")
            b = unique_name.generate("fc")
            assert a == "fc_0" and b == "fc_1"
        with unique_name.guard():
            assert unique_name.generate("fc") == "fc_0"  # fresh scope

    def test_run_check_and_try_import(self, capsys):
        paddle.utils.run_check()
        assert "working" in capsys.readouterr().out
        assert paddle.utils.try_import("math") is not None
        with pytest.raises(ImportError):
            paddle.utils.try_import("definitely_not_a_module_xyz")

    def test_set_global_initializer(self):
        I = paddle.nn.initializer
        I.set_global_initializer(I.Constant(0.5), I.Constant(-0.5))
        try:
            lin = paddle.nn.Linear(3, 3)
            np.testing.assert_allclose(lin.weight.numpy(), 0.5)
            np.testing.assert_allclose(lin.bias.numpy(), -0.5)
        finally:
            I.set_global_initializer(None, None)
        lin2 = paddle.nn.Linear(3, 3)
        assert not np.allclose(lin2.weight.numpy(), 0.5)
