"""Self-speculative decoding — the draft-and-verify subsystem.

Deterministic CPU coverage of the PR's acceptance bars: spec==non-spec
greedy tokens BIT-identical (cold, prefix-cache-warm, mid-decode
admission, truncated and full-depth drafts), verify-then-commit pool /
prefix-cache cleanliness (the committed pool is bit-identical to a
plain run's — rejection never writes), acceptance accounting, zero
post-warmup recompiles with spec config in every memo key, the
engine's quarantine plain-decode fallback for victims of a failed spec
tick, spec × int8-KV interplay (shared pool, sequential-commit scale
cleanliness), and trace_report's accepted-per-step column.
"""
import importlib.util
import pathlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.nlp import llama
from paddle_tpu.nlp.paged import ContinuousBatcher
from paddle_tpu import serving
from paddle_tpu.serving.faults import FaultInjector
from paddle_tpu.serving.speculative import SpecConfig, SpecStats

REPO = pathlib.Path(__file__).resolve().parent.parent

_RNG = np.random.RandomState(17)
# mixed lengths incl. past the bucket cap (chunked prefill) and a
# shared-prefix pair (prefix-cache hits under spec)
PROMPTS = [list(map(int, _RNG.randint(1, 200, n)))
           for n in (5, 9, 12, 7)]
SHARED = list(map(int, _RNG.randint(1, 200, 8)))
PROMPTS += [SHARED + [11], SHARED + [13]]
MAX_NEW = 8


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _batcher(params, cfg, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_total_len", 48)
    kw.setdefault("max_new_tokens", MAX_NEW)
    kw.setdefault("chunk", 3)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("max_prefill_bucket", 8)
    return ContinuousBatcher(params, cfg, **kw)


def _run(cb, prompts, budgets=None):
    """Warmup, serve `prompts`, return ({submit order: tokens},
    post-warmup recompiles)."""
    cb.warmup_prefill()
    c0 = cb.compile_count
    rids = [cb.submit(p, max_new_tokens=mn)
            for p, mn in zip(prompts, budgets or [None] * len(prompts))]
    out = cb.run()
    return [list(out[r]) for r in rids], cb.compile_count - c0


class TestSpecConfig:
    def test_validation(self):
        with pytest.raises(ValueError):
            SpecConfig(0)
        with pytest.raises(ValueError):
            SpecConfig(4, draft_layers=0)
        with pytest.raises(ValueError):
            SpecConfig(4, draft_layers=5, num_layers=2)
        c = SpecConfig(3, draft_layers=1, num_layers=2)
        assert c.depth(2) == 1
        assert SpecConfig(3).depth(2) == 2          # None = full depth
        assert c.key(2) == ("spec", 3, 1)
        assert c.as_dict(2) == {"k": 3, "draft_layers": 1,
                                "draft_depth": 1}

    def test_stats_math(self):
        s = SpecStats()
        assert s.accept_rate() == 0.0 and s.tokens_per_step() == 0.0
        s.record_step(drafted=6, accepted=3, emitted=4, slots=2)
        s.record_step(drafted=6, accepted=6, emitted=7, slots=2)
        assert s.accept_rate() == pytest.approx(9 / 12)
        # per (sweep, slot): directly comparable to plain decode's 1.0
        assert s.tokens_per_step() == pytest.approx(11 / 4)
        d = s.as_dict()
        assert d["steps"] == 2 and d["emitted"] == 11
        assert d["slot_sweeps"] == 4

    def test_batcher_rejects_bad_config(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError):
            _batcher(params, cfg, speculative=True, spec_k=0)
        with pytest.raises(ValueError):
            _batcher(params, cfg, speculative=True, draft_layers=3)


class TestSpecParity:
    def test_bit_identical_cold_full_and_truncated(self, setup):
        """Greedy spec output == plain greedy output, token for token,
        with zero post-warmup recompiles — full-depth AND truncated
        drafts (a rejected draft changes the schedule, never the
        tokens)."""
        cfg, params = setup
        ref, rec0 = _run(_batcher(params, cfg), PROMPTS)
        assert rec0 == 0
        for dl in (None, 1):
            cb = _batcher(params, cfg, speculative=True, spec_k=3,
                          draft_layers=dl)
            got, rec = _run(cb, PROMPTS)
            assert got == ref, f"draft_layers={dl} diverged"
            assert rec == 0, f"draft_layers={dl} recompiled post-warmup"

    def test_bit_identical_prefix_cache_warm(self, setup):
        """A warm repeat (prefix-cache hits serving the prompts' full
        blocks) decodes the same tokens under spec as plain — and the
        cache actually hit."""
        cfg, params = setup
        cb_ref = _batcher(params, cfg)
        ref1, _ = _run(cb_ref, PROMPTS)
        r2 = [cb_ref.submit(p) for p in PROMPTS]
        out = cb_ref.run()
        ref2 = [list(out[r]) for r in r2]

        cb = _batcher(params, cfg, speculative=True, spec_k=3)
        got1, rec1 = _run(cb, PROMPTS)
        hits0 = cb.prefix_stats()["hit_tokens"]
        r2 = [cb.submit(p) for p in PROMPTS]
        out = cb.run()
        got2 = [list(out[r]) for r in r2]
        assert got1 == ref1 and got2 == ref2
        assert cb.prefix_stats()["hit_tokens"] > hits0   # warm, not vacuous
        assert cb.compile_count and rec1 == 0

    def test_bit_identical_mid_decode_admission(self, setup):
        """n_requests >> max_batch with staggered budgets: admissions
        land while slots decode (the PR 5 fused path carries them) and
        spec ticks interleave with fused ticks — tokens still match
        plain decode exactly, recompiles stay 0."""
        cfg, params = setup
        prompts = PROMPTS + PROMPTS[:2]
        budgets = [1 + (i % MAX_NEW) for i in range(len(prompts))]
        ref, _ = _run(_batcher(params, cfg, chunk=2), prompts, budgets)
        cb = _batcher(params, cfg, chunk=2, speculative=True, spec_k=3)
        got, rec = _run(cb, prompts, budgets)
        assert got == ref
        assert rec == 0
        assert cb.fused_steps > 0        # admissions really piggybacked
        assert cb.spec.steps > 0         # and spec ticks really ran

    def test_budget_exactness(self, setup):
        """Multi-token emission must respect per-request budgets
        exactly — a verify sweep never over-emits past max_new."""
        cfg, params = setup
        budgets = [1, 2, 3, MAX_NEW, 5, 4]
        cb = _batcher(params, cfg, speculative=True, spec_k=4)
        got, _ = _run(cb, PROMPTS, budgets)
        assert [len(t) for t in got] == budgets


class TestVerifyThenCommit:
    def test_rejected_rows_never_write_the_pool(self, setup):
        """THE verify-then-commit invariant, at the write-set level:
        per spec tick, the pool changes at EXACTLY the accepted rows'
        (block, slot) positions — a rejected draft row's K/V never
        lands anywhere. A truncated draft guarantees real rejections
        occur along the way."""
        cfg, params = setup
        cb = _batcher(params, cfg, speculative=True, spec_k=3,
                      draft_layers=1)
        cb.warmup_prefill()
        cb.submit(PROMPTS[0])
        cb._admit()                # standalone prefill, slot 0 active
        assert cb.active[0]
        saw_rejection = False
        while cb.active[0]:
            len0 = int(np.asarray(cb.cache.lengths)[0])
            bud0 = cb.budget[0]
            pre = np.asarray(cb.cache.k.astype(jnp.float32))
            out, n_emit = cb._step_spec()
            n = int(n_emit[0])
            assert 1 <= n <= cb.spec_k + 1
            if n < min(cb.spec_k + 1, bud0):
                saw_rejection = True     # not a budget truncation
            post = np.asarray(cb.cache.k.astype(jnp.float32))
            changed = {tuple(c) for c in np.argwhere(
                np.any(pre != post, axis=(0, 3, 4)))}
            chain = cb.slot_blocks[0]
            expect = {(chain[p // cb.bs], p % cb.bs)
                      for p in range(len0, len0 + n)}
            assert changed == expect, \
                "a rejected (or phantom) row wrote the pool"
            cb._emit_spec([0], out, n_emit)
        assert saw_rejection

    def test_state_matches_plain_run(self, setup):
        """After identical workloads the spec batcher's allocator and
        prefix index are IDENTICAL to the plain batcher's, tokens are
        bit-equal, and committed pool values agree to bf16 noise (the
        score path is a different FP reduction than write-then-gather;
        the write SET is exact — previous test)."""
        cfg, params = setup
        cb0 = _batcher(params, cfg)
        ref, _ = _run(cb0, PROMPTS)
        cb1 = _batcher(params, cfg, speculative=True, spec_k=3,
                       draft_layers=1)
        got, _ = _run(cb1, PROMPTS)
        assert got == ref
        assert 0 < cb1.spec.accepted < cb1.spec.drafted  # real rejections
        assert cb0.alloc.stats() == cb1.alloc.stats()
        for p in PROMPTS:
            assert cb0._match_cached(p)[1] == cb1._match_cached(p)[1]
        assert np.allclose(np.asarray(cb0.cache.k.astype(jnp.float32)),
                           np.asarray(cb1.cache.k.astype(jnp.float32)),
                           atol=0.05)

    def test_acceptance_accounting(self, setup):
        """Full-depth draft (draft == target): every proposal accepted,
        tokens/step multiplies; truncated draft: accepted <= drafted
        with the counters internally consistent."""
        cfg, params = setup
        # two same-bucket short prompts: ONE cold batched prefill,
        # then pure spec decode (no fused ticks to share emission)
        short = [PROMPTS[0], PROMPTS[3]]
        cb = _batcher(params, cfg, speculative=True, spec_k=3)
        got, _ = _run(cb, short)
        s = cb.spec
        assert s.steps > 0
        assert s.accept_rate() == pytest.approx(1.0)
        assert s.tokens_per_step() > 1.0
        # every token after each request's prefill-emitted FIRST one
        # came from a verify sweep
        assert s.emitted == sum(len(t) for t in got) - len(got)
        st = cb.spec_stats()
        assert st["enabled"] and st["k"] == 3 and st["draft_depth"] == 2

        cb2 = _batcher(params, cfg, speculative=True, spec_k=3,
                       draft_layers=1)
        _run(cb2, short)
        assert cb2.spec.accepted <= cb2.spec.drafted
        assert cb2.spec.emitted >= cb2.spec.steps     # >= 1 token/sweep

    def test_memo_keys_carry_spec_config(self, setup):
        """Every compiled-shape memo key carries the spec config
        BEFORE the trailing (weight_dtype, kv_dtype) qkey — and the
        spec cache holds exactly the warmed draft/verify pair."""
        cfg, params = setup
        cb = _batcher(params, cfg, speculative=True, spec_k=3,
                      draft_layers=1, kv_dtype="int8")
        cb.warmup_prefill()
        keys = (list(cb._prefill_cache) + list(cb._fused_cache)
                + list(cb._chunk_cache))
        assert keys
        for k in keys:
            assert k[-2:] == ("fp", "int8")
            assert ("spec", 3, 1, "xla") == tuple(k[-6:-2])
        assert {k[0] for k in cb._spec_cache} == {"draft", "verify"}
        # a plain batcher's keys are unchanged (no spec element)
        cb0 = _batcher(params, cfg)
        cb0.warmup_prefill()
        assert all(k[-3] in (True, False, "xla", "pallas")
                   for k in cb0._prefill_cache)

    def test_per_request_opt_out(self, setup):
        """submit(speculative=False) decodes THAT request plain inside
        a spec batcher (acceptance forced to 0) with tokens unchanged,
        and the opt-out set drains on retire."""
        cfg, params = setup
        ref, _ = _run(_batcher(params, cfg), PROMPTS[:2])
        cb = _batcher(params, cfg, speculative=True, spec_k=3)
        cb.warmup_prefill()
        r0 = cb.submit(PROMPTS[0], speculative=False)
        r1 = cb.submit(PROMPTS[1])
        out = cb.run()
        assert [list(out[r0]), list(out[r1])] == ref
        # the opted-out slot drafted nothing; the spec slot did
        assert cb.spec.drafted == cb.spec.steps * cb.spec_k
        assert not cb._no_spec


class TestSpecInt8KV:
    def test_int8_kv_parity(self, setup):
        """Spec and plain share one int8 pool discipline (the
        row-sequential commit keeps grow-only scales evolving like
        sequential decode's); the score path reads full-precision
        slab rows, so spec-vs-plain under int8 is a documented
        match-rate floor rather than bitwise (README "Speculative
        decoding") — in practice it is exact or near-exact."""
        cfg, params = setup
        cb0 = _batcher(params, cfg, kv_dtype="int8")
        ref, _ = _run(cb0, PROMPTS)
        cb1 = _batcher(params, cfg, kv_dtype="int8", speculative=True,
                       spec_k=3, draft_layers=1)
        got, rec = _run(cb1, PROMPTS)
        n = sum(len(t) for t in ref)
        m = sum(1 for a, b in zip(ref, got)
                for x, y in zip(a, b) if x == y)
        assert m / n >= 0.9, f"int8 spec match {m}/{n}"
        assert rec == 0
        assert cb0.alloc.stats() == cb1.alloc.stats()

    def test_int8_scale_cleanliness_per_tick(self, setup):
        """Grow-only scale hygiene under spec: per spec tick, scale
        entries change ONLY at (layer, block) slots of blocks holding
        accepted rows — a rejected draft's magnitudes can never
        coarsen a block's quantization."""
        cfg, params = setup
        cb = _batcher(params, cfg, speculative=True, spec_k=3,
                      draft_layers=1, kv_dtype="int8")
        cb.warmup_prefill()
        cb.submit(PROMPTS[0])
        cb._admit()
        assert cb.active[0]
        while cb.active[0]:
            len0 = int(np.asarray(cb.cache.lengths)[0])
            pre = np.asarray(cb.cache.k_scale)
            out, n_emit = cb._step_spec()
            n = int(n_emit[0])
            post = np.asarray(cb.cache.k_scale)
            chain = cb.slot_blocks[0]
            touched = {chain[p // cb.bs]
                       for p in range(len0, len0 + n)}
            changed = set(np.argwhere(
                np.any(pre != post, axis=0)).ravel().tolist())
            assert changed <= touched, \
                "a rejected draft row grew a block scale"
            cb._emit_spec([0], out, n_emit)


class TestTreeSpecConfig:
    def test_tree_validation_and_geometry(self):
        with pytest.raises(ValueError):
            SpecConfig(tree=[])
        with pytest.raises(ValueError):
            SpecConfig(tree=[2, 0])
        sc = SpecConfig(tree=[2, 2])
        assert sc.k == 6 and sc.slab_rows() == 7
        assert sc.tree_depth() == 2
        assert sc.level_sizes() == [1, 2, 4]
        assert sc.level_offsets() == [0, 1, 3, 7]
        assert sc.row_levels() == [0, 1, 1, 2, 2, 2, 2]
        assert sc.row_parents() == [0, 0, 0, 1, 1, 2, 2]
        A = sc.ancestor_mask()
        # node 5 (child 0 of slab row 2): sees exactly root -> 2 -> 5
        assert [s for s in range(7) if A[5][s]] == [0, 2, 5]
        # the chain's mask is the causal triangle (the pre-tree shape)
        Ac = SpecConfig(k=3).ancestor_mask()
        assert all(Ac[p][s] == (s <= p)
                   for p in range(4) for s in range(4))
        assert SpecConfig(k=3).row_parents() == [0, 0, 1, 2]

    def test_tree_key_and_dict(self):
        """Tree / draft_w8 configs extend the memo-key element; chain
        configs keep the pre-tree 3-tuple byte-identical."""
        sc = SpecConfig(tree=[2, 1], draft_layers=1, num_layers=2)
        assert sc.key(2) == ("spec", 4, 1, "tree", 2, 1)
        d = sc.as_dict(2)
        assert d["tree"] == [2, 1] and d["k"] == 4
        assert SpecConfig(3).key(2) == ("spec", 3, 2)
        assert SpecConfig(3, draft_w8=True).key(2) == \
            ("spec", 3, 2, "w8")

    def test_depth_hist_and_accepted_per_sweep(self):
        s = SpecStats()
        s.record_step(drafted=8, accepted=5, emitted=6, slots=2,
                      depths=[2, 3])
        s.record_step(drafted=8, accepted=3, emitted=4, slots=2,
                      depths=[0, 3])
        assert s.accepted_per_sweep() == pytest.approx(8 / 4)
        assert s.depth_hist == {0: 1, 2: 1, 3: 2}
        # fresh depths drain exactly once (the engine's gauge sync)
        assert s.drain_depths() == [2, 3, 0, 3]
        assert s.drain_depths() == []
        d = s.as_dict()
        assert d["accept_depth_hist"] == {0: 1, 2: 1, 3: 2}
        assert d["accepted_per_sweep"] == pytest.approx(2.0)


class TestTreeSpecParity:
    def test_tree_bit_identical_and_dominates_chain(self, setup):
        """Tree speculation emits plain greedy's exact tokens with 0
        post-warmup recompiles, and at equal accepted-path budget
        (tree depth == chain k) tree acceptance per sweep dominates
        the chain's — child 0 of every node IS the chain's draft."""
        cfg, params = setup
        ref, _ = _run(_batcher(params, cfg), PROMPTS)
        chain = _batcher(params, cfg, speculative=True, spec_k=3)
        gc, _ = _run(chain, PROMPTS)
        tree = _batcher(params, cfg, speculative=True,
                        spec_tree=[2, 1, 1])
        gt, rec = _run(tree, PROMPTS)
        assert gc == ref and gt == ref
        assert rec == 0
        assert tree.spec.steps > 0
        assert tree.spec.accepted_per_sweep() >= \
            chain.spec.accepted_per_sweep()
        assert tree.spec.depth_hist          # histogram populated
        st = tree.spec_stats()
        assert st["tree"] == [2, 1, 1] and st["k"] == 6

    def test_degenerate_tree_equals_chain(self, setup):
        """tree=[1,1,1] IS a chain of k=3: identical tokens AND
        identical acceptance counters (the tree machinery reduces
        exactly to the chain when every branching factor is 1)."""
        cfg, params = setup
        short = PROMPTS[:3]
        chain = _batcher(params, cfg, speculative=True, spec_k=3,
                         draft_layers=1)
        gc, _ = _run(chain, short)
        tree = _batcher(params, cfg, speculative=True,
                        spec_tree=[1, 1, 1], draft_layers=1)
        gt, _ = _run(tree, short)
        assert gt == gc
        assert tree.spec.accepted == chain.spec.accepted
        assert tree.spec.emitted == chain.spec.emitted
        assert tree.spec.depth_hist == chain.spec.depth_hist

    def test_tree_truncated_draft_bit_identical(self, setup):
        """A truncated tree draft (real rejections at every level)
        still lands plain greedy's exact tokens."""
        cfg, params = setup
        ref, _ = _run(_batcher(params, cfg), PROMPTS)
        cb = _batcher(params, cfg, speculative=True,
                      spec_tree=[2, 2], draft_layers=1)
        got, rec = _run(cb, PROMPTS)
        assert got == ref
        assert rec == 0
        assert cb.spec.accepted < cb.spec.drafted    # real rejections

    def test_tree_write_set(self, setup):
        """Verify-then-commit at the write-set level under TREE drafts:
        per tick the pool changes at exactly the accepted PATH's rows
        — no sibling branch's K/V ever lands."""
        cfg, params = setup
        cb = _batcher(params, cfg, speculative=True,
                      spec_tree=[2, 1], draft_layers=1)
        cb.warmup_prefill()
        cb.submit(PROMPTS[0])
        cb._admit()
        assert cb.active[0]
        while cb.active[0]:
            len0 = int(np.asarray(cb.cache.lengths)[0])
            pre = np.asarray(cb.cache.k.astype(jnp.float32))
            out, n_emit = cb._step_spec()
            n = int(n_emit[0])
            assert 1 <= n <= cb._spec_cfg.tree_depth() + 1
            post = np.asarray(cb.cache.k.astype(jnp.float32))
            changed = {tuple(c) for c in np.argwhere(
                np.any(pre != post, axis=(0, 3, 4)))}
            chain = cb.slot_blocks[0]
            expect = {(chain[p // cb.bs], p % cb.bs)
                      for p in range(len0, len0 + n)}
            assert changed == expect, \
                "a sibling/rejected tree row wrote the pool"
            cb._emit_spec([0], out, n_emit)

    def test_tree_int8_kv_scale_cleanliness(self, setup):
        """Tree spec over an int8 pool: per tick, block scales grow
        only at blocks holding accepted-path rows (grow-only hygiene
        survives the tree commit loop)."""
        cfg, params = setup
        cb = _batcher(params, cfg, speculative=True,
                      spec_tree=[2, 1], draft_layers=1,
                      kv_dtype="int8")
        cb.warmup_prefill()
        cb.submit(PROMPTS[0])
        cb._admit()
        while cb.active[0]:
            len0 = int(np.asarray(cb.cache.lengths)[0])
            pre = np.asarray(cb.cache.k_scale)
            out, n_emit = cb._step_spec()
            n = int(n_emit[0])
            post = np.asarray(cb.cache.k_scale)
            chain = cb.slot_blocks[0]
            touched = {chain[p // cb.bs]
                       for p in range(len0, len0 + n)}
            changed = set(np.argwhere(
                np.any(pre != post, axis=0)).ravel().tolist())
            assert changed <= touched
            cb._emit_spec([0], out, n_emit)

    def test_draft_w8_bit_identical(self, setup):
        """draft-from-w8: the truncated draft reads an int8 weight-only
        quantization of its layer stack (built once at construction on
        an fp target; a no-op on an int8 target) — verification runs
        the target's weights, so emitted tokens stay plain greedy's."""
        cfg, params = setup
        ref, _ = _run(_batcher(params, cfg), PROMPTS)
        cb = _batcher(params, cfg, speculative=True, spec_k=3,
                      draft_layers=1, spec_draft_w8=True)
        assert cb._spec_dlayers is not None      # built on fp target
        got, rec = _run(cb, PROMPTS)
        assert got == ref
        assert rec == 0
        # tree x w8 compose
        cb2 = _batcher(params, cfg, speculative=True,
                       spec_tree=[2, 1, 1], draft_layers=1,
                       spec_draft_w8=True)
        got2, _ = _run(cb2, PROMPTS)
        assert got2 == ref
        # int8 target: the draft already reads quantized weights
        cb3 = _batcher(params, cfg, speculative=True, spec_k=3,
                       weight_dtype="int8", spec_draft_w8=True)
        assert cb3._spec_dlayers is None

    def test_pallas_verify_parity(self, setup):
        """spec_attention_impl="pallas" routes the spec score path
        through the kernel's suffix-slab operand (interpret mode on
        CPU) — tokens bit-identical to the XLA score path and to
        plain decode, chain AND tree."""
        cfg, params = setup
        short = PROMPTS[:2]
        ref, _ = _run(_batcher(params, cfg), short)
        for tree in (None, [2, 1]):
            cb = _batcher(params, cfg, speculative=True, spec_k=2,
                          spec_tree=tree, draft_layers=1,
                          spec_attention_impl="pallas")
            assert cb.spec_attention_impl == "pallas"
            assert cb.attention_impl == "xla"    # decode path unchanged
            got, rec = _run(cb, short)
            assert got == ref, f"tree={tree} diverged under pallas"
            assert rec == 0

    def test_tree_memo_keys(self, setup):
        """Tree + spec-impl configs ride every compiled-shape memo key
        (prefill/fused/chunk caches via _skey; the spec cache via
        _spec_key's phase tuple) — no aliasing across shapes."""
        cfg, params = setup
        cb = _batcher(params, cfg, speculative=True,
                      spec_tree=[2, 1], draft_layers=1,
                      kv_dtype="int8",
                      spec_attention_impl="pallas")
        cb.warmup_prefill()
        keys = (list(cb._prefill_cache) + list(cb._fused_cache)
                + list(cb._chunk_cache))
        assert keys
        for k in keys:
            assert k[-2:] == ("fp", "int8")
            assert tuple(k[-9:-2]) == ("spec", 4, 1, "tree", 2, 1,
                                       "pallas")
        sk = [k for k in cb._spec_cache]
        assert {k[0] for k in sk} == {"draft", "verify"}
        for k in sk:
            assert k[1] == 4 and k[2] == 1       # spec_k, draft depth
            assert "pallas" in k and "xla" in k  # both resolved impls
            assert "tree" in k


class TestSpecEngine:
    def test_engine_parity_gauges_snapshot(self, setup):
        cfg, params = setup
        def serve(**kw):
            eng = serving.ServingEngine(
                params, cfg, max_batch=2, block_size=4,
                max_total_len=48, max_new_tokens=MAX_NEW, chunk=3,
                max_prefill_bucket=8, start=False, **kw)
            eng.warmup()
            eng.start()
            reqs = [eng.submit(p) for p in PROMPTS]
            outs = [r.result(300) for r in reqs]
            snap = eng.snapshot()
            eng.shutdown()
            return outs, snap
        ref, snap0 = serve()
        got, snap = serve(speculative=True, spec_tree=[2, 1, 1])
        assert got == ref
        sp = snap["speculative"]
        assert sp["enabled"] and sp["tokens_per_step"] > 1.0
        assert sp["tree"] == [2, 1, 1]
        assert snap["gauges"]["spec_accept_rate"] == \
            pytest.approx(sp["accept_rate"])
        assert snap["gauges"]["spec_tokens_per_step"] > 1.0
        # the accept-depth distribution surfaces twice: spec_stats'
        # exact dict and the drained Prometheus histogram — counts
        # must agree (every depth observed exactly once)
        assert sp["accept_depth_hist"]
        h = snap["histograms"]["spec_accept_depth"]
        assert h["count"] == sum(sp["accept_depth_hist"].values())
        assert snap0["speculative"]["enabled"] is False
        assert snap0["gauges"]["spec_steps"] == 0

    def test_quarantine_spec_fallback(self, setup):
        """A failed spec tick quarantines like any step failure — and
        every surviving request re-admits OPTED OUT of speculation
        (plain decode for the victims), with tokens still identical
        to the fault-free run."""
        cfg, params = setup
        def serve(inj=None):
            eng = serving.ServingEngine(
                params, cfg, max_batch=2, block_size=4,
                max_total_len=48, max_new_tokens=MAX_NEW, chunk=3,
                max_prefill_bucket=8, start=False, speculative=True,
                spec_k=3, fault_injector=inj, retry_backoff_s=0.01)
            eng.warmup()
            eng.start()
            # ONE short request: tick 1 is its standalone prefill,
            # ticks 2/3 the first spec draft/verify pair —
            # deterministic tick numbering for the injected fault
            reqs = [eng.submit(PROMPTS[0])]
            outs = [r.result(300) for r in reqs]
            return eng, reqs, outs
        eng0, _, ref = serve()
        eng0.shutdown()
        # fail the FIRST spec verify once, transient
        inj = FaultInjector(seed=0).fail_on_step(3, transient=True)
        eng, reqs, outs = serve(inj)
        assert outs == ref                       # recovery is lossless
        h = eng.health()
        assert h["quarantines"] >= 1
        assert h["requests_retried"] >= 1
        assert all(r.spec_opt_out for r in reqs)
        b = eng.batcher
        # the fallback held: the only attempted sweep FAILED before
        # recording, and with every active request opted out the
        # batcher dropped to the plain chunk path (no vacuous sweeps)
        assert b.spec.steps == 0 and b.spec.accepted == 0
        assert not b._no_spec                    # drained at retire
        eng.shutdown()

    def test_trace_report_accepted_per_step(self, setup):
        """spec_draft/spec_verify events land in the timeline and
        trace_report grows the accepted-per-step column."""
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=2, block_size=4, max_total_len=48,
            max_new_tokens=MAX_NEW, chunk=3, max_prefill_bucket=8,
            start=False, speculative=True, spec_k=3)
        eng.warmup()
        eng.start()
        for p in PROMPTS[:2]:
            eng.generate(p, timeout=300)
        chrome = eng.trace.to_chrome_trace()
        eng.shutdown()
        names = {e.get("name") for e in chrome["traceEvents"]}
        assert "spec_draft" in names and "spec_verify" in names
        spec = importlib.util.spec_from_file_location(
            "trace_report", REPO / "tools" / "trace_report.py")
        tr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tr)
        evs = sorted([e for e in chrome["traceEvents"]
                      if e.get("ph") != "M"],
                     key=lambda e: e.get("ts", 0.0))
        summary = tr.summarize(evs)
        t = summary["total"]
        assert t["spec_verify_steps"] > 0
        assert t["spec_accepted_tokens"] > 0
        # accepted drafts/sweep, and total tokens landed/sweep (the
        # latter adds the corrected token: always >= accepted + ~1)
        assert t["accepted_per_step"] > 1.0
        assert t["spec_tokens_per_step"] > t["accepted_per_step"]
        rows = [r for r in summary["requests"]
                if r.get("spec_steps")]
        assert rows and all(r["acc_per_step"] is not None for r in rows)
        assert "acc_per_step" in tr.render(summary)
