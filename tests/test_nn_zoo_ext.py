"""Tests for the nn layer-zoo extension (pooling 3D, Bilinear, Fold/Unfold,
loss zoo additions, grid_sample/affine_grid, adaptive log softmax).
Reference test style: eager asserts vs numpy/torch-consistent formulas
(SURVEY.md §4 API/layer tests row)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F

RNG = np.random.default_rng(11)


def t(*shape, dtype=np.float32):
    return paddle.to_tensor(RNG.standard_normal(shape).astype(dtype))


class TestPool3D:
    def test_max_avg_pool3d(self):
        x = t(2, 3, 8, 8, 8)
        out = nn.MaxPool3D(2, 2)(x)
        assert out.shape == [2, 3, 4, 4, 4]
        ref = np.asarray(x.numpy()).reshape(2, 3, 4, 2, 4, 2, 4, 2) \
            .max(axis=(3, 5, 7))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)
        avg = nn.AvgPool3D(2, 2)(x)
        ref_a = np.asarray(x.numpy()).reshape(2, 3, 4, 2, 4, 2, 4, 2) \
            .mean(axis=(3, 5, 7))
        np.testing.assert_allclose(avg.numpy(), ref_a, rtol=1e-5)

    def test_adaptive_max(self):
        x = t(2, 3, 12)
        out = nn.AdaptiveMaxPool1D(4)(x)
        assert out.shape == [2, 3, 4]
        ref = x.numpy().reshape(2, 3, 4, 3).max(-1)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)
        x3 = t(1, 2, 8, 8, 8)
        assert nn.AdaptiveMaxPool3D(2)(x3).shape == [1, 2, 2, 2, 2]


class TestBilinearFold:
    def test_bilinear(self):
        layer = nn.Bilinear(3, 4, 5)
        x1, x2 = t(6, 3), t(6, 4)
        out = layer(x1, x2)
        assert out.shape == [6, 5]
        w = layer.weight.numpy()
        b = layer.bias.numpy()
        ref = np.einsum("ni,oij,nj->no", x1.numpy(), w, x2.numpy()) + b
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_unfold_fold_round_trip(self):
        x = t(1, 2, 6, 6)
        cols = nn.Unfold(2, strides=2)(x)
        assert cols.shape == [1, 2 * 2 * 2, 9]
        back = nn.Fold([6, 6], 2, strides=2)(cols)
        np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-6)


class TestActZoo:
    def test_glu(self):
        x = t(4, 8)
        out = nn.GLU()(x)
        a, b = np.split(x.numpy(), 2, axis=-1)
        np.testing.assert_allclose(out.numpy(), a / (1 + np.exp(-b)),
                                   rtol=1e-5)

    def test_rrelu_eval_uses_mean_slope(self):
        layer = nn.RReLU(0.1, 0.3)
        layer.eval()
        x = paddle.to_tensor(np.array([-10.0, 10.0], np.float32))
        np.testing.assert_allclose(layer(x).numpy(), [-2.0, 10.0], rtol=1e-5)

    def test_softmax2d(self):
        x = t(2, 3, 4, 4)
        out = nn.Softmax2D()(x)
        np.testing.assert_allclose(out.numpy().sum(axis=1),
                                   np.ones((2, 4, 4)), rtol=1e-5)

    def test_silu_alias(self):
        assert nn.Silu is nn.SiLU


class TestLossZoo:
    def test_huber(self):
        i, l = t(8), t(8)
        out = nn.HuberLoss(delta=0.5)(i, l).numpy()
        d = i.numpy() - l.numpy()
        ref = np.where(np.abs(d) <= 0.5, 0.5 * d * d,
                       0.5 * (np.abs(d) - 0.25)).mean()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_soft_margin(self):
        i = t(6)
        lbl = paddle.to_tensor(
            np.sign(RNG.standard_normal(6)).astype(np.float32))
        out = nn.SoftMarginLoss()(i, lbl).numpy()
        ref = np.log1p(np.exp(-lbl.numpy() * i.numpy())).mean()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_multi_margin(self):
        logits = t(4, 5)
        labels = paddle.to_tensor(np.array([0, 2, 1, 4]))
        out = nn.MultiMarginLoss()(logits, labels).numpy()
        lg = logits.numpy()
        ref = 0.0
        for n in range(4):
            c = labels.numpy()[n]
            margins = np.maximum(0, 1 - lg[n, c] + lg[n])
            margins[c] = 0
            ref += margins.sum() / 5
        np.testing.assert_allclose(out, ref / 4, rtol=1e-5)

    def test_poisson_gaussian_nll(self):
        i, lbl = t(6).abs(), t(6).abs()
        out = nn.PoissonNLLLoss()(i, lbl).numpy()
        ref = (np.exp(i.numpy()) - lbl.numpy() * i.numpy()).mean()
        np.testing.assert_allclose(out, ref, rtol=1e-5)
        var = t(6).abs() + 0.5
        g = nn.GaussianNLLLoss()(i, lbl, var).numpy()
        ref_g = 0.5 * (np.log(var.numpy()) +
                       (i.numpy() - lbl.numpy()) ** 2 / var.numpy())
        np.testing.assert_allclose(g, ref_g.mean(), rtol=1e-5)

    def test_multilabel_soft_margin(self):
        i = t(3, 4)
        lbl = paddle.to_tensor((RNG.random((3, 4)) > 0.5).astype(np.float32))
        out = nn.MultiLabelSoftMarginLoss()(i, lbl).numpy()
        x, y = i.numpy(), lbl.numpy()
        ref = -(y * np.log(1 / (1 + np.exp(-x))) +
                (1 - y) * np.log(1 - 1 / (1 + np.exp(-x)))).mean(-1).mean()
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_triplet_with_distance(self):
        a, p, n = t(4, 8), t(4, 8), t(4, 8)
        out = nn.TripletMarginWithDistanceLoss(margin=0.5)(a, p, n).numpy()
        dp = np.linalg.norm(a.numpy() - p.numpy(), axis=1)
        dn = np.linalg.norm(a.numpy() - n.numpy(), axis=1)
        ref = np.maximum(0, dp - dn + 0.5).mean()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_dice_log_npair(self):
        probs = paddle.nn.functional.softmax(t(4, 3), axis=-1)
        lbl = paddle.to_tensor(np.array([[0], [1], [2], [1]]))
        d = F.dice_loss(probs, lbl).numpy()
        assert 0.0 < d < 1.0
        pred = paddle.to_tensor(np.clip(RNG.random(5), 0.01, 0.99)
                                .astype(np.float32))
        y = paddle.to_tensor((RNG.random(5) > 0.5).astype(np.float32))
        ll = F.log_loss(pred, y).numpy()
        ref = -(y.numpy() * np.log(pred.numpy() + 1e-4) +
                (1 - y.numpy()) * np.log(1 - pred.numpy() + 1e-4))
        np.testing.assert_allclose(ll, ref, rtol=1e-4)
        anchor, pos = t(4, 6), t(4, 6)
        labels = paddle.to_tensor(np.array([0, 0, 1, 1]))
        npl = F.npair_loss(anchor, pos, labels).numpy()
        assert np.isfinite(npl)

    def test_ctc_loss_layer(self):
        logp = F.log_softmax(t(6, 2, 5), axis=-1)  # T,N,C
        labels = paddle.to_tensor(np.array([[1, 2, 3], [2, 3, 1]]))
        ilen = paddle.to_tensor(np.array([6, 6]))
        llen = paddle.to_tensor(np.array([3, 3]))
        loss = nn.CTCLoss()(logp, labels, ilen, llen)
        assert np.isfinite(loss.numpy())


class TestGridOps:
    def test_affine_grid_identity(self):
        theta = paddle.to_tensor(
            np.tile(np.array([[1, 0, 0], [0, 1, 0]], np.float32), (2, 1, 1)))
        grid = F.affine_grid(theta, [2, 3, 4, 4])
        assert grid.shape == [2, 4, 4, 2]
        np.testing.assert_allclose(grid.numpy()[0, 0, :, 0],
                                   np.linspace(-1, 1, 4), rtol=1e-6)

    def test_grid_sample_identity(self):
        x = t(2, 3, 5, 5)
        theta = paddle.to_tensor(
            np.tile(np.array([[1, 0, 0], [0, 1, 0]], np.float32), (2, 1, 1)))
        grid = F.affine_grid(theta, [2, 3, 5, 5])
        out = F.grid_sample(x, grid)
        np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_grid_sample_nearest_and_zeros(self):
        x = paddle.to_tensor(
            np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        # grid entirely out of range → zeros padding
        grid = paddle.to_tensor(np.full((1, 2, 2, 2), 5.0, np.float32))
        out = F.grid_sample(x, grid, mode="nearest", padding_mode="zeros")
        np.testing.assert_allclose(out.numpy(), np.zeros((1, 1, 2, 2)))

    def test_sequence_mask_and_temporal_shift(self):
        lens = paddle.to_tensor(np.array([1, 3]))
        m = F.sequence_mask(lens, maxlen=4)
        np.testing.assert_array_equal(
            m.numpy(), [[1, 0, 0, 0], [1, 1, 1, 0]])
        x = t(4, 8, 2, 2)  # nt=4 = n2*seg2
        out = F.temporal_shift(x, seg_num=2, shift_ratio=0.25)
        assert out.shape == [4, 8, 2, 2]
        # last channels pass through unshifted
        np.testing.assert_allclose(out.numpy()[:, 4:], x.numpy()[:, 4:])


class TestAdaptiveLogSoftmax:
    def test_log_prob_normalized_and_loss(self):
        m = nn.AdaptiveLogSoftmaxWithLoss(16, 20, [5, 10], div_value=2.0)
        x = t(8, 16)
        logp = m.log_prob(x)
        assert logp.shape == [8, 20]
        np.testing.assert_allclose(np.exp(logp.numpy()).sum(-1),
                                   np.ones(8), rtol=1e-4)
        lbl = paddle.to_tensor(RNG.integers(0, 20, 8))
        out, loss = m(x, lbl)
        np.testing.assert_allclose(
            -out.numpy().mean(), loss.numpy(), rtol=1e-5)
        pred = m.predict(x)
        np.testing.assert_array_equal(pred.numpy(),
                                      logp.numpy().argmax(-1))


class TestCTCAgainstTorch:
    def test_ctc_matches_torch(self):
        import torch
        T, N, C, S = 8, 3, 6, 4
        lp = RNG.standard_normal((T, N, C)).astype(np.float32)
        lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
        labels = RNG.integers(1, C, (N, S))
        ilen = np.array([8, 7, 5])
        llen = np.array([4, 2, 3])
        ours = F.ctc_loss(
            paddle.to_tensor(lp), paddle.to_tensor(labels),
            paddle.to_tensor(ilen), paddle.to_tensor(llen),
            reduction="none").numpy()
        ref = torch.nn.functional.ctc_loss(
            torch.tensor(lp), torch.tensor(labels),
            torch.tensor(ilen), torch.tensor(llen),
            blank=0, reduction="none").numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)

    def test_ctc_grad_finite(self):
        lp = paddle.to_tensor(
            RNG.standard_normal((6, 2, 5)).astype(np.float32),
            stop_gradient=False)
        logp = F.log_softmax(lp, axis=-1)
        loss = F.ctc_loss(logp, paddle.to_tensor(RNG.integers(1, 5, (2, 3))),
                          paddle.to_tensor(np.array([6, 6])),
                          paddle.to_tensor(np.array([3, 3])))
        loss.backward()
        assert np.isfinite(lp.grad.numpy()).all()


class TestReviewRegressions:
    def test_max_pool3d_return_mask(self):
        x = t(1, 2, 4, 4, 4)
        out, mask = nn.MaxPool3D(2, 2, return_mask=True)(x)
        assert out.shape == [1, 2, 2, 2, 2] and mask.shape == out.shape
        flat = x.numpy().reshape(1, 2, -1)
        np.testing.assert_allclose(
            np.take_along_axis(flat, mask.numpy().reshape(1, 2, -1),
                               axis=2).reshape(out.shape),
            out.numpy())

    def test_adaptive_max_pool_return_mask(self):
        x = t(2, 3, 12)
        out, mask = nn.AdaptiveMaxPool1D(4, return_mask=True)(x)
        flat = x.numpy().reshape(2, 3, -1)
        np.testing.assert_allclose(
            np.take_along_axis(flat, mask.numpy().reshape(2, 3, -1),
                               axis=2).reshape(out.shape), out.numpy())

    def test_avg_pool3d_channels_last(self):
        x = t(1, 4, 4, 4, 2)  # NDHWC
        out = nn.AvgPool3D(2, 2, data_format="NDHWC")(x)
        assert out.shape == [1, 2, 2, 2, 2]
        ref = x.numpy().reshape(1, 2, 2, 2, 2, 2, 2, 2).mean(axis=(2, 4, 6))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_rrelu_training_randomizes(self):
        layer = nn.RReLU(0.1, 0.9)
        layer.train()
        x = paddle.to_tensor(np.full((1000,), -1.0, np.float32))
        out = layer(x).numpy()
        assert out.std() > 0.01  # random slopes, not the fixed mean
        assert ((-out >= 0.1 - 1e-6) & (-out <= 0.9 + 1e-6)).all()

    def test_ctc_norm_by_times(self):
        lp = F.log_softmax(t(8, 2, 5), axis=-1)
        labels = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        il = paddle.to_tensor(np.array([8, 4]))
        ll = paddle.to_tensor(np.array([2, 2]))
        plain = F.ctc_loss(lp, labels, il, ll, reduction="none").numpy()
        normed = F.ctc_loss(lp, labels, il, ll, reduction="none",
                            norm_by_times=True).numpy()
        np.testing.assert_allclose(normed, plain / np.array([8, 4]),
                                   rtol=1e-6)

    def test_soft_margin_large_logits_stable(self):
        out = nn.SoftMarginLoss()(
            paddle.to_tensor(np.array([200.0], np.float32)),
            paddle.to_tensor(np.array([-1.0], np.float32)))
        assert np.isfinite(out.numpy()) and out.numpy() == 200.0


class TestMaxUnPool:
    def test_unpool2d_inverts_pool(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(
            np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        y, mask = F.max_pool2d(x, 2, return_mask=True)
        up = nn.MaxUnPool2D(2)(y, mask)
        assert up.shape == [1, 1, 4, 4]
        # pooled maxima land back at their argmax positions; rest zero
        assert float(up.sum().numpy()) == float(y.sum().numpy())
        assert float(up.numpy()[0, 0, 3, 3]) == 15.0

    def test_unpool1d_shapes(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(np.random.randn(2, 3, 8).astype(np.float32))
        y, mask = F.max_pool1d(x, 2, return_mask=True)
        assert nn.MaxUnPool1D(2)(y, mask).shape == [2, 3, 8]


class TestHSigmoidLoss:
    def test_loss_positive_and_trains(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        hs = nn.HSigmoidLoss(8, 6)
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                             .astype(np.float32), stop_gradient=False)
        lab = paddle.to_tensor(np.array([0, 1, 2, 5]))
        loss = hs(x, lab).mean()
        assert float(loss.numpy()) > 0
        loss.backward()
        assert x.grad is not None
        assert hs.weight.grad is not None


class TestBeamSearchDecode:
    def _cell(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        class Cell(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x, states):
                h = paddle.tanh(self.fc(x) + states)
                return h, h

        return Cell()

    def test_beam_shapes_and_greedy_consistency(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        paddle.seed(7)
        cell = self._cell()
        emb = nn.Embedding(10, 4)
        proj = nn.Linear(4, 10)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=9,
                                   beam_size=3, embedding_fn=emb,
                                   output_fn=proj)
        init = paddle.zeros([2, 4])
        out, _ = nn.dynamic_decode(dec, inits=init, max_step_num=5)
        assert out.shape == [2, 5, 3]
        # beam 0 of beam_size=1 == greedy argmax rollout of the same cell
        dec1 = nn.BeamSearchDecoder(cell, 0, 9, 1, embedding_fn=emb,
                                    output_fn=proj)
        o1, _ = nn.dynamic_decode(dec1, inits=init, max_step_num=4)
        state = init
        tok = paddle.to_tensor(np.zeros(2, np.int64))
        want = []
        for _ in range(4):
            h, state = cell(emb(tok), state)
            tok = paddle.argmax(proj(h), axis=-1)
            want.append(tok.numpy())
        np.testing.assert_array_equal(
            o1.numpy()[:, :, 0], np.stack(want, axis=1))


class TestHSigmoidLabelShape:
    def test_n_by_1_label(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        hs = nn.HSigmoidLoss(8, 6)
        out = hs(paddle.to_tensor(np.random.randn(4, 8).astype(np.float32)),
                 paddle.to_tensor(np.array([[0], [1], [2], [5]])))
        assert out.shape == [4, 1]
