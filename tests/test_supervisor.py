"""paddle_tpu.serving.supervisor — the self-healing serving tier.

Deterministic CPU coverage of the detect→kill→respawn→re-warm→rejoin
loop: backoff/breaker units, affinity invalidate-then-relearn, the
full seeded-hang → watchdog → respawn → readiness-gated rejoin e2e
(new requests served on the respawned slot with zero post-readiness
recompiles), a persistent re-hang injector driving the crash-loop
circuit breaker open, and bounded shutdown during an in-flight
restart.

Watchdog deadlines here are COMPILE-SCALE (2s, against 8s injected
hangs): a supervisor respawn runs jax tracing + XLA compile
concurrently with the survivor's serving steps, and a sub-second
deadline can trip on that CPU contention alone — the same "warm up
before serving under a tight deadline" guidance PR 8 documented,
extended to restarts.
"""
import importlib.util
import pathlib
import random
import threading
import time

import numpy as np
import pytest
import jax

from paddle_tpu.nlp import llama
from paddle_tpu import serving
from paddle_tpu.serving.faults import FaultInjector
from paddle_tpu.serving.router import Router, _AffinityIndex
from paddle_tpu.serving.supervisor import (
    ReplicaSupervisor, compute_backoff, _Slot,
    SLOT_SERVING, SLOT_RESTARTING, SLOT_FAILED)

REPO = pathlib.Path(__file__).resolve().parent.parent

_RNG = np.random.RandomState(11)
PROMPTS = [list(map(int, _RNG.randint(1, 200, n)))
           for n in (5, 7, 9, 6, 11, 4)]
MAX_NEW = 6


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny(use_flash=False, num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def baselines(setup):
    """Single-engine reference tokens (greedy — replica-invariant)."""
    cfg, params = setup
    eng = serving.ServingEngine(
        params, cfg, max_batch=2, block_size=4, max_total_len=48,
        max_new_tokens=MAX_NEW, chunk=3)
    out = [eng.generate(p, timeout=300) for p in PROMPTS]
    eng.shutdown()
    return out


def _router(setup, injs, **restart_opts):
    cfg, params = setup
    opts = {"backoff_s": 0.05, "poll_s": 0.02,
            "probe_timeout_s": 120.0}
    opts.update(restart_opts)
    return Router(
        params, cfg, replicas=2, max_batch=2, block_size=4,
        max_total_len=48, max_new_tokens=MAX_NEW, chunk=3,
        max_queue_depth=32, max_prefill_bucket=16, watchdog_s=2.0,
        per_replica=[{"fault_injector": injs[0]},
                     {"fault_injector": injs[1]}],
        auto_restart=True, restart_opts=opts, start=False)


class TestUnits:
    def test_backoff_schedule(self):
        rng = random.Random(0)
        vals = [compute_backoff(a, base_s=0.25, max_s=8.0, jitter=0.0,
                                rng=rng) for a in range(1, 8)]
        # pure exponential with no jitter, capped at max_s
        assert vals == [0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 8.0]
        assert compute_backoff(0, base_s=1, max_s=8, jitter=0.0,
                               rng=rng) == 0.0
        # jitter scales into [1, 1+jitter) and is seed-deterministic
        a = [compute_backoff(3, base_s=0.25, max_s=8.0, jitter=0.5,
                             rng=random.Random(7)) for _ in range(2)]
        b = [compute_backoff(3, base_s=0.25, max_s=8.0, jitter=0.5,
                             rng=random.Random(7)) for _ in range(2)]
        assert a == b
        assert all(1.0 <= v / 1.0 < 1.5 for v in a)

    def test_breaker_window(self):
        class _FakeEng:
            replica_id = "r0"
        class _FakeRouter:
            engines = [_FakeEng()]
        t = [100.0]
        sup = ReplicaSupervisor(_FakeRouter(), breaker_threshold=3,
                                breaker_window_s=10.0,
                                clock=lambda: t[0])
        slot = _Slot(0)
        # two failures inside the window: breaker stays shut
        slot.failure_times.extend([100.0, 101.0])
        assert not sup._breaker_tripped(slot, consecutive=2)
        slot.failure_times.append(102.0)
        # third inside the window → open
        assert sup._breaker_tripped(slot, consecutive=3)
        # failures age out of the trailing window...
        t[0] = 111.5
        assert not sup._breaker_tripped(slot, consecutive=1)
        assert list(slot.failure_times) == [102.0]
        # ...but CONSECUTIVE failures in one cycle trip regardless of
        # window age — attempts slower than the window (a 120s probe
        # timeout vs a 60s window) must not crash-loop forever
        assert sup._breaker_tripped(slot, consecutive=3)

    def test_slot_info_shape(self):
        s = _Slot(0)
        info = s.info()
        assert info["state"] == SLOT_SERVING
        assert info["restarts"] == 0 and not info["circuit_open"]
        s.state = SLOT_RESTARTING
        assert s.info()["restarting"] is True
        s.state = SLOT_FAILED
        assert s.info()["state"] == "FAILED"

    def test_affinity_invalidate_and_relearn(self):
        idx = _AffinityIndex(block_size=2, cap=64)
        idx.observe([1, 2, 3, 4], replica=0)
        idx.observe([1, 2, 5, 6], replica=1)      # shared head re-points
        idx.observe([7, 8], replica=1)
        assert idx.match([1, 2]) == {1: 2}
        dropped = idx.invalidate(1)
        assert dropped >= 2
        # nothing points at the dead replica any more
        assert idx.match([7, 8]) == {}
        assert 1 not in idx.match([1, 2, 5, 6]).values() or \
            idx.match([1, 2, 5, 6]) == {}
        # the index re-learns from fresh routing observations
        idx.observe([7, 8], replica=1)
        assert idx.match([7, 8]) == {1: 2}

    def test_engine_ready_state(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(
            params, cfg, max_batch=1, block_size=4, max_total_len=32,
            max_new_tokens=4, chunk=2, max_prefill_bucket=8,
            start=False)
        assert eng.health()["ready"] is False      # not warmed, no loop
        eng.warmup()
        assert eng.health()["ready"] is False      # warm but parked
        eng.start()
        assert eng.health()["ready"] is True
        eng.shutdown()
        assert eng.health()["ready"] is False

    def test_auto_restart_rejects_prebuilt_engines(self):
        # without an engine_factory there is no rebuild recipe
        with pytest.raises(ValueError):
            Router(engines=[object()], auto_restart=True)

    def test_fuse_broken_requests_are_failover_eligible(self):
        """_mark_broken fails never-served queued/parked requests with
        fault_streak_engine_unhealthy — the default failover predicate
        must re-place them (the replica died, not the request), while
        ordinary step errors stay terminal."""
        from paddle_tpu.serving.router import _default_failover_on
        req = serving.GenerationRequest([1, 2, 3])
        err = RuntimeError("injected device error")
        assert _default_failover_on(req, err,
                                    "fault_streak_engine_unhealthy")
        assert _default_failover_on(req, err, "watchdog_hung_step")
        assert not _default_failover_on(req, err, "decode_step_raised")


def _stub_engine_cls():
    class _Stub:
        """Minimal router-shaped engine for factory plumbing units."""

        def __init__(self, rid):
            self.replica_id = rid
            self.trace = None

        def health(self):
            return {"status": "HEALTHY", "replica_id": self.replica_id}

        def load(self):
            return {"replica_id": self.replica_id, "queue_depth": 0,
                    "in_flight": 0, "parked_retries": 0,
                    "kv_utilization": 0.0, "accepting": True}

        def start(self):
            return self

        def shutdown(self, drain=True, timeout=None):
            return True

    return _Stub


class TestEngineFactory:
    def test_prebuilt_engines_accept_factory_for_auto_restart(self):
        """The PR 12 gap: prebuilt engines= + auto_restart raises
        without a rebuild recipe, but an engine_factory= IS one."""
        Stub = _stub_engine_cls()
        with pytest.raises(ValueError):
            Router(engines=[Stub("r0")], auto_restart=True)
        r = Router(engines=[Stub("r0")], auto_restart=True,
                   engine_factory=lambda i: Stub(f"r{i}"), start=False)
        assert r._supervisor is not None
        r.shutdown(drain=False)

    def test_factory_replica_id_enforced(self):
        """A factory engine with the wrong replica_id would corrupt
        per-slot metrics/trace attribution across the swap — rejected
        at build time."""
        Stub = _stub_engine_cls()
        r = Router(engines=[Stub("r0")],
                   engine_factory=lambda i: Stub("nope"), start=False)
        with pytest.raises(ValueError):
            r._build_replica(0)
        r.shutdown(drain=False)

    def test_factory_rejects_engine_kwargs(self):
        """engine kwargs / per_replica would be silently dropped by a
        factory build (the factory never reads them) — loud failure
        at construction instead."""
        Stub = _stub_engine_cls()
        with pytest.raises(ValueError):
            Router(engine_factory=lambda i: Stub(f"r{i}"), replicas=1,
                   max_batch=2, start=False)
        with pytest.raises(ValueError):
            Router(engines=[Stub("r0")],
                   engine_factory=lambda i: Stub(f"r{i}"),
                   per_replica=[{}], start=False)

    def test_factory_builds_initial_fleet(self):
        """engines=None + engine_factory builds the fleet through the
        factory (params/cfg not required)."""
        Stub = _stub_engine_cls()
        calls = []

        def fact(i):
            calls.append(i)
            return Stub(f"r{i}")

        r = Router(engine_factory=fact, replicas=2, start=False)
        assert calls == [0, 1]
        assert [e.replica_id for e in r.engines] == ["r0", "r1"]
        r.shutdown(drain=False)

    def test_prebuilt_respawn_through_factory(self, setup):
        """E2e: a prebuilt replica killed by the watchdog respawns
        THROUGH the factory, passes the readiness gate, rejoins and
        serves — the respawn that used to be impossible for
        engines=."""
        cfg, params = setup
        injs = [FaultInjector(seed=0), FaultInjector(seed=1)]
        factory_calls = []

        def build(i):
            return serving.ServingEngine(
                params, cfg, max_batch=2, block_size=4,
                max_total_len=48, max_new_tokens=MAX_NEW, chunk=3,
                max_queue_depth=32, max_prefill_bucket=16,
                watchdog_s=2.0, fault_injector=injs[i],
                replica_id=f"r{i}", start=False)

        def fact(i):
            factory_calls.append(i)
            return build(i)

        r = Router(engines=[build(0), build(1)], auto_restart=True,
                   engine_factory=fact,
                   restart_opts={"backoff_s": 0.05, "poll_s": 0.02,
                                 "probe_timeout_s": 120.0},
                   start=False)
        r.warmup()
        r.start()
        armed = threading.Event()
        ready = threading.Event()
        reqs = []

        def on_token(t):
            if not armed.is_set():
                armed.set()
                ready.wait(30)
                inj = injs[int(reqs[0].replica_id[1:])]
                c = inj.stats()["calls"]
                for k in range(1, 6):
                    inj.hang_on_step(c + k, 8.0)

        reqs.append(r.submit(PROMPTS[0], on_token=on_token))
        for p in PROMPTS[1:3]:
            reqs.append(r.submit(p))
        ready.set()
        outs = [q.result(300) for q in reqs]
        assert all(outs) and armed.is_set()
        for inj in injs:
            inj.heal()           # the respawn probe must run clean
        deadline = time.monotonic() + 240
        h = r.health()
        while time.monotonic() < deadline:
            h = r.health()
            if h["serving_replicas"] == 2 \
                    and h["replica_restarts"] >= 1:
                break
            time.sleep(0.05)
        assert h["replica_restarts"] >= 1, h
        assert h["serving_replicas"] == 2
        assert factory_calls, "respawn bypassed the engine_factory"
        dead = factory_calls[0]
        assert h["supervisor"][f"r{dead}"]["state"] == "SERVING"
        post = r.submit(list(PROMPTS[3]), max_new_tokens=2)
        assert post.result(300)
        assert r.shutdown()


class TestSelfHealingE2E:
    def test_hang_respawn_rejoin_and_serve(self, setup, baselines):
        """The acceptance bar: a watchdog-killed replica is respawned,
        passes the readiness gate, rejoins rotation and serves fresh
        requests with zero post-readiness recompiles — while every
        stream open during the outage fails over with the pre-failover
        stream a strict prefix, and affinity entries for the dead slot
        are invalidated then re-learned."""
        injs = [FaultInjector(seed=0), FaultInjector(seed=1)]
        r = _router(setup, injs)
        r.warmup()
        r.start()
        originals = {e.replica_id: e for e in r.engines}
        compiles0 = {e.replica_id: e.batcher.compile_count
                     for e in r.engines}
        armed = threading.Event()
        ready = threading.Event()
        reqs = []
        streamed = {i: [] for i in range(len(PROMPTS))}

        def cb(i):
            def on_token(t):
                streamed[i].append(t)
                if i == 0 and not armed.is_set():
                    armed.set()
                    ready.wait(30)
                    inj = injs[int(reqs[0].replica_id[1:])]
                    c = inj.stats()["calls"]
                    for k in range(1, 6):
                        inj.hang_on_step(c + k, 8.0)
            return on_token

        for i, p in enumerate(PROMPTS):
            reqs.append(r.submit(p, on_token=cb(i)))
        ready.set()
        outs = [q.result(300) for q in reqs]
        assert outs == baselines             # parity incl. the victims
        assert armed.is_set()
        # nothing re-emitted across the failover
        assert streamed[0] == baselines[0]
        h = r.health()
        assert h["failovers"] >= 1
        snap = r.snapshot()
        by_rid = {e["router_rid"]: e for e in snap["failover_log"]}
        kept = by_rid[reqs[0].request_id]["tokens_kept"]
        assert 0 < kept < len(baselines[0])     # strict prefix resumed
        dead_rid = by_rid[reqs[0].request_id]["from_replica"]
        # disarm leftover hang rules so the respawn probe runs clean
        for inj in injs:
            inj.heal()

        # ---- the self-healing half ----------------------------------
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            h = r.health()
            if h["serving_replicas"] == 2 and h["replica_restarts"] >= 1:
                break
            time.sleep(0.05)
        assert h["serving_replicas"] == 2, h
        assert h["replica_restarts"] >= 1
        assert h["circuit_open"] == 0 and h["failed_replicas"] == 0
        sup = h["supervisor"]
        assert sup[dead_rid]["state"] == "SERVING"
        assert sup[dead_rid]["restarts"] == 1
        respawn = next(e for e in r.engines if e.replica_id == dead_rid)
        respawn_idx = int(dead_rid[1:])
        # a NEW engine incarnation sits in the same slot, warmed, with
        # zero recompiles past its readiness gate
        assert respawn is not originals[dead_rid]
        assert respawn.health()["ready"] is True
        assert respawn.batcher.compile_count == \
            sup[dead_rid]["warm_compile_count"]
        # affinity hygiene: nothing points at the cold respawned slot
        assert all(n.replica != respawn_idx
                   for n in r._affinity._order.values())

        # post-restart: a concurrent burst of fresh short prompts (no
        # affinity pull) must land traffic on the respawned slot too
        post_rng = np.random.RandomState(99)
        post = [r.submit(list(map(int, post_rng.randint(1, 200, 3))),
                         max_new_tokens=4) for _ in range(4)]
        post_outs = [q.result(300) for q in post]
        assert all(post_outs)
        assert dead_rid in {q.replica_id for q in post}
        # survivors never recompiled either (vs their warmup baseline)
        for e in r.engines:
            if e is not respawn:
                assert e.batcher.compile_count == \
                    compiles0[e.replica_id]
        # affinity re-learns: a fresh 2-block prompt maps to whichever
        # replica served it (the respawned slot included)
        learn = list(map(int, post_rng.randint(1, 200, 8)))
        lr = r.submit(learn, max_new_tokens=2)
        lr.result(300)
        assert r._affinity.match(learn) == {int(lr.replica_id[1:]): 8}

        # observability: restarted event in the merged trace, counted
        # by trace_report's churn totals; counters in the exposition
        merged = r.to_chrome_trace()
        names = [e.get("name") for e in merged["traceEvents"]]
        assert "restarted" in names
        spec = importlib.util.spec_from_file_location(
            "trace_report", REPO / "tools" / "trace_report.py")
        tr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tr)
        evs = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
        evs.sort(key=lambda e: e.get("ts", 0.0))
        summary = tr.summarize(evs)
        assert summary["total"]["restart_events"] >= 1
        assert "restarts" in tr.render(summary)
        prom = r.to_prometheus()
        assert "paddle_tpu_replica_restarts_total" in prom
        assert r.shutdown()

    def test_persistent_hang_opens_breaker(self, setup):
        """An injector that re-hangs EVERY respawned incarnation (the
        on_attach chaos hook) must open the crash-loop circuit breaker
        within the attempt budget: the slot pins FAILED, health() and
        the Prometheus exposition surface it, and the survivor keeps
        serving."""
        injs = [FaultInjector(seed=0), FaultInjector(seed=1)]

        def rearm(inj, n, rid):
            # every RE-attach (a respawned incarnation wires the same
            # injector back in) poisons that incarnation's first
            # device calls — the readiness probe hangs, its watchdog
            # trips, the attempt fails
            if n > 1:
                c = inj.stats()["calls"]
                for k in range(1, 5):
                    inj.hang_on_step(c + k, 8.0)
        for inj in injs:
            inj.on_attach(rearm)
        r = _router(setup, injs, breaker_threshold=2,
                    breaker_window_s=300.0)
        r.warmup()
        r.start()
        armed = threading.Event()
        ready = threading.Event()
        holder = []

        def on_token(t):
            if not armed.is_set():
                armed.set()
                ready.wait(30)
                inj = injs[int(holder[0].replica_id[1:])]
                c = inj.stats()["calls"]
                for k in range(1, 6):
                    inj.hang_on_step(c + k, 8.0)

        holder.append(r.submit(PROMPTS[0], on_token=on_token))
        ready.set()
        # the victim fails over (or terminally fails if exhausted mid-
        # churn — breaker coverage is what this test gates)
        try:
            holder[0].result(300)
        except serving.RequestFailed:
            pass
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            h = r.health()
            if h["failed_replicas"] >= 1:
                break
            time.sleep(0.05)
        assert h["failed_replicas"] == 1, h
        assert h["circuit_open"] >= 1
        assert h["restart_failures"] >= 2          # the attempt budget
        assert h["replica_restarts"] == 0          # nothing rejoined
        sup = h["supervisor"]
        failed = [rid for rid, s in sup.items()
                  if s["state"] == "FAILED"]
        assert len(failed) == 1
        assert sup[failed[0]]["circuit_open"] is True
        assert sup[failed[0]]["last_error"] is not None
        # the pinned slot is out of rotation; the survivor serves on
        survivor_out = r.generate(PROMPTS[5], timeout=300)
        assert survivor_out
        assert h["serving_replicas"] == 1
        prom = r.to_prometheus()
        assert "paddle_tpu_circuit_open_total" in prom
        line = next(ln for ln in prom.splitlines()
                    if ln.startswith("paddle_tpu_circuit_open_total"))
        assert line.rstrip().endswith((" 1", " 1.0"))
        assert r.shutdown(drain=False)

    def test_shutdown_during_restart_joins_bounded(self, setup):
        """drain/shutdown while a restart is in flight (the supervisor
        parked in a long backoff after a failed attempt) interrupts
        the cycle and joins bounded — no leaked half-built replica
        keeps the process hostage."""
        injs = [FaultInjector(seed=0), FaultInjector(seed=1)]

        def rearm(inj, n, rid):
            if n > 1:
                c = inj.stats()["calls"]
                for k in range(1, 5):
                    inj.hang_on_step(c + k, 8.0)
        for inj in injs:
            inj.on_attach(rearm)
        # huge backoff: after the first failed respawn the supervisor
        # sits waiting — exactly the in-flight window shutdown must cut
        r = _router(setup, injs, backoff_s=60.0,
                    breaker_threshold=10)
        r.warmup()
        r.start()
        armed = threading.Event()
        ready = threading.Event()
        holder = []

        def on_token(t):
            if not armed.is_set():
                armed.set()
                ready.wait(30)
                inj = injs[int(holder[0].replica_id[1:])]
                c = inj.stats()["calls"]
                for k in range(1, 6):
                    inj.hang_on_step(c + k, 8.0)

        holder.append(r.submit(PROMPTS[0], on_token=on_token))
        ready.set()
        try:
            holder[0].result(300)
        except serving.RequestFailed:
            pass
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            h = r.health()
            if h["restart_failures"] >= 1 or h["restarting_replicas"]:
                break
            time.sleep(0.05)
        assert h["restart_failures"] >= 1 or h["restarting_replicas"]
        t0 = time.monotonic()
        r.shutdown(drain=False)
        # bounded: stop-event interrupts the backoff wait and the
        # probe's poll slices; teardown joins are capped
        assert time.monotonic() - t0 < 30.0
        assert r._supervisor._thread is not None
        assert not r._supervisor._thread.is_alive()


class TestBreakerReset:
    def test_reset_requires_failed_slot(self, setup):
        """reset_breaker on a SERVING slot is a no-op (False), unknown
        slots/ids raise LookupError, and a router without a supervisor
        raises RuntimeError."""
        injs = [FaultInjector(seed=0), FaultInjector(seed=1)]
        r = _router(setup, injs)
        r.warmup()
        r.start()
        out = r.reset_breaker(0)
        assert out == {"slot": 0, "replica": "r0", "reset": False,
                       "state": SLOT_SERVING}
        assert r.reset_breaker("r1")["reset"] is False
        with pytest.raises(LookupError):
            r.reset_breaker(7)
        with pytest.raises(LookupError):
            r.reset_breaker("r7")
        assert r.health()["breaker_resets"] == 0
        assert r.shutdown()
        cfg, params = setup
        plain = serving.Router(params, cfg, replicas=1, max_batch=1,
                               block_size=4, max_total_len=48,
                               max_new_tokens=2, start=False)
        with pytest.raises(RuntimeError):
            plain.reset_breaker(0)
        plain.shutdown()

    def test_reset_revives_breaker_pinned_slot(self, setup):
        """The PR 12 operator gap closed e2e: a persistent-hang chaos
        opens the breaker (slot FAILED), the operator heals the fault
        and calls reset_breaker — the slot re-enters the readiness-
        gated recovery cycle, rejoins rotation, and serves again; the
        breaker_resets counter and the breaker_reset trace event record
        the intervention."""
        injs = [FaultInjector(seed=0), FaultInjector(seed=1)]
        chaos = {"on": True}

        def rearm(inj, n, rid):
            if n > 1 and chaos["on"]:
                c = inj.stats()["calls"]
                for k in range(1, 5):
                    inj.hang_on_step(c + k, 8.0)
        for inj in injs:
            inj.on_attach(rearm)
        r = _router(setup, injs, breaker_threshold=2,
                    breaker_window_s=300.0)
        r.warmup()
        r.start()
        armed = threading.Event()
        ready = threading.Event()
        holder = []

        def on_token(t):
            if not armed.is_set():
                armed.set()
                ready.wait(30)
                inj = injs[int(holder[0].replica_id[1:])]
                c = inj.stats()["calls"]
                for k in range(1, 6):
                    inj.hang_on_step(c + k, 8.0)

        holder.append(r.submit(PROMPTS[0], on_token=on_token))
        ready.set()
        try:
            holder[0].result(300)
        except serving.RequestFailed:
            pass
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            h = r.health()
            if h["failed_replicas"] >= 1:
                break
            time.sleep(0.05)
        assert h["failed_replicas"] == 1, h
        failed_rid = next(rid for rid, s in h["supervisor"].items()
                          if s["state"] == "FAILED")
        # the operator fixes the underlying fault, then resets
        chaos["on"] = False
        for inj in injs:
            inj.heal()
        dead_eng = next(e for e in r.engines
                        if e.replica_id == failed_rid)
        out = r.reset_breaker(failed_rid)
        assert out["reset"] is True
        assert out["state"] == SLOT_RESTARTING
        # the breaker_reset event lands on the (still-pinned) dead
        # engine's sink at reset time — read it before the swap drops
        # that sink from the merged export
        dead_events = [e.get("name") for e in
                       dead_eng.trace.to_chrome_trace()["traceEvents"]]
        assert "breaker_reset" in dead_events
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            h = r.health()
            if h["serving_replicas"] == 2 and h["replica_restarts"] >= 1:
                break
            time.sleep(0.05)
        assert h["serving_replicas"] == 2, h
        assert h["failed_replicas"] == 0
        assert h["circuit_open"] >= 1          # history: it DID open
        assert h["breaker_resets"] == 1
        sup = h["supervisor"][failed_rid]
        assert sup["state"] == SLOT_SERVING
        assert sup["circuit_open"] is False
        # the revived slot serves: fresh no-affinity prompts spread by
        # occupancy, so a small burst must land on it
        outs = [r.submit(list(map(int, np.random.RandomState(50 + i)
                                  .randint(1, 200, 4))),
                         max_new_tokens=MAX_NEW) for i in range(4)]
        assert all(q.result(300) for q in outs)
        assert failed_rid in {q.replica_id for q in outs}
        prom = r.to_prometheus()
        assert "paddle_tpu_breaker_resets_total" in prom
        # the revival's provenance survives the swap on the FRESH
        # engine's `restarted` span in the merged artifact
        restarted = [e for e in r.to_chrome_trace()["traceEvents"]
                     if e.get("name") == "restarted"]
        assert any(e["args"].get("via_breaker_reset")
                   for e in restarted)
        assert r.shutdown(drain=False)


class TestProbeMirror:
    def test_respawn_probe_replays_live_shape(self, setup):
        """restart_opts={"probe_mirror": True}: the respawn gate
        replays the shape of the newest LIVE request served by the
        dead incarnation instead of the synthetic probe prompt — and
        falls back to the synthetic prompt when the dead engine never
        served anything."""
        cfg, params = setup
        r = Router(params, cfg, replicas=1, max_batch=2, block_size=4,
                   max_total_len=48, max_new_tokens=MAX_NEW, chunk=3,
                   max_queue_depth=32, max_prefill_bucket=16,
                   auto_restart=True,
                   restart_opts={"backoff_s": 0.05, "poll_s": 0.02,
                                 "probe_timeout_s": 120.0,
                                 "probe_mirror": True},
                   start=False)
        r.warmup()
        r.start()
        sup = r._supervisor
        assert sup._probe_mirror

        def planned_restart():
            dead = r.engines[0]
            assert sup.restart_slot(0)
            deadline = time.monotonic() + 300
            while sup.states()[0] != SLOT_SERVING \
                    or r.engines[0] is dead:
                assert time.monotonic() < deadline, "respawn stalled"
                time.sleep(0.02)
            return r.engines[0]

        # no live traffic yet: mirror capture finds nothing, the gate
        # falls back to the synthetic probe shape
        fresh = planned_restart()
        assert fresh.recent_prompts()[0] == ([1, 2, 3], 2)

        out = r.generate(PROMPTS[2], timeout=300)
        assert r.engines[0].recent_prompts()[-1] == (PROMPTS[2], MAX_NEW)
        # now the gate replays the live shape (newest entry — the dead
        # engine's own synthetic-probe generation is older)
        fresh = planned_restart()
        assert fresh.recent_prompts()[0] == (PROMPTS[2], MAX_NEW)
        # and the respawned sharded-or-not slot still serves correctly
        assert r.generate(PROMPTS[2], timeout=300) == out
        assert r.shutdown()
